"""ControlNet conditioned streaming (BASELINE config 4: ControlNet-canny).

Covers: in-graph canny annotator, zero-conv no-op property (an untrained
ControlNet must not perturb the base UNet — reference ControlNet wiring at
lib/wrapper.py:617-643), conditioning ring rotation alongside the latent
ring, runtime conditioning-scale swap, and diffusers key-map coverage.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ai_rtc_agent_tpu.models import loader as LD
from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.models import unet as U
from ai_rtc_agent_tpu.models.controlnet import (
    apply_controlnet,
    canny_soft,
    cond_embed_widths,
    init_controlnet,
)
from ai_rtc_agent_tpu.stream.engine import StreamEngine

MODEL = "tiny-test"


def _engine(**cfg_overrides):
    bundle = registry.load_model_bundle(MODEL, controlnet="tiny-cnet")
    cfg = registry.default_stream_config(MODEL, use_controlnet=True, **cfg_overrides)
    eng = StreamEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        jit_compile=False, donate=False,
    )
    eng.prepare("ctrl", guidance_scale=1.0, seed=3)
    return eng, bundle, cfg


def test_canny_soft_shape_and_range():
    img = jnp.asarray(
        np.random.default_rng(0).random((2, 16, 16, 3), dtype=np.float32)
    )
    edge = canny_soft(img)
    assert edge.shape == (2, 16, 16, 3)
    assert float(edge.min()) >= 0.0 and float(edge.max()) <= 1.0
    # all three channels identical (edge map broadcast)
    np.testing.assert_array_equal(np.asarray(edge[..., 0]), np.asarray(edge[..., 1]))


@pytest.mark.slow  # builds TWO engines (~17s); the zero-conv plumbing
# stays tier-1 via test_apply_controlnet_residual_shapes_match_unet_skips
# and test_nonzero_controlnet_changes_output_and_scale_swaps (ISSUE 11
# shave)
def test_untrained_controlnet_is_noop():
    """Zero convs make an untrained ControlNet an exact no-op on the UNet."""
    rng = np.random.default_rng(1)
    frame = rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)

    eng_c, bundle, cfg = _engine()
    out_c = eng_c(frame)

    bundle2 = registry.load_model_bundle(MODEL)
    cfg2 = registry.default_stream_config(MODEL)
    eng_p = StreamEngine(
        bundle2.stream_models, bundle2.params, cfg2, bundle2.encode_prompt,
        jit_compile=False, donate=False,
    )
    eng_p.prepare("ctrl", guidance_scale=1.0, seed=3)
    out_p = eng_p(frame)
    np.testing.assert_allclose(out_c, out_p, atol=1)  # uint8 rounding slack


@pytest.mark.slow  # THREE engine builds for the nonzero-conditioning x
# runtime-scale-swap composition (~14s; ISSUE 15 budget pairing):
# test_cond_ring_rotates_with_latent_ring and
# test_apply_controlnet_residual_shapes_match_unet_skips keep the
# controlnet stream path compiled + pinned in tier-1
def test_nonzero_controlnet_changes_output_and_scale_swaps():
    rng = np.random.default_rng(2)
    frame = rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)

    eng, bundle, cfg = _engine()
    # make the mid zero conv non-zero -> conditioning now perturbs the UNet
    zc = eng.params["controlnet"]["mid_zero_conv"]
    zc["kernel"] = jnp.asarray(
        rng.standard_normal(zc["kernel"].shape), zc["kernel"].dtype
    )
    out1 = np.asarray(eng(frame))

    eng2, bundle2, _ = _engine()
    eng2.params["controlnet"]["mid_zero_conv"]["kernel"] = zc["kernel"]
    eng2.update_controlnet_scale(0.0)  # scale 0 must restore the no-op
    out_scale0 = np.asarray(eng2(frame))

    eng3, bundle3, _ = _engine()
    out_base = np.asarray(eng3(frame))

    assert np.abs(out1.astype(int) - out_base.astype(int)).max() > 1
    np.testing.assert_allclose(out_scale0, out_base, atol=1)


def test_cond_ring_rotates_with_latent_ring():
    eng, bundle, cfg = _engine()
    assert cfg.batch_size > cfg.frame_buffer_size  # ring exists
    rng = np.random.default_rng(3)
    f1 = rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
    f2 = rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
    eng(f1)
    ring1 = np.asarray(eng.state["cnet_cond"])
    eng(f2)
    ring2 = np.asarray(eng.state["cnet_cond"])
    # head of the ring is always the latest frame's annotation
    img1 = jnp.asarray(f1[None], jnp.float32) / 255.0
    np.testing.assert_allclose(
        ring1[0], np.asarray(canny_soft(img1))[0], atol=1e-5
    )
    # f1's annotation advanced one slot when f2 entered
    np.testing.assert_allclose(ring2[1], ring1[0], atol=1e-5)


def test_controlnet_key_map_covers_params():
    """Every real-checkpoint leaf path must exist in the param tree."""
    cfg = U.UNetConfig.tiny()
    p = init_controlnet(jax.random.PRNGKey(0), cfg, num_down=2)
    km = LD.controlnet_key_map(cfg)
    # round-trip: export -> reload reproduces the tree (non-strict: the tiny
    # config has fewer cond-embed blocks than the full diffusers ladder)
    sd = LD.tree_to_state_dict(p, km)
    assert len(sd) > 20
    p2, n = LD.load_into_tree(p, sd, km, strict=False)
    assert n == len(sd)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_apply_controlnet_residual_shapes_match_unet_skips():
    cfg = U.UNetConfig.tiny()
    key = jax.random.PRNGKey(0)
    cnet = init_controlnet(key, cfg, num_down=2)
    unet = U.init_unet(key, cfg)
    B, h, w = 2, 8, 8
    x = jnp.zeros((B, h, w, 4))
    t = jnp.zeros((B,), jnp.int32)
    ctx = jnp.zeros((B, 7, cfg.cross_attention_dim))
    cond = jnp.zeros((B, h * 4, w * 4, 3))
    dres, mres = apply_controlnet(cnet, x, t, ctx, cond, cfg)
    # feeding them into apply_unet must not raise (shape agreement)
    out = U.apply_unet(
        unet, x, t, ctx, cfg, down_residuals=dres, mid_residual=mres
    )
    assert out.shape == (B, h, w, 4)
