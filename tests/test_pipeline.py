"""Pipeline façade tests (reference lib/pipeline.py parity surface)."""

import numpy as np
import pytest

from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.stream.pipeline import StreamDiffusionPipeline


@pytest.fixture(scope="module")
def pipe():
    return StreamDiffusionPipeline("tiny-test")


def test_ndarray_path(pipe):
    rng = np.random.default_rng(0)
    f = rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
    out = pipe(f)
    assert isinstance(out, np.ndarray)
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8


def test_videoframe_path_preserves_pts(pipe):
    from fractions import Fraction

    rng = np.random.default_rng(1)
    vf = VideoFrame.from_ndarray(rng.integers(0, 256, (64, 64, 3), dtype=np.uint8))
    vf.pts = 12345
    vf.time_base = Fraction(1, 90000)
    out = pipe(vf)
    assert isinstance(out, VideoFrame)
    assert out.pts == 12345
    assert out.time_base == Fraction(1, 90000)


def test_mismatched_resolution_resized(pipe):
    rng = np.random.default_rng(2)
    f = rng.integers(0, 256, (48, 80, 3), dtype=np.uint8)
    out = pipe(f)
    assert out.shape == (64, 64, 3)


def test_invalid_frame_type_raises(pipe):
    with pytest.raises(TypeError):
        pipe(object())


def test_update_prompt_and_t_index(pipe):
    pipe.update_prompt("new style")
    assert pipe.prompt == "new style"
    pipe.update_t_index_list([12, 22, 32, 42])
    assert pipe.t_index_list == [12, 22, 32, 42]
    with pytest.raises(ValueError):
        pipe.update_t_index_list([1, 2, 3])
