"""Pipeline façade tests (reference lib/pipeline.py parity surface)."""

import numpy as np
import pytest

from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.stream.pipeline import StreamDiffusionPipeline


@pytest.fixture(scope="module")
def pipe():
    return StreamDiffusionPipeline("tiny-test")


def test_ndarray_path(pipe):
    rng = np.random.default_rng(0)
    f = rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
    out = pipe(f)
    assert isinstance(out, np.ndarray)
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8


def test_videoframe_path_preserves_pts(pipe):
    from fractions import Fraction

    rng = np.random.default_rng(1)
    vf = VideoFrame.from_ndarray(rng.integers(0, 256, (64, 64, 3), dtype=np.uint8))
    vf.pts = 12345
    vf.time_base = Fraction(1, 90000)
    out = pipe(vf)
    assert isinstance(out, VideoFrame)
    assert out.pts == 12345
    assert out.time_base == Fraction(1, 90000)


def test_mismatched_resolution_resized(pipe):
    rng = np.random.default_rng(2)
    f = rng.integers(0, 256, (48, 80, 3), dtype=np.uint8)
    out = pipe(f)
    assert out.shape == (64, 64, 3)


def test_invalid_frame_type_raises(pipe):
    with pytest.raises(TypeError):
        pipe(object())


def test_update_prompt_and_t_index(pipe):
    pipe.update_prompt("new style")
    assert pipe.prompt == "new style"
    pipe.update_t_index_list([12, 22, 32, 42])
    assert pipe.t_index_list == [12, 22, 32, 42]
    with pytest.raises(ValueError):
        pipe.update_t_index_list([1, 2, 3])


def test_restart_preserves_runtime_guidance_and_delta(pipe):
    """ROADMAP open item 2: restart() used to re-prepare with
    DEFAULT_GUIDANCE_SCALE/DEFAULT_DELTA, silently reverting runtime
    /config guidance updates the moment a fault recovery ran.  The live
    values must survive — exactly like prompt and t_index_list do."""
    from ai_rtc_agent_tpu.server.agent import apply_runtime_config
    from ai_rtc_agent_tpu.stream.pipeline import (
        DEFAULT_DELTA,
        DEFAULT_GUIDANCE_SCALE,
    )

    try:
        apply_runtime_config(pipe, {"guidance_scale": 3.5, "delta": 0.7})
        assert pipe.guidance_scale == 3.5 and pipe.delta == 0.7
        assert float(pipe.engine.state["guidance"]) == pytest.approx(3.5)
        assert float(pipe.engine.state["delta"]) == pytest.approx(0.7)

        # a rejected update must apply NOTHING: neither the prompt (400
        # means rejected, not half-applied) nor the façade snapshot a
        # later restart() would silently push into the engine
        with pytest.raises((TypeError, ValueError)):
            apply_runtime_config(
                pipe, {"prompt": "must-not-apply", "delta": "abc"}
            )
        assert pipe.prompt != "must-not-apply"
        assert pipe.guidance_scale == 3.5 and pipe.delta == 0.7

        pipe.restart()  # the supervisor's fault-recovery hook

        assert float(pipe.engine.state["guidance"]) == pytest.approx(3.5)
        assert float(pipe.engine.state["delta"]) == pytest.approx(0.7)
        # the engine still steps after the live-param re-prepare
        out = pipe(np.zeros((64, 64, 3), np.uint8))
        assert out.shape == (64, 64, 3)
    finally:
        # the fixture is module-scoped: later tests must see defaults
        pipe.update_guidance(
            guidance_scale=DEFAULT_GUIDANCE_SCALE, delta=DEFAULT_DELTA
        )


def test_fbs2_serving_through_track(monkeypatch):
    """frame_buffer_size=2 in the LIVE serving path: the track batches 2
    consecutive frames per device step and drains outputs one per recv()
    in order (the reference's fbs amortization, lib/wrapper.py:159-163,
    previously bench-only)."""
    import asyncio

    from ai_rtc_agent_tpu.server.tracks import VideoStreamTrack
    from ai_rtc_agent_tpu.stream.pipeline import StreamDiffusionPipeline
    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.stream.engine import StreamConfig

    monkeypatch.setenv("WARMUP_FRAMES", "2")
    cfg = registry.default_stream_config("tiny-test", frame_buffer_size=2)
    pipe = StreamDiffusionPipeline("tiny-test", config=cfg)
    assert pipe.frame_buffer_size == 2

    class Source:
        def __init__(self):
            self.n = 0

        async def recv(self):
            self.n += 1
            return np.full((64, 64, 3), (self.n * 9) % 256, np.uint8)

    src = Source()
    track = VideoStreamTrack(src, pipe, pipeline_depth=2)

    async def go():
        outs = [await track.recv() for _ in range(6)]
        return outs

    outs = asyncio.run(go())
    assert len(outs) == 6
    for o in outs:
        arr = o if isinstance(o, np.ndarray) else o.to_ndarray()
        assert arr.shape == (64, 64, 3) and arr.dtype == np.uint8
    # warmup consumed 2 frames; 6 outputs need 3 more batches (2 each) with
    # depth-2 batch pipelining keeping one extra batch in flight
    assert src.n >= 2 + 6
