"""--multipeer serving: N peers batched on one engine, per-peer prompts.

Covers VERDICT r1 'Serve MultiPeerEngine': slot claim per connection, 503 on
exhaustion, per-peer datachannel config, slot release on close (the agent
analog of BASELINE configs[4]; reference shares one global pipeline,
agent.py:144-176, 423-430).
"""

import asyncio
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.parallel.multipeer import CapacityError
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.signaling import LoopbackProvider, make_loopback_offer


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# engine-level: real MultiPeerPipeline on the tiny hermetic model
# ---------------------------------------------------------------------------

def test_multipeer_pipeline_two_peers_independent(rng):
    from ai_rtc_agent_tpu.server.multipeer_serving import MultiPeerPipeline

    mp = MultiPeerPipeline("tiny-test", max_peers=2)
    try:
        p1 = mp.claim("a red cat")
        p2 = mp.claim("a blue dog")
        with pytest.raises(CapacityError):
            mp.claim("third peer")

        frame = rng.integers(
            0, 256, (mp.height, mp.width, 3), dtype=np.uint8
        )
        o1 = p1(frame)
        o2 = p2(frame)
        assert o1.shape == frame.shape and o1.dtype == np.uint8
        assert o2.shape == frame.shape
        # different prompts + per-slot seeds -> different streams
        assert not np.array_equal(o1, o2)

        # per-peer prompt update only touches that slot
        p1.update_prompt("another style")
        o1b = p1(frame)
        assert o1b.shape == frame.shape

        # release frees capacity; double-release is a no-op
        p1.release()
        p1.release()
        assert mp.free_slots == 1
        p3 = mp.claim("replacement peer")
        assert p3.slot == p1.slot
    finally:
        mp.close()


def test_multipeer_pipeline_t_index_update():
    from ai_rtc_agent_tpu.server.multipeer_serving import MultiPeerPipeline

    mp = MultiPeerPipeline("tiny-test", max_peers=2)
    try:
        p1 = mp.claim("x")
        p1.update_t_index_list([5, 15, 25, 35])
        with pytest.raises(ValueError):
            p1.update_t_index_list([5, 15])  # wrong length
        # global POST /config surface applies to active slots only
        mp.update_t_index_list([6, 16, 26, 36])
        mp.update_prompt("global prompt")
    finally:
        mp.close()


# ---------------------------------------------------------------------------
# agent-level: slot claim / 503 / release via HTTP (fake engine, no jax)
# ---------------------------------------------------------------------------

class _FakePeer:
    def __init__(self, owner, slot):
        self.owner, self.slot = owner, slot
        self.prompt = None
        self.released = False

    def __call__(self, frame):
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def update_prompt(self, p):
        self.prompt = p

    def update_t_index_list(self, t):
        pass

    def release(self):
        if not self.released:
            self.released = True
            self.owner.free += 1


class _FakeMultiPeer:
    def __init__(self, capacity):
        self.free = capacity
        self.peers = []

    def claim(self, prompt=None):
        if self.free == 0:
            raise CapacityError("full")
        self.free -= 1
        peer = _FakePeer(self, len(self.peers))
        self.peers.append(peer)
        return peer

    def update_prompt(self, p):
        for peer in self.peers:
            peer.update_prompt(p)

    def update_t_index_list(self, t):
        pass

    def close(self):
        pass


def test_agent_multipeer_offer_claims_and_503(monkeypatch):
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    fake = _FakeMultiPeer(capacity=2)

    async def go():
        app = build_app(
            multipeer=2, multipeer_pipeline=fake, provider=LoopbackProvider()
        )
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            async def post_offer(room):
                return await client.post(
                    "/offer",
                    json={
                        "room_id": room,
                        "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                    },
                )

            r1 = await post_offer("room1")
            assert r1.status == 200
            r2 = await post_offer("room2")
            assert r2.status == 200
            assert fake.free == 0

            r3 = await post_offer("room3")
            assert r3.status == 503

            # per-peer datachannel prompt reaches only that peer
            pcs = [pc for pc in app["pcs"] if pc.datachannel is not None]
            await pcs[0].datachannel.deliver(json.dumps({"prompt": "peer0 style"}))
            prompts = sorted(
                (p.prompt or "") for p in fake.peers
            )
            assert prompts.count("peer0 style") == 1

            # closing a connection releases its slot (release is scheduled
            # off the event loop — give it a tick)
            await pcs[0].close()
            for _ in range(50):
                if fake.free == 1:
                    break
                await asyncio.sleep(0.02)
            assert fake.free == 1
            r4 = await post_offer("room4")
            assert r4.status == 200
        finally:
            await client.close()

    run(go())


def test_multipeer_native_rtp_two_udp_clients(monkeypatch):
    """--multipeer over the native RTP transport: two UDP clients each claim
    a slot and each gets its own processed stream back (BASELINE configs[4]
    end-to-end on a real wire)."""
    from ai_rtc_agent_tpu.media import native

    if native.load() is None:
        pytest.skip("native lib unavailable")
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    # Deterministic under full-suite load: the admission gate refuses
    # (503) when the event loop looks laggy, and a busy CI box running
    # the whole suite can trip the default 200ms budget right as this
    # test's /offer lands — the only test here that admits TWO sessions
    # back to back.  The lag shield is not what this test exercises, so
    # pin the budget far above any scheduler hiccup.
    monkeypatch.setenv("OVERLOAD_LOOP_LAG_BUDGET_MS", "10000")
    from ai_rtc_agent_tpu.media.frames import VideoFrame
    from ai_rtc_agent_tpu.media.plane import H264RingSource, H264Sink
    from ai_rtc_agent_tpu.server.multipeer_serving import MultiPeerPipeline
    from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider

    use_h264 = native.h264_available()

    async def go():
        mp = MultiPeerPipeline("tiny-test", max_peers=2)
        provider = NativeRtpProvider(use_h264=use_h264)
        app = build_app(multipeer=2, multipeer_pipeline=mp, provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        loop = asyncio.get_event_loop()
        w, h = mp.width, mp.height
        try:
            clients = []
            for n in range(2):
                q: asyncio.Queue = asyncio.Queue()

                class _Recv(asyncio.DatagramProtocol):
                    def __init__(self, q=q):
                        self.q = q

                    def datagram_received(self, data, addr):
                        self.q.put_nowait(data)

                tr, _ = await loop.create_datagram_endpoint(
                    _Recv, local_addr=("127.0.0.1", 0)
                )
                port = tr.get_extra_info("sockname")[1]
                offer = json.dumps(
                    {
                        "native_rtp": True,
                        "video": True,
                        "client_addr": ["127.0.0.1", port],
                        "width": w,
                        "height": h,
                    }
                )
                r = await client.post(
                    "/offer",
                    json={"room_id": f"rtp{n}", "offer": {"sdp": offer, "type": "offer"}},
                )
                assert r.status == 200, await r.text()
                server_port = json.loads((await r.json())["sdp"])["server_port"]
                send, _ = await loop.create_datagram_endpoint(
                    asyncio.DatagramProtocol,
                    remote_addr=("127.0.0.1", server_port),
                )
                clients.append(
                    dict(
                        q=q, recv_tr=tr, send=send,
                        sink=H264Sink(w, h, use_h264=use_h264, ssrc=0x100 + n),
                        back=H264RingSource(w, h, use_h264=use_h264),
                        decoded=[],
                    )
                )
            assert mp.free_slots == 0

            rng = np.random.default_rng(1)
            import time as _time

            deadline = _time.monotonic() + 300  # first step jit-compiles
            i = 0
            while _time.monotonic() < deadline:
                i += 1
                for c in clients:
                    f = VideoFrame.from_ndarray(
                        rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
                    )
                    f.pts = i * 3000
                    for pkt in c["sink"].consume(f):
                        c["send"].sendto(pkt)
                await asyncio.sleep(0.05)
                for c in clients:
                    try:
                        while True:
                            c["back"].feed_packet(c["q"].get_nowait())
                    except asyncio.QueueEmpty:
                        pass
                    while (item := c["back"]._ring.pop()) is not None:
                        c["decoded"].append(item[0])
                if all(c["decoded"] for c in clients):
                    break
            for n, c in enumerate(clients):
                assert c["decoded"], f"client {n} got no frames back"
                assert c["decoded"][0].shape == (h, w, 3)
        finally:
            for c in clients:
                c["sink"].close()
                c["back"].close()
                c["recv_tr"].close()
                c["send"].close()
            await client.close()
            mp.close()

    run(go())


@pytest.mark.slow  # multipeer x controlnet composition compile (~14s);
# multipeer serving and the controlnet residual path each keep lighter
# tier-1 siblings in this file / test_controlnet_stream (ISSUE 11 shave)
def test_multipeer_with_controlnet(rng):
    """--multipeer + --controlnet combine (round-2 review fix: the flag was
    silently dropped): the batched engine carries the conditioned branch and
    per-peer streams step with in-graph canny annotation."""
    from ai_rtc_agent_tpu.server.multipeer_serving import MultiPeerPipeline

    mp = MultiPeerPipeline(
        "tiny-test", max_peers=2, controlnet="tiny-cnet-random"
    )
    try:
        assert mp.config.use_controlnet
        p1 = mp.claim("conditioned stream")
        frame = rng.integers(0, 256, (mp.height, mp.width, 3), dtype=np.uint8)
        out = p1(frame)
        assert out.shape == frame.shape and out.dtype == np.uint8
    finally:
        mp.close()


def test_fetch_output_type_matches_single_peer_under_hw_encode(monkeypatch, rng):
    """HW_ENCODE serving must hand the track layer bare ndarrays in BOTH
    serving modes (ADVICE r2: multipeer used to wrap VideoFrames while the
    single-peer pipeline returned arrays under identical config)."""
    from ai_rtc_agent_tpu.media.frames import VideoFrame
    from ai_rtc_agent_tpu.server.multipeer_serving import MultiPeerPipeline

    monkeypatch.setenv("HW_ENCODE", "true")
    mp = MultiPeerPipeline("tiny-test", max_peers=1)
    try:
        peer = mp.claim("style")
        arr = rng.integers(0, 256, (mp.height, mp.width, 3), dtype=np.uint8)
        src = VideoFrame.from_ndarray(arr)
        src.pts = 3000
        out = peer.fetch(peer.submit(src), src_frame=src)
        assert isinstance(out, np.ndarray)  # no VideoFrame wrap in hw path

        monkeypatch.delenv("HW_ENCODE")
        out2 = peer.fetch(peer.submit(src), src_frame=src)
        assert hasattr(out2, "pts")  # sw path: metadata-carrying frame
    finally:
        mp.close()


def test_coordinator_below_capacity_uses_bucket_path(rng):
    """1 claimed slot of 3: the coordinator's all-peers tick routes through
    the active-count bucket step and still resolves the peer's future."""
    from ai_rtc_agent_tpu.server.multipeer_serving import MultiPeerPipeline

    mp = MultiPeerPipeline("tiny-test", max_peers=3)
    try:
        peer = mp.claim("solo style")
        frame = rng.integers(0, 256, (mp.height, mp.width, 3), dtype=np.uint8)
        out = peer(frame)
        assert out.shape == frame.shape and out.dtype == np.uint8
        assert (1, "full") in mp.engine._bucket_steps  # k=1 variant ran
    finally:
        mp.close()
