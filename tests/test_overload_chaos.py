"""Overload chaos (hermetic, tier-1): a sustained injected slow-step fault
drives a live loopback session into overload; the control plane must

* keep every frame queue at/below its bound (the source queue never grows
  past its maxsize while the producer runs ahead of the engine),
* shed stale frames at ingest with every shed counted — pushed frames ==
  delivered + shed + still-queued, exactly,
* keep admitted-frame freshness p99 under the configured deadline,
* refuse new sessions (503 + Retry-After) while saturated,
* walk the session down the shedding ladder (supervisor DEGRADED with an
  overload reason, no restart budget spent) and, once the fault clears,
  back up: ladder fully unwound, admission open, session HEALTHY.

Fast and deterministic-by-construction: the fault plan is seeded, the
engine slowdown is a worker-thread sleep well under the step timeout (slow
≠ wedged: no restarts, no FAILED), and every wait is bounded.
"""

import asyncio
import json
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.resilience import faults
from ai_rtc_agent_tpu.resilience.faults import FaultPlan, FaultSpec
from ai_rtc_agent_tpu.resilience.overload import RUNG_PASSTHROUGH
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.signaling import (
    LoopbackProvider,
    make_loopback_offer,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.deactivate()
    yield
    faults.deactivate()


class SlowableChaosPipeline:
    """Invert-colors pipeline whose steps block on the injected slow_step
    fault — SLOW, not wedged (the delay stays under the step timeout), so
    overload pressure builds without consuming the restart budget."""

    def __init__(self):
        self._fault_scope = faults.scope("engine")
        self.calls = 0
        self.restarts = 0

    def clear_faults(self):
        self._fault_scope = None

    def __call__(self, frame):
        self.calls += 1
        if self._fault_scope is not None:
            self._fault_scope.step()
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def restart(self):
        self.restarts += 1


def _offer_body(room="overload"):
    return {
        "room_id": room,
        "offer": {"sdp": make_loopback_offer(), "type": "offer"},
    }


def test_overload_chaos_sheds_bounded_refuses_then_recovers(monkeypatch):
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    # slow (0.25s) steps stay far under the 5s step timeout: no stall
    # verdicts, no restarts — pure capacity pressure
    monkeypatch.setenv("RESILIENCE_STEP_TIMEOUT_S", "5")
    monkeypatch.setenv("RESILIENCE_FIRST_STEP_TIMEOUT_S", "5")
    monkeypatch.setenv("SUPERVISOR_STALL_AFTER_S", "30")
    monkeypatch.setenv("OVERLOAD_STEP_BUDGET_MS", "60")
    monkeypatch.setenv("OVERLOAD_FRAME_DEADLINE_MS", "300")
    monkeypatch.setenv("OVERLOAD_TICK_S", "0.05")
    monkeypatch.setenv("OVERLOAD_UP_TICKS", "2")
    monkeypatch.setenv("OVERLOAD_DOWN_TICKS", "2")
    monkeypatch.setenv("OVERLOAD_PROBE_S", "0.1")
    monkeypatch.setenv("OVERLOAD_RETRY_AFTER_S", "1")

    faults.activate(
        FaultPlan(
            specs=(
                FaultSpec(target="engine", kind="slow_step", delay_s=0.25),
            ),
            seed=11,
        )
    )
    pipe = SlowableChaosPipeline()

    async def go():
        app = build_app(pipeline=pipe, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 200
            pc = next(iter(app["pcs"]))
            viewer = pc.out_tracks[0]
            src_q = pc.in_track._q  # the bounded loopback source queue
            (sup,) = app["supervisors"].values()
            ov = app["overload"]
            ladder = ov.ladders[sup.session_id]

            pushed = 0
            delivered = []
            max_qsize = 0
            producer_alive = True

            async def producer():
                # a camera that does not slow down for the server: one
                # stamped frame every 10 ms, for as long as the test runs
                nonlocal pushed, max_qsize
                i = 0
                while producer_alive:
                    f = VideoFrame.from_ndarray(
                        np.full((8, 8, 3), i % 200, np.uint8)
                    )
                    f.wall_ts = time.monotonic()  # decode stamp
                    await pc.in_track.push(f)  # blocks at the queue bound
                    pushed += 1
                    max_qsize = max(max_qsize, src_q.qsize())
                    i += 1
                    await asyncio.sleep(0.01)

            prod_task = asyncio.ensure_future(producer())

            async def consume_until(pred, deadline_s):
                deadline = time.monotonic() + deadline_s
                while time.monotonic() < deadline and not pred():
                    out = await asyncio.wait_for(viewer.recv(), timeout=5.0)
                    delivered.append(out)
                return pred()

            # --- phase 1: saturation.  The ladder must reach passthrough,
            # the supervisor must be DEGRADED with an overload reason, and
            # admission must refuse new sessions with Retry-After.
            assert await consume_until(
                lambda: ladder.rung >= RUNG_PASSTHROUGH
                and sup.state == "DEGRADED",
                deadline_s=20.0,
            ), f"never saturated (rung={ladder.rung}, state={sup.state})"
            assert "overload" in sup.snapshot()["reason"]
            assert sup.snapshot()["restarts"] == 0  # capacity, not a fault

            r = await client.post("/offer", json=_offer_body("late"))
            assert r.status == 503, "saturated box must refuse new sessions"
            assert int(r.headers["Retry-After"]) >= 1
            cap = await (await client.get("/capacity")).json()
            assert cap["saturated"] is True and cap["capacity"] == 0

            m = await (await client.get("/metrics")).json()
            assert m["overload_pressure"] >= 1.0
            assert m["overload_rung_max"] >= RUNG_PASSTHROUGH
            assert m.get("overload_admission_rejected_total", 0) >= 1
            # the ingest queue is visible at /metrics, inside its bound
            qsnap = m["overload_queues"][f"ingest:{sup.session_id}"]
            assert 0 <= qsnap["depth"] <= qsnap["bound"]

            # --- phase 2: the fault clears; probe frames wash the EWMA
            # down, the ladder unwinds rung by rung, and the supervisor
            # walks DEGRADED -> RECOVERING -> HEALTHY on real steps.
            pipe.clear_faults()
            assert await consume_until(
                lambda: ladder.rung == 0 and sup.state == "HEALTHY",
                deadline_s=30.0,
            ), f"no recovery (rung={ladder.rung}, state={sup.state})"

            # admission is open again
            r = await client.post("/offer", json=_offer_body("post"))
            assert r.status == 200

            # --- accounting: stop the producer, then balance the books.
            producer_alive = False
            await asyncio.sleep(0.05)
            prod_task.cancel()

            m = await (await client.get("/metrics")).json()
            shed = m.get("overload_shed_ingest_total", 0)
            assert shed > 0, "saturation never shed a stale frame"
            still_queued = src_q.qsize()
            assert pushed == len(delivered) + shed + still_queued, (
                f"shed accounting leaks frames: pushed={pushed} "
                f"delivered={len(delivered)} shed={shed} "
                f"queued={still_queued}"
            )

            # every queue stayed at/below its bound throughout
            assert max_qsize <= src_q.maxsize

            # freshness: the queue-wait age of every admitted frame stayed
            # under the deadline at p99 — staleness was shed, not served
            assert m["overload_freshness_p99_ms"] < 300.0

            # the ride is visible at /health: DEGRADED with an overload
            # reason happened, and the final state is HEALTHY
            h = await (await client.get("/health")).json()
            assert h["status"] == "HEALTHY"
            snap = h["sessions"][sup.session_id]
            assert snap["overload_rung"] == 0
            reasons = [t["reason"] for t in snap["transitions"]]
            assert any("overload" in x for x in reasons)
            seen = {t["to"] for t in snap["transitions"]}
            assert {"DEGRADED", "RECOVERING", "HEALTHY"} <= seen
        finally:
            await client.close()

    asyncio.run(go())


def test_overload_chaos_passthrough_keeps_stream_alive(monkeypatch):
    """During full passthrough shedding the viewer still receives frames
    (source pixels, delivered promptly) — the stream thins, never freezes."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("RESILIENCE_STEP_TIMEOUT_S", "5")
    monkeypatch.setenv("RESILIENCE_FIRST_STEP_TIMEOUT_S", "5")
    monkeypatch.setenv("SUPERVISOR_STALL_AFTER_S", "30")
    monkeypatch.setenv("OVERLOAD_STEP_BUDGET_MS", "60")
    monkeypatch.setenv("OVERLOAD_TICK_S", "0.05")
    monkeypatch.setenv("OVERLOAD_UP_TICKS", "2")
    monkeypatch.setenv("OVERLOAD_DOWN_TICKS", "50")  # stay escalated
    monkeypatch.setenv("OVERLOAD_PROBE_S", "10")  # no probes: pure shed

    faults.activate(
        FaultPlan(
            specs=(
                FaultSpec(target="engine", kind="slow_step", delay_s=0.25),
            ),
            seed=3,
        )
    )
    pipe = SlowableChaosPipeline()

    async def go():
        app = build_app(pipeline=pipe, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 200
            pc = next(iter(app["pcs"]))
            viewer = pc.out_tracks[0]
            (sup,) = app["supervisors"].values()
            ov = app["overload"]
            ladder = ov.ladders[sup.session_id]

            deadline = time.monotonic() + 20.0
            i = 0
            while time.monotonic() < deadline and ladder.rung < RUNG_PASSTHROUGH:
                await pc.in_track.push(np.full((8, 8, 3), i % 200, np.uint8))
                await asyncio.wait_for(viewer.recv(), timeout=5.0)
                i += 1
            assert ladder.rung >= RUNG_PASSTHROUGH

            # full shed: every frame comes back passthrough, and FAST
            engine_calls = pipe.calls
            t0 = time.monotonic()
            for j in range(10):
                src = np.full((8, 8, 3), 7 + j, np.uint8)
                await pc.in_track.push(src)
                out = await asyncio.wait_for(viewer.recv(), timeout=5.0)
                arr = out if isinstance(out, np.ndarray) else out.to_ndarray()
                assert np.array_equal(arr, src), "passthrough must be source"
            assert time.monotonic() - t0 < 2.0, "shed frames must not queue"
            assert pipe.calls == engine_calls  # no engine work at all
            assert ladder.frames_skipped >= 10
            m = await (await client.get("/metrics")).json()
            assert m["overload_frames_skipped"] >= 10
        finally:
            await client.close()

    asyncio.run(go())
