"""Stream engine tests on the tiny model family (CPU, hermetic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.stream.engine import StreamConfig, StreamEngine


def _engine(**overrides):
    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test", **overrides)
    eng = StreamEngine(
        models=bundle.stream_models,
        params=bundle.params,
        cfg=cfg,
        encode_prompt=bundle.encode_prompt,
    )
    return eng, cfg


def _frames(n, h=64, w=64, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, (h, w, 3), dtype=np.uint8) for _ in range(n)]


def test_img2img_stream_batch_end_to_end():
    eng, cfg = _engine()
    eng.prepare("a cat", guidance_scale=1.2, seed=1)
    outs = [eng(f) for f in _frames(6)]
    for o in outs:
        assert o.shape == (64, 64, 3) and o.dtype == np.uint8
    # ring depth = 4: the first outputs drain a buffer seeded with noise,
    # steady-state outputs must differ across distinct inputs
    assert not np.array_equal(outs[4], outs[5])


def test_prompt_update_changes_output_no_retrace():
    eng, cfg = _engine()
    eng.prepare("a cat", seed=1)
    frames = _frames(8, seed=3)
    for f in frames[:5]:
        eng(f)
    baseline = eng(frames[5])
    eng2, _ = _engine()
    eng2.prepare("a cat", seed=1)
    for f in frames[:5]:
        eng2(f)
    eng2.update_prompt("a dog in space")
    changed = eng2(frames[5])
    assert baseline.shape == changed.shape
    assert not np.array_equal(baseline, changed)


def test_t_index_update_same_length_ok_wrong_length_raises():
    eng, cfg = _engine()
    eng.prepare("x", seed=0)
    eng.update_t_index_list([10, 20, 30, 40])
    with pytest.raises(ValueError):
        eng.update_t_index_list([10, 20])


def test_txt2img_mode():
    eng, cfg = _engine(mode="txt2img")
    eng.prepare("scenery", seed=2)
    # txt2img still takes a frame arg for API uniformity; content ignored
    out = eng(_frames(1)[0])
    assert out.shape == (64, 64, 3)


def test_cfg_full_double_batch():
    eng, cfg = _engine(cfg_type="full")
    eng.prepare("p", guidance_scale=3.0, seed=0)
    out = eng(_frames(1)[0])
    assert out.shape == (64, 64, 3)


def test_cfg_initialize():
    eng, cfg = _engine(cfg_type="initialize")
    eng.prepare("p", guidance_scale=1.4, seed=0)
    out = eng(_frames(1)[0])
    assert out.shape == (64, 64, 3)


def test_turbo_1_step():
    eng, cfg = _engine(
        t_index_list=(0,),
        num_inference_steps=1,
        timestep_spacing="trailing",
        scheduler="turbo",
        cfg_type="none",
    )
    eng.prepare("p", seed=0)
    f = _frames(2, seed=1)
    o1, o2 = eng(f[0]), eng(f[1])
    # depth-1 ring: output responds to the current frame immediately
    assert not np.array_equal(o1, o2)


@pytest.mark.slow  # n_stages separate UNet compiles for a shape assert
def test_sequential_mode_matches_shapes():
    eng, cfg = _engine(use_denoising_batch=False)
    eng.prepare("p", seed=0)
    out = eng(_frames(1)[0])
    assert out.shape == (64, 64, 3)


def test_frame_buffer_size_2():
    eng, cfg = _engine(frame_buffer_size=2)
    eng.prepare("p", seed=0)
    f = np.stack(_frames(2, seed=5))
    out = eng(f)
    assert out.shape == (2, 64, 64, 3)


def test_similar_image_filter_skips_device_call():
    eng, cfg = _engine(similar_image_filter=True, similar_image_threshold=0.9)
    eng.prepare("p", seed=0)
    f = _frames(1)[0]
    o1 = eng(f)
    calls = {"n": 0}
    orig = eng._step

    def counting_step(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    eng._step = counting_step
    o2 = eng(f.copy())  # identical frame -> skip
    assert calls["n"] == 0
    np.testing.assert_array_equal(o1, o2)


def test_guidance_update():
    eng, cfg = _engine()
    eng.prepare("p", guidance_scale=1.0, seed=0)
    eng.update_guidance(guidance_scale=2.0, delta=0.8)
    assert float(eng.state["guidance"]) == 2.0
    assert float(eng.state["delta"]) == pytest.approx(0.8)


def test_fused_epilogue_parity():
    """Fused Pallas epilogue == composed XLA ops, bitwise-near (both stream
    LCM 'self' and turbo 'none' shapes), including ring + stock evolution."""
    import numpy as np

    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.stream.engine import StreamEngine

    rng = np.random.default_rng(7)
    frames = [rng.integers(0, 256, (64, 64, 3), dtype=np.uint8) for _ in range(3)]

    for overrides in (
        dict(),  # tiny default: 4-stage LCM stream batch, cfg self
        dict(t_index_list=(0,), num_inference_steps=1,
             timestep_spacing="trailing", scheduler="turbo", cfg_type="none"),
    ):
        outs = {}
        for fused in (False, True):
            bundle = registry.load_model_bundle("tiny-test")
            cfg = registry.default_stream_config(
                "tiny-test", use_fused_epilogue=fused, **overrides
            )
            eng = StreamEngine(
                bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
                jit_compile=False, donate=False,
            )
            eng.prepare("parity", guidance_scale=1.4, delta=0.7, seed=5)
            outs[fused] = [np.asarray(eng(f), np.int32) for f in frames]
        for a, b in zip(outs[False], outs[True]):
            assert np.abs(a - b).max() <= 1, overrides  # uint8 rounding slack


def test_similar_image_filter_with_pipelined_depth():
    """VERDICT r1 weak #9: the similarity filter must stay correct when
    PIPELINE_DEPTH frames are in flight — skip handles duplicate the most
    recently SUBMITTED output, fetches resolve in order, and the skip
    counter respects max_skip."""
    from collections import deque

    eng, cfg = _engine(
        similar_image_filter=True,
        similar_image_threshold=0.9,
        similar_image_max_skip=3,
    )
    eng.prepare("static scene", seed=3)
    static = _frames(1)[0]
    depth = 3
    pending: deque = deque()
    outs = []
    submitted_real = 0
    for i in range(12):
        before = eng._skip_count
        pending.append(eng.submit(static))
        if eng._skip_count == 0 or eng._skip_count <= before:
            submitted_real += 1
        if len(pending) >= depth:
            outs.append(eng.fetch(pending.popleft()))
    while pending:
        outs.append(eng.fetch(pending.popleft()))
    assert len(outs) == 12
    for o in outs:
        assert o.shape == (cfg.height, cfg.width, 3)
    # max_skip=3 forces a real device step at least every 4th frame
    assert submitted_real >= 12 // 4
    # duplicated (skipped) handles resolve to SOME real output bytes —
    # identical to the most recent real frame's output at submit time
    assert all(o.dtype == np.uint8 for o in outs)


@pytest.mark.slow  # two full engine builds + a tp=2 virtual mesh (~12s);
# the deepcache sharded-compose legs keep tp-mesh coverage in tier-1
def test_tp_sharded_stream_engine_matches_single():
    """Tensor-parallel single-stream serving (--tp N): the tp=2-sharded
    engine computes the same stream as the single-device one (SURVEY
    sec.2c TP row — Megatron rules on the serving step, psums over ICI)."""
    from ai_rtc_agent_tpu.parallel import mesh as M

    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test")
    mk = lambda mesh: StreamEngine(
        models=bundle.stream_models,
        params=bundle.params,
        cfg=cfg,
        encode_prompt=bundle.encode_prompt,
        mesh=mesh,
    ).prepare("tp parity", seed=5)
    eng1 = mk(None)
    eng2 = mk(M.make_mesh(tp=2))
    for f in _frames(3, seed=9):
        o1, o2 = eng1(f), eng2(f)
        # same math modulo reduction order: uint8 outputs within 2 LSB
        assert np.abs(o1.astype(int) - o2.astype(int)).max() <= 2


@pytest.mark.slow  # two full engine builds + an sp=2 virtual mesh (~14s);
# test_parallel's ring-attention parity + the deepcache sp-mesh compose
# leg keep the sequence-parallel path covered in tier-1
def test_sp_sharded_stream_engine_matches_single(monkeypatch):
    """Sequence-parallel single-stream serving (--sp N + ATTN_IMPL=ring):
    the sp=2 engine routes UNet attention through ring attention
    (parallel/ring_attention) and must match the single-device stream."""
    from ai_rtc_agent_tpu.parallel import mesh as M

    cfg = registry.default_stream_config("tiny-test")
    bundle_xla = registry.load_model_bundle("tiny-test")
    eng1 = StreamEngine(
        models=bundle_xla.stream_models,
        params=bundle_xla.params,
        cfg=cfg,
        encode_prompt=bundle_xla.encode_prompt,
    ).prepare("sp parity", seed=5)

    monkeypatch.setenv("ATTN_IMPL", "ring")
    bundle_ring = registry.load_model_bundle("tiny-test")
    eng2 = StreamEngine(
        models=bundle_ring.stream_models,
        params=bundle_ring.params,
        cfg=cfg,
        encode_prompt=bundle_ring.encode_prompt,
        mesh=M.make_mesh(sp=2),
    ).prepare("sp parity", seed=5)

    for f in _frames(3, seed=11):
        o1, o2 = eng1(f), eng2(f)
        assert np.abs(o1.astype(int) - o2.astype(int)).max() <= 2


def test_concurrent_submits_from_two_threads():
    """Two tracks sharing one engine dispatch from worker threads (single-
    pipeline serving with multiple connections): the submit lock must keep
    every handle resolvable and outputs well-formed."""
    from concurrent.futures import ThreadPoolExecutor

    eng, cfg = _engine()
    eng.prepare("two tracks", seed=2)
    frames = _frames(16, seed=3)

    def worker(fs):
        outs = []
        for f in fs:
            outs.append(eng.fetch(eng.submit(f)))
        return outs

    with ThreadPoolExecutor(max_workers=2) as pool:
        r1 = pool.submit(worker, frames[:8])
        r2 = pool.submit(worker, frames[8:])
        outs = r1.result() + r2.result()
    assert len(outs) == 16
    for o in outs:
        assert o.shape == (cfg.height, cfg.width, 3) and o.dtype == np.uint8


def test_tinyxl_added_cond_stream_and_prompt_swap():
    """The hermetic SDXL-style family (dual text towers + text_time
    addition embeds) streams end to end, and a prompt update swaps the
    POOLED embeds too (reference SDXL conditioning surface)."""
    bundle = registry.load_model_bundle("tiny-xl-test")
    cfg = registry.default_stream_config("tiny-xl-test")
    assert cfg.use_added_cond
    eng = StreamEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt
    ).prepare("an sdxl-style prompt", seed=6)
    assert "added_text" in eng.state

    frame = _frames(1, seed=13)[0]
    outs_a = [eng(frame) for _ in range(5)]
    pooled_before = np.asarray(eng.state["added_text"])
    eng.update_prompt("a totally different style")
    pooled_after = np.asarray(eng.state["added_text"])
    assert not np.array_equal(pooled_before, pooled_after)
    out_b = eng(frame)
    assert out_b.shape == frame.shape
    assert not np.array_equal(outs_a[-1], out_b)


def test_similarity_filter_stochastic_semantics():
    """Fork-parity semantics (VERDICT r2 weak #7, reference
    lib/wrapper.py:192-195): cosine similarity with a LINEAR skip-probability
    ramp — sim=1 always skips, sim<=threshold never does, the band between
    skips stochastically, and max_skip forces a refresh."""
    eng, cfg = _engine(
        similar_image_filter=True,
        similar_image_threshold=0.9,
        similar_image_max_skip=2,
    )
    eng.prepare("ramp", seed=0)
    base = _frames(1)[0]
    eng(base)

    # orthogonal-ish content (sim << threshold): never skipped
    different = 255 - base
    assert eng._maybe_skip(different) is False

    # identical (sim == 1 -> prob 1): skipped, until max_skip forces work
    eng(base)
    assert eng._maybe_skip(base.copy()) is True
    assert eng._maybe_skip(base.copy()) is True
    assert eng._maybe_skip(base.copy()) is False  # max_skip=2 exhausted
    assert eng._skip_count == 0  # forced refresh resets the counter

    # the stochastic band: sim just above threshold -> prob strictly
    # between 0 and 1 -> over many draws some skip, some don't
    eng(base)
    jitter = base.astype(np.int16)
    rng = np.random.default_rng(7)
    skips = 0
    trials = 60
    for _ in range(trials):
        eng._skip_count = 0  # isolate each draw from the max-skip guard
        # +/-40 jitter puts cosine similarity ~0.985 against threshold 0.9:
        # skip probability ~0.85 — a REAL stochastic band (smaller jitter
        # gives prob ~0.99 and the "some don't skip" half flakes on seeds)
        noisy = np.clip(
            jitter + rng.integers(-40, 41, jitter.shape), 0, 255
        ).astype(np.uint8)
        if eng._maybe_skip(noisy):
            skips += 1
            # a skip leaves prev_frame unchanged; reset for the next draw
        eng._prev_frame_small = np.asarray(base, np.float32)[..., ::16, ::16, :]
    assert 0 < skips < trials, f"expected a stochastic band, got {skips}/{trials}"


def test_similarity_filter_black_frame_not_similar_to_content():
    """Zero-norm guard (code-review r3): a fade to black must not read as
    'identical' to arbitrary content (cosine denominator is 0)."""
    eng, cfg = _engine(similar_image_filter=True, similar_image_threshold=0.9)
    eng.prepare("fade", seed=0)
    content = _frames(1)[0]
    eng(content)
    black = np.zeros_like(content)
    assert eng._maybe_skip(black) is False  # black vs content: process it
    eng._last_out = np.zeros_like(content)  # pretend it was served
    assert eng._maybe_skip(black.copy()) is True  # black vs black: skip


# -- ISSUE 9: device-resident frame path -------------------------------------
# One module-scoped engine serves all three tests (tier-1 budget: each
# build pays the tiny-model compile; prepare() between tests is cheap)


@pytest.fixture(scope="module")
def devpath_engine():
    eng, cfg = _engine()
    eng.prepare("device path", seed=1)
    eng(_frames(1)[0])  # compile once here, not inside a patched test
    return eng


def test_submit_stages_h2d_outside_submit_lock(devpath_engine, monkeypatch):
    """The H2D staging (stage_frame) must run BEFORE the submit lock is
    taken: a large-frame device_put under the lock serializes concurrent
    sessions' dispatches on a copy.  The fake device_put asserts the lock
    is free at transfer time — if staging ever moves back inside the lock
    this trips single-threaded, no timing involved."""
    eng = devpath_engine
    real_put = jax.device_put
    seen = {"n": 0, "locked": []}

    def fake_put(x, *a, **k):
        seen["n"] += 1
        seen["locked"].append(eng._submit_lock.locked())
        return real_put(x, *a, **k)

    monkeypatch.setattr(jax, "device_put", fake_put)
    out = eng.fetch(eng.submit(_frames(1)[0]))
    assert out.shape == (64, 64, 3)
    assert seen["n"] >= 1
    assert not any(seen["locked"]), (
        "device_put ran while the submit lock was held"
    )


def test_concurrent_submits_overlap_h2d_staging(devpath_engine, monkeypatch):
    """Regression for the serialized-transfer bug with a deliberately slow
    fake device_put: BOTH threads must be inside the transfer at once
    (each blocks until the other arrives).  With staging under the submit
    lock, thread B cannot enter device_put until A's whole step finishes
    — A would hold the barrier forever and it breaks."""
    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    eng = devpath_engine
    real_put = jax.device_put
    barrier = _threading.Barrier(2, timeout=15)
    results = {"broken": 0}

    def slow_put(x, *a, **k):
        try:
            barrier.wait()  # "slow": returns only when BOTH transfers run
        except _threading.BrokenBarrierError:
            results["broken"] += 1
        return real_put(x, *a, **k)

    monkeypatch.setattr(jax, "device_put", slow_put)
    fs = _frames(2, seed=9)
    with ThreadPoolExecutor(max_workers=2) as pool:
        f1 = pool.submit(lambda: eng.fetch(eng.submit(fs[0])))
        f2 = pool.submit(lambda: eng.fetch(eng.submit(fs[1])))
        o1, o2 = f1.result(timeout=60), f2.result(timeout=60)
    assert o1.shape == o2.shape == (64, 64, 3)
    assert results["broken"] == 0, (
        "concurrent submits serialized their H2D staging"
    )


def test_step_donates_state_no_defensive_copy(devpath_engine):
    """The donation audit (ISSUE 9): the jitted step really consumes the
    state pytree in place — the pre-step buffers are deleted, not kept
    alive by a hidden defensive copy (the HBM-residency property the
    whole ring-buffer design assumes)."""
    eng = devpath_engine
    before = jax.tree.leaves(eng.state)
    eng(_frames(1)[0])
    deleted = [leaf.is_deleted() for leaf in before]
    assert all(deleted), f"{sum(deleted)}/{len(deleted)} leaves donated"
