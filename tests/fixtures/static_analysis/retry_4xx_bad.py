"""Known-bad fixture: ROADMAP open item 3, reproduced verbatim in shape.

This is server/worker.py default_publish as it shipped before the fix:
``urlopen`` raises HTTPError (a URLError subclass) BEFORE the status
check, so ``retry_on=(URLError, OSError)`` re-POSTs a permanent 404
until the attempt budget burns out."""

import json
import urllib.error
import urllib.request

from ai_rtc_agent_tpu.resilience.retry import transient_policy


def shipped_default_publish(url: str, info: dict) -> bool:
    req = urllib.request.Request(url, data=json.dumps(info).encode())

    def post():
        with urllib.request.urlopen(req, timeout=5) as r:
            if not 200 <= r.status < 300:
                raise OSError(f"publish returned {r.status}")
        return True

    return transient_policy(attempts=3).run(
        post,
        retry_on=(urllib.error.URLError, OSError),  # BAD: catches 4xx too
        default=False,
        label="worker publish",
    )
