"""Known-bad fixture: ROADMAP open item 2, reproduced verbatim in shape.

This is stream/pipeline.py restart() as it shipped before the fix: a
fault-recovery re-prepare that passes the module-level defaults,
silently reverting every runtime /config guidance/delta update the
moment the engine heals."""

DEFAULT_GUIDANCE_SCALE = 1.2
DEFAULT_DELTA = 1.0


class ShippedPipeline:
    def __init__(self, engine, prompt, seed):
        self.engine = engine
        self.prompt = prompt
        self._seed = seed

    def restart(self):
        self.engine.prepare(
            prompt=self.prompt,
            guidance_scale=DEFAULT_GUIDANCE_SCALE,  # BAD: reverts /config
            delta=DEFAULT_DELTA,  # BAD: reverts /config
            seed=self._seed,
        )
