"""Fixture for suppression mechanics: one properly allowed finding, one
reasonless allow, one unused allow."""

import time


async def allowed_with_reason():
    # tpurtc: allow[async-blocking] -- fixture: demonstrates a reasoned allow
    time.sleep(0.001)


async def allowed_without_reason():
    time.sleep(0.002)  # tpurtc: allow[async-blocking]


def nothing_to_allow():
    # tpurtc: allow[pooled-view] -- stale: nothing here is flagged anymore
    return 1
