"""Known-bad fixture for the reservation-pairing checker.

``gate_leak_except_path`` is the PR 4 ``_end_supervision`` leak shape:
a counted admission taken, then an error path that returns without ever
releasing it — the box's session budget shrinks by one forever.
``gate_leak_refusal_without_release`` is the PR 15 shape: gate, fail a
later step, refuse — without ``_release_admission``.  Every ``ok_*``
spelling (release on all paths, park into app state, closure handoff,
return-of-key, ``*_locked`` convention) must stay clean.
"""

from aiohttp import web  # fixture: parsed, never imported


async def gate_leak_except_path(app, request, make_pc):
    # the PR 4 shape
    stream_id = "s"
    rejected = _admission_gate(app, stream_id)
    if rejected is not None:
        return rejected
    try:
        pc = make_pc(request)
    except ValueError:
        # BAD: error path returns without _release_admission
        return web.Response(status=400, text="bad sdp")
    register_session(app, stream_id, pc)
    return web.Response(text="ok")


async def gate_leak_refusal_without_release(app, request):
    # the PR 15 shape: the refusal return does NOT discharge a keyed
    # gate — only _release_admission does
    stream_id = "s"
    rejected = _admission_gate(app, stream_id)
    if rejected is not None:
        return rejected
    pipeline, release_pipeline = await _claim_pipeline(app)
    if pipeline is None:
        # BAD: admission still counted while we turn the client away
        return _overloaded_response(app, "slots full")
    release_pipeline()
    _release_admission(app, stream_id)
    return web.Response(text="ok")


async def claim_leak_on_error(app, request, negotiate):
    pipeline, release_pipeline = await _claim_pipeline(app)
    if pipeline is None:
        return _overloaded_response(app, "slots full")  # ok: held nothing
    if not negotiate(request):
        # BAD: engine slot held forever
        return web.Response(status=400, text="bad offer")
    release_pipeline()
    return web.Response(text="ok")


async def gate_leak_raise_path(app, payload):
    token = "rcy-1"
    rejected = _admission_gate(app, token)
    if rejected is not None:
        return rejected
    if not payload:
        # BAD: raises straight out, gate still counted
        raise ValueError("bad payload")
    _release_admission(app, token)
    return web.Response(text="ok")


async def ok_released_everywhere(app, request, make_pc):
    stream_id = "s"
    rejected = _admission_gate(app, stream_id)
    if rejected is not None:
        return rejected
    try:
        pc = make_pc(request)
    except ValueError:
        _release_admission(app, stream_id)
        return web.Response(status=400, text="bad sdp")
    except BaseException:
        _release_admission(app, stream_id)
        raise
    register_session(app, stream_id, pc)
    return web.Response(text="ok")


async def ok_parked_into_app_state(app, snap):
    token = "mig-1"
    rejected = _admission_gate(app, token)
    if rejected is not None:
        return rejected
    # the reservation now lives in app state (the import park): a later
    # adopt or expiry sweep owns it
    app["imported"][token] = {"snap": snap}
    return web.Response(text="ok")


async def ok_closure_handoff(app, request, pc):
    stream_id = "s"
    rejected = _admission_gate(app, stream_id)
    if rejected is not None:
        return rejected

    def on_track(track):
        # the aiortc event handler consumes the reservation long after
        # this request handler returned
        register_session(app, stream_id, track)

    pc.on("track", on_track)
    return web.Response(text="ok")


async def ok_finally_release(app, key, work):
    rejected = _admission_gate(app, key)
    if rejected is not None:
        return rejected
    try:
        await work()
    finally:
        _release_admission(app, key)
    return web.Response(text="ok")


def _sweep_locked(app, token):
    # *_locked: the caller holds the pairing discipline
    rejected = _admission_gate(app, token)
    return rejected
