"""Known-bad fixture: /metrics names that violate grammar or collide."""


def record(stats, label):
    stats.count("TX-Packets")  # BAD: not snake_case
    stats.gauge("srtp_handshakes")  # BAD: kind conflict with the counter
    stats.count("srtp_handshakes")
    stats.gauge("rx_bursts_total")  # BAD: collides with counter's _total
    stats.count("rx_bursts")
    stats.count(label)  # BAD: dynamic name
    stats.gauge("rr_jitter_ms")  # fine
