"""Known-bad fixture for the http-contract checker.

``_PASS_HEADERS`` is the drift shape this checker exists for: the fleet
router shipped its OWN copy of the agent's response-header names, and a
header the copy didn't know about was silently dropped at the proxy.
Plus: an undocumented route registration, client calls targeting paths
the docs/http-api.md registry doesn't know, and raw header literals
where server/wire.py constants are required.  Every ``ok_*`` spelling
must stay clean.
"""

from aiohttp import web  # fixture: parsed, never imported

from ai_rtc_agent_tpu.server import wire

# BAD twice over: a local copy of the pass-through set, carrying one
# raw wire literal (X-Stream-Id -> use wire.STREAM_ID) and one header
# wire.py has never heard of
_PASS_HEADERS = ("Content-Type", "X-Stream-Id", "X-Edge-Hint")


def build_bad_app(handler):
    app = web.Application()
    app.router.add_post("/not/in/registry", handler)  # BAD: undocumented
    app.router.add_get("/capacity", handler)  # ok: documented
    return app


async def bad_clients(http, base):
    await http.post(base + "/offerz")  # BAD: typo'd path, 404s live
    resp = await http.get("http://127.0.0.1:8080/capacityz")  # BAD
    return resp


def bad_headers(request, resp):
    jid = request.headers.get("X-Journey-Id")  # BAD: wire.JOURNEY_ID
    resp.headers["X-Edge-Hint"] = "1"  # BAD: unregistered X- header
    return web.Response(headers={"X-Edge-Hint": jid or ""})  # BAD


async def ok_clients(http, base, session):
    await http.post(base + "/offer")
    await http.get(base + "/capacity")
    await http.delete(f"{base}/whip/{session}")  # dynamic tail: skipped
    return await http.get("http://127.0.0.1:8080/health")


def ok_headers(request, out_headers, jmeta):
    jid = request.headers.get(wire.JOURNEY_ID)
    out_headers[wire.STREAM_ID] = jmeta["stream_id"]
    ct = request.headers.get("Content-Type")  # universal: free
    return jid, ct
