"""Known-bad fixture for the refusal-discipline checker.

``whep_refusal_bad`` is the pre-fix server/agent.py whep edge-refusal
VERBATIM — a bare 503 with no Retry-After, built inline instead of
through ``_overloaded_response``: the exact shipped bug the checker
exists to make unshippable.  The vocab functions exercise the closed
EVENT_NAMES / STATE_NAMES webhook enums.  Every ``ok_*`` spelling must
stay clean.
"""

from aiohttp import web  # fixture: parsed, never imported


async def whep_refusal_bad(request, app):
    # the shipped shape: ad-hoc 503, Retry-After forgotten
    if app.get("broadcast") is None:
        return web.Response(
            status=503, text="edge stream requires the broadcast plane"
        )
    return web.Response(text="ok")


def _overloaded_response(app, text="overloaded", retry_after=None):
    # the blessed helper itself forgetting the header is ALSO a finding
    return web.Response(status=503, text=text)


async def adhoc_with_header_still_bad(request):
    # carrying Retry-After does not excuse bypassing the helper: one
    # constructor per plane, or drift returns
    return web.Response(
        status=503, text="busy", headers={"Retry-After": "2"}
    )


def aiohttp_exc_bad():
    raise web.HTTPServiceUnavailable(text="nope")


def bad_event(handler, stream_id, room_id):
    handler.send_request("StreamExploded", stream_id, room_id)


def bad_state_kwarg(ev_cls):
    return ev_cls(state="TOTALLY_BROKEN")


def bad_state_positional(handler, stream_id, room_id):
    handler.handle_session_state(stream_id, room_id, "KINDA_BAD", "x")


def bad_state_compare(rec):
    if rec.state == "ZOMBIE":
        return True
    return rec.state in ("HEALTHY", "UNDEAD")


def bad_state_dict(reason):
    return {"state": "WAT_BROKE", "reason": reason}


def bad_state_assign(rec):
    rec.state = "EXTREMELY_DEAD"


def _refuse_503(text, retry_after):
    # the router-plane helper done right: 503 + Retry-After, in-helper
    return web.Response(
        status=503, text=text, headers={"Retry-After": str(retry_after)}
    )


def ok_vocab(handler, stream_id, room_id, rec):
    handler.send_request("StreamMigrated", stream_id, room_id)
    handler.handle_session_state(stream_id, room_id, "DEGRADED", "slo")
    rec.state = "DRAINING"
    if rec.state in ("HEALTHY", "FAILED"):
        return {"state": "RECOVERING"}
    return None


def ok_non_state_screaming(flag):
    # SCREAMING literals OUTSIDE state contexts are free — env knob
    # names, modes, log levels
    mode = "DEBUG" if flag else "RELEASE"
    return mode
