"""Known-bad fixture: unbounded queues on the frame path.

Every shape the bounded-queue checker must catch (bare ctor, explicit
unbounded spellings, from-import aliases) plus the good spellings that
must stay clean (finite literals, computed bounds, stdlib queue.Queue)."""

import asyncio
import collections
import collections as colls
import queue
from asyncio import Queue
from asyncio import Queue as RenamedQ
from collections import deque
from collections import deque as renamed_dq


class BadBuffers:
    def __init__(self, bound):
        self.q1 = asyncio.Queue()  # BAD: no maxsize
        self.q2 = asyncio.Queue(maxsize=0)  # BAD: 0 = unbounded spelling
        self.q3 = Queue()  # BAD: from-import alias, no maxsize
        self.q4 = RenamedQ()  # BAD: renamed from-import, no maxsize
        self.d1 = collections.deque()  # BAD: no maxlen
        self.d2 = deque(maxlen=None)  # BAD: None = unbounded spelling
        self.d3 = deque([1, 2, 3])  # BAD: iterable but no maxlen
        self.d4 = renamed_dq()  # BAD: renamed from-import, no maxlen
        self.d5 = colls.deque()  # BAD: module alias, no maxlen

        # good spellings — must stay clean
        self.ok1 = asyncio.Queue(maxsize=16)
        self.ok2 = asyncio.Queue(8)
        self.ok3 = deque(maxlen=4)
        self.ok4 = collections.deque([1], 4)
        self.ok5 = deque(maxlen=bound)  # computed bound is still a bound
        self.ok6 = queue.Queue()  # thread control queue: out of scope
        self.ok7 = RenamedQ(maxsize=16)  # renamed but bounded
        self.ok8 = colls.deque([1], 4)  # module alias but bounded
