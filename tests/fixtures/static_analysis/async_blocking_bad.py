"""Known-bad fixture: every async-blocking pattern the checker covers.

Parsed by tests/test_static_analysis.py, never imported or executed —
this is what the reference actually shipped (blocking requests.post on
the event loop, SURVEY.md section 5)."""

import subprocess
import time
import urllib.request


async def wedge_the_loop(sock, state_lock):
    time.sleep(0.5)  # BAD: parks every session in the process
    urllib.request.urlopen("http://orchestrator/health")  # BAD
    pkt = sock.recvfrom(2048)  # BAD: raw socket on the loop
    subprocess.run(["ffprobe", "x.h264"])  # BAD
    state_lock.acquire()  # BAD: no timeout
    with open("dump.bin") as f:
        payload = f.read()  # BAD: unbounded read
    return pkt, payload


async def fine_patterns(sock, state_lock):
    # the non-blocking spellings are NOT flagged
    state_lock.acquire(timeout=0.1)
    payload = b""

    def worker():  # nested sync def: runs via to_thread, blocking is fine
        time.sleep(0.5)
        return urllib.request.urlopen("http://x").read()

    return worker, payload
