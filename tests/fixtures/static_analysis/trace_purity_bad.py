"""Known-bad fixture: host state read inside traced functions — the
value is frozen at trace time and the knob silently stops working."""

import os
import time
from functools import partial

import jax
import numpy as np

from ai_rtc_agent_tpu.utils import env


def step(x):
    scale = env.get_float("GUIDANCE_HACK", 1.0)  # BAD: frozen at trace
    t0 = time.perf_counter()  # BAD: host clock
    noise = np.random.normal(size=(4,))  # BAD: host RNG
    return x * scale, t0, noise


jitted_step = jax.jit(step)


@partial(jax.jit, donate_argnums=(0,))
def decorated_step(x):
    return x * float(os.environ["SCALE"])  # BAD: env subscript read


def make_step(cfg):
    def inner(x):
        return x + _helper(x)

    return inner


def _helper(x):
    time.sleep(0.001)  # BAD: reached transitively from the traced inner
    return x


compiled = jax.jit(make_step(None))


def pure_step(x):
    k = jax.random.PRNGKey(0)  # fine: jax RNG is trace-pure
    return x + jax.random.normal(k, x.shape)


pure = jax.jit(pure_step)
