"""Known-bad fixture: env knobs that bypass the registry contract."""

from ai_rtc_agent_tpu.utils import env

KNOB = "PICKED_AT_RUNTIME"


def read_config():
    secret = env.get_str("TOTALLY_UNDOCUMENTED_KNOB")  # BAD: not in docs
    dyn = env.get_int(KNOB, 0)  # BAD: dynamic name defeats the registry
    return secret, dyn
