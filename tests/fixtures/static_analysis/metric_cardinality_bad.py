"""Known-bad fixture for the metric-cardinality checker: label values
carrying per-session / per-frame identities (unbounded series growth).
Mirrors the tempting-but-wrong way to export the per-queue overload
snapshot as labeled Prometheus series."""


def labeled(name, labels, value):  # the promexport-style helper shape
    return f"{name}{labels} {value}"


def export_queues(queues):
    out = []
    for qname, q in queues.items():
        # BAD: queue names embed session keys ("ingest:<session>")
        out.append(labeled("queue_depth", {"queue": qname}, q.depth))
    return out


def export_frame(frame, session_id):
    # BAD: per-session and per-frame identities as label values
    lines = [labeled("frame_latency_ms", {"session": session_id}, 1.0)]
    lines.append(
        labeled("frame_done", {"frame": str(frame.frame_id)}, 1)
    )
    return lines


def export_dynamic(samples):
    # BAD: label set built elsewhere — cardinality unreadable at the site
    for labels, v in samples:
        yield labeled("sample", labels, v)
