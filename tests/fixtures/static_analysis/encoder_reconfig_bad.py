"""Known-bad fixture for the encoder-reconfig checker: direct native rate
calls and rate-carrying encoder construction outside media/codec.py.
Every line marked # BAD must be flagged; the ok_* spellings stay clean."""

from ai_rtc_agent_tpu.media.codec import H264Encoder
from ai_rtc_agent_tpu.media.codec import H264Encoder as RenamedEncoder


class BadSink:
    def __init__(self, lib, enc):
        self._lib = lib
        self._enc = enc

    def set_bitrate_native(self, bps):
        self._lib.tr_h264_encoder_destroy(self._enc)  # BAD tr-call
        self._enc = self._lib.tr_h264_encoder_create(  # BAD tr-call
            64, 64, 30, 1, bps, 60, b"ultrafast", b"zerolatency"
        )

    def force_native(self):
        self._lib.tr_h264_force_keyframe(self._enc)  # BAD tr-call

    def throttle_kw(self):
        return H264Encoder(64, 64, bitrate=500_000)  # BAD rate-ctor kw

    def throttle_gop(self):
        return H264Encoder(64, 64, 30, None, 30)  # BAD rate-ctor positional

    def throttle_renamed(self):
        return RenamedEncoder(64, 64, gop=12)  # BAD rate-ctor renamed

    def ok_rateless_ctor(self):
        # geometry is the caller's to choose; rate targets are not
        return H264Encoder(64, 64, 30)

    def ok_blessed_path(self, enc):
        enc.reconfigure(bitrate=250_000, gop=30)
        enc.force_keyframe()

    def ok_unrelated_call(self, other):
        other.tr_something_else(1)
