"""Known-bad fixture: pool-returned views escaping frame scope.

``chaos_send`` reproduces the PR 2 chaos-TX bug byte-for-byte in shape:
pooled packetizer views handed to the fault injector, which holds
packets across calls when a reorder fault is active (the fix —
media/rtp_client.py — stabilizes with bytes() first)."""


class BadHolder:
    def __init__(self, packetizer, ring, pool, loop, tx_faults):
        self._pkt = packetizer
        self._ring = ring
        self._pool = pool
        self._loop = loop
        self._tx_faults = tx_faults
        self._cache = []
        self.last_frame = None

    def chaos_send(self, au, ts):
        pkts = self._pkt.packetize(au, ts)
        for pkt in pkts:
            self._tx_faults.apply(pkt)  # BAD: injector holds across calls

    def store_frame(self):
        frame, meta = self._ring.pop()
        self.last_frame = frame  # BAD: outlives the pop pool rotation
        return meta

    def queue_packets(self, au, ts):
        for pkt in self._pkt.packetize(au, ts):
            self._cache.append(pkt)  # BAD: retransmit cache must copy
        buf, arr, mv = self._pool.acquire(1500)
        self._loop.call_later(0.02, self._flush, mv)  # BAD: deferred use

    def _flush(self, pkt):
        pass

    def good_send(self, au, ts):
        pkts = self._pkt.packetize(au, ts)
        for pkt in pkts:
            pkt = bytes(pkt)  # stabilized: taint cleared
            self._tx_faults.apply(pkt)
            self._cache.append(pkt)
