"""Known-bad fixture for the span-pairing checker (analysis/span_pairing.py).

Every function marked BAD leaves a trace span open on some path (or closes
one that was never opened); every function marked ok is a correct spelling
that must stay clean — the precision half of the contract.
"""


def bad_early_return(trace, frame):  # BAD: return before end
    trace.begin("submit")
    if frame is None:
        return None  # "submit" still open here
    trace.end("submit")
    return frame


def bad_branch_only_begin(trace, flag):  # BAD: begin in one branch only
    if flag:
        trace.begin("encode")
    do_work()
    return 1  # open iff flag — flagged at the return


def bad_raise_path(trace, data):  # BAD: raise skips the end
    trace.begin("packetize")
    if not data:
        raise ValueError("no data")  # "packetize" open
    trace.end()
    return data


def bad_never_closed(trace):  # BAD: fall-through with an open span
    trace.begin("send")
    do_work()


def bad_unbalanced_end(trace):  # BAD: end with nothing open
    trace.end("decode")


def bad_wrong_name(trace):  # BAD: end closes a name never begun
    trace.begin("encode")
    trace.end("decode")  # "decode" not open
    trace.end("encode")


def bad_handler_swallow(trace):  # BAD: raise mid-try leaks via the handler
    try:
        trace.begin("submit")
        do_work()  # may raise with "submit" open
        trace.end("submit")
    except Exception:
        return None  # entered between begin and end: "submit" still open


def bad_with_begin(trace):  # BAD: begin() returns None — crashes as a ctx mgr
    with trace.begin("encode"):
        do_work()


def ok_linear(trace):
    trace.begin("submit")
    do_work()
    trace.end("submit")


def ok_try_finally(trace, frame):
    trace.begin("engine_step")
    try:
        if frame is None:
            return None  # finally still closes the span
        return do_work()
    finally:
        trace.end("engine_step")


def ok_context_manager(trace):
    with trace.span("encode"):
        do_work()
    return 1


def ok_both_branches(trace, flag):
    trace.begin("fetch")
    if flag:
        trace.end("fetch")
    else:
        trace.end()
    return flag


def ok_bare_end_stack(trace):
    trace.begin("outer")
    trace.begin("inner")
    trace.end()  # inner
    trace.end()  # outer


def ok_not_a_trace(queue):
    # receivers without "trace" in the name are out of scope — a DB
    # transaction's begin() must not be mistaken for a span
    queue.begin("txn")
    return queue


def do_work():
    return 0
