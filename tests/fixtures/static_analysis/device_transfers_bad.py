"""Known-bad fixture for the device-transfer checker.

``BadScheduler._drain_batch`` reproduces the shape of PR 9's pre-fix
dispatcher: the ENTIRE stacked bucket output drained to host with one
``np.asarray`` and sliced per session afterwards — every fetch billed
all the others.  Every line marked # BAD must be flagged; the ok_*
spellings stay clean."""

import jax
import numpy as np


class BadScheduler:
    def _drain_batch(self, entries, k, variant, frames, idx):
        # the pre-fix whole-batch drain (old scheduler.py:1062): step,
        # then ONE host copy of the stacked [S, ...] output
        self.states, out = self._bucket_step(k, variant)(
            self.params, self.states, frames, idx
        )
        host = np.asarray(out)  # BAD batch-drain
        for i, (s, p) in enumerate(entries):
            p.future.set_result(host[i])

    def _drain_subscript(self, frames, idx):
        out = self._step(self.params, self.states, frames)
        return np.asarray(out[0])  # BAD batch-drain (subscript of tainted)

    def _drain_via_alias(self, frames):
        fn = self._step_cached
        self.states, out = fn(self.params, self.states, frames)
        return np.array(out)  # BAD batch-drain (aliased step callable)

    def _stage(self, frame):
        return jax.device_put(frame)  # BAD stray-h2d (bare staging form)

    def _pull(self, out):
        out.copy_to_host_async()  # BAD stray-async-d2h
        return jax.device_get(out)  # BAD stray-d2h

    def _drain_sharded_assembly(self, shards, sharding, entries):
        # the ISSUE 12 sharded spelling of the same bug: np.asarray of a
        # mesh-sharded global array is a CROSS-SHARD gather + host drain
        # — one session's fetch pulls every shard's bytes through host
        frames = jax.make_array_from_single_device_arrays(
            (8, 64, 64, 3), sharding, shards
        )
        host = np.asarray(frames)  # BAD batch-drain (cross-shard gather)
        for i, (s, p) in enumerate(entries):
            p.future.set_result(host[i])

    # -- clean spellings ------------------------------------------------------

    def ok_host_asarray(self, frame_u8):
        # host pixels (the similarity-filter idiom): never tainted
        return np.asarray(frame_u8)[..., ::16, ::16, :]

    def ok_sharded_placement(self, params, shardings):
        # explicit placement is mesh layout, not frame staging
        return jax.device_put(params, shardings)

    def ok_retaint_cleared(self, frames):
        out = self._step(self.params, self.states, frames)
        out = frames  # reassignment clears the taint
        return np.asarray(out)

    def ok_blessed_helper(self, frame, stage_frame):
        # routing through the blessed helper is the whole point
        return stage_frame(frame)
