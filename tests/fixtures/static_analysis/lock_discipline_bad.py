"""Known-bad fixture for the lock-discipline checker.

``BadSharedEngine`` reproduces the shape of the PR 5 shipped bug:
sessions share ONE engine, and ``submit`` wrote a flag lock-free at the
top while also writing it (and the skip counter) under ``_submit_lock``
further down — a concurrent session's ``to_thread`` hop read the other
session's write (the shipped fix made the flag thread-local).  The
checker's signal is MIXED DISCIPLINE: the guarded write declares the
attribute shared, so every lock-free write elsewhere in the class is a
race half-fixed.

``OkEngine`` pins the clean spellings: all writes guarded, ``__init__``
construction writes, the ``*_locked`` caller-holds-the-lock suffix
idiom, and a reasoned suppression for a proven single-thread phase.
"""

import threading


class BadSharedEngine:
    def __init__(self):
        self._submit_lock = threading.Lock()
        self.last_submit_was_skip = False
        self._skip_count = 0

    def submit(self, frame):
        self.last_submit_was_skip = False  # BAD: lock-free write
        with self._submit_lock:
            if self._similar(frame):
                self.last_submit_was_skip = True  # guarded: mixed!
                self._skip_count += 1
                return None
            self._skip_count = 0
            return frame

    def reset(self):
        self._skip_count = 0  # BAD: lock-free write elsewhere

    def _similar(self, frame):
        return False


class OkEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self._tick = 0  # ok: construction happens before sharing
        self._mode = "full"

    def submit(self, frame):
        with self._lock:
            self._tick += 1  # ok: guarded
            return self._advance_locked(frame)

    def _advance_locked(self, frame):
        self._mode = "cached"  # ok: *_locked = caller holds the lock
        return frame

    def set_mode(self, mode):
        with self._lock:
            self._mode = mode  # ok: guarded

    def prepare(self):
        # ok only with the proof attached: reasoned suppression
        self._tick = 0  # tpurtc: allow[lock-discipline] -- prepare() runs before worker threads exist
