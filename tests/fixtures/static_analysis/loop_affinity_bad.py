"""Known-bad fixture for the loop-affinity checker.

``BadSinkActuation`` reproduces the shape of the PR 6 shipped bug: the
network-adaptation tick actuated ``sink.reconfigure()`` ON the event
loop, and reconfigure takes ``_enc_lock`` — the lock a codec worker
holds across whole encodes — so one rung move stalled every session
sharing the loop.  The fix pushed actuation to ``run_in_executor``
(``OkSinkActuation``).  ``BadDispatcher`` is the thread side: a
dispatcher thread touching loop-bound asyncio objects directly
(``put_nowait`` on an asyncio.Queue, ``set_result`` on a
``create_future`` future, ``set`` on an asyncio.Event, ``call_later``)
instead of crossing via ``call_soon_threadsafe`` /
``run_coroutine_threadsafe`` — the ok_* spellings.  Thread-safe
primitives (``queue.Queue``, ``threading.Event``,
``concurrent.futures.Future``) stay clean by construction.
"""

import asyncio
import queue
import threading
from asyncio import Event as AEvent, Queue as AQueue
from concurrent.futures import Future


class BadDispatcher:
    def __init__(self, loop):
        self._loop = loop
        self._frames: asyncio.Queue = asyncio.Queue(maxsize=8)
        self._ready = asyncio.Event()
        self._handoff: queue.Queue = queue.Queue(maxsize=8)
        self._done = threading.Event()
        self._thread = None

    async def arm(self):
        self._waiter = asyncio.get_running_loop().create_future()

    def start(self):
        self._thread = threading.Thread(target=self._drive, daemon=True)
        self._thread.start()

    def _drive(self):
        while True:
            item = self._step()
            self._frames.put_nowait(item)  # BAD: asyncio queue off-loop
            self._waiter.set_result(item)  # BAD: asyncio future off-loop
            self._ready.set()  # BAD: asyncio event off-loop
            self._loop.call_later(0.1, self._tick)  # BAD: loop-only API
            self._loop.create_task(self._notify())  # BAD: loop-only API

    def _step(self):
        return None

    def _tick(self):
        pass

    async def _notify(self):
        pass


class OkDispatcher:
    def __init__(self, loop):
        self._loop = loop
        self._frames: asyncio.Queue = asyncio.Queue(maxsize=8)
        self._ready = asyncio.Event()
        self._handoff: queue.Queue = queue.Queue(maxsize=8)
        self._done = threading.Event()
        self._row_fut: Future = Future()

    def start(self):
        self._thread = threading.Thread(target=self._drive, daemon=True)
        self._thread.start()

    def stop(self):
        self._thread.join()

    def _drive(self):
        item = self._step()
        # ok: the threadsafe crossings
        self._loop.call_soon_threadsafe(self._frames.put_nowait, item)
        self._loop.call_soon_threadsafe(self._ready.set)
        asyncio.run_coroutine_threadsafe(self._notify(), self._loop)
        # ok: thread-safe primitives are THE handoff tier
        self._handoff.put_nowait(item)
        self._done.set()
        self._row_fut.set_result(item)

    def _step(self):
        return None

    async def _notify(self):
        pass


class BadAliasDispatcher:
    """Renamed imports cannot smuggle an asyncio object past the taint:
    ``from asyncio import Queue as AQueue`` resolves to the same
    canonical origin (the bounded-queue alias discipline)."""

    def __init__(self):
        self._frames = AQueue(maxsize=8)
        self._ready = AEvent()

    def start(self):
        self._thread = threading.Thread(target=self._drive, daemon=True)
        self._thread.start()

    def _drive(self):
        item = self._step()
        self._frames.put_nowait(item)  # BAD: alias-imported asyncio queue
        self._ready.set()  # BAD: alias-imported asyncio event

    def _step(self):
        return None


class BadSinkActuation:
    """The PR 6 _enc_lock-on-the-loop incident, in shape."""

    def __init__(self):
        self._enc_lock = threading.Lock()

    async def apply_profile(self, profile):
        with self._enc_lock:  # BAD: threading lock on the event loop
            self._set_rate(profile)

    async def apply_profile_worse(self, profile):
        with self._enc_lock:  # BAD: and held ACROSS an await
            await self._push_config(profile)

    def _set_rate(self, profile):
        pass

    async def _push_config(self, profile):
        pass


class BadResultWait:
    async def fetch(self, pool, coro, loop):
        handle = pool.submit(self._work)
        out = handle.result()  # BAD: blocks the loop on a worker
        fut = asyncio.run_coroutine_threadsafe(coro, loop)
        val = fut.result()  # BAD: the canonical hybrid deadlock
        direct = asyncio.run_coroutine_threadsafe(coro, loop).result()  # BAD
        return out, val, direct

    def _work(self):
        pass


class OkSinkActuation:
    def __init__(self):
        self._enc_lock = threading.Lock()

    async def apply_profile(self, profile):
        loop = asyncio.get_running_loop()
        # ok: the lock is taken on a worker, off the loop
        await loop.run_in_executor(None, self._actuate, profile)

    async def await_cross_thread(self, pool):
        loop = asyncio.get_running_loop()
        handle = loop.run_in_executor(None, self._work)
        return await handle  # ok: awaited, never .result()

    def _actuate(self, profile):
        with self._enc_lock:  # ok: sync executor-side code may lock
            self._work()

    def _work(self):
        pass
