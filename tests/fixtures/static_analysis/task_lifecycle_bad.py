"""Known-bad fixture for the task-lifecycle checker.

``BadInlineBatch.submit`` reproduces the shape of the PR 9 shipped bug:
the inline fast path resolved a pending future selected by SLOT ORDER
(pop the oldest) and assumed it was the submitter's own — when it was
not, the future the caller actually awaited was abandoned unresolved and
the fetch hung for the full 120 s timeout.  The fixed spelling
identifies the submitter's entry by PENDING IDENTITY and resolves a
future on every path (``ok_submit``).

The orphan-task shapes are the PR 13 review class ("_on_cleanup cancels
pending pulls"): a spawn whose result is discarded, a bound task that an
early return abandons before the registry add, a task attribute no
method of the class ever cancels, and a rebind that drops a still-unowned
task.  The ok_* spellings are the repo's real disciplines: registry add
+ done-callback (server/events.py), self._task with cancel in stop()
(every tick loop), await/return/gather handoffs.
"""

import asyncio
from concurrent.futures import Future


class BadSpawner:
    def kick(self):
        asyncio.ensure_future(self._pull())  # BAD: discarded task

    def kick_on_loop(self, loop):
        loop.create_task(self._pull())  # BAD: discarded task

    def kick_conditional(self):
        self._started or asyncio.ensure_future(self._pull())  # BAD

    def kick_ternary(self, fast):
        asyncio.ensure_future(self._pull()) if fast else None  # BAD

    def kick_comprehension(self, coros):
        [asyncio.ensure_future(c) for c in coros]  # BAD: list discarded

    def pull_fast_path(self, fast):
        t = asyncio.create_task(self._pull())
        if fast:
            return None  # BAD: t orphaned on the early-return path
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        return t

    def double_kick(self):
        t = asyncio.create_task(self._pull())
        t = asyncio.create_task(self._pull())  # BAD: first t still unowned
        self._tasks.add(t)

    def start(self):
        # BAD: no method of BadSpawner ever cancels/awaits _poll_task
        self._poll_task = asyncio.create_task(self._poll())

    async def _pull(self):
        pass

    async def _poll(self):
        pass


class BadInlineBatch:
    """The PR 9 inline-batch hang, in shape: resolve-by-slot-order."""

    def submit(self, frame):
        fut = Future()
        if self._batch_ready():
            # inline fast path: the submit that completes the batch
            # dispatches it and resolves the slot's OLDEST pending entry,
            # ASSUMING it was this submitter's own — the future the
            # caller will actually block on is dropped unresolved
            self._resolve_oldest(self._step(frame))
            return self._last_out  # BAD: fut never resolved/enqueued
        self._enqueue(frame, fut)
        return fut

    def _batch_ready(self):
        return True

    def _resolve_oldest(self, out):
        pass

    def _step(self, frame):
        return frame

    def _enqueue(self, frame, fut):
        pass


class OkSpawner:
    def __init__(self):
        self._tasks: set = set()
        self._task = None

    def kick(self):
        task = asyncio.ensure_future(self._pull())
        self._tasks.add(task)  # ok: registry owns it
        task.add_done_callback(self._tasks.discard)

    def start(self):
        self._task = asyncio.create_task(self._poll())

    def stop(self):
        if self._task is not None:
            self._task.cancel()  # ok: the class owns its loop

    async def run_once(self):
        await asyncio.create_task(self._pull())  # ok: awaited

    def handoff(self):
        return asyncio.create_task(self._pull())  # ok: caller owns it

    async def fan_out(self):
        a = asyncio.create_task(self._pull())
        b = asyncio.create_task(self._pull())
        await asyncio.gather(a, b)  # ok: both escape into gather

    async def _pull(self):
        pass

    async def _poll(self):
        pass


class OkGroup:
    """Structured concurrency: a TaskGroup owns, awaits and cancels its
    children — ``tg.create_task`` is never a source."""

    async def run(self):
        async with asyncio.TaskGroup() as tg:
            tg.create_task(self._pull())  # ok: the group owns it
            last = tg.create_task(self._pull())  # ok: same, bound or not
        return last

    async def _pull(self):
        pass


class OkInlineBatch:
    """Pending-identity discipline: a future is resolved or handed off on
    EVERY path."""

    def submit(self, frame):
        fut = Future()
        if self._batch_ready():
            out = self._step(frame)
            entry = self._pop_pending()
            if entry is not None and entry.fut is not fut:
                entry.fut.set_result(out)  # the rider's own future
            fut.set_result(out)  # ok: the submitter's future resolves too
            return fut
        self._enqueue(frame, fut)  # ok: escapes into the pending queue
        return fut

    def cancel_all(self, exc):
        fut = Future()
        try:
            self._enqueue(None, fut)
        except RuntimeError:
            fut.set_exception(exc)  # ok: resolved on the failure path
        return fut

    def _batch_ready(self):
        return False

    def _pop_pending(self):
        return None

    def _step(self, frame):
        return frame

    def _enqueue(self, frame, fut):
        pass
