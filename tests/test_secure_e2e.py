"""Secure-tier end-to-end: a browser-shaped WebRTC client (ICE + DTLS +
SRTP, built from the same server/secure modules a real browser's stack
mirrors) against the agent over real UDP.

This is the round-4 closure of VERDICT r3 missing #3 ("no browser can
actually connect"): the reference serves browsers through aiortc's
ICE/DTLS/SRTP (reference agent.py:13-20); here the agent's OWN secure tier
answers a Chrome-fixture-shaped offer and moves encrypted media both ways.
"""

import pytest

# the secure tier's crypto backend is optional at the package level
# (signaling degrades to loopback without it) — these tests must SKIP,
# not fail collection, on a box without it (resilience PR satellite)
pytest.importorskip("cryptography", reason="secure tier needs cryptography")

import asyncio
import json
import re

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.media import native
from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.media.plane import H264RingSource, H264Sink
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider
from tests.secure_client import SecureTestPeer, sdp_attr, secure_offer


@pytest.fixture(scope="module")
def native_lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native lib unavailable")
    return lib


class InvertPipeline:
    def __call__(self, frame):
        arr = frame.to_ndarray(format="rgb24")
        out = VideoFrame.from_ndarray(255 - arr)
        out.pts = frame.pts
        out.time_base = frame.time_base
        out.wall_ts = frame.wall_ts
        return out


def test_browser_whip_offer_gets_secure_answer(native_lib):
    """The Chrome WHIP fixture must now get an ICE-lite + DTLS answer
    (UDP/TLS/RTP/SAVPF, fingerprint, setup:passive) instead of plain RTP."""
    with open("tests/fixtures/sdp/browser_whip_offer.sdp") as f:
        offer_sdp = f.read()

    async def go():
        provider = NativeRtpProvider(use_h264=native.h264_available())
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/whip",
                data=offer_sdp,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            answer = await r.text()
            assert "m=video" in answer
            assert "UDP/TLS/RTP/SAVPF" in answer
            assert "a=ice-lite" in answer
            assert sdp_attr(answer, "ice-ufrag")
            assert len(sdp_attr(answer, "ice-pwd") or "") >= 22
            fp = sdp_attr(answer, "fingerprint")
            assert fp and fp.startswith("sha-256 ")
            assert len(fp.split(" ", 1)[1].split(":")) == 32
            assert "a=setup:passive" in answer
            assert "a=candidate:" in answer
            # the offered H264 pt (102) is echoed
            assert re.search(r"^m=video \d+ UDP/TLS/RTP/SAVPF 102\r?$", answer, re.M)
        finally:
            await client.close()

    asyncio.run(go())


def test_secure_e2e_encrypted_media_roundtrip(native_lib, monkeypatch):
    """Full browser-shaped session: /offer -> authenticated STUN binding ->
    DTLS 1.2 handshake (mutual certs, fingerprints checked both ways) ->
    SRTP-protected H.264 up, SRTP-protected processed H.264 back."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    use_h264 = native.h264_available()
    w = h = 64

    async def go():
        # real SDP carries no frame geometry (the JSON envelope does) — the
        # operator's provider defaults set the decode ring size
        provider = NativeRtpProvider(
            default_width=w, default_height=h, use_h264=use_h264
        )
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        http = TestClient(TestServer(app))
        await http.start_server()
        peer = await SecureTestPeer("browser-shaped-client").open_socket()
        out_sink = H264Sink(w, h, use_h264=use_h264, payload_type=102)
        back_src = H264RingSource(w, h, use_h264=use_h264)
        try:
            r = await http.post(
                "/offer",
                json={
                    "room_id": "secure-room",
                    "offer": {
                        "sdp": secure_offer(peer.cert.fingerprint),
                        "type": "offer",
                    },
                },
            )
            assert r.status == 200
            await peer.establish((await r.json())["sdp"])
            assert peer.dtls.srtp_profile == 1

            val = 200
            decoded = []

            def pop_all():
                while (item := back_src.poll()) is not None:
                    decoded.append(item[0])

            for i in range(16):
                f = VideoFrame.from_ndarray(np.full((h, w, 3), val, np.uint8))
                f.pts = i * 3000
                peer.send_rtp(out_sink.consume(f))
                peer.drain_into(back_src)
                pop_all()
                await asyncio.sleep(0.05)
            for _ in range(60):
                if decoded:
                    break
                await asyncio.sleep(0.05)
                peer.drain_into(back_src)
                pop_all()

            assert decoded, "no SRTP-protected frames made it back"
            mean = float(decoded[-1].astype(np.float32).mean())
            assert abs(mean - (255 - val)) < 20, mean

            # the secure handshake is observable at /metrics
            m = await http.get("/metrics")
            snap = await m.json()
            assert snap.get("secure_sessions_total", 0) >= 1
        finally:
            out_sink.close()
            back_src.close()
            peer.close()
            await http.close()

    asyncio.run(go())


def test_obs_whip_offer_gets_secure_answer_with_bundle(native_lib):
    """OBS's WHIP offer carries a DTLS fingerprint + BUNDLE group too — it
    must route through the secure tier and get the group echoed."""
    with open("tests/fixtures/sdp/obs_whip_offer.sdp") as f:
        offer_sdp = f.read()

    async def go():
        provider = NativeRtpProvider(use_h264=native.h264_available())
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/whip",
                data=offer_sdp,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            answer = await r.text()
            assert "UDP/TLS/RTP/SAVPF" in answer
            assert "a=ice-lite" in answer
            assert sdp_attr(answer, "fingerprint")
            assert "a=setup:passive" in answer
            assert "a=group:BUNDLE video0" in answer
        finally:
            await client.close()

    asyncio.run(go())


def test_secure_whep_viewer_receives_encrypted_stream(native_lib, monkeypatch):
    """The send-only (WHEP viewer) secure path: a recvonly offer with a
    fingerprint still gets the demuxed socket (ICE checks + DTLS have to
    run somewhere), and the processed stream arrives SRTP-protected after
    the handshake — no plain-RTP fallback."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    use_h264 = native.h264_available()
    w = h = 64

    async def go():
        provider = NativeRtpProvider(
            default_width=w, default_height=h, use_h264=use_h264
        )
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        http = TestClient(TestServer(app))
        await http.start_server()
        loop = asyncio.get_running_loop()
        peer = await SecureTestPeer("secure-whep-viewer", ufrag="view").open_socket()
        pub_sink = H264Sink(w, h, use_h264=use_h264)
        back_src = H264RingSource(w, h, use_h264=use_h264)
        try:
            # publisher: plain JSON envelope (LAN tier)
            r = await http.post(
                "/whip",
                data=json.dumps(
                    {"native_rtp": True, "video": True, "width": w, "height": h}
                ),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            pub_port = json.loads(await r.text())["server_port"]

            # secure viewer: browser-shaped recvonly offer w/ fingerprint
            r = await http.post(
                "/whep",
                data=secure_offer(
                    peer.cert.fingerprint,
                    ufrag="view",
                    pwd="viewerpwd0123456789abc",
                    direction="recvonly",
                ),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            answer = await r.text()
            assert "a=setup:passive" in answer and "a=sendonly" in answer
            await peer.establish(answer)

            # drive the publisher; expect encrypted frames at the viewer
            pub_sock, _ = await loop.create_datagram_endpoint(
                asyncio.DatagramProtocol,
                remote_addr=("127.0.0.1", pub_port),
            )
            decoded = []
            val = 60

            def pop_all():
                while (item := back_src.poll()) is not None:
                    decoded.append(item[0])

            try:
                for i in range(40):
                    f = VideoFrame.from_ndarray(
                        np.full((h, w, 3), val, np.uint8)
                    )
                    f.pts = i * 3000
                    for pkt in pub_sink.consume(f):
                        pub_sock.sendto(pkt)
                    await asyncio.sleep(0.05)
                    peer.drain_into(back_src)
                    pop_all()
                    if decoded:
                        break
            finally:
                pub_sock.close()
            assert decoded, "secure WHEP viewer got no frames"
            mean = float(decoded[-1].astype(np.float32).mean())
            assert abs(mean - (255 - val)) < 25, mean
        finally:
            pub_sink.close()
            back_src.close()
            peer.close()
            await http.close()

    asyncio.run(go())


def test_sha384_fingerprint_offer_rejected(native_lib):
    """Non-sha-256 fingerprints are refused with a 400 (code-review r4):
    better than every connection dying mid-handshake with a misleading
    mismatch error."""

    async def go():
        provider = NativeRtpProvider(use_h264=native.h264_available())
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            offer = secure_offer("AA:" * 47 + "AA", ufrag="u", pwd="p" * 22, direction="sendonly")
            offer = offer.replace("fingerprint:sha-256", "fingerprint:sha-384")
            r = await client.post(
                "/whip",
                data=offer,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 400
            assert "sha-256" in await r.text()
        finally:
            await client.close()

    asyncio.run(go())


def test_plain_rtp_offer_still_plain(native_lib):
    """No fingerprint in the offer -> the old plain-RTP tier answers
    unchanged (LAN/test tier regression guard)."""
    with open("tests/fixtures/sdp/plainrtp_whep_offer.sdp") as f:
        offer_sdp = f.read()

    async def go():
        provider = NativeRtpProvider(use_h264=native.h264_available())
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # a publisher must exist before a viewer may subscribe
            r = await client.post(
                "/whip",
                data='{"native_rtp": true, "video": true}',
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            r = await client.post(
                "/whep",
                data=offer_sdp,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            answer = await r.text()
            assert "a=fingerprint" not in answer
            assert "a=ice-lite" not in answer
        finally:
            await client.close()

    asyncio.run(go())
