"""Golden tests for LCM step math vs fp64 closed forms."""

import numpy as np
import jax.numpy as jnp

from ai_rtc_agent_tpu.ops import lcm as L
from ai_rtc_agent_tpu.ops import schedule as S


def _coeffs(t_idx=(18, 26, 35, 45), steps=50, fbs=1):
    sch = S.make_schedule()
    bt = S.batched_sub_timesteps(list(t_idx), steps, frame_buffer_size=fbs)
    return sch, L.make_step_coeffs(sch, bt, frame_buffer_size=fbs)


def test_boundary_coeffs_golden():
    # independent fp64 recomputation: sigma_data=0.5, scaling=10
    t = np.array([0.0, 100.0, 500.0, 999.0])
    c_skip, c_out = L.boundary_coeffs(t)
    s = t / 10.0
    np.testing.assert_allclose(
        np.asarray(c_skip), 0.25 / (s**2 + 0.25), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(c_out), s / np.sqrt(s**2 + 0.25), rtol=1e-6
    )
    # at t=0 the consistency fn is the identity on x_t
    assert abs(float(c_skip[0]) - 1.0) < 1e-6 and abs(float(c_out[0])) < 1e-6


def test_step_coeffs_next_shifts_by_fbs():
    sch, c = _coeffs(fbs=2)
    # entry i's next-stage coeffs are entry i+fbs's current-stage coeffs
    np.testing.assert_allclose(c.next_alpha[:-2], c.alpha[2:], rtol=1e-6)
    np.testing.assert_allclose(c.next_sigma[:-2], c.sigma[2:], rtol=1e-6)
    # exit entries re-noise to clean
    np.testing.assert_allclose(c.next_alpha[-2:], 1.0)
    np.testing.assert_allclose(c.next_sigma[-2:], 0.0)


def test_pred_x0_inverts_add_noise(rng):
    sch, c = _coeffs()
    x0 = rng.standard_normal((4, 4, 8, 8)).astype(np.float32)
    eps = rng.standard_normal((4, 4, 8, 8)).astype(np.float32)
    x_t = S.add_noise(sch, jnp.asarray(x0), jnp.asarray(eps), c.timesteps)
    got = L.pred_x0(x_t, jnp.asarray(eps), c.as_jnp())
    np.testing.assert_allclose(np.asarray(got), x0, rtol=2e-3, atol=2e-3)


def test_lcm_denoise_blend(rng):
    sch, c = _coeffs()
    x_t = rng.standard_normal((4, 4, 4, 4)).astype(np.float32)
    eps = rng.standard_normal((4, 4, 4, 4)).astype(np.float32)
    den = np.asarray(L.lcm_denoise(jnp.asarray(x_t), jnp.asarray(eps), c.as_jnp()))
    x0 = np.asarray(L.pred_x0(jnp.asarray(x_t), jnp.asarray(eps), c.as_jnp()))
    want = (
        c.c_skip[:, None, None, None] * x_t + c.c_out[:, None, None, None] * x0
    )
    np.testing.assert_allclose(den, want, rtol=1e-5, atol=1e-6)


def test_renoise_next_exit_is_identity(rng):
    sch, c = _coeffs()
    den = rng.standard_normal((4, 4, 4, 4)).astype(np.float32)
    noise = rng.standard_normal((4, 4, 4, 4)).astype(np.float32)
    out = np.asarray(L.renoise_next(jnp.asarray(den), jnp.asarray(noise), c.as_jnp()))
    # last entry exits clean: renoise is identity
    np.testing.assert_allclose(out[-1], den[-1], rtol=1e-6)
    # earlier entries follow q(x_{t_next} | x0=denoised)
    ac = sch.alphas_cumprod[np.asarray(c.timesteps)[1]]
    want0 = np.sqrt(ac) * den[0] + np.sqrt(1 - ac) * noise[0]
    np.testing.assert_allclose(out[0], want0.astype(np.float32), rtol=1e-5, atol=1e-5)


def test_turbo_denoise_is_pred_x0(rng):
    sch = S.make_schedule()
    bt = S.batched_sub_timesteps([0], 1, num_train_steps=1000, spacing="trailing")
    c = L.make_step_coeffs(sch, bt)
    assert c.timesteps.tolist() == [999]
    x_t = rng.standard_normal((1, 4, 8, 8)).astype(np.float32)
    eps = rng.standard_normal((1, 4, 8, 8)).astype(np.float32)
    td = L.turbo_denoise(jnp.asarray(x_t), jnp.asarray(eps), c.as_jnp())
    px = L.pred_x0(jnp.asarray(x_t), jnp.asarray(eps), c.as_jnp())
    np.testing.assert_allclose(np.asarray(td), np.asarray(px))


def test_v_prediction(rng):
    sch, c = _coeffs()
    x_t = rng.standard_normal((4, 4, 4, 4)).astype(np.float32)
    v = rng.standard_normal((4, 4, 4, 4)).astype(np.float32)
    got = np.asarray(L.pred_x0(jnp.asarray(x_t), jnp.asarray(v), c.as_jnp(), "v_prediction"))
    want = (
        c.alpha[:, None, None, None] * x_t - c.sigma[:, None, None, None] * v
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
