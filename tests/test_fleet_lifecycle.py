"""Fleet lifecycle units (ISSUE 16): registry epochs + old-process-ghost
hardening, the rolling-upgrade sweep's halt/cancel discipline, the
autoscale controller's hysteresis, and the agent's restart-in-place
surface — all in-process; the real-process acceptance lives in
tests/test_fleet_procs.py.
"""

import asyncio
import os

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.fleet.registry import (
    AutoscaleController,
    FleetPoller,
    FleetRegistry,
)
from ai_rtc_agent_tpu.fleet.router import build_router_app
from ai_rtc_agent_tpu.server import lifecycle
from ai_rtc_agent_tpu.utils.profiling import FrameStats


def run(coro):
    return asyncio.run(coro)


class Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _reg(**kw):
    kw.setdefault("clock", Clock())
    kw.setdefault("stats", FrameStats())
    return FleetRegistry(**kw)


def _info(wid, port=9000, **extra):
    return {"worker_id": wid, "public_ip": "127.0.0.1",
            "public_port": str(port), "status": "ready", **extra}


# ---------------------------------------------------------------------------
# registry epochs: the old-process-ghost shape
# ---------------------------------------------------------------------------

def test_same_url_new_boot_bumps_epoch():
    reg = _reg()
    a = reg.register(_info("a", 9001, boot_id="b1", capacity=4))
    assert a.epoch == 1 and a.boot_id == "b1"
    # same publish refreshes in place — no bump
    assert reg.register(_info("a", 9001, boot_id="b1")) is a and a.epoch == 1
    # SAME address, NEW process nonce: the restart-in-place recycle —
    # fresh record, epoch bumped
    a2 = reg.register(_info("a", 9001, boot_id="b2", capacity=4))
    assert a2 is not a and a2.epoch == 2 and a2.boot_id == "b2"
    assert reg.agents["a"] is a2


def test_retired_boot_ghost_publish_dropped():
    stats = FrameStats()
    reg = _reg(stats=stats)
    reg.register(_info("a", 9001, boot_id="b1"))
    a2 = reg.register(_info("a", 9001, boot_id="b2"))
    assert a2.epoch == 2
    # the OLD process's worker sidecar republishing after the swap: the
    # record must not absorb the ghost's capacity view
    ghost = reg.register(_info("a", 9001, boot_id="b1", capacity=1))
    assert ghost is a2 and a2.epoch == 2 and a2.capacity == -1
    assert stats.snapshot()["fleet_stale_epoch_dropped_total"] == 1


def test_dead_revival_and_address_change_bump_epoch():
    reg = _reg()
    a = reg.register(_info("a", 9001, boot_id="b1"))
    reg.mark_dead(a)
    a2 = reg.register(_info("a", 9001, boot_id="b2"))
    assert a2.epoch == 2 and a2.state == "HEALTHY"
    a3 = reg.register(_info("a", 9002, boot_id="b2"))  # new address
    assert a3.epoch == 3
    # a bootless first publish later learning its nonce is NOT a swap
    b = reg.register(_info("b", 9003))
    assert b.epoch == 1 and b.boot_id == ""
    assert reg.register(_info("b", 9003, boot_id="x")) is b and b.epoch == 1


def test_poller_drops_superseded_poll_answer():
    stats = FrameStats()
    reg = _reg(stats=stats)
    a = reg.register(_info("a", 9001, boot_id="b1"))
    poller = FleetPoller(reg, interval_s=0.01, timeout_s=0.5)

    async def fake_get(url):
        # the record is superseded while this poll's HTTP is in flight:
        # the bodies describe the OLD process
        reg.register(_info("a", 9001, boot_id="b2"))
        if url.endswith("/capacity"):
            return {"capacity": 0, "saturated": True, "boot_id": "b1"}
        return {"status": "DEGRADED", "sessions": {"s": {}}}

    poller._get_json = fake_get

    async def go():
        await poller._poll_agent(a)

    run(go())
    new = reg.agents["a"]
    assert new.epoch == 2
    # the ghost answer touched NOTHING on the new record
    assert new.capacity == -1 and not new.saturated and new.state == "HEALTHY"
    assert stats.snapshot()["fleet_stale_epoch_dropped_total"] >= 1


def test_poller_drops_foreign_boot_answer():
    stats = FrameStats()
    reg = _reg(stats=stats)
    a = reg.register(_info("a", 9001, boot_id="b1"))
    poller = FleetPoller(reg, interval_s=0.01, timeout_s=0.5)

    async def fake_get(url):
        # a recycled replacement bound the port before its worker
        # re-registered: its answers carry a DIFFERENT nonce
        if url.endswith("/capacity"):
            return {"capacity": 9, "saturated": False, "boot_id": "b2"}
        return {"status": "HEALTHY", "sessions": {}}

    poller._get_json = fake_get
    run(poller._poll_agent(a))
    assert a.capacity == -1 and a.last_ok is None
    assert stats.snapshot()["fleet_stale_epoch_dropped_total"] == 1


# ---------------------------------------------------------------------------
# router webhook attribution across epochs
# ---------------------------------------------------------------------------

def test_router_drops_stale_epoch_webhook_but_not_recycled():
    async def go():
        reg = FleetRegistry(clock=Clock(), stats=None)
        app = build_router_app(registry=reg, poll=False)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await client.post("/fleet/register",
                              json=_info("a", 9001, boot_id="b1"))
            # a placement from epoch 1
            app["session_table"].remember(
                "s1", "a", "r1", "offer", epoch=reg.agents["a"].epoch
            )
            # the agent recycles: epoch moves under the same address
            await client.post("/fleet/register",
                              json=_info("a", 9001, boot_id="b2"))
            assert reg.agents["a"].epoch == 2
            # an ordinary breach webhook minted by the OLD process: drop
            r = await client.post("/fleet/events", json={
                "event": "StreamDegraded", "stream_id": "s1",
                "state": "DEGRADED", "reason": "late ghost",
            })
            assert r.status == 200
            m = await (await client.get("/metrics")).json()
            assert m["fleet_stale_epoch_dropped_total"] == 1
            assert m.get("fleet_breaches_total", 0) == 0
            # AGENT_RECYCLED is exempt — only the NEW process announces
            # the swap, and the announce races the worker re-register
            r = await client.post("/fleet/events", json={
                "event": "StreamDegraded", "stream_id": "s1",
                "state": "AGENT_RECYCLED", "reason": "recycled",
            })
            assert r.status == 200
            m = await (await client.get("/metrics")).json()
            assert m["fleet_recycled_sessions_total"] == 1
            # the re-offer mints a fresh stream id: the old row is gone
            assert app["session_table"].owner("s1") is None
        finally:
            await client.close()

    run(go())


# ---------------------------------------------------------------------------
# autoscale controller: hysteresis, cooldown, retire choice
# ---------------------------------------------------------------------------

def _ctl(reg, clock, **kw):
    kw.setdefault("enabled", True)
    kw.setdefault("alpha", 1.0)  # no smoothing: deterministic streaks
    kw.setdefault("up_ticks", 3)
    kw.setdefault("down_ticks", 3)
    kw.setdefault("cooldown_s", 30.0)
    return AutoscaleController(reg, clock=clock, **kw)


def test_autoscale_spawns_exactly_once_under_sustained_pressure():
    clock = Clock()
    reg = _reg(clock=clock)
    a = reg.register(_info("a", 9001, capacity=2))
    a.saturated = True  # sustained 503 pressure
    ctl = _ctl(reg, clock)
    decisions = []
    for _ in range(20):  # way past up_ticks: hysteresis must pin at ONE
        clock.now += 1.0
        decisions.append(ctl.tick())
    assert decisions.count("up") == 1
    assert decisions.index("up") == 2  # the third >= high read
    # cooldown elapsed + still saturated -> exactly one more
    clock.now += 31.0
    more = [ctl.tick() for _ in range(5)]
    assert more.count("up") == 1


def test_autoscale_reject_pressure_and_disabled_default():
    clock = Clock()
    reg = _reg(clock=clock)
    reg.register(_info("a", 9001, capacity=8))  # plenty of headroom
    ctl = _ctl(reg, clock)
    # router-level 503s override the calm per-agent reads
    assert ctl.sample(rejects_total=1) == 1.0
    assert ctl.sample(rejects_total=1) == 0.0  # no NEW rejects: calm
    # default-off: inert no matter the pressure
    off = AutoscaleController(reg, clock=clock)
    assert off.enabled is False and off.tick(rejects_total=99) is None


def test_autoscale_retires_emptiest_and_respects_floor():
    clock = Clock()
    reg = _reg(clock=clock)
    a = reg.register(_info("a", 9001, capacity=8))
    b = reg.register(_info("b", 9002, capacity=8))
    a.live_sessions = 3
    b.live_sessions = 1
    ctl = _ctl(reg, clock, min_agents=1)
    assert ctl.retire_candidate() is b  # emptiest healthy box
    b.draining = True  # mid-retire: not a candidate twice
    assert ctl.retire_candidate() is None  # a alone == min_agents floor
    b.draining = False
    decisions = []
    for _ in range(5):  # idle fleet: EWMA sits at 0 <= low
        clock.now += 1.0
        decisions.append(ctl.tick())
    assert decisions.count("down") == 1


# ---------------------------------------------------------------------------
# rolling upgrade sweep (in-process, fake agents)
# ---------------------------------------------------------------------------

class LifecycleAgent:
    """Fake agent for upgrade-sweep tests: /health, /capacity (with the
    process nonce), /drain, and an /admin/recycle that either swaps the
    nonce (success) or refuses."""

    def __init__(self, name, recycle_status=202):
        self.name = name
        self.boot = f"{name}-boot1"
        self.recycle_status = recycle_status
        self.recycles = 0
        self.drains = []
        self.server = None

    def _app(self):
        app = web.Application()

        async def health(req):
            return web.json_response({"status": "HEALTHY", "sessions": {}})

        async def capacity(req):
            return web.json_response({
                "capacity": 2, "saturated": False, "boot_id": self.boot,
            })

        async def drain(req):
            self.drains.append((await req.json())["action"])
            return web.json_response({"draining": True})

        async def recycle(req):
            self.recycles += 1
            if self.recycle_status >= 400:
                return web.json_response(
                    {"error": "refused"}, status=self.recycle_status
                )
            self.boot = f"{self.name}-boot{self.recycles + 1}"
            return web.json_response({"recycling": True}, status=202)

        app.router.add_get("/health", health)
        app.router.add_get("/capacity", capacity)
        app.router.add_post("/drain", drain)
        app.router.add_post("/admin/recycle", recycle)
        return app

    async def start(self):
        self.server = TestServer(self._app())
        await self.server.start_server()
        return self

    async def close(self):
        await self.server.close()


async def _upgrade_router(agents, **env_keys):
    reg = FleetRegistry(clock=Clock())
    app = build_router_app(registry=reg, poll=False)
    app["upgrade_step_timeout_s"] = env_keys.pop("step_timeout", 5.0)
    client = TestClient(TestServer(app))
    await client.start_server()
    for agent in agents:
        r = await client.post("/fleet/register", json=_info(
            agent.name, agent.server.port, boot_id=agent.boot, capacity=2
        ))
        assert r.status == 200
        # polled evidence: the sweep refuses to recycle a record whose
        # live_sessions is only the pre-first-poll default
        reg.note_poll(reg.agents[agent.name], {"capacity": 2},
                      {"status": "HEALTHY", "sessions": {}})
    return app, client, reg


async def _wait_upgrade_idle(app, client, agents, budget=5.0):
    """Drive the sweep to completion, playing the worker-republish part
    (the real fleet's sidecar re-registers the replacement's nonce)."""
    deadline = asyncio.get_event_loop().time() + budget
    while app["upgrade"]["active"]:
        assert asyncio.get_event_loop().time() < deadline, "sweep stuck"
        for agent in agents:
            rec = app["fleet"].agents.get(agent.name)
            if rec is not None and rec.boot_id != agent.boot:
                await client.post("/fleet/register", json=_info(
                    agent.name, agent.server.port, boot_id=agent.boot,
                    capacity=2,
                ))
                rec = app["fleet"].agents[agent.name]
                app["fleet"].note_poll(rec, {"capacity": 2},
                                       {"status": "HEALTHY", "sessions": {}})
        await asyncio.sleep(0.05)


def test_upgrade_sweeps_all_agents_and_bumps_epochs():
    async def go():
        a = await LifecycleAgent("a").start()
        b = await LifecycleAgent("b").start()
        app, client, reg = await _upgrade_router([a, b])
        try:
            r = await client.post("/fleet/upgrade")
            assert r.status == 202 and (await r.json())["active"]
            # double-start refused while the sweep runs
            assert (await client.post("/fleet/upgrade")).status == 409
            await _wait_upgrade_idle(app, client, [a, b])
            up = (await (await client.get("/fleet/health")).json())["upgrade"]
            assert up["halted"] is None and sorted(up["done"]) == ["a", "b"]
            assert a.recycles == 1 and b.recycles == 1
            assert reg.agents["a"].epoch == 2 and reg.agents["b"].epoch == 2
            m = await (await client.get("/metrics")).json()
            assert m["fleet_upgrades_total"] == 1
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_upgrade_halts_on_recycle_refusal_leaving_agent_serving():
    async def go():
        a = await LifecycleAgent("a", recycle_status=409).start()
        b = await LifecycleAgent("b").start()
        app, client, reg = await _upgrade_router([a, b])
        try:
            r = await client.post("/fleet/upgrade")
            assert r.status == 202
            await _wait_upgrade_idle(app, client, [a, b])
            up = app["upgrade"]
            assert up["halted"] and up["halted"].startswith("a:")
            assert "recycle refused" in up["halted"]
            # the failed step un-drained its target: still serving
            rec = reg.agents["a"]
            assert rec.draining is False and rec.state != "DEAD"
            assert a.drains[-1] == "unfreeze"
            # the sweep stopped BEFORE b
            assert b.recycles == 0 and up["done"] == []
            m = await (await client.get("/metrics")).json()
            assert m["fleet_upgrade_halts_total"] == 1
            assert m.get("fleet_upgrades_total", 0) == 0
            # a fresh start is allowed once the halted sweep is inactive
            a.recycle_status = 202
            assert (await client.post("/fleet/upgrade")).status == 202
            await _wait_upgrade_idle(app, client, [a, b])
            assert app["upgrade"]["halted"] is None
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_upgrade_cancel_undrains_current_target():
    async def go():
        a = await LifecycleAgent("a").start()
        app, client, reg = await _upgrade_router([a], step_timeout=10.0)
        try:
            # a live session pins the drain-to-zero wait open (nothing in
            # the session table to move — the poll view says busy)
            reg.note_poll(reg.agents["a"], {"capacity": 2},
                          {"status": "HEALTHY", "sessions": {"s": {}}})
            r = await client.post("/fleet/upgrade")
            assert r.status == 202
            await asyncio.sleep(0.2)
            assert app["upgrade"]["current"] == "a"
            r = await client.post("/fleet/upgrade", params={
                "action": "cancel"
            })
            assert r.status == 200
            deadline = asyncio.get_event_loop().time() + 5.0
            while app["upgrade"]["active"]:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)
            assert "cancelled" in (app["upgrade"]["halted"] or "")
            rec = reg.agents["a"]
            assert rec.draining is False and a.recycles == 0
            assert a.drains[-1] == "unfreeze"
        finally:
            await client.close()
            await a.close()

    run(go())


def test_upgrade_needs_migration_and_agents():
    async def go():
        app = build_router_app(poll=False)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            assert (await client.post("/fleet/upgrade")).status == 409
            r = await client.post("/fleet/upgrade", params={"action": "zap"})
            assert r.status == 400
            # cancel with no sweep running is a cheap no-op status read
            r = await client.post("/fleet/upgrade",
                                  params={"action": "cancel"})
            assert r.status == 200 and (await r.json())["active"] is False
        finally:
            await client.close()

    run(go())


# ---------------------------------------------------------------------------
# agent restart-in-place surface
# ---------------------------------------------------------------------------

def test_handoff_file_round_trip(tmp_path):
    path = str(tmp_path / "handoff.json")
    lifecycle.write_handoff(path, [{"session": "s1", "snapshot": {}}],
                            {"worker_id": "a"})
    data = lifecycle.read_handoff(path)
    assert data["worker_id"] == "a" and len(data["sessions"]) == 1
    lifecycle.consume_handoff(path)
    assert not os.path.exists(path)
    assert lifecycle.read_handoff(path) is None  # gone
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("{\"schema\": 99}")
    assert lifecycle.read_handoff(bad) is None  # foreign schema
    with open(bad, "w") as f:
        f.write("not json")
    assert lifecycle.read_handoff(bad) is None


def test_admin_recycle_exports_and_spawns(monkeypatch, tmp_path):
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import (
        LoopbackProvider,
        make_loopback_offer,
    )

    handoff = str(tmp_path / "handoff.json")
    monkeypatch.setenv("RECYCLE_HANDOFF", handoff)
    monkeypatch.setenv("RECYCLE_EXIT_DELAY_S", "0.01")
    spawned = []
    exited = []
    monkeypatch.setattr(
        lifecycle, "spawn_replacement",
        lambda p: spawned.append(p) or True,
    )
    monkeypatch.setattr(
        lifecycle, "exit_process", lambda code=0: exited.append(code)
    )

    class FakePipeline:
        def __call__(self, frame):
            return frame

        def update_prompt(self, p):
            pass

        def update_t_index_list(self, t):
            pass

    async def go():
        app = build_app(pipeline=FakePipeline(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json={
                "room_id": "r1",
                "offer": {"sdp": make_loopback_offer(), "type": "offer"},
            })
            assert r.status == 200
            r = await client.post("/admin/recycle", json={"respawn": True})
            assert r.status == 202
            body = await r.json()
            assert body["sessions"] == 1 and body["handoff"] == handoff
            # double-recycle refused while the first is in flight
            assert (await client.post("/admin/recycle")).status == 409
            deadline = asyncio.get_event_loop().time() + 3.0
            while not exited:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)
            assert spawned == [handoff] and exited == [0]
            data = lifecycle.read_handoff(handoff)
            assert len(data["sessions"]) == 1
            entry = data["sessions"][0]
            assert entry["room_id"] == "r1" and entry["snapshot"]["session"]
        finally:
            await client.close()

    run(go())


def test_admin_recycle_gates(monkeypatch):
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    monkeypatch.setenv("RECYCLE_ENABLE", "0")

    async def go():
        app = build_app(pipeline=object(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            assert (await client.post("/admin/recycle")).status == 404
        finally:
            await client.close()

    run(go())


def test_replacement_imports_handoff_and_announces(monkeypatch, tmp_path):
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    handoff = str(tmp_path / "handoff.json")
    lifecycle.write_handoff(
        handoff,
        [{
            "session": "old-sid", "room_id": "r1",
            "snapshot": {"schema": 1, "kind": "control-plane",
                         "session": "old-sid"},
            "journey": {"journey_id": "j1", "leg": 2},
        }],
        {"worker_id": "a", "webhook": {"url": None, "token": None}},
    )
    monkeypatch.setenv("RECYCLE_HANDOFF", handoff)
    announced = []

    from ai_rtc_agent_tpu.server.events import StreamEventHandler

    def record(self, stream_id, room_id, state, reason,
               flight_snapshot_id=None, recent_events=None, journey=None):
        announced.append((stream_id, room_id, state, journey))

    monkeypatch.setattr(StreamEventHandler, "handle_session_state", record)

    async def go():
        app = build_app(pipeline=object(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()  # on_startup runs the import
        try:
            assert not os.path.exists(handoff)  # consumed whatever happens
            parked = app.get("imported_sessions", {})
            assert "rcy-old-sid" in parked
            assert announced == [
                ("old-sid", "r1", "AGENT_RECYCLED",
                 {"journey_id": "j1", "leg": 2}),
            ]
            m = await (await client.get("/metrics")).json()
            assert m["recycle_imports_total"] == 1
        finally:
            await client.close()

    run(go())


def test_exec_hook_spawn_backends(monkeypatch, tmp_path):
    calls = []
    monkeypatch.setattr(lifecycle.subprocess, "Popen",
                        lambda *a, **k: calls.append((a, k)) or
                        type("P", (), {"pid": 123})())
    assert lifecycle.run_exec_hook(None) is False  # no hook: explicit no
    assert lifecycle.run_exec_hook("spawn-agent --here",
                                   {"RECYCLE_HANDOFF": "/x"}) is True
    (args, kw) = calls[-1]
    assert args[0] == "spawn-agent --here" and kw["shell"] is True
    assert kw["env"]["RECYCLE_HANDOFF"] == "/x"
    # spawn_replacement prefers the hook; falls back to argv re-exec
    monkeypatch.setenv("RECYCLE_EXEC_HOOK", "spawn-agent")
    assert lifecycle.spawn_replacement("/h") is True
    assert calls[-1][1]["env"]["RECYCLE_HANDOFF"] == "/h"
    monkeypatch.delenv("RECYCLE_EXEC_HOOK")
    assert lifecycle.spawn_replacement("/h2") is True
    assert calls[-1][1]["env"]["RECYCLE_HANDOFF"] == "/h2"
    assert isinstance(calls[-1][0][0], list)  # argv re-exec form


def test_reexec_argv_reconstructs_module_launch(monkeypatch):
    """``python -m pkg.mod`` sets sys.argv[0] to the module's FILE path;
    re-running that file as a script breaks the package's relative
    imports, so the re-exec argv must restore the ``-m`` form (and strip
    the ``.__main__`` suffix a bare ``-m pkg`` launch carries).  Plain
    script launches (no __main__ spec) re-exec their argv verbatim."""
    import sys
    import types

    monkeypatch.setattr(sys, "argv",
                        ["/repo/pkg/server/agent.py", "--port", "8899"])
    fake_main = types.ModuleType("__main__")
    fake_main.__spec__ = types.SimpleNamespace(name="pkg.server.agent")
    monkeypatch.setitem(sys.modules, "__main__", fake_main)
    assert lifecycle.reexec_argv() == [
        sys.executable, "-m", "pkg.server.agent", "--port", "8899"]

    fake_main.__spec__ = types.SimpleNamespace(name="pkg.__main__")
    assert lifecycle.reexec_argv()[1:3] == ["-m", "pkg"]

    fake_main.__spec__ = None  # plain `python script.py` launch
    assert lifecycle.reexec_argv() == [
        sys.executable, "/repo/pkg/server/agent.py", "--port", "8899"]
