"""Signaling/server tests: endpoint parity + hermetic loopback end-to-end.

(SURVEY.md section 4 'Integration' + 'End-to-end' tiers — the reference has
zero tests; these encode the behavior its agent.py exhibits.)
"""

import asyncio
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.signaling import (
    LoopbackProvider,
    make_loopback_offer,
)


class FakePipeline:
    """Pipeline stand-in: invert colors; records control-plane calls."""

    def __init__(self):
        self.prompt = None
        self.t_index_list = None
        self.calls = 0

    def __call__(self, frame):
        self.calls += 1
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def update_prompt(self, p):
        self.prompt = p

    def update_t_index_list(self, t):
        if len(t) != 4:
            raise ValueError("length must stay 4")
        self.t_index_list = list(t)


def run(coro):
    return asyncio.run(coro)


async def _client(pipeline):
    app = build_app(pipeline=pipeline, provider=LoopbackProvider())
    client = TestClient(TestServer(app))
    await client.start_server()
    return app, client


def test_health_and_cors():
    async def go():
        app, client = await _client(FakePipeline())
        try:
            r = await client.get("/")
            assert r.status == 200 and await r.text() == "OK"
            assert r.headers["Access-Control-Allow-Origin"] == "*"
            r = await client.options("/config")
            assert r.status == 200
        finally:
            await client.close()

    run(go())


def test_config_endpoint_updates_pipeline():
    pipe = FakePipeline()

    async def go():
        app, client = await _client(pipe)
        try:
            r = await client.post(
                "/config", json={"prompt": "hello", "t_index_list": [1, 2, 3, 4]}
            )
            assert r.status == 200
            # invalid length -> 400, not a crash (engine validates)
            r = await client.post("/config", json={"t_index_list": [1]})
            assert r.status == 400
        finally:
            await client.close()

    run(go())
    assert pipe.prompt == "hello"
    assert pipe.t_index_list == [1, 2, 3, 4]


def test_config_guidance_capability_checked_before_mutation():
    """A /config body mixing prompt with guidance against a pipeline that
    cannot do guidance (multipeer global plane) must apply NOTHING —
    a 400 has to mean 'rejected', never 'half-applied'."""
    import pytest

    from ai_rtc_agent_tpu.server.agent import apply_runtime_config

    pipe = FakePipeline()  # has no update_guidance
    with pytest.raises(ValueError):
        apply_runtime_config(pipe, {"prompt": "late", "guidance_scale": 2.0})
    assert pipe.prompt is None and pipe.t_index_list is None

    class Guided(FakePipeline):
        def update_guidance(self, guidance_scale=None, delta=None):
            self.guidance = guidance_scale
            self.delta = delta

    g = Guided()
    apply_runtime_config(g, {"prompt": "p", "guidance_scale": 2.0, "delta": 0.5})
    assert (g.prompt, g.guidance, g.delta) == ("p", 2.0, 0.5)


def test_config_adapter_presence_keyed_and_capability_checked():
    """ISSUE 20: the "adapter" /config key is PRESENCE-keyed (JSON null
    CLEARS to the base style; an absent key touches nothing), refused
    against a pipeline without the factor-bank surface BEFORE any other
    key applies, and applied FIRST so an unknown style name rejects the
    whole body un-applied."""
    import pytest

    from ai_rtc_agent_tpu.server.agent import apply_runtime_config

    pipe = FakePipeline()  # has no update_adapter
    with pytest.raises(ValueError, match="adapter hot-swap not supported"):
        apply_runtime_config(pipe, {"prompt": "late", "adapter": "ghibli"})
    assert pipe.prompt is None  # nothing half-applied

    class Adapted(FakePipeline):
        def __init__(self):
            super().__init__()
            self.swaps = []

        def update_adapter(self, name):
            if name == "nope":
                raise KeyError("unknown adapter 'nope'")
            self.swaps.append(name)

    a = Adapted()
    apply_runtime_config(a, {"adapter": "ghibli", "prompt": "p"})
    assert a.swaps == ["ghibli"] and a.prompt == "p"
    apply_runtime_config(a, {"adapter": None})  # null = clear, not absent
    assert a.swaps == ["ghibli", None]
    apply_runtime_config(a, {"prompt": "q"})  # absent key: style untouched
    assert a.swaps == ["ghibli", None] and a.prompt == "q"
    with pytest.raises(ValueError, match="string name or null"):
        apply_runtime_config(a, {"adapter": 3})
    # adapter applies FIRST: a registry refusal leaves the prompt alone
    with pytest.raises(KeyError):
        apply_runtime_config(a, {"adapter": "nope", "prompt": "never"})
    assert a.prompt == "q" and a.swaps == ["ghibli", None]


def test_whep_without_source_is_401_and_delete_200():
    async def go():
        app, client = await _client(FakePipeline())
        try:
            r = await client.post(
                "/whep", data="fake", headers={"Content-Type": "application/sdp"}
            )
            assert r.status == 401
            r = await client.delete("/whep")
            assert r.status == 200
            r = await client.post(
                "/whip", data="x", headers={"Content-Type": "text/plain"}
            )
            assert r.status == 400
        finally:
            await client.close()

    run(go())


def test_whip_then_whep_loopback_end_to_end(monkeypatch):
    """Full loop: publish via WHIP, subscribe via WHEP, frames flow through
    the (fake) pipeline with warm-up frames dropped."""
    monkeypatch.setenv("WARMUP_FRAMES", "2")
    pipe = FakePipeline()

    async def go():
        app, client = await _client(pipe)
        try:
            r = await client.post(
                "/whip",
                data=make_loopback_offer(),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            assert r.headers["Location"].startswith("/whip/")
            source = app["state"]["source_track"]
            assert source is not None

            r = await client.post(
                "/whep",
                data=make_loopback_offer(video=False, datachannel=False),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201

            # the viewer gets a RELAYED view of the processed stream (the
            # reference's MediaRelay fan-out, agent.py:424-430) — never the
            # raw shared track
            whep_pc = next(pc for pc in app["pcs"] if pc.out_tracks)
            viewer = whep_pc.out_tracks[0]
            assert viewer is not source

            # find the publisher pc and push frames into its inbound track
            pub_pc = next(pc for pc in app["pcs"] if pc.in_track is not None)
            frames = [
                np.full((8, 8, 3), i * 10, dtype=np.uint8) for i in range(4)
            ]
            for f in frames:
                await pub_pc.in_track.push(f)

            out = await viewer.recv()  # 2 warmups dropped by the track
            expected = [255 - f for f in frames[2:]]
            assert any(np.array_equal(out, e) for e in expected)
            assert pipe.calls >= 3  # 2 warmups + >=1 real

            # datachannel config reaches the pipeline
            await pub_pc.datachannel.deliver(json.dumps({"prompt": "via dc"}))
            assert pipe.prompt == "via dc"
        finally:
            await client.close()

    run(go())


def test_offer_full_cycle_with_webhooks(monkeypatch):
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    events = []

    async def go():
        pipe = FakePipeline()
        app, client = await _client(pipe)
        app["stream_event_handler"].webhook_url = None  # default: disabled
        # capture events instead of HTTP
        app["stream_event_handler"].handle_stream_started = (
            lambda s, r, **kw: events.append(("started", r))
        )
        app["stream_event_handler"].handle_stream_ended = (
            lambda s, r, **kw: events.append(("ended", r))
        )
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "room1",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
            )
            assert r.status == 200
            body = await r.json()
            assert body["type"] == "answer"
            pc = next(iter(app["pcs"]))
            assert pc.connectionState == "connected"
            assert pc.out_tracks, "processed track must be sent back"
            await pc.close()
        finally:
            await client.close()

    run(go())
    assert ("started", "room1") in events
    assert ("ended", "room1") in events


def test_metrics_endpoint():
    async def go():
        app, client = await _client(FakePipeline())
        try:
            r = await client.get("/metrics")
            assert r.status == 200
            body = await r.json()
            assert "fps" in body and "frames_total" in body
        finally:
            await client.close()

    run(go())


def test_metrics_exposes_host_plane_sessions():
    """ISSUE 2: /metrics carries per-session packetize/protect/send/recv
    µs histograms when the provider runs the batched host plane."""
    from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider
    from ai_rtc_agent_tpu.utils.profiling import FrameStats

    async def go():
        provider = NativeRtpProvider()
        app = build_app(pipeline=FakePipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            st = FrameStats()
            for us in (3e-6, 5e-6, 8e-6):
                st.record_stage("packetize", us)
                st.record_stage("send", us)
            provider.register_plane_session("pc-test", st)
            body = await (await client.get("/metrics")).json()
            sess = body["host_plane_sessions"]["pc-test"]
            assert sess["packetize_count"] == 3
            assert sess["send_p90_us"] > sess["send_p50_us"] > 0
            provider.unregister_plane_session("pc-test")
            body = await (await client.get("/metrics")).json()
            assert body["host_plane_sessions"] == {}
        finally:
            await client.close()

    run(go())


def test_whep_session_scoped_delete(monkeypatch):
    """DELETE /whep/{session} (the Location we return) closes ONLY that
    subscriber; other viewers keep streaming (VERDICT r1 weak #6)."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")

    async def go():
        app, client = await _client(FakePipeline())
        try:
            r = await client.post(
                "/whip",
                data=make_loopback_offer(),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201

            locs = []
            for _ in range(2):
                r = await client.post(
                    "/whep",
                    data=make_loopback_offer(video=False, datachannel=False),
                    headers={"Content-Type": "application/sdp"},
                )
                assert r.status == 201
                locs.append(r.headers["Location"])
            assert locs[0] != locs[1] and locs[0].startswith("/whep/")
            pcs_by_session = dict(app["state"]["whep_pcs"])
            assert len(pcs_by_session) == 2

            r = await client.delete(locs[0])
            assert r.status == 200
            sid0 = locs[0].rsplit("/", 1)[1]
            sid1 = locs[1].rsplit("/", 1)[1]
            assert pcs_by_session[sid0].connectionState == "closed"
            assert pcs_by_session[sid1].connectionState == "connected"
            assert sid1 in app["state"]["whep_pcs"]

            # unknown session -> 404; bare DELETE closes the rest
            r = await client.delete("/whep/nonexistent")
            assert r.status == 404
            r = await client.delete("/whep")
            assert r.status == 200
            assert pcs_by_session[sid1].connectionState == "closed"

            # WHIP DELETE closes the publisher(s) and drops the source track
            r = await client.delete("/whip")
            assert r.status == 200
            assert app["state"]["source_track"] is None
            assert not app["state"]["whip_pcs"]
        finally:
            await client.close()

    run(go())


def test_whep_two_viewers_both_get_frames(monkeypatch):
    """Relay fan-out: TWO WHEP viewers each receive the processed stream
    (without a relay each frame went to exactly one viewer and concurrent
    recv() corrupted the shared track's state)."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    pipe = FakePipeline()

    async def go():
        app, client = await _client(pipe)
        try:
            r = await client.post(
                "/whip",
                data=make_loopback_offer(),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            viewers = []
            for _ in range(2):
                r = await client.post(
                    "/whep",
                    data=make_loopback_offer(video=False, datachannel=False),
                    headers={"Content-Type": "application/sdp"},
                )
                assert r.status == 201
            for pc in app["pcs"]:
                if pc.out_tracks:
                    viewers.append(pc.out_tracks[0])
            assert len(viewers) == 2

            pub_pc = next(pc for pc in app["pcs"] if pc.in_track is not None)
            frames = [
                np.full((8, 8, 3), 30 + i * 40, dtype=np.uint8) for i in range(3)
            ]
            for f in frames:
                await pub_pc.in_track.push(f)

            outs = [await v.recv() for v in viewers]
            expected = [255 - f for f in frames]
            for out in outs:
                assert any(np.array_equal(out, e) for e in expected)
        finally:
            await client.close()

    run(go())


def test_relay_slow_viewer_drops_not_blocks():
    """Latest-wins fan-out: a stalled viewer must not block the pump or the
    healthy viewer, and catches up to a RECENT frame when it resumes."""
    from ai_rtc_agent_tpu.server.relay import TrackRelay

    class Source:
        def __init__(self):
            self.q = asyncio.Queue()

        async def recv(self):
            return await self.q.get()

    async def go():
        src = Source()
        relay = TrackRelay(src)
        fast = relay.subscribe(maxsize=2)
        slow = relay.subscribe(maxsize=2)

        for i in range(8):
            await src.q.put(np.full((4, 4, 3), i, np.uint8))

        fast_frames = [await fast.recv() for _ in range(2)]
        assert all(f.shape == (4, 4, 3) for f in fast_frames)
        # slow viewer never polled while 8 frames flowed: its queue kept only
        # the freshest maxsize frames
        got = await slow.recv()
        assert int(got[0, 0, 0]) >= 4, "stalled viewer should skip stale frames"

        slow.stop()
        await src.q.put(np.full((4, 4, 3), 99, np.uint8))
        out = await fast.recv()
        assert out is not None
        relay.stop()

    run(go())


def test_whip_publisher_failover(monkeypatch):
    """Two publishers: viewers follow the newest; when it leaves, NEW
    viewers land on the previous still-live publisher's relay."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    pipe = FakePipeline()

    async def go():
        app, client = await _client(pipe)
        try:
            locs = []
            for _ in range(2):
                r = await client.post(
                    "/whip",
                    data=make_loopback_offer(),
                    headers={"Content-Type": "application/sdp"},
                )
                assert r.status == 201
                locs.append(r.headers["Location"])
            sids = [loc.rsplit("/", 1)[1] for loc in locs]
            # active source is publisher B (latest wins)
            assert app["state"]["source_relay"] is app["state"]["whip_relays"][sids[1]]

            # B leaves -> A's relay becomes the source again
            r = await client.delete(locs[1])
            assert r.status == 200
            assert app["state"]["source_track"] is app["state"]["whip_tracks"][sids[0]]
            assert app["state"]["source_relay"] is app["state"]["whip_relays"][sids[0]]
            assert sids[1] not in app["state"]["whip_relays"]

            # a new viewer now gets frames from publisher A
            r = await client.post(
                "/whep",
                data=make_loopback_offer(video=False, datachannel=False),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            viewer = next(pc for pc in app["pcs"] if pc.out_tracks).out_tracks[0]
            pub_a = app["state"]["whip_pcs"][sids[0]]
            frame = np.full((8, 8, 3), 77, np.uint8)
            await pub_a.in_track.push(frame)
            out = await viewer.recv()
            np.testing.assert_array_equal(out, 255 - frame)
        finally:
            await client.close()

    run(go())


def test_udp_port_pinning_patch():
    """patch_loop_datagram: unbound datagram endpoints land on an
    operator-pinned port (reference agent.py:32-69 — firewall/serverless
    deployments); explicit ports and local_addr=None bypass the patch."""
    from ai_rtc_agent_tpu.server.agent import patch_loop_datagram

    async def go():
        loop = asyncio.get_event_loop()
        patch_loop_datagram(["39551", "39552"])

        tr1, _ = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol, local_addr=("127.0.0.1", 0)
        )
        port1 = tr1.get_extra_info("sockname")[1]
        assert port1 in (39551, 39552)

        tr2, _ = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol, local_addr=("127.0.0.1", 0)
        )
        port2 = tr2.get_extra_info("sockname")[1]
        assert port2 in (39551, 39552) and port2 != port1

        # both pinned ports busy -> OSError, not an ephemeral fallback
        with pytest.raises(OSError):
            await loop.create_datagram_endpoint(
                asyncio.DatagramProtocol, local_addr=("127.0.0.1", 0)
            )

        # explicit port bypasses the pin list
        tr3, _ = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol, local_addr=("127.0.0.1", 39600)
        )
        assert tr3.get_extra_info("sockname")[1] == 39600
        for tr in (tr1, tr2, tr3):
            tr.close()

    run(go())


def test_whip_publisher_churn_sweeps_old_dead_sessions(monkeypatch):
    """An OLDER publisher leaving while a newer one stays live must have its
    track/relay swept immediately (ADVICE r2: the pre-fix code stopped at
    the first live session, leaking entries forever under churn)."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    pipe = FakePipeline()

    async def go():
        app, client = await _client(pipe)
        try:
            locs = []
            for _ in range(2):
                r = await client.post(
                    "/whip",
                    data=make_loopback_offer(),
                    headers={"Content-Type": "application/sdp"},
                )
                assert r.status == 201
                locs.append(r.headers["Location"])
            sids = [loc.rsplit("/", 1)[1] for loc in locs]

            # A (older) leaves; B stays live and stays the source
            r = await client.delete(locs[0])
            assert r.status == 200
            assert sids[0] not in app["state"]["whip_tracks"]
            assert sids[0] not in app["state"]["whip_relays"]
            assert app["state"]["source_relay"] is app["state"]["whip_relays"][sids[1]]
        finally:
            await client.close()

    run(go())


def test_offer_failure_closes_half_built_pc(monkeypatch):
    """A failure after the pc exists (e.g. SDP answer generation) must close
    and discard it — with native-rtp providers a bound UDP socket would
    otherwise linger until shutdown (ADVICE r2)."""
    from ai_rtc_agent_tpu.server.signaling import LoopbackPeerConnection

    async def boom(self):
        raise RuntimeError("synthetic createAnswer failure")

    monkeypatch.setattr(LoopbackPeerConnection, "createAnswer", boom)

    async def go():
        app, client = await _client(FakePipeline())
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "r1",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
            )
            assert r.status == 500
            assert app["pcs"] == set()  # nothing half-built left behind
        finally:
            await client.close()

    run(go())


def test_sp_flag_defaults_attention_to_ring(monkeypatch):
    """--sp N with a non-sp attention impl must not be a silent no-op
    (ADVICE r2 medium): startup defaults the impl to ring so the sequence
    axis actually shards over the allocated mesh."""
    monkeypatch.delenv("ATTN_IMPL", raising=False)

    async def go():
        app = build_app(model_id="tiny-test", provider=LoopbackProvider(), sp=2)
        client = TestClient(TestServer(app))
        await client.start_server()  # runs on_startup: builds the pipeline
        try:
            assert app["pipeline"].config.attn_impl == "ring"
        finally:
            await client.close()

    run(go())


def test_demo_page_served():
    """GET /demo: the in-repo browser client (the reference points at a
    hosted app instead — ref docs/connect.md:3-5)."""
    async def go():
        app, client = await _client(FakePipeline())
        try:
            r = await client.get("/demo")
            assert r.status == 200
            body = await r.text()
            assert "RTCPeerConnection" in body and "/offer" in body
        finally:
            await client.close()

    run(go())


def test_config_structurally_wrong_bodies_are_400():
    """JSON that parses but is the wrong shape (array body, null t_index
    entries) must map to 400, never escape as a 500 (hostile/buggy demo
    clients)."""
    async def go():
        app, client = await _client(FakePipeline())
        try:
            r = await client.post(
                "/config", data="[1,2]",
                headers={"Content-Type": "application/json"},
            )
            assert r.status == 400
            r = await client.post("/config", json={"t_index_list": [18, None]})
            assert r.status == 400
        finally:
            await client.close()

    run(go())


def test_default_provider_without_aiortc_is_native(monkeypatch):
    """r5: a deployment without aiortc serves real browsers (native secure
    tier), not the loopback test shim — loopback only on explicit request."""
    monkeypatch.delenv("WEBRTC_PROVIDER", raising=False)
    import builtins

    real_import = builtins.__import__

    def no_aiortc(name, *a, **kw):
        if name == "aiortc" or name.startswith("aiortc."):
            raise ImportError("aiortc unavailable (test)")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_aiortc)
    import importlib.util

    from ai_rtc_agent_tpu.media import native
    from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider, get_provider

    native_tier_viable = (
        native.load() is not None
        # the native tier also needs the secure stack's crypto backend —
        # without it every browser session would die at setup, so the
        # documented degrade is a WORKING loopback (signaling.py r5)
        and importlib.util.find_spec("cryptography") is not None
    )
    if native_tier_viable:
        assert isinstance(get_provider(), NativeRtpProvider)
    else:
        assert isinstance(get_provider(), LoopbackProvider)
    assert isinstance(get_provider("loopback"), LoopbackProvider)
