"""DTLS 1.2 (server/secure/dtls.py): in-memory handshake matrix plus live
interop against the system OpenSSL CLI — the same TLS stack family a
browser's WebRTC brings, which is what the reference's aiortc tier
ultimately speaks (reference agent.py:13-20).
"""

import pytest

# the secure tier's crypto backend is optional at the package level
# (signaling degrades to loopback without it) — these tests must SKIP,
# not fail collection, on a box without it (resilience PR satellite)
pytest.importorskip("cryptography", reason="secure tier needs cryptography")

import json
import os
import shutil
import socket
import subprocess
import threading

import pytest

from ai_rtc_agent_tpu.server.secure.dtls import (
    DTLS_12,
    GROUP_X25519,
    HS_HEADER_LEN,
    DtlsEndpoint,
    DtlsError,
    generate_certificate,
)
from ai_rtc_agent_tpu.server.secure.srtp import derive_srtp_contexts

OPENSSL = shutil.which("openssl")


def run_handshake(server, client, drop=None, max_rounds=80, duplicate=False):
    """Pump datagrams between the two sans-IO endpoints until quiescent.
    `drop`: set of 0-based indices of datagrams to drop (loss injection);
    `duplicate`: deliver every datagram twice (duplication injection)."""
    n = 0
    retransmits = 0
    inflight = [("s", d) for d in client.start()]
    while n < max_rounds * 10:
        if not inflight:
            if server.established and client.established:
                break
            if server.failed or client.failed:
                break
            # a dropped flight stalled the pumps — drive a retransmit timer
            retransmits += 1
            if retransmits > 5:
                break
            src = client if not client.established else server
            inflight = [
                ("s" if src is client else "c", d) for d in src.retransmit()
            ]
            if not inflight:
                break
            continue
        to, dgram = inflight.pop(0)
        n += 1
        if drop and (n - 1) in drop:
            continue
        target, back = (server, "c") if to == "s" else (client, "s")
        outs = target.handle_datagram(dgram)
        if duplicate:
            outs = outs + target.handle_datagram(dgram)
        inflight.extend((back, d) for d in outs)
        if duplicate and server.established and client.established:
            break  # echo amplification has no more work to do
    return server, client


class TestInMemoryHandshake:
    def test_basic_handshake_and_exporter(self):
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        run_handshake(server, client)
        assert server.established and client.established
        assert server.failed is None and client.failed is None
        assert (
            server.export_srtp_keying_material()
            == client.export_srtp_keying_material()
        )
        assert server.srtp_profile == 1 and client.srtp_profile == 1

    def test_application_data_both_ways(self):
        server = DtlsEndpoint("server")
        client = DtlsEndpoint("client")
        run_handshake(server, client)
        for d in client.send_application_data(b"c->s"):
            server.handle_datagram(d)
        for d in server.send_application_data(b"s->c"):
            client.handle_datagram(d)
        assert server.recv_application_data() == [b"c->s"]
        assert client.recv_application_data() == [b"s->c"]

    def test_mutual_cert_fingerprint_verification(self):
        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint(
            "server",
            scert,
            request_client_cert=True,
            verify_fingerprint=ccert.fingerprint,
        )
        client = DtlsEndpoint("client", ccert, verify_fingerprint=scert.fingerprint)
        run_handshake(server, client)
        assert server.established and client.established
        assert server.peer_fingerprint() == ccert.fingerprint

    def test_fingerprint_mismatch_fails_handshake(self):
        scert, ccert, other = (
            generate_certificate(),
            generate_certificate(),
            generate_certificate(),
        )
        server = DtlsEndpoint(
            "server",
            scert,
            request_client_cert=True,
            verify_fingerprint=other.fingerprint,  # NOT the client's
        )
        client = DtlsEndpoint("client", ccert)
        run_handshake(server, client)
        assert not server.established
        assert "fingerprint mismatch" in (server.failed or "")

    def test_lost_server_flight_recovers_via_retransmit(self):
        server = DtlsEndpoint("server")
        client = DtlsEndpoint("client")
        # drop the server's flight-4 datagram (index 2: ch1, hvr, ch2 → [2]
        # is the first server flight after ch2)
        run_handshake(server, client, drop={3})
        assert server.established and client.established

    def test_fragmentation_reassembly(self):
        server = DtlsEndpoint("server")
        client = DtlsEndpoint("client")
        server.MTU = 200  # force the Certificate message to fragment
        run_handshake(server, client)
        assert server.established and client.established

    def test_srtp_contexts_from_exporter_interoperate(self):
        server = DtlsEndpoint("server")
        client = DtlsEndpoint("client")
        run_handshake(server, client)
        km = server.export_srtp_keying_material()
        s_tx, s_rx = derive_srtp_contexts(km, is_server=True)
        c_tx, c_rx = derive_srtp_contexts(
            client.export_srtp_keying_material(), is_server=False
        )
        import struct

        pkt = struct.pack("!BBHII", 0x80, 96, 1, 0, 0xAA) + b"x" * 64
        assert c_rx.unprotect(s_tx.protect(pkt)) == pkt
        assert s_rx.unprotect(c_tx.protect(pkt)) == pkt

    def test_no_common_srtp_profile_leaves_none(self):
        server = DtlsEndpoint("server", srtp_profiles=(0x0042,))  # unknown
        client = DtlsEndpoint("client")
        run_handshake(server, client)
        assert server.established
        assert server.srtp_profile is None

    def test_aead_profile_negotiated_when_cm_not_offered(self):
        """A peer offering ONLY RFC 7714 AEAD gets it; the exporter sizes
        itself to the profile (2*(16+12)=56); SRTP contexts interoperate."""
        from ai_rtc_agent_tpu.server.secure.srtp import (
            PROFILE_AEAD_AES_128_GCM,
        )

        server = DtlsEndpoint("server")
        client = DtlsEndpoint(
            "client", srtp_profiles=(PROFILE_AEAD_AES_128_GCM,)
        )
        run_handshake(server, client)
        assert server.established
        assert server.srtp_profile == PROFILE_AEAD_AES_128_GCM
        km_s = server.export_srtp_keying_material()
        km_c = client.export_srtp_keying_material()
        assert km_s == km_c and len(km_s) == 56
        s_tx, s_rx = derive_srtp_contexts(
            km_s, is_server=True, profile=PROFILE_AEAD_AES_128_GCM
        )
        c_tx, c_rx = derive_srtp_contexts(
            km_c, is_server=False, profile=PROFILE_AEAD_AES_128_GCM
        )
        import struct

        pkt = struct.pack("!BBHII", 0x80, 96, 1, 0, 0xAA) + b"x" * 64
        assert c_rx.unprotect(s_tx.protect(pkt)) == pkt
        assert s_rx.unprotect(c_tx.protect(pkt)) == pkt

    def test_cm_profile_preferred_when_both_offered(self):
        """Until the AEAD KDF is validated against a real peer, the
        openssl-keymat-validated CM profile wins (docs/security.md)."""
        server = DtlsEndpoint("server")
        client = DtlsEndpoint("client")  # default: offers both
        run_handshake(server, client)
        assert server.srtp_profile == 0x0001

    def test_chrome_shaped_client_hello_tolerated(self):
        """BoringSSL (Chrome's stack) sends GREASE cipher/extension values,
        unknown extensions, a non-empty session id, and a 4-profile
        use_srtp list — all of which must be skipped, not choked on
        (RFC 8701: unknown values MUST be ignored)."""
        import os as _os
        import struct as _s

        server = DtlsEndpoint("server")
        client_random = _os.urandom(32)

        def chrome_ch(cookie: bytes) -> bytes:
            exts = b""
            exts += _s.pack("!HH", 0x3A3A, 1) + b"\x00"  # GREASE ext
            exts += _s.pack("!HHH", 0x000A, 8, 6) + _s.pack(
                "!HHH", 0x7A7A, 0x001D, 0x0017  # GREASE group first
            )
            exts += _s.pack("!HH", 0x000B, 2) + b"\x01\x00"
            exts += _s.pack("!HHH", 0x000D, 6, 4) + _s.pack(
                "!HH", 0x0403, 0x0804
            )
            profiles = _s.pack("!HHHH", 0x0007, 0x0008, 0x0001, 0x0002)
            exts += (
                _s.pack("!HH", 0x000E, len(profiles) + 3)
                + _s.pack("!H", len(profiles))
                + profiles
                + b"\x00"
            )
            exts += _s.pack("!HH", 0x0017, 0)
            exts += _s.pack("!HH", 0x0023, 0)  # session_ticket
            exts += _s.pack("!HH", 0xFF01, 1) + b"\x00"
            session_id = _os.urandom(32)  # BoringSSL sends a fake one
            body = _s.pack("!H", DTLS_12) + client_random
            body += _s.pack("!B", len(session_id)) + session_id
            body += _s.pack("!B", len(cookie)) + cookie
            ciphers = _s.pack(
                "!HHHH", 0x8A8A, 0xC02B, 0xC02F, 0x00FF  # GREASE first
            )
            body += _s.pack("!H", len(ciphers)) + ciphers
            body += b"\x01\x00"
            body += _s.pack("!H", len(exts)) + exts
            hdr = (
                _s.pack("!B", 1)
                + len(body).to_bytes(3, "big")
                + _s.pack("!H", 0 if not cookie else 1)
                + (0).to_bytes(3, "big")
                + len(body).to_bytes(3, "big")
            )
            payload = hdr + body
            return (
                _s.pack("!BH", 22, 0xFEFF)
                + _s.pack("!H", 0)
                + (0 if not cookie else 1).to_bytes(6, "big")
                + _s.pack("!H", len(payload))
                + payload
            )

        (hvr,) = server.handle_datagram(chrome_ch(b""))
        # extract the cookie from the HelloVerifyRequest
        cookie_len = hvr[13 + HS_HEADER_LEN + 2]
        cookie = hvr[
            13 + HS_HEADER_LEN + 3 : 13 + HS_HEADER_LEN + 3 + cookie_len
        ]
        flight = server.handle_datagram(chrome_ch(cookie))
        assert flight, "server did not answer the Chrome-shaped CH2"
        assert server._state == "WAIT_CLIENT_FLIGHT"
        # SRTP: our preference (CM) chosen from Chrome's 4-profile list
        assert server.srtp_profile == 0x0001
        # the GREASE group was skipped; x25519 won
        assert server._ecdh_group == GROUP_X25519

    def test_garbage_datagram_ignored(self):
        server = DtlsEndpoint("server")
        assert server.handle_datagram(b"\x00" * 40) == []
        # random noise must never raise out of the packet handler
        for _ in range(50):
            server.handle_datagram(os.urandom(64))

    def test_malformed_handshake_bodies_discarded_not_crash(self):
        """Crafted truncated handshake messages (empty ClientKeyExchange,
        truncated ClientHello, bogus key share) are spoofable pre-auth —
        they must be SILENTLY DISCARDED (RFC 6347 s4.1.2.7): no uncaught
        exception, and no one-datagram kill of the association."""
        import struct as _s

        def record(hs_type, body, msg_seq=0, seq=0):
            hdr = (
                _s.pack("!B", hs_type)
                + len(body).to_bytes(3, "big")
                + _s.pack("!H", msg_seq)
                + (0).to_bytes(3, "big")
                + len(body).to_bytes(3, "big")
            )
            payload = hdr + body
            return (
                _s.pack("!BH", 22, 0xFEFF)
                + _s.pack("!H", 0)
                + seq.to_bytes(6, "big")
                + _s.pack("!H", len(payload))
                + payload
            )

        for hs_type, body in [
            (16, b""),          # empty ClientKeyExchange
            (1, b"\xfe\xfd"),   # truncated ClientHello
            (15, b"\x04\x03"),  # truncated CertificateVerify
            (11, b"\x00"),      # truncated Certificate
        ]:
            server = DtlsEndpoint("server")
            out = server.handle_datagram(record(hs_type, body))
            assert isinstance(out, list)  # returned, didn't raise
            assert server.failed is None  # association NOT killed

    def test_spoofed_garbage_does_not_brick_pending_handshake(self):
        """A hostile datagram (DTLS content type, garbage body) hitting the
        socket BEFORE the real client's handshake must not prevent that
        handshake from completing (code-review r4: one-datagram DoS)."""
        server = DtlsEndpoint("server")
        client = DtlsEndpoint("client")
        # 20 hostile datagrams first: DTLS-classified garbage + a spoofed
        # plaintext fatal alert
        import struct as _s

        for i in range(20):
            noise = (
                _s.pack("!BH", 22, 0xFEFD)
                + _s.pack("!H", 0)
                + (1000 + i).to_bytes(6, "big")
                + _s.pack("!H", 30)
                + os.urandom(30)
            )
            server.handle_datagram(noise)
        spoofed_alert = (
            _s.pack("!BH", 21, 0xFEFF)
            + _s.pack("!H", 0)
            + (999).to_bytes(6, "big")
            + _s.pack("!H", 2)
            + b"\x02\x28"
        )
        server.handle_datagram(spoofed_alert)
        assert server.failed is None
        run_handshake(server, client)
        assert server.established and client.established

    def test_plaintext_records_dropped_after_handshake(self):
        """A spoofed unencrypted epoch-0 alert must not kill an established
        association (unauthenticated off-path DoS)."""
        server = DtlsEndpoint("server")
        client = DtlsEndpoint("client")
        run_handshake(server, client)
        assert server.established
        import struct as _s

        fatal_alert = (
            _s.pack("!BH", 21, 0xFEFD)
            + _s.pack("!H", 0)
            + (99).to_bytes(6, "big")
            + _s.pack("!H", 2)
            + b"\x02\x28"
        )
        server.handle_datagram(fatal_alert)
        assert server.failed is None
        # the authenticated channel still works
        for d in client.send_application_data(b"still-alive"):
            server.handle_datagram(d)
        assert server.recv_application_data() == [b"still-alive"]

    def test_cert_without_certificate_verify_rejected(self):
        """Presenting a (replayed) certificate but skipping
        CertificateVerify must fail the handshake — possession of the
        private key is the authentication."""
        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint(
            "server", scert, request_client_cert=True,
            verify_fingerprint=ccert.fingerprint,
        )
        client = DtlsEndpoint("client", ccert, verify_fingerprint=scert.fingerprint)
        # sabotage: make the client skip CertificateVerify while still
        # sending its Certificate (simulates a fingerprint replay attack)
        orig = client._flush_handshake

        def no_cv(msgs, _orig=orig):
            from ai_rtc_agent_tpu.server.secure import dtls as D

            kept = [m for m in msgs if m[0] != D.HT_CERTIFICATE_VERIFY]
            return _orig(kept)

        client._flush_handshake = no_cv
        run_handshake(server, client)
        # r5: the CCS state gate (spoofed-CCS immunity) means a CV-less
        # client now STALLS pre-epoch-1 instead of drawing a fatal alert —
        # either way it must never authenticate
        assert not server.established
        assert server._cert_verify_ok is False
        assert server._state == "WAIT_CLIENT_FLIGHT"

    def test_declined_certificate_with_pin_fails(self):
        """A peer that answers the CertificateRequest with an EMPTY
        certificate list must not complete a fingerprint-pinned handshake
        (code-review r4: the pin would otherwise be advisory)."""
        from ai_rtc_agent_tpu.server.secure import dtls as D

        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint(
            "server", scert, request_client_cert=True,
            verify_fingerprint=ccert.fingerprint,
        )
        client = DtlsEndpoint("client", ccert)
        orig = client._flush_handshake

        def empty_cert(msgs, _orig=orig):
            out = []
            for t, b, e in msgs:
                if t == D.HT_CERTIFICATE:
                    b = (0).to_bytes(3, "big")  # declare zero certificates
                if t == D.HT_CERTIFICATE_VERIFY:
                    continue  # nothing to prove possession of
                out.append((t, b, e))
            return _orig(out)

        client._flush_handshake = empty_cert
        run_handshake(server, client)
        assert not server.established
        assert "declined to present a certificate" in (server.failed or "")

    def test_reassembly_allocation_bounded(self):
        """Tiny fragments claiming 16 MB totals must not allocate."""
        import struct as _s

        server = DtlsEndpoint("server")
        for msg_seq in range(40):
            body = b"x"
            hdr = (
                _s.pack("!B", 11)
                + (0xFFFFFF).to_bytes(3, "big")  # total: 16 MB claim
                + _s.pack("!H", msg_seq)
                + (0).to_bytes(3, "big")
                + (1).to_bytes(3, "big")
            )
            payload = hdr + body
            rec = (
                _s.pack("!BH", 22, 0xFEFF)
                + _s.pack("!H", 0)
                + msg_seq.to_bytes(6, "big")
                + _s.pack("!H", len(payload))
                + payload
            )
            server.handle_datagram(rec)
        assert len(server._reassembly) == 0


def _serve_one_handshake(sock, ep, result):
    peer = None
    try:
        while not ep.established:
            data, peer = sock.recvfrom(8192)
            for out in ep.handle_datagram(data):
                sock.sendto(out, peer)
        result["keymat"] = ep.export_srtp_keying_material().hex()
        result["profile"] = ep.srtp_profile
    except Exception as e:  # pragma: no cover - surfaced via assert below
        result["error"] = f"{type(e).__name__}: {e}"


def _openssl_s_client_interop(profile_name: str, keymatlen: int):
    """Shared harness: our DTLS server vs `openssl s_client` offering
    ``profile_name``.  Returns (server result dict, openssl stdout,
    exported-keymat candidate strings parsed from the output)."""
    ep = DtlsEndpoint("server", generate_certificate())
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(20)
    port = sock.getsockname()[1]
    result: dict = {}
    t = threading.Thread(target=_serve_one_handshake, args=(sock, ep, result))
    t.start()
    proc = subprocess.run(
        [
            OPENSSL, "s_client", "-dtls1_2",
            "-connect", f"127.0.0.1:{port}",
            "-use_srtp", profile_name,
            "-keymatexport", "EXTRACTOR-dtls_srtp",
            "-keymatexportlen", str(keymatlen),
        ],
        input=b"Q\n",
        capture_output=True,
        timeout=30,
    )
    t.join(timeout=25)
    sock.close()
    out = proc.stdout.decode("utf-8", "replace")
    lines = [ln.strip() for ln in out.splitlines()]
    # openssl prints the keymat either on the label line or the next one
    candidates = [
        lines[i + 1]
        for i, ln in enumerate(lines)
        if ln.startswith("Keying material:") and i + 1 < len(lines)
    ] + [
        ln.split("Keying material:", 1)[1].strip()
        for ln in lines
        if ln.startswith("Keying material:") and ln != "Keying material:"
    ]
    return result, out, candidates


@pytest.mark.skipif(OPENSSL is None, reason="openssl CLI not available")
class TestOpensslInterop:
    def test_openssl_s_client_full_handshake_srtp_keymat(self):
        """The gold-standard artifact: a stock OpenSSL DTLS client (the
        browser-shaped peer) completes the handshake against our server and
        both sides export identical SRTP keying material."""
        result, out, candidates = _openssl_s_client_interop(
            "SRTP_AES128_CM_SHA1_80", 60
        )
        assert "error" not in result, result
        assert result.get("profile") == 1
        assert "Cipher is ECDHE-ECDSA-AES128-GCM-SHA256" in out
        assert "SRTP Extension negotiated, profile=SRTP_AES128_CM_SHA1_80" in out
        assert any(
            c.lower() == result["keymat"] for c in candidates if c
        ), f"openssl keymat {candidates} != ours {result['keymat'][:20]}…"

    def test_openssl_s_client_negotiates_aead_profile(self):
        """openssl offering only SRTP_AEAD_AES_128_GCM negotiates it and
        exports the 56-byte keying material identically."""
        result, out, candidates = _openssl_s_client_interop(
            "SRTP_AEAD_AES_128_GCM", 56
        )
        assert "error" not in result, result
        assert result.get("profile") == 0x0007
        assert "SRTP Extension negotiated, profile=SRTP_AEAD_AES_128_GCM" in out
        assert any(
            c.lower() == result["keymat"] for c in candidates if c
        ), f"openssl keymat mismatch: {candidates}"

    def test_our_client_against_openssl_s_server(self, tmp_path):
        """Reverse direction: our DTLS client handshakes with a stock
        OpenSSL DTLS server (the a=setup:active case)."""
        key = tmp_path / "k.pem"
        crt = tmp_path / "c.pem"
        subprocess.run(
            [
                OPENSSL, "req", "-x509", "-newkey", "ec",
                "-pkeyopt", "ec_paramgen_curve:prime256v1",
                "-keyout", str(key), "-out", str(crt),
                "-days", "2", "-nodes", "-subj", "/CN=ossl-dtls-test",
            ],
            check=True,
            capture_output=True,
            timeout=30,
        )
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()  # free it for s_server
        proc = subprocess.Popen(
            [
                OPENSSL, "s_server", "-dtls1_2",
                "-accept", f"127.0.0.1:{port}",
                "-cert", str(crt), "-key", str(key),
                "-use_srtp", "SRTP_AES128_CM_SHA1_80",
                "-keymatexport", "EXTRACTOR-dtls_srtp",
                "-keymatexportlen", "60",
                "-naccept", "1", "-quiet",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        try:
            import time

            time.sleep(1.0)
            ep = DtlsEndpoint("client", generate_certificate())
            cli = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            cli.settimeout(5)
            cli.connect(("127.0.0.1", port))
            pending = ep.start()
            deadline = time.monotonic() + 20
            while not ep.established and time.monotonic() < deadline:
                for d in pending:
                    cli.send(d)
                pending = []
                try:
                    data = cli.recv(8192)
                except socket.timeout:
                    pending = ep.retransmit()
                    continue
                pending = ep.handle_datagram(data)
                assert ep.failed is None, ep.failed
            assert ep.established
            assert ep.srtp_profile == 1
            assert len(ep.export_srtp_keying_material()) == 60
            cli.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)


def test_certificate_fingerprint_format():
    cert = generate_certificate()
    parts = cert.fingerprint.split(":")
    assert len(parts) == 32
    assert all(len(p) == 2 and p == p.upper() for p in parts)


def test_exporter_requires_handshake():
    ep = DtlsEndpoint("server")
    with pytest.raises(DtlsError):
        ep.export_srtp_keying_material()


def test_reordered_client_flight_still_completes():
    """UDP reorders: the client's multi-datagram final flight (Certificate/
    CKE[/CV] then CCS+Finished) delivered BACKWARDS must still complete —
    out-of-order handshake messages buffer in the reassembly window and
    the early epoch-1 Finished is dropped + recovered via retransmit."""
    server = DtlsEndpoint("server")
    client = DtlsEndpoint("client")
    (ch1,) = client.start()
    (hvr,) = server.handle_datagram(ch1)
    (ch2,) = client.handle_datagram(hvr)
    (flight4,) = server.handle_datagram(ch2)
    final = client.handle_datagram(flight4)
    assert len(final) >= 2  # multi-datagram flight to reorder
    outs = []
    for d in reversed(final):
        outs.extend(server.handle_datagram(d))
    if not server.established:
        # the dropped-early-Finished case: one client retransmit recovers
        for d in client.retransmit():
            outs.extend(server.handle_datagram(d))
    assert server.established, server.failed
    for d in outs:
        client.handle_datagram(d)
    assert client.established, client.failed
    assert (
        server.export_srtp_keying_material()
        == client.export_srtp_keying_material()
    )


def test_duplicated_datagrams_harmless():
    """Every datagram delivered TWICE (duplication, not loss): handshake
    completes and nothing double-processes into a failure."""
    server = DtlsEndpoint("server")
    client = DtlsEndpoint("client")
    run_handshake(server, client, duplicate=True)
    assert server.established and client.established
    assert server.failed is None and client.failed is None

# ----------------------------------------------------------------------
# Advisor r4 hardening: client-auth enforcement, mid-flight plaintext
# spoof immunity, path-bound HVR cookies
# ----------------------------------------------------------------------

import struct as _struct


def _rewrap_hs(dgram: bytes, msg_seq: int) -> bytes:
    """Take a single plaintext handshake record and re-number its handshake
    msg_seq (and record seq) — an off-path attacker impersonating the next
    in-window handshake message with bytes it observed earlier."""
    hdr, payload = bytearray(dgram[:13]), bytearray(dgram[13:])
    _struct.pack_into("!H", payload, 4, msg_seq)
    hdr[5:11] = (1000 + msg_seq).to_bytes(6, "big")
    return bytes(hdr) + bytes(payload)


def _plain_hs_record(hs_type: int, body: bytes, msg_seq: int) -> bytes:
    """Forge a plaintext epoch-0 handshake record from nothing — the
    cheapest datagram an off-path attacker can aim at the port."""
    hs = (
        _struct.pack("!B", hs_type)
        + len(body).to_bytes(3, "big")
        + _struct.pack("!H", msg_seq)
        + (0).to_bytes(3, "big")
        + len(body).to_bytes(3, "big")
        + body
    )
    rec = (
        _struct.pack("!BH", 22, 0xFEFF)
        + _struct.pack("!H", 0)
        + (5000 + msg_seq).to_bytes(6, "big")
        + _struct.pack("!H", len(hs))
        + hs
    )
    return rec


class TestAdvisorR4Hardening:
    def test_client_omitting_certificate_cannot_authenticate(self):
        """Advisor r4 HIGH: a client that simply never sends its Certificate
        (so no CertificateVerify is 'owed') must not complete a handshake
        whose SDP pinned an identity — pre-fix this established."""
        from ai_rtc_agent_tpu.server.secure import dtls as D

        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint(
            "server", scert, request_client_cert=True,
            verify_fingerprint=ccert.fingerprint,
        )
        client = DtlsEndpoint("client", ccert, verify_fingerprint=scert.fingerprint)
        orig = client._flush_handshake

        def no_cert(msgs, _orig=orig):
            kept = [
                m for m in msgs
                if m[0] not in (D.HT_CERTIFICATE, D.HT_CERTIFICATE_VERIFY)
            ]
            return _orig(kept)

        client._flush_handshake = no_cert
        run_handshake(server, client)
        assert not server.established
        # the CKE-before-required-Certificate guard silently discards, so
        # the server must still be parked waiting for a legitimate flight
        assert server._state == "WAIT_CLIENT_FLIGHT"

    def test_certificate_replayed_after_cke_cannot_authenticate(self):
        """Advisor r4 HIGH: Certificate smuggled AFTER ClientKeyExchange
        (dodging the CertificateVerify it owes) must not authenticate even
        though the replayed cert matches the pinned fingerprint."""
        from ai_rtc_agent_tpu.server.secure import dtls as D

        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint(
            "server", scert, request_client_cert=True,
            verify_fingerprint=ccert.fingerprint,
        )
        client = DtlsEndpoint("client", ccert, verify_fingerprint=scert.fingerprint)
        orig = client._flush_handshake

        def reorder_no_cv(msgs, _orig=orig):
            certs = [m for m in msgs if m[0] == D.HT_CERTIFICATE]
            ckes = [m for m in msgs if m[0] == D.HT_CLIENT_KEY_EXCHANGE]
            if certs and ckes:
                msgs = ckes + certs
            else:
                msgs = [m for m in msgs if m[0] != D.HT_CERTIFICATE_VERIFY]
            return _orig(msgs)

        client._flush_handshake = reorder_no_cv
        run_handshake(server, client)
        assert not server.established
        assert server._state == "WAIT_CLIENT_FLIGHT"

    def test_spoofed_client_hello_mid_flight_harmless(self):
        """Advisor r4 MEDIUM: one spoofed plaintext ClientHello with an
        in-window msg_seq, landing while the server waits for the client
        flight, must not wedge the handshake (pre-fix it re-entered the
        hello logic, consumed a msg_seq and overwrote _last_flight)."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        assert server._state == "WAIT_CLIENT_FLIGHT"
        seq_before = server._recv_next_seq
        flight_before = list(server._last_flight)
        # blind off-path spoof: a hello whose cookie cannot match (a
        # replayed valid-cookie hello instead triggers the documented
        # lockstep-restart path — see the HVR-restart test)
        other = DtlsEndpoint("client", generate_certificate())
        (blind,) = other.start()
        spoof = _rewrap_hs(blind, server._recv_next_seq)
        assert server.handle_datagram(spoof) == []
        assert server._state == "WAIT_CLIENT_FLIGHT"
        assert server._recv_next_seq == seq_before
        assert server._last_flight == flight_before
        # and the real handshake still completes
        outs = []
        for d in client.handle_datagram(flight4):
            outs.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in outs:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_spoofed_hvr_mid_flight_harmless(self):
        """Advisor r4 MEDIUM (client side): a spoofed HelloVerifyRequest
        after the real ServerHello must not reset the transcript or emit a
        fresh ClientHello."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        final = client.handle_datagram(flight4)
        transcript_before = bytes(client._session_hash_input)
        spoof = _rewrap_hs(hvr, client._recv_next_seq)
        assert client.handle_datagram(spoof) == []
        assert bytes(client._session_hash_input) == transcript_before
        outs = []
        for d in final:
            outs.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in outs:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_cookie_bound_to_source_address(self):
        """Advisor r4 LOW: a cookie minted for one source address must not
        validate a ClientHello replayed from a spoofed source — the server
        answers with another HVR (small), never the ~1.5 KB cert flight."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        addr_a = ("198.51.100.7", 40000)
        addr_b = ("203.0.113.9", 40000)
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1, addr_a)
        (ch2,) = client.handle_datagram(hvr)
        out = server.handle_datagram(ch2, addr_b)  # spoofed source
        # cookie minted for A fails from B: the reply is one HVR (smaller
        # than the request — no amplification) and nothing is consumed
        assert len(out) == 1 and out[0][13] == 3
        assert server._state == "WAIT_CH2"
        # the same CH2 from the real address still completes the exchange
        out = server.handle_datagram(ch2, addr_a)
        assert len(out) >= 1 and out[0][13] == 2  # ServerHello flight
        assert server._state == "WAIT_CLIENT_FLIGHT"

    def test_handshake_completes_with_consistent_address(self):
        """Positive control for the path-bound cookie: the same source
        address end-to-end still completes (and without any address the
        binding degrades to client_random-only, covered by every other
        test in this file)."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        addr = ("198.51.100.7", 40000)
        inflight = client.start()
        for _ in range(50):
            if server.established and client.established:
                break
            back = []
            for d in inflight:
                back.extend(server.handle_datagram(d, addr))
            inflight = []
            for d in back:
                inflight.extend(client.handle_datagram(d))
        assert server.established and client.established
        assert (
            server.export_srtp_keying_material()
            == client.export_srtp_keying_material()
        )

    def test_spoofed_hvr_between_ch2_and_serverhello_recovers(self):
        """Code review r5: the CH2→ServerHello window — a replayed HVR
        there used to reset the transcript and turn the real server flight
        into a fatal SKE signature failure.  With the stateless hello
        phase it now costs one benign restart round and the handshake
        still completes."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        # client sits between CH2 and the (not yet delivered) ServerHello;
        # a replayed HVR restarts its hello — both sides re-lockstep
        spoof = _rewrap_hs(hvr, client._recv_next_seq)
        inflight = client.handle_datagram(spoof)
        assert client.failed is None
        for _ in range(30):
            if server.established and client.established:
                break
            back = []
            for d in inflight:
                back.extend(server.handle_datagram(d))
            inflight = []
            for d in back:
                inflight.extend(client.handle_datagram(d))
        assert server.established, server.failed
        assert client.established, client.failed

    def test_empty_certificate_without_pin_fails_fatally(self):
        """Code review r5: a spec-legal empty certificate list answering a
        CertificateRequest must produce a FATAL alert when auth is
        required, not a silent retransmit livelock."""
        from ai_rtc_agent_tpu.server.secure import dtls as D

        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint("server", scert, request_client_cert=True)
        client = DtlsEndpoint("client", ccert)
        orig = client._flush_handshake

        def empty_cert(msgs, _orig=orig):
            out = []
            for t, b, e in msgs:
                if t == D.HT_CERTIFICATE:
                    b = (0).to_bytes(3, "big")
                if t == D.HT_CERTIFICATE_VERIFY:
                    continue
                out.append((t, b, e))
            return _orig(out)

        client._flush_handshake = empty_cert
        run_handshake(server, client)
        assert not server.established
        assert "empty certificate list" in (server.failed or "")

    def test_spoofed_shd_replay_does_not_refork_final_flight(self):
        """Code review r5: an EMPTY spoofed ServerHelloDone after the client
        already sent its final flight must not re-run _client_final_flight
        (which would regenerate the ECDH key and fork the transcript)."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        final = client.handle_datagram(flight4)
        assert client._state == "WAIT_SERVER_FINISHED"
        shd = _plain_hs_record(14, b"", client._recv_next_seq)
        key_before = client._pre_master
        assert client.handle_datagram(shd) == []
        assert client._pre_master == key_before
        outs = []
        for d in final:
            outs.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in outs:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_replayed_flight4_record_harmless(self):
        """Code review r5: the server's own flight-4 Certificate replayed
        with a bumped msg_seq after the client processed the flight must be
        discarded (repeat guard), not re-transcribed."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        final = client.handle_datagram(flight4)
        # pull the Certificate record (hs type 11) out of flight 4
        cert_rec = None
        off = 0
        while off + 13 <= len(flight4):
            (rlen,) = _struct.unpack_from("!H", flight4, off + 11)
            rec = flight4[off : off + 13 + rlen]
            if rec[0] == 22 and rec[13] == 11:
                cert_rec = rec
            off += 13 + rlen
        assert cert_rec is not None
        transcript_before = bytes(client._session_hash_input)
        spoof = _rewrap_hs(cert_rec, client._recv_next_seq)
        assert client.handle_datagram(spoof) == []
        assert bytes(client._session_hash_input) == transcript_before
        outs = []
        for d in final:
            outs.extend(server.handle_datagram(d))
        for d in outs:
            client.handle_datagram(d)
        assert server.established and client.established

    def test_unknown_handshake_type_does_not_consume_msg_seq(self):
        """Code review r5: a handshake message matching no state branch must
        REWIND the msg_seq cursor — silently consuming it would turn the
        real peer's next message into a permanent duplicate (livelock)."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        # server sits in WAIT_CH2 expecting the real CH2 at this msg_seq
        seq = server._recv_next_seq
        spoof = _plain_hs_record(99, b"junk", seq)
        assert server.handle_datagram(spoof) == []
        assert server._recv_next_seq == seq
        (flight4,) = server.handle_datagram(ch2)  # real CH2 still lands
        outs = []
        for d in client.handle_datagram(flight4):
            outs.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in outs:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_malformed_hvr_spoof_does_not_burn_real_hvr(self):
        """Code review r5 (pass 3): a malformed empty-body HVR spoofed at
        msg_seq 0 must rewind _hvr_seen, or the real server HVR at that
        seq is dropped forever (silent permanent wedge)."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        assert client.handle_datagram(_plain_hs_record(3, b"", 0)) == []
        assert client._hvr_count == 0
        (hvr,) = server.handle_datagram(ch1)
        outs = client.handle_datagram(hvr)  # real HVR must still work
        assert len(outs) == 1  # CH2 went out
        (flight4,) = server.handle_datagram(outs[0])
        back = []
        for d in client.handle_datagram(flight4):
            back.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in back:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_spoofed_cookieless_ch_in_wait_ch2_rewound(self):
        """Code review r5 (pass 3): a cookie-less ClientHello spoofed into
        the WAIT_CH2 window must not consume the real CH2's msg_seq or
        overwrite _last_flight with an attacker-addressed HVR."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        assert server._state == "WAIT_CH2"
        seq = server._recv_next_seq
        # forge a fresh cookie-less CH at the real CH2's msg_seq
        other = DtlsEndpoint("client", generate_certificate())
        (spoof_src,) = other.start()
        spoof = _rewrap_hs(spoof_src, seq)
        flight_before = list(server._last_flight)
        out = server.handle_datagram(spoof)
        # stateless HVR reply; NOTHING of the association is consumed
        assert len(out) == 1 and out[0][13] == 3
        assert server._recv_next_seq == seq
        assert server._last_flight == flight_before
        (flight4,) = server.handle_datagram(ch2)  # real CH2 still lands
        back = []
        for d in client.handle_datagram(flight4):
            back.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in back:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_truncated_serverhello_spoof_rewinds_server_random(self):
        """Code review r5 (pass 3): a truncated spoofed ServerHello must
        rewind _server_random/_record_version, or the real server flight
        trips the repeat guard forever."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        spoof = _plain_hs_record(2, os.urandom(34), client._recv_next_seq)
        assert client.handle_datagram(spoof) == []
        assert client._server_random == b""
        back = []
        for d in client.handle_datagram(flight4):  # real flight still lands
            back.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in back:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_spoofed_plaintext_finished_harmless_both_roles(self):
        """Code review r5 (pass 4): a forged epoch-0 Finished must be
        rewound-and-dropped in both roles — a legitimate Finished only ever
        arrives encrypted on epoch 1, after the peer's CCS."""
        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint(
            "server", scert, request_client_cert=True,
            verify_fingerprint=ccert.fingerprint,
        )
        client = DtlsEndpoint("client", ccert, verify_fingerprint=scert.fingerprint)
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        # server in WAIT_CLIENT_FLIGHT: empty spoofed Finished (the 0-byte
        # forgery that used to trip the fatal auth check)
        assert server.handle_datagram(
            _plain_hs_record(20, b"", server._recv_next_seq)
        ) == []
        assert server.failed is None
        final = client.handle_datagram(flight4)
        # client in WAIT_SERVER_FINISHED: garbage spoofed plaintext Finished
        assert client.handle_datagram(
            _plain_hs_record(20, os.urandom(12), client._recv_next_seq)
        ) == []
        assert client.failed is None
        outs = []
        for d in final:
            outs.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in outs:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_malformed_cv_spoof_discarded_real_cv_still_lands(self):
        """Code review r5 (pass 4): a structurally-broken CertificateVerify
        (empty body / unknown alg) is a discardable forgery; only a failed
        SIGNATURE check may kill the association."""
        scert, ccert = generate_certificate(), generate_certificate()
        server = DtlsEndpoint(
            "server", scert, request_client_cert=True,
            verify_fingerprint=ccert.fingerprint,
        )
        client = DtlsEndpoint("client", ccert, verify_fingerprint=scert.fingerprint)
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        (flight4,) = server.handle_datagram(ch2)
        final = client.handle_datagram(flight4)
        # deliver Certificate+CKE, hold back the real CV
        server.handle_datagram(final[0])
        assert server._peer_key_share is not None
        assert server.handle_datagram(
            _plain_hs_record(15, b"", server._recv_next_seq)
        ) == []
        assert server.failed is None
        outs = []
        for d in final[1:]:
            outs.extend(server.handle_datagram(d))
        assert server.established, server.failed
        for d in outs:
            client.handle_datagram(d)
        assert client.established, client.failed

    def test_stale_seq_dup_from_wrong_address_gets_no_retransmit(self):
        """Code review r5 (pass 4): the duplicate-triggered flight resend is
        address-gated — a 25-byte stale-seq forgery from a spoofed source
        must not extract the ~1.5 KB flight (amplification)."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        addr_a = ("198.51.100.7", 40000)
        addr_b = ("203.0.113.9", 666)
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1, addr_a)
        (ch2,) = client.handle_datagram(hvr)
        flight4 = server.handle_datagram(ch2, addr_a)
        assert flight4
        stale = _plain_hs_record(16, b"x", 0)  # stale CKE: long consumed
        assert server.handle_datagram(stale, addr_b) == []
        # the real peer's address still gets the recovery resend
        assert server.handle_datagram(stale, addr_a) == flight4

    def test_hvr_restart_budget_fails_loudly(self):
        """Code review r5 (pass 6): exhausting the HVR restart budget must
        set `failed` (signaling can re-offer) — never a silent livelock."""
        client = DtlsEndpoint("client", generate_certificate())
        client.start()
        for i in range(10):
            bogus = _plain_hs_record(3, b"\xfe\xff" + b"\x10" + os.urandom(16), i)
            client.handle_datagram(bogus)
            if client.failed:
                break
        assert client.failed is not None
        assert "restart budget" in client.failed

    def test_replayed_accepted_ch_datagram_not_amplified(self):
        """Code review r5 (pass 6): N copies of the accepted CH2 packed in
        one datagram extract at most ONE flight resend."""
        server = DtlsEndpoint("server", generate_certificate())
        client = DtlsEndpoint("client", generate_certificate())
        (ch1,) = client.start()
        (hvr,) = server.handle_datagram(ch1)
        (ch2,) = client.handle_datagram(hvr)
        flight4 = server.handle_datagram(ch2)
        assert flight4
        replay = ch2 * 10  # 10 records in one datagram
        out = server.handle_datagram(replay)
        assert sum(len(d) for d in out) <= sum(len(d) for d in flight4)
