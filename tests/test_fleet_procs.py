"""Hermetic 3-process fleet acceptance (ISSUE 11).

Three REAL agent processes (tests/fleet_agent_proc.py — the full serving
agent with admission/overload/capacity/drain surfaces, fake pipeline,
loopback media) on loopback ports behind an in-process fleet router:

1. placement by capacity — three offers spread one per agent
   (least-loaded against each agent's own /capacity feed);
2. drain-to-zero — one agent drains via the admission-freeze rung while
   the OTHERS keep delivering every pumped frame, and flips
   ``recyclable`` once its sessions close;
3. crash replacement — a SIGKILLed agent is declared DEAD by the poll
   loop, its client is re-pointed through the webhook path
   (StreamDegraded state=AGENT_DEAD), and the re-offer lands and
   streams on a surviving agent.

One test function: the 3 process spawns (~a second each, concurrent)
are paid once for all three acceptance legs.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.fleet.registry import FleetRegistry
from ai_rtc_agent_tpu.fleet.router import build_router_app
from ai_rtc_agent_tpu.server.events import StreamEventHandler
from ai_rtc_agent_tpu.server.signaling import make_loopback_offer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROC = os.path.join(REPO, "tests", "fleet_agent_proc.py")

AGENT_ENV = {
    # small + deterministic: 2 sessions per agent, no device planes, no
    # warmup drops (pushed == delivered must hold exactly)
    "OVERLOAD_MAX_SESSIONS": "2",
    "WARMUP_FRAMES": "0",
    "DROP_FRAMES": "0",
    "PIPELINE_DEPTH": "1",
    "DEVTEL_ENABLE": "0",
    "SLO_ENABLE": "0",
    "FLIGHT_RECORDER": "0",
    "JAX_PLATFORMS": "cpu",
}


def _spawn_agents(n):
    procs = []
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update(AGENT_ENV)
    for _ in range(n):
        procs.append(subprocess.Popen(
            [sys.executable, PROC, "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env,
        ))
    ports = []
    deadline = time.monotonic() + 60
    for p in procs:
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                f"agent proc died at startup: {p.stderr.read()[-2000:]}"
            )
        ports.append(int(json.loads(line)["port"]))
        assert time.monotonic() < deadline, "agent spawn exceeded budget"
    return procs, ports


def _kill(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        p.stdout.close()
        p.stderr.close()


_OFFER = {
    "room_id": "fleet-room",
    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
}


async def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while True:
        r = await predicate()
        if r:
            return r
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        await asyncio.sleep(0.1)


def test_three_process_fleet(monkeypatch):
    monkeypatch.setenv("FLEET_POLL_S", "0.15")
    monkeypatch.setenv("FLEET_POLL_TIMEOUT_S", "2.0")
    monkeypatch.setenv("FLEET_DEAD_AFTER", "2")
    procs, ports = _spawn_agents(3)
    names = [f"agent{i}" for i in range(3)]
    by_name = dict(zip(names, zip(procs, ports)))
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        import aiohttp

        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        reg = FleetRegistry(dead_after=2)
        app = build_router_app(registry=reg, events_handler=events,
                               poll=True)
        client = TestClient(TestServer(app))
        await client.start_server()
        http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        )

        async def agent_get(port, path):
            async with http.get(f"http://127.0.0.1:{port}{path}") as r:
                return await r.json()

        async def agent_post(port, path, body):
            async with http.post(
                f"http://127.0.0.1:{port}{path}", json=body
            ) as r:
                return await r.json()

        try:
            for name, (_p, port) in by_name.items():
                r = await client.post("/fleet/register", json={
                    "worker_id": name, "public_ip": "127.0.0.1",
                    "public_port": str(port), "status": "ready",
                    "capacity": 2,
                })
                assert r.status == 200

            # let one poll round refresh from the agents' real /capacity
            async def first_poll():
                return all(
                    rec.last_ok is not None for rec in reg.agents.values()
                )

            await _wait_for(first_poll, 10, "first poll round")

            # -- leg 1: placement by capacity spreads one per agent -----
            sids = []
            for _ in range(3):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200, await r.text()
                sids.append(r.headers["X-Stream-Id"])
            owners = {sid: app["session_table"].owner(sid) for sid in sids}
            assert sorted(owners.values()) == sorted(names), owners
            for name in names:
                h = await agent_get(by_name[name][1], "/health")
                assert len(h["sessions"]) == 1, (name, h)

            # every session streams: pushed == delivered, no drops
            for name in names:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 15}
                )
                assert list(pumped["sessions"].values()) == [15], pumped

            # -- leg 2: drain one agent to zero without touching others -
            drain_name = owners[sids[1]]
            keep = [n for n in names if n != drain_name]
            r = await client.post(f"/fleet/drain?agent={drain_name}")
            body = await r.json()
            assert body["draining"] and body["agent_ack"], body
            cap = await agent_get(by_name[drain_name][1], "/capacity")
            assert cap["draining"] and cap["saturated"]
            # a new session never lands on the draining agent
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            extra_owner = app["session_table"].owner(
                r.headers["X-Stream-Id"]
            )
            assert extra_owner in keep
            # the OTHERS keep delivering every frame mid-drain
            for name in keep:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 20}
                )
                total = sum(pumped["sessions"].values())
                expect = 20 * len(pumped["sessions"])
                assert total == expect, (name, pumped)
            # clients hang up on the draining agent -> recyclable
            await agent_post(by_name[drain_name][1], "/_test/close", {})

            async def drained():
                h = await (await client.get("/fleet/health")).json()
                a = h["agents"][drain_name]
                return a["state"] == "DRAINING" and a["recyclable"]

            await _wait_for(drained, 15, "drain to zero")

            # -- leg 3: crash replacement ------------------------------
            crash_name = extra_owner  # owns sessions; NOT the drained box
            crash_sids = [
                sid for sid, e in list(app["session_table"]._m.items())
                if e["agent"] == crash_name
            ]
            assert crash_sids
            by_name[crash_name][0].kill()

            async def dead():
                h = await (await client.get("/fleet/health")).json()
                return h["agents"][crash_name]["state"] == "DEAD"

            await _wait_for(dead, 20, "death detection")

            async def repointed():
                evs = [
                    ev for ev in posted if ev.get("state") == "AGENT_DEAD"
                ]
                got = {ev["stream_id"] for ev in evs}
                return evs if got == set(crash_sids) else None

            events_seen = await _wait_for(repointed, 10, "AGENT_DEAD webhooks")
            assert all(
                ev["event"] == "StreamDegraded" for ev in events_seen
            )

            # the re-pointed client re-offers through the router and
            # lands on the ONE agent still taking sessions...
            survivor = [n for n in keep if n != crash_name][0]
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200, await r.text()
            new_sid = r.headers["X-Stream-Id"]
            assert app["session_table"].owner(new_sid) == survivor
            # ...and the replacement session streams end to end (the
            # agent-side PLI/keyframe machinery re-primes on connect)
            pumped = await agent_post(
                by_name[survivor][1], "/_test/pump", {"frames": 10}
            )
            assert sum(pumped["sessions"].values()) == (
                10 * len(pumped["sessions"])
            )

            # rollup reflects the whole story
            m = await (await client.get("/metrics")).json()
            assert m["fleet_agents_dead"] == 1
            assert m["fleet_agents_draining"] == 1
            assert m["fleet_agents_died_total"] == 1
            assert m["fleet_sessions_repointed_total"] == len(crash_sids)
            assert m["fleet_placements_total"] == 5
        finally:
            await http.close()
            await client.close()

    try:
        asyncio.run(go())
    finally:
        _kill(procs)
