"""Hermetic 3-process fleet acceptance (ISSUE 11).

Three REAL agent processes (tests/fleet_agent_proc.py — the full serving
agent with admission/overload/capacity/drain surfaces, fake pipeline,
loopback media) on loopback ports behind an in-process fleet router:

1. placement by capacity — three offers spread one per agent
   (least-loaded against each agent's own /capacity feed);
2. drain-to-zero — one agent drains via the admission-freeze rung while
   the OTHERS keep delivering every pumped frame, and flips
   ``recyclable`` once its sessions close;
3. crash replacement + journey stitching (ISSUE 13) — the victim's
   sessions degrade first (the real supervisor path: auto flight
   snapshot + StreamDegraded webhook to the router's /fleet/events,
   which auto-captures the agent's ``?journey=`` evidence), THEN the
   agent is SIGKILLed mid-stream: the poll loop declares it DEAD, the
   AGENT_DEAD webhook carries the ``journey_id``, the client re-offers
   echoing it and lands on a survivor as leg 2 — and ONE
   ``GET /fleet/debug/journey/<id>`` returns the stitched incident
   bundle: router journey ring (placed → degraded → agent_dead →
   re_placed) + the dead agent's auto-captured snapshot + the
   survivor's live timeline, all sharing one journey id; the merged
   ``?format=chrome`` export validates with per-agent disjoint pids.

One test function: the 3 process spawns (~a second each, concurrent)
are paid once for all three acceptance legs.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.fleet.registry import FleetRegistry
from ai_rtc_agent_tpu.fleet.router import build_router_app
from ai_rtc_agent_tpu.server.events import StreamEventHandler
from ai_rtc_agent_tpu.server.signaling import make_loopback_offer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROC = os.path.join(REPO, "tests", "fleet_agent_proc.py")

AGENT_ENV = {
    # small + deterministic: 2 sessions per agent, no device planes, no
    # warmup drops (pushed == delivered must hold exactly).  The flight
    # recorder + tracing are ON: the journey-stitch leg needs the
    # victim's auto-captured snapshot and sealed timelines.
    "OVERLOAD_MAX_SESSIONS": "2",
    "WARMUP_FRAMES": "0",
    "DROP_FRAMES": "0",
    "PIPELINE_DEPTH": "1",
    "DEVTEL_ENABLE": "0",
    "SLO_ENABLE": "0",
    "FLIGHT_RECORDER": "1",
    "TRACE_ENABLE": "1",
    "JAX_PLATFORMS": "cpu",
}


def _spawn_agents(n):
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.update(AGENT_ENV)
        # the agent's published identity — journey fragments stamp it,
        # so the merged chrome export can tell the legs' agents apart
        env["WORKER_ID"] = f"agent{i}"
        procs.append(subprocess.Popen(
            [sys.executable, PROC, "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env,
        ))
    ports = []
    deadline = time.monotonic() + 60
    for p in procs:
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                f"agent proc died at startup: {p.stderr.read()[-2000:]}"
            )
        ports.append(int(json.loads(line)["port"]))
        assert time.monotonic() < deadline, "agent spawn exceeded budget"
    return procs, ports


def _kill(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        p.stdout.close()
        p.stderr.close()


_OFFER = {
    "room_id": "fleet-room",
    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
}


async def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while True:
        r = await predicate()
        if r:
            return r
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        await asyncio.sleep(0.1)


def test_three_process_fleet(monkeypatch):
    monkeypatch.setenv("FLEET_POLL_S", "0.15")
    monkeypatch.setenv("FLEET_POLL_TIMEOUT_S", "2.0")
    monkeypatch.setenv("FLEET_DEAD_AFTER", "2")
    procs, ports = _spawn_agents(3)
    names = [f"agent{i}" for i in range(3)]
    by_name = dict(zip(names, zip(procs, ports)))
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        import aiohttp

        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        reg = FleetRegistry(dead_after=2)
        app = build_router_app(registry=reg, events_handler=events,
                               poll=True)
        client = TestClient(TestServer(app))
        await client.start_server()
        http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        )

        async def agent_get(port, path):
            async with http.get(f"http://127.0.0.1:{port}{path}") as r:
                return await r.json()

        async def agent_post(port, path, body):
            async with http.post(
                f"http://127.0.0.1:{port}{path}", json=body
            ) as r:
                return await r.json()

        try:
            for name, (_p, port) in by_name.items():
                r = await client.post("/fleet/register", json={
                    "worker_id": name, "public_ip": "127.0.0.1",
                    "public_port": str(port), "status": "ready",
                    "capacity": 2,
                })
                assert r.status == 200

            # let one poll round refresh from the agents' real /capacity
            async def first_poll():
                return all(
                    rec.last_ok is not None for rec in reg.agents.values()
                )

            await _wait_for(first_poll, 10, "first poll round")

            # -- leg 1: placement by capacity spreads one per agent -----
            sids = []
            jids = {}  # stream id -> journey id (the correlation key)
            for _ in range(3):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200, await r.text()
                sid = r.headers["X-Stream-Id"]
                sids.append(sid)
                # the journey id is minted at placement, threaded to the
                # agent and echoed on the answer
                jids[sid] = r.headers["X-Journey-Id"]
                assert r.headers["X-Journey-Leg"] == "1"
            assert len(set(jids.values())) == 3
            owners = {sid: app["session_table"].owner(sid) for sid in sids}
            assert sorted(owners.values()) == sorted(names), owners
            for name in names:
                h = await agent_get(by_name[name][1], "/health")
                assert len(h["sessions"]) == 1, (name, h)

            # every session streams: pushed == delivered, no drops
            for name in names:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 15}
                )
                assert list(pumped["sessions"].values()) == [15], pumped

            # point every agent's webhook plane at the router's ingest
            # (the production WEBHOOK_URL wiring, set post-spawn because
            # the router's port is only known now)
            events_url = str(client.make_url("/fleet/events"))
            for name in names:
                await agent_post(by_name[name][1], "/_test/webhook",
                                 {"url": events_url, "token": "t"})

            # -- leg 2: drain one agent to zero without touching others -
            drain_name = owners[sids[1]]
            keep = [n for n in names if n != drain_name]
            r = await client.post(f"/fleet/drain?agent={drain_name}")
            body = await r.json()
            assert body["draining"] and body["agent_ack"], body
            cap = await agent_get(by_name[drain_name][1], "/capacity")
            assert cap["draining"] and cap["saturated"]
            # a new session never lands on the draining agent
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            extra_sid = r.headers["X-Stream-Id"]
            jids[extra_sid] = r.headers["X-Journey-Id"]
            extra_owner = app["session_table"].owner(extra_sid)
            assert extra_owner in keep
            # the OTHERS keep delivering every frame mid-drain
            for name in keep:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 20}
                )
                total = sum(pumped["sessions"].values())
                expect = 20 * len(pumped["sessions"])
                assert total == expect, (name, pumped)
            # clients hang up on the draining agent -> recyclable
            await agent_post(by_name[drain_name][1], "/_test/close", {})

            async def drained():
                h = await (await client.get("/fleet/health")).json()
                a = h["agents"][drain_name]
                return a["state"] == "DRAINING" and a["recyclable"]

            await _wait_for(drained, 15, "drain to zero")

            # -- leg 3: crash replacement + journey stitching ----------
            crash_name = extra_owner  # owns sessions; NOT the drained box
            crash_port = by_name[crash_name][1]
            crash_sids = [
                sid for sid, e in list(app["session_table"]._m.items())
                if e["agent"] == crash_name
            ]
            assert crash_sids

            # seal some timelines on the victim (aged frames shed at
            # ingest), then force the real breach path: DEGRADED ->
            # auto flight snapshot -> StreamDegraded webhook -> the
            # router pulls the agent's ?journey= evidence EAGERLY —
            # the records that must survive the SIGKILL below
            pumped = await agent_post(
                crash_port, "/_test/pump", {"frames": 5, "stale": 3}
            )
            assert list(pumped["sessions"].values())
            degraded = await agent_post(crash_port, "/_test/degrade", {})
            assert set(degraded["sessions"].values()) == {"DEGRADED"}
            crash_jids = [jids[sid] for sid in crash_sids]

            async def evidence_banked():
                jl = app["journeys"]
                return all(jl.evidence_for(j) for j in crash_jids)

            await _wait_for(evidence_banked, 15, "evidence auto-capture")

            by_name[crash_name][0].kill()

            async def dead():
                h = await (await client.get("/fleet/health")).json()
                return h["agents"][crash_name]["state"] == "DEAD"

            await _wait_for(dead, 20, "death detection")

            async def repointed():
                evs = [
                    ev for ev in posted if ev.get("state") == "AGENT_DEAD"
                ]
                got = {ev["stream_id"] for ev in evs}
                return evs if got == set(crash_sids) else None

            events_seen = await _wait_for(repointed, 10, "AGENT_DEAD webhooks")
            assert all(
                ev["event"] == "StreamDegraded" for ev in events_seen
            )
            # the re-point webhook teaches the client its journey id
            assert {ev["journey_id"] for ev in events_seen} == set(
                crash_jids
            )

            # the re-pointed client re-offers ECHOING the journey id and
            # lands on the ONE agent still taking sessions as leg 2...
            survivor = [n for n in keep if n != crash_name][0]
            crash_jid = events_seen[0]["journey_id"]
            r = await client.post(
                "/offer", json=_OFFER,
                headers={"X-Journey-Id": crash_jid},
            )
            assert r.status == 200, await r.text()
            new_sid = r.headers["X-Stream-Id"]
            assert app["session_table"].owner(new_sid) == survivor
            assert r.headers["X-Journey-Id"] == crash_jid
            assert r.headers["X-Journey-Leg"] == "2"
            # ...and the replacement session streams end to end (the
            # agent-side PLI/keyframe machinery re-primes on connect),
            # sealing post-re-offer timelines for the bundle below
            pumped = await agent_post(
                by_name[survivor][1], "/_test/pump",
                {"frames": 10, "stale": 3},
            )
            assert sum(pumped["sessions"].values()) == (
                10 * len(pumped["sessions"])
            )

            # -- the ISSUE 13 acceptance: ONE GET returns the stitched
            # incident bundle for the whole cross-process journey
            r = await client.get(f"/fleet/debug/journey/{crash_jid}")
            assert r.status == 200
            bundle = await r.json()
            kinds = [e["kind"] for e in bundle["journey"]["events"]]
            for expected in ("placed", "degraded", "agent_dead",
                             "re_placed"):
                assert expected in kinds, kinds
            legs = bundle["journey"]["legs"]
            assert [(leg["leg"], leg["agent"]) for leg in legs] == [
                (1, crash_name), (2, survivor),
            ]
            # the dead agent's records came from the auto-captured
            # evidence (its process is a corpse by now)...
            ev = [e for e in bundle["evidence"] if e["agent"] == crash_name]
            assert ev
            dead_frag = ev[0]["fragment"]
            dead_snaps = dead_frag["snapshots"]
            assert dead_snaps, dead_frag
            assert all(
                s["journey"]["journey_id"] == crash_jid
                and s["journey"]["agent"] == crash_name
                for s in dead_snaps
            )
            # ...the auto-snapshot holds the supervisor DEGRADED event
            # and the sealed (shed) timelines from before the crash
            assert any(
                e.get("kind") == "supervisor" and e.get("new") == "DEGRADED"
                for s in dead_snaps for e in s["events"]
            )
            assert any(s["frames"] for s in dead_snaps)
            assert {f["journey_id"]
                    for s in dead_snaps for f in s["frames"]} <= {crash_jid}
            assert "unreachable" in {
                f["source"] for f in bundle["fragments"]
            }
            # ...and the survivor's live timeline joins the same journey
            live = [f for f in bundle["fragments"]
                    if f.get("source") == "live"]
            assert [f["agent"] for f in live] == [survivor]
            live_caps = live[0]["sessions"]
            assert new_sid in live_caps
            assert live_caps[new_sid]["journey"]["leg"] == 2
            assert live_caps[new_sid]["frames"]  # post-re-offer timelines
            assert bundle["bundles"], "alert paths sealed no bundle"

            # the merged chrome export validates with per-agent pids
            from test_obs import _validate_chrome

            r = await client.get(
                f"/fleet/debug/journey/{crash_jid}",
                params={"format": "chrome"},
            )
            assert r.status == 200
            evs = _validate_chrome(await r.json())
            agent_by_pid = {
                e["pid"]: e["args"].get("agent") for e in evs
                if e["ph"] == "M" and e["name"] == "process_name"
            }
            assert {crash_name, survivor} <= set(agent_by_pid.values())

            # rollup reflects the whole story
            m = await (await client.get("/metrics")).json()
            assert m["fleet_agents_dead"] == 1
            assert m["fleet_agents_draining"] == 1
            assert m["fleet_agents_died_total"] == 1
            assert m["fleet_sessions_repointed_total"] == len(crash_sids)
            assert m["fleet_placements_total"] == 5
            assert m["journeys_total"] == 4
            assert m["journey_legs_total"] == 5
            assert m["journey_replacements_total"] == 1
            assert m["journey_evidence_captured_total"] >= len(crash_sids)
            assert m["journey_bundles_sealed_total"] >= 1
        finally:
            await http.close()
            await client.close()

    try:
        asyncio.run(go())
    finally:
        _kill(procs)


def test_three_process_migrate_drain(monkeypatch):
    """ISSUE 15 acceptance: ``POST /fleet/drain?mode=migrate`` drains a
    REAL agent process to zero by MOVING its session — export off the
    source, counted-reservation import on a healthy target, a
    StreamMigrated webhook re-points the client, whose echoed re-offer
    is pinned to the target and adopted as journey leg 2 — with every
    pumped frame delivered (before on the source, after on the target)
    and the journey ring showing the ``migrated`` leg.  (The SIGKILL
    fallback path is the previous test, unchanged.)"""
    monkeypatch.setenv("FLEET_POLL_S", "0.15")
    monkeypatch.setenv("FLEET_POLL_TIMEOUT_S", "2.0")
    monkeypatch.setenv("FLEET_DEAD_AFTER", "2")
    procs, ports = _spawn_agents(3)
    names = [f"agent{i}" for i in range(3)]
    by_name = dict(zip(names, zip(procs, ports)))
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        import aiohttp

        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        reg = FleetRegistry(dead_after=2)
        app = build_router_app(registry=reg, events_handler=events,
                               poll=True)
        client = TestClient(TestServer(app))
        await client.start_server()
        http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        )

        async def agent_get(port, path):
            async with http.get(f"http://127.0.0.1:{port}{path}") as r:
                return await r.json()

        async def agent_post(port, path, body):
            async with http.post(
                f"http://127.0.0.1:{port}{path}", json=body
            ) as r:
                return await r.json()

        try:
            for name, (_p, port) in by_name.items():
                r = await client.post("/fleet/register", json={
                    "worker_id": name, "public_ip": "127.0.0.1",
                    "public_port": str(port), "status": "ready",
                    "capacity": 2,
                })
                assert r.status == 200

            async def first_poll():
                return all(
                    rec.last_ok is not None for rec in reg.agents.values()
                )

            await _wait_for(first_poll, 10, "first poll round")

            # one session per agent; every pumped frame delivered
            sids, jids = [], {}
            for _ in range(3):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200, await r.text()
                sid = r.headers["X-Stream-Id"]
                sids.append(sid)
                jids[sid] = r.headers["X-Journey-Id"]
            for name in names:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 10}
                )
                assert list(pumped["sessions"].values()) == [10], pumped

            # move-not-kill: drain the owner of sids[0] with mode=migrate
            victim = app["session_table"].owner(sids[0])
            vic_port = by_name[victim][1]
            r = await client.post(
                f"/fleet/drain?agent={victim}&mode=migrate"
            )
            body = await r.json()
            assert body["draining"] and body["mode"] == "migrate"
            assert body["migrating"] == 1, body

            async def migrated():
                return [e for e in posted
                        if e.get("event") == "StreamMigrated"]

            events_seen = await _wait_for(
                migrated, 15, "StreamMigrated webhook"
            )
            ev = events_seen[0]
            assert ev["stream_id"] == sids[0]
            assert ev["source_agent"] == victim
            assert ev["journey_id"] == jids[sids[0]]
            assert ev["reason"] == "drain"
            target = ev["target_agent"]
            assert target in names and target != victim

            # the re-pointed client re-offers echoing the journey id:
            # pinned to the TARGET (which holds the import), leg 2
            r = await client.post(
                "/offer", json=_OFFER,
                headers={"X-Journey-Id": ev["journey_id"]},
            )
            assert r.status == 200, await r.text()
            assert r.headers["X-Journey-Id"] == ev["journey_id"]
            assert r.headers["X-Journey-Leg"] == "2"
            new_sid = r.headers["X-Stream-Id"]
            assert app["session_table"].owner(new_sid) == target

            # ...and streams: every post-migration frame delivered on
            # the target (its own session + the adopted one)
            pumped = await agent_post(
                by_name[target][1], "/_test/pump", {"frames": 8}
            )
            assert sum(pumped["sessions"].values()) == 8 * len(
                pumped["sessions"]
            )
            assert len(pumped["sessions"]) == 2

            # the client hangs up its OLD connection -> source drains to
            # zero and flips recyclable
            await agent_post(vic_port, "/_test/close", {})

            async def drained():
                h = await (await client.get("/fleet/health")).json()
                a = h["agents"][victim]
                return a["state"] == "DRAINING" and a["recyclable"]

            await _wait_for(drained, 15, "drain to zero")

            # the journey ring tells the move story end to end
            record = app["journeys"].get(ev["journey_id"])
            kinds = [e["kind"] for e in record["events"]]
            assert "migrated" in kinds, kinds
            assert [leg["agent"] for leg in record["legs"]] == [
                victim, target,
            ]
            m = await (await client.get("/metrics")).json()
            assert m["migrations_total"] == 1
            assert m.get("migrations_failed_total", 0) == 0
            assert m["fleet_drains_total"] == 1
        finally:
            await http.close()
            await client.close()

    try:
        asyncio.run(go())
    finally:
        _kill(procs)
