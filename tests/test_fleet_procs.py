"""Hermetic 3-process fleet acceptance (ISSUE 11).

Three REAL agent processes (tests/fleet_agent_proc.py — the full serving
agent with admission/overload/capacity/drain surfaces, fake pipeline,
loopback media) on loopback ports behind an in-process fleet router:

1. placement by capacity — three offers spread one per agent
   (least-loaded against each agent's own /capacity feed);
2. drain-to-zero — one agent drains via the admission-freeze rung while
   the OTHERS keep delivering every pumped frame, and flips
   ``recyclable`` once its sessions close;
3. crash replacement + journey stitching (ISSUE 13) — the victim's
   sessions degrade first (the real supervisor path: auto flight
   snapshot + StreamDegraded webhook to the router's /fleet/events,
   which auto-captures the agent's ``?journey=`` evidence), THEN the
   agent is SIGKILLed mid-stream: the poll loop declares it DEAD, the
   AGENT_DEAD webhook carries the ``journey_id``, the client re-offers
   echoing it and lands on a survivor as leg 2 — and ONE
   ``GET /fleet/debug/journey/<id>`` returns the stitched incident
   bundle: router journey ring (placed → degraded → agent_dead →
   re_placed) + the dead agent's auto-captured snapshot + the
   survivor's live timeline, all sharing one journey id; the merged
   ``?format=chrome`` export validates with per-agent disjoint pids.

One test function: the 3 process spawns (~a second each, concurrent)
are paid once for all three acceptance legs.

ISSUE 16 adds the zero-downtime lifecycle acceptance: a 2-real-process
rolling upgrade (``POST /fleet/upgrade`` — drain-as-move, real
``/admin/recycle`` re-exec respawns read off the inherited stdout pipe,
epoch-bumped re-registration, a final restart-in-place WITH live
sessions through the AGENT_RECYCLED same-box adoption) and a SIGKILL
mid-upgrade halt.  To pay for the added wall-time, the original
3-process composite (whose crash/journey surface the migrate-drain +
upgrade siblings now cover piecewise) moved to the slow tier.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.fleet.registry import FleetRegistry
from ai_rtc_agent_tpu.fleet.router import build_router_app
from ai_rtc_agent_tpu.server.events import StreamEventHandler
from ai_rtc_agent_tpu.server.signaling import make_loopback_offer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROC = os.path.join(REPO, "tests", "fleet_agent_proc.py")

AGENT_ENV = {
    # small + deterministic: 2 sessions per agent, no device planes, no
    # warmup drops (pushed == delivered must hold exactly).  The flight
    # recorder + tracing are ON: the journey-stitch leg needs the
    # victim's auto-captured snapshot and sealed timelines.
    "OVERLOAD_MAX_SESSIONS": "2",
    "WARMUP_FRAMES": "0",
    "DROP_FRAMES": "0",
    "PIPELINE_DEPTH": "1",
    "DEVTEL_ENABLE": "0",
    "SLO_ENABLE": "0",
    "FLIGHT_RECORDER": "1",
    "TRACE_ENABLE": "1",
    "JAX_PLATFORMS": "cpu",
}


def _spawn_agents(n, extra_env=None):
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.update(AGENT_ENV)
        env.update(extra_env or {})
        # the agent's published identity — journey fragments stamp it,
        # so the merged chrome export can tell the legs' agents apart
        env["WORKER_ID"] = f"agent{i}"
        procs.append(subprocess.Popen(
            [sys.executable, PROC, "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env,
        ))
    ports = []
    deadline = time.monotonic() + 60
    for p in procs:
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                f"agent proc died at startup: {p.stderr.read()[-2000:]}"
            )
        ports.append(int(json.loads(line)["port"]))
        assert time.monotonic() < deadline, "agent spawn exceeded budget"
    return procs, ports


def _kill(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        p.stdout.close()
        p.stderr.close()


def _kill_pids(pids):
    """Reap recycle replacements: argv re-exec children of agent procs
    that have since exited — not our children, so SIGKILL by pid."""
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


_OFFER = {
    "room_id": "fleet-room",
    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
}


async def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while True:
        r = await predicate()
        if r:
            return r
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        await asyncio.sleep(0.1)


@pytest.mark.slow  # the lifecycle siblings below cover this composite's
# surfaces piecewise in tier-1; the full 3-process crash/journey story
# stays as the slow-tier integration sweep
def test_three_process_fleet(monkeypatch):
    monkeypatch.setenv("FLEET_POLL_S", "0.15")
    monkeypatch.setenv("FLEET_POLL_TIMEOUT_S", "2.0")
    monkeypatch.setenv("FLEET_DEAD_AFTER", "2")
    procs, ports = _spawn_agents(3)
    names = [f"agent{i}" for i in range(3)]
    by_name = dict(zip(names, zip(procs, ports)))
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        import aiohttp

        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        reg = FleetRegistry(dead_after=2)
        app = build_router_app(registry=reg, events_handler=events,
                               poll=True)
        client = TestClient(TestServer(app))
        await client.start_server()
        http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        )

        async def agent_get(port, path):
            async with http.get(f"http://127.0.0.1:{port}{path}") as r:
                return await r.json()

        async def agent_post(port, path, body):
            async with http.post(
                f"http://127.0.0.1:{port}{path}", json=body
            ) as r:
                return await r.json()

        try:
            for name, (_p, port) in by_name.items():
                r = await client.post("/fleet/register", json={
                    "worker_id": name, "public_ip": "127.0.0.1",
                    "public_port": str(port), "status": "ready",
                    "capacity": 2,
                })
                assert r.status == 200

            # let one poll round refresh from the agents' real /capacity
            async def first_poll():
                return all(
                    rec.last_ok is not None for rec in reg.agents.values()
                )

            await _wait_for(first_poll, 10, "first poll round")

            # -- leg 1: placement by capacity spreads one per agent -----
            sids = []
            jids = {}  # stream id -> journey id (the correlation key)
            for _ in range(3):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200, await r.text()
                sid = r.headers["X-Stream-Id"]
                sids.append(sid)
                # the journey id is minted at placement, threaded to the
                # agent and echoed on the answer
                jids[sid] = r.headers["X-Journey-Id"]
                assert r.headers["X-Journey-Leg"] == "1"
            assert len(set(jids.values())) == 3
            owners = {sid: app["session_table"].owner(sid) for sid in sids}
            assert sorted(owners.values()) == sorted(names), owners
            for name in names:
                h = await agent_get(by_name[name][1], "/health")
                assert len(h["sessions"]) == 1, (name, h)

            # every session streams: pushed == delivered, no drops
            for name in names:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 15}
                )
                assert list(pumped["sessions"].values()) == [15], pumped

            # point every agent's webhook plane at the router's ingest
            # (the production WEBHOOK_URL wiring, set post-spawn because
            # the router's port is only known now)
            events_url = str(client.make_url("/fleet/events"))
            for name in names:
                await agent_post(by_name[name][1], "/_test/webhook",
                                 {"url": events_url, "token": "t"})

            # -- leg 2: drain one agent to zero without touching others -
            drain_name = owners[sids[1]]
            keep = [n for n in names if n != drain_name]
            r = await client.post(f"/fleet/drain?agent={drain_name}")
            body = await r.json()
            assert body["draining"] and body["agent_ack"], body
            cap = await agent_get(by_name[drain_name][1], "/capacity")
            assert cap["draining"] and cap["saturated"]
            # a new session never lands on the draining agent
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            extra_sid = r.headers["X-Stream-Id"]
            jids[extra_sid] = r.headers["X-Journey-Id"]
            extra_owner = app["session_table"].owner(extra_sid)
            assert extra_owner in keep
            # the OTHERS keep delivering every frame mid-drain
            for name in keep:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 20}
                )
                total = sum(pumped["sessions"].values())
                expect = 20 * len(pumped["sessions"])
                assert total == expect, (name, pumped)
            # clients hang up on the draining agent -> recyclable
            await agent_post(by_name[drain_name][1], "/_test/close", {})

            async def drained():
                h = await (await client.get("/fleet/health")).json()
                a = h["agents"][drain_name]
                return a["state"] == "DRAINING" and a["recyclable"]

            await _wait_for(drained, 15, "drain to zero")

            # -- leg 3: crash replacement + journey stitching ----------
            crash_name = extra_owner  # owns sessions; NOT the drained box
            crash_port = by_name[crash_name][1]
            crash_sids = [
                sid for sid, e in list(app["session_table"]._m.items())
                if e["agent"] == crash_name
            ]
            assert crash_sids

            # seal some timelines on the victim (aged frames shed at
            # ingest), then force the real breach path: DEGRADED ->
            # auto flight snapshot -> StreamDegraded webhook -> the
            # router pulls the agent's ?journey= evidence EAGERLY —
            # the records that must survive the SIGKILL below
            pumped = await agent_post(
                crash_port, "/_test/pump", {"frames": 5, "stale": 3}
            )
            assert list(pumped["sessions"].values())
            degraded = await agent_post(crash_port, "/_test/degrade", {})
            assert set(degraded["sessions"].values()) == {"DEGRADED"}
            crash_jids = [jids[sid] for sid in crash_sids]

            async def evidence_banked():
                jl = app["journeys"]
                return all(jl.evidence_for(j) for j in crash_jids)

            await _wait_for(evidence_banked, 15, "evidence auto-capture")

            by_name[crash_name][0].kill()

            async def dead():
                h = await (await client.get("/fleet/health")).json()
                return h["agents"][crash_name]["state"] == "DEAD"

            await _wait_for(dead, 20, "death detection")

            async def repointed():
                evs = [
                    ev for ev in posted if ev.get("state") == "AGENT_DEAD"
                ]
                got = {ev["stream_id"] for ev in evs}
                return evs if got == set(crash_sids) else None

            events_seen = await _wait_for(repointed, 10, "AGENT_DEAD webhooks")
            assert all(
                ev["event"] == "StreamDegraded" for ev in events_seen
            )
            # the re-point webhook teaches the client its journey id
            assert {ev["journey_id"] for ev in events_seen} == set(
                crash_jids
            )

            # the re-pointed client re-offers ECHOING the journey id and
            # lands on the ONE agent still taking sessions as leg 2...
            survivor = [n for n in keep if n != crash_name][0]
            crash_jid = events_seen[0]["journey_id"]
            r = await client.post(
                "/offer", json=_OFFER,
                headers={"X-Journey-Id": crash_jid},
            )
            assert r.status == 200, await r.text()
            new_sid = r.headers["X-Stream-Id"]
            assert app["session_table"].owner(new_sid) == survivor
            assert r.headers["X-Journey-Id"] == crash_jid
            assert r.headers["X-Journey-Leg"] == "2"
            # ...and the replacement session streams end to end (the
            # agent-side PLI/keyframe machinery re-primes on connect),
            # sealing post-re-offer timelines for the bundle below
            pumped = await agent_post(
                by_name[survivor][1], "/_test/pump",
                {"frames": 10, "stale": 3},
            )
            assert sum(pumped["sessions"].values()) == (
                10 * len(pumped["sessions"])
            )

            # -- the ISSUE 13 acceptance: ONE GET returns the stitched
            # incident bundle for the whole cross-process journey
            r = await client.get(f"/fleet/debug/journey/{crash_jid}")
            assert r.status == 200
            bundle = await r.json()
            kinds = [e["kind"] for e in bundle["journey"]["events"]]
            for expected in ("placed", "degraded", "agent_dead",
                             "re_placed"):
                assert expected in kinds, kinds
            legs = bundle["journey"]["legs"]
            assert [(leg["leg"], leg["agent"]) for leg in legs] == [
                (1, crash_name), (2, survivor),
            ]
            # the dead agent's records came from the auto-captured
            # evidence (its process is a corpse by now)...
            ev = [e for e in bundle["evidence"] if e["agent"] == crash_name]
            assert ev
            dead_frag = ev[0]["fragment"]
            dead_snaps = dead_frag["snapshots"]
            assert dead_snaps, dead_frag
            assert all(
                s["journey"]["journey_id"] == crash_jid
                and s["journey"]["agent"] == crash_name
                for s in dead_snaps
            )
            # ...the auto-snapshot holds the supervisor DEGRADED event
            # and the sealed (shed) timelines from before the crash
            assert any(
                e.get("kind") == "supervisor" and e.get("new") == "DEGRADED"
                for s in dead_snaps for e in s["events"]
            )
            assert any(s["frames"] for s in dead_snaps)
            assert {f["journey_id"]
                    for s in dead_snaps for f in s["frames"]} <= {crash_jid}
            assert "unreachable" in {
                f["source"] for f in bundle["fragments"]
            }
            # ...and the survivor's live timeline joins the same journey
            live = [f for f in bundle["fragments"]
                    if f.get("source") == "live"]
            assert [f["agent"] for f in live] == [survivor]
            live_caps = live[0]["sessions"]
            assert new_sid in live_caps
            assert live_caps[new_sid]["journey"]["leg"] == 2
            assert live_caps[new_sid]["frames"]  # post-re-offer timelines
            assert bundle["bundles"], "alert paths sealed no bundle"

            # the merged chrome export validates with per-agent pids
            from test_obs import _validate_chrome

            r = await client.get(
                f"/fleet/debug/journey/{crash_jid}",
                params={"format": "chrome"},
            )
            assert r.status == 200
            evs = _validate_chrome(await r.json())
            agent_by_pid = {
                e["pid"]: e["args"].get("agent") for e in evs
                if e["ph"] == "M" and e["name"] == "process_name"
            }
            assert {crash_name, survivor} <= set(agent_by_pid.values())

            # rollup reflects the whole story
            m = await (await client.get("/metrics")).json()
            assert m["fleet_agents_dead"] == 1
            assert m["fleet_agents_draining"] == 1
            assert m["fleet_agents_died_total"] == 1
            assert m["fleet_sessions_repointed_total"] == len(crash_sids)
            assert m["fleet_placements_total"] == 5
            assert m["journeys_total"] == 4
            assert m["journey_legs_total"] == 5
            assert m["journey_replacements_total"] == 1
            assert m["journey_evidence_captured_total"] >= len(crash_sids)
            assert m["journey_bundles_sealed_total"] >= 1
        finally:
            await http.close()
            await client.close()

    try:
        asyncio.run(go())
    finally:
        _kill(procs)


def test_three_process_migrate_drain(monkeypatch):
    """ISSUE 15 acceptance: ``POST /fleet/drain?mode=migrate`` drains a
    REAL agent process to zero by MOVING its session — export off the
    source, counted-reservation import on a healthy target, a
    StreamMigrated webhook re-points the client, whose echoed re-offer
    is pinned to the target and adopted as journey leg 2 — with every
    pumped frame delivered (before on the source, after on the target)
    and the journey ring showing the ``migrated`` leg.  (The SIGKILL
    fallback path is the previous test, unchanged.)"""
    monkeypatch.setenv("FLEET_POLL_S", "0.15")
    monkeypatch.setenv("FLEET_POLL_TIMEOUT_S", "2.0")
    monkeypatch.setenv("FLEET_DEAD_AFTER", "2")
    procs, ports = _spawn_agents(3)
    names = [f"agent{i}" for i in range(3)]
    by_name = dict(zip(names, zip(procs, ports)))
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        import aiohttp

        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        reg = FleetRegistry(dead_after=2)
        app = build_router_app(registry=reg, events_handler=events,
                               poll=True)
        client = TestClient(TestServer(app))
        await client.start_server()
        http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        )

        async def agent_get(port, path):
            async with http.get(f"http://127.0.0.1:{port}{path}") as r:
                return await r.json()

        async def agent_post(port, path, body):
            async with http.post(
                f"http://127.0.0.1:{port}{path}", json=body
            ) as r:
                return await r.json()

        try:
            for name, (_p, port) in by_name.items():
                r = await client.post("/fleet/register", json={
                    "worker_id": name, "public_ip": "127.0.0.1",
                    "public_port": str(port), "status": "ready",
                    "capacity": 2,
                })
                assert r.status == 200

            async def first_poll():
                return all(
                    rec.last_ok is not None for rec in reg.agents.values()
                )

            await _wait_for(first_poll, 10, "first poll round")

            # one session per agent; every pumped frame delivered
            sids, jids = [], {}
            for _ in range(3):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200, await r.text()
                sid = r.headers["X-Stream-Id"]
                sids.append(sid)
                jids[sid] = r.headers["X-Journey-Id"]
            for name in names:
                pumped = await agent_post(
                    by_name[name][1], "/_test/pump", {"frames": 10}
                )
                assert list(pumped["sessions"].values()) == [10], pumped

            # move-not-kill: drain the owner of sids[0] with mode=migrate
            victim = app["session_table"].owner(sids[0])
            vic_port = by_name[victim][1]
            r = await client.post(
                f"/fleet/drain?agent={victim}&mode=migrate"
            )
            body = await r.json()
            assert body["draining"] and body["mode"] == "migrate"
            assert body["migrating"] == 1, body

            async def migrated():
                return [e for e in posted
                        if e.get("event") == "StreamMigrated"]

            events_seen = await _wait_for(
                migrated, 15, "StreamMigrated webhook"
            )
            ev = events_seen[0]
            assert ev["stream_id"] == sids[0]
            assert ev["source_agent"] == victim
            assert ev["journey_id"] == jids[sids[0]]
            assert ev["reason"] == "drain"
            target = ev["target_agent"]
            assert target in names and target != victim

            # the re-pointed client re-offers echoing the journey id:
            # pinned to the TARGET (which holds the import), leg 2
            r = await client.post(
                "/offer", json=_OFFER,
                headers={"X-Journey-Id": ev["journey_id"]},
            )
            assert r.status == 200, await r.text()
            assert r.headers["X-Journey-Id"] == ev["journey_id"]
            assert r.headers["X-Journey-Leg"] == "2"
            new_sid = r.headers["X-Stream-Id"]
            assert app["session_table"].owner(new_sid) == target

            # ...and streams: every post-migration frame delivered on
            # the target (its own session + the adopted one)
            pumped = await agent_post(
                by_name[target][1], "/_test/pump", {"frames": 8}
            )
            assert sum(pumped["sessions"].values()) == 8 * len(
                pumped["sessions"]
            )
            assert len(pumped["sessions"]) == 2

            # the client hangs up its OLD connection -> source drains to
            # zero and flips recyclable
            await agent_post(vic_port, "/_test/close", {})

            async def drained():
                h = await (await client.get("/fleet/health")).json()
                a = h["agents"][victim]
                return a["state"] == "DRAINING" and a["recyclable"]

            await _wait_for(drained, 15, "drain to zero")

            # the journey ring tells the move story end to end
            record = app["journeys"].get(ev["journey_id"])
            kinds = [e["kind"] for e in record["events"]]
            assert "migrated" in kinds, kinds
            assert [leg["agent"] for leg in record["legs"]] == [
                victim, target,
            ]
            m = await (await client.get("/metrics")).json()
            assert m["migrations_total"] == 1
            assert m.get("migrations_failed_total", 0) == 0
            assert m["fleet_drains_total"] == 1
        finally:
            await http.close()
            await client.close()

    try:
        asyncio.run(go())
    finally:
        _kill(procs)


def test_two_process_rolling_upgrade(monkeypatch):
    """ISSUE 16 acceptance: ``POST /fleet/upgrade`` rolls TWO real agent
    processes through drain-as-move -> ``/admin/recycle`` (real argv
    re-exec respawn, announce read off the inherited stdout pipe) ->
    epoch-bumped re-registration + prewarm, one at a time, with every
    pumped frame delivered at every leg (zero drops).  The finale is the
    OTHER half of the tentpole: restart-in-place WITH live sessions —
    ``/admin/recycle`` on a box serving two streams, whose replacement
    imports the handoff before binding and announces AGENT_RECYCLED, so
    the clients re-offer back onto the SAME box at the next journey leg."""
    monkeypatch.setenv("FLEET_POLL_S", "0.15")
    monkeypatch.setenv("FLEET_POLL_TIMEOUT_S", "2.0")
    # more failed-poll tolerance than the crash tests: a recycle gap
    # (old process exit -> replacement announce) is an EXPECTED outage
    monkeypatch.setenv("FLEET_DEAD_AFTER", "3")
    procs, ports = _spawn_agents(2, extra_env={"RECYCLE_EXIT_DELAY_S": "0.1"})
    names = [f"agent{i}" for i in range(2)]
    port_of = dict(zip(names, ports))
    proc_of = dict(zip(names, procs))
    child_pids = []  # re-exec replacements: not our children, kill by pid
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        import aiohttp

        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        reg = FleetRegistry(dead_after=3)
        app = build_router_app(registry=reg, events_handler=events,
                               poll=True)
        client = TestClient(TestServer(app))
        await client.start_server()
        http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        )

        async def agent_get(port, path):
            async with http.get(f"http://127.0.0.1:{port}{path}") as r:
                return await r.json()

        async def agent_post(port, path, body):
            async with http.post(
                f"http://127.0.0.1:{port}{path}", json=body
            ) as r:
                return await r.json()

        async def register(name):
            # what server/worker.py publishes: address + capacity + the
            # process boot nonce (the registry's epoch/ghost discipline)
            cap = await agent_get(port_of[name], "/capacity")
            r = await client.post("/fleet/register", json={
                "worker_id": name, "public_ip": "127.0.0.1",
                "public_port": str(port_of[name]), "status": "ready",
                "capacity": 2, "boot_id": cap["boot_id"],
            })
            assert r.status == 200, await r.text()

        async def read_announce(name):
            # a recycled replacement re-execs argv and inherits stdout:
            # its {"port","pid"} announce arrives on the SAME pipe the
            # original process used at spawn
            proc = proc_of[name]
            line = await asyncio.wait_for(
                asyncio.to_thread(proc.stdout.readline), timeout=45
            )
            assert line, f"{name}: pipe EOF before replacement announce"
            info = json.loads(line)
            child_pids.append(info["pid"])
            port_of[name] = int(info["port"])

        async def pump(name, frames, expect_sessions):
            pumped = await agent_post(
                port_of[name], "/_test/pump", {"frames": frames}
            )
            assert len(pumped["sessions"]) == expect_sessions, pumped
            # the zero-drop acceptance: pushed == delivered, exactly
            assert sum(pumped["sessions"].values()) == (
                frames * expect_sessions
            ), pumped

        legs = {}  # journey id -> last acked leg

        async def reoffer(jid, expect_owner):
            r = await client.post(
                "/offer", json=_OFFER, headers={"X-Journey-Id": jid}
            )
            assert r.status == 200, await r.text()
            assert r.headers["X-Journey-Id"] == jid
            # every continuation is exactly leg+1 — no journey ever
            # skips or repeats a leg across the whole rolling sweep
            assert int(r.headers["X-Journey-Leg"]) == legs[jid] + 1
            legs[jid] += 1
            sid = r.headers["X-Stream-Id"]
            assert app["session_table"].owner(sid) == expect_owner
            return sid

        try:
            for name in names:
                await register(name)

            async def first_poll():
                return all(
                    rec.last_ok is not None for rec in reg.agents.values()
                )

            await _wait_for(first_poll, 10, "first poll round")

            # one session per agent, webhooks at the router's ingest
            jid_of = {}
            for _ in range(2):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200, await r.text()
                sid = r.headers["X-Stream-Id"]
                jid_of[app["session_table"].owner(sid)] = (
                    r.headers["X-Journey-Id"]
                )
                legs[r.headers["X-Journey-Id"]] = 1
            assert set(jid_of) == set(names), jid_of
            events_url = str(client.make_url("/fleet/events"))
            for name in names:
                await agent_post(port_of[name], "/_test/webhook",
                                 {"url": events_url, "token": "t"})
                await pump(name, 10, 1)
            jid_a0, jid_a1 = jid_of["agent0"], jid_of["agent1"]

            # ---- the rolling sweep: agent0 then agent1 ---------------
            r = await client.post("/fleet/upgrade")
            assert r.status == 202, await r.text()
            body = await r.json()
            assert body["active"] and body["total"] == 2

            # step 1: agent0's session moves to agent1; the re-pointed
            # client re-offers as leg 2 and streams there mid-sweep
            async def step1_moved():
                evs = [e for e in posted
                       if e.get("event") == "StreamMigrated"
                       and e.get("source_agent") == "agent0"]
                return evs or None

            ev = (await _wait_for(step1_moved, 20, "step-1 move"))[0]
            assert ev["target_agent"] == "agent1"
            assert ev["reason"] == "upgrade"
            assert ev["journey_id"] == jid_a0
            await reoffer(jid_a0, "agent1")
            await pump("agent1", 8, 2)

            async def sweep0_done():
                return app["migrate_sweeps"].get("agent0") is None

            await _wait_for(sweep0_done, 20, "step-1 sweep retire")
            # the client hangs up its OLD agent0 connection -> drain hits
            # zero -> the sweep recycles agent0; its replacement
            # announces on the inherited pipe and re-registers
            await agent_post(port_of["agent0"], "/_test/close", {})
            await read_announce("agent0")
            await register("agent0")

            # step 2: BOTH of agent1's sessions (its own + the adopted
            # one) move onto the fresh agent0
            async def step2_moved():
                evs = [e for e in posted
                       if e.get("event") == "StreamMigrated"
                       and e.get("source_agent") == "agent1"]
                return evs if len(evs) == 2 else None

            evs = await _wait_for(step2_moved, 30, "step-2 moves")
            assert {e["journey_id"] for e in evs} == {jid_a0, jid_a1}
            for e in evs:
                assert e["target_agent"] == "agent0"
                assert e["reason"] == "upgrade"
                await reoffer(e["journey_id"], "agent0")
            await pump("agent0", 8, 2)

            async def sweep1_done():
                return app["migrate_sweeps"].get("agent1") is None

            await _wait_for(sweep1_done, 20, "step-2 sweep retire")
            await agent_post(port_of["agent1"], "/_test/close", {})
            await read_announce("agent1")
            await register("agent1")

            async def upgrade_done():
                h = await (await client.get("/fleet/health")).json()
                u = h["upgrade"]
                return u if (not u["active"] and u["done"]) else None

            up = await _wait_for(upgrade_done, 30, "sweep completion")
            assert up["halted"] is None, up
            assert up["done"] == ["agent0", "agent1"]

            # ---- finale: restart-in-place WITH live sessions ---------
            # agent0 is serving both streams; recycle it directly (the
            # single-box operator surface, no drain).  The replacement
            # imports the handoff BEFORE binding, announces
            # AGENT_RECYCLED per session, and the router pins each
            # journey's next re-offer back to the SAME box.
            r = await agent_post(
                port_of["agent0"], "/admin/recycle", {"respawn": True}
            )
            assert r["recycling"] and r["sessions"] == 2, r

            async def recycled():
                evs = [e for e in posted
                       if e.get("state") == "AGENT_RECYCLED"]
                return evs if len(evs) == 2 else None

            evs = await _wait_for(recycled, 30, "AGENT_RECYCLED re-points")
            assert {e["journey_id"] for e in evs} == {jid_a0, jid_a1}
            await read_announce("agent0")
            await register("agent0")  # adoption pins need the new address
            for jid in (jid_a0, jid_a1):
                await reoffer(jid, "agent0")
            await pump("agent0", 8, 2)

            # ---- evidence: epochs, rings, metrics --------------------
            h = await (await client.get("/fleet/health")).json()
            # agent0: initial + upgrade recycle + in-place recycle
            assert h["agents"]["agent0"]["epoch"] == 3, h["agents"]
            assert h["agents"]["agent1"]["epoch"] == 2, h["agents"]
            ring = app["journeys"].get(jid_a0)
            kinds = [e["kind"] for e in ring["events"]]
            for expected in ("migrated", "upgraded", "recycled"):
                assert expected in kinds, kinds
            assert [(leg["leg"], leg["agent"]) for leg in ring["legs"]] == [
                (1, "agent0"), (2, "agent1"), (3, "agent0"), (4, "agent0"),
            ]
            m = await (await client.get("/metrics")).json()
            assert m["fleet_upgrades_total"] == 1
            assert m.get("fleet_upgrade_halts_total", 0) == 0
            assert m["migrations_total"] == 3
            assert m.get("migrations_failed_total", 0) == 0
            assert m["fleet_recycled_sessions_total"] == 2
            assert m["upgrade_session_move_ms_p50"] > 0
            assert m["upgrade_session_move_ms_p99"] >= (
                m["upgrade_session_move_ms_p50"]
            )
        finally:
            # unblock any to_thread readline (EOF needs every writer
            # gone) BEFORE the loop's executor shutdown would join it
            for p in procs:
                if p.poll() is None:
                    p.kill()
            _kill_pids(child_pids)
            await http.close()
            await client.close()

    try:
        asyncio.run(go())
    finally:
        _kill(procs)
        _kill_pids(child_pids)


@pytest.mark.slow  # SIGKILL + poller death detection riding on top of the
# tier-1 upgrade sweep above; the halt logic itself also has fast unit
# coverage in test_fleet_lifecycle.py
def test_upgrade_sigkill_falls_back_to_crash_restore(monkeypatch):
    """A mid-upgrade SIGKILL of the in-flight target halts the sweep
    cleanly ("died mid-drain") and hands its sessions to the EXISTING
    crash path: the banked drain export crash-restores onto the
    survivor, the client re-offers as leg 2, and the untouched second
    agent never enters the sweep."""
    monkeypatch.setenv("FLEET_POLL_S", "0.15")
    monkeypatch.setenv("FLEET_POLL_TIMEOUT_S", "2.0")
    monkeypatch.setenv("FLEET_DEAD_AFTER", "2")
    # 3 slots: survivor's own session + the sweep's parked import + the
    # crash-restore import all fit
    procs, ports = _spawn_agents(
        2, extra_env={"OVERLOAD_MAX_SESSIONS": "3"}
    )
    names = [f"agent{i}" for i in range(2)]
    port_of = dict(zip(names, ports))
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        import aiohttp

        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        reg = FleetRegistry(dead_after=2)
        app = build_router_app(registry=reg, events_handler=events,
                               poll=True)
        client = TestClient(TestServer(app))
        await client.start_server()
        http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        )

        async def agent_get(port, path):
            async with http.get(f"http://127.0.0.1:{port}{path}") as r:
                return await r.json()

        async def agent_post(port, path, body):
            async with http.post(
                f"http://127.0.0.1:{port}{path}", json=body
            ) as r:
                return await r.json()

        try:
            for name in names:
                r = await client.post("/fleet/register", json={
                    "worker_id": name, "public_ip": "127.0.0.1",
                    "public_port": str(port_of[name]), "status": "ready",
                    "capacity": 3,
                })
                assert r.status == 200

            async def first_poll():
                return all(
                    rec.last_ok is not None for rec in reg.agents.values()
                )

            await _wait_for(first_poll, 10, "first poll round")

            sids, jids = [], {}
            for _ in range(2):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200, await r.text()
                sid = r.headers["X-Stream-Id"]
                sids.append(sid)
                jids[sid] = r.headers["X-Journey-Id"]
            events_url = str(client.make_url("/fleet/events"))
            for name in names:
                await agent_post(port_of[name], "/_test/webhook",
                                 {"url": events_url, "token": "t"})
            owner = {sid: app["session_table"].owner(sid) for sid in sids}
            victim_sid = next(
                sid for sid in sids if owner[sid] == "agent0"
            )
            vic_jid = jids[victim_sid]

            r = await client.post("/fleet/upgrade")
            assert r.status == 202, await r.text()

            # the sweep exports + parks agent0's session on agent1, but
            # the client never plays along (no re-offer, no hang-up):
            # drain-to-zero blocks with the placement row still live
            async def sweep_settled():
                done = app["migrate_sweeps"].get("agent0") is None
                moved = any(e.get("event") == "StreamMigrated"
                            and e.get("source_agent") == "agent0"
                            for e in posted)
                return done and moved

            await _wait_for(sweep_settled, 20, "step-1 sweep settle")
            assert app["session_table"].owner(victim_sid) == "agent0"

            # a successful move retires its banked export (so the crash
            # path can't double-restore) — re-bank a fresh one, exactly
            # the state of a sweep killed between export and client move
            snap = await agent_get(
                port_of["agent0"],
                f"/migrate/export?session={victim_sid}",
            )
            app["snapshot_bank"][victim_sid] = {
                "snapshot": snap, "ts": time.monotonic(),
            }

            procs[0].kill()  # SIGKILL mid-upgrade, session still placed

            async def halted():
                h = await (await client.get("/fleet/health")).json()
                u = h["upgrade"]
                return u if (not u["active"] and u["halted"]) else None

            up = await _wait_for(halted, 20, "sweep halt")
            assert "died mid-drain" in up["halted"], up
            assert up["done"] == []

            # the crash path owns the session now: banked snapshot
            # restores onto the survivor and re-points the client
            async def restored():
                evs = [e for e in posted
                       if e.get("event") == "StreamMigrated"
                       and e.get("reason") == "agent_dead"]
                return evs or None

            ev = (await _wait_for(restored, 20, "crash restore"))[0]
            assert ev["stream_id"] == victim_sid
            assert ev["target_agent"] == "agent1"
            r = await client.post(
                "/offer", json=_OFFER, headers={"X-Journey-Id": vic_jid}
            )
            assert r.status == 200, await r.text()
            assert r.headers["X-Journey-Leg"] == "2"
            new_sid = r.headers["X-Stream-Id"]
            assert app["session_table"].owner(new_sid) == "agent1"
            pumped = await agent_post(
                port_of["agent1"], "/_test/pump", {"frames": 10}
            )
            assert len(pumped["sessions"]) == 2, pumped
            assert sum(pumped["sessions"].values()) == 20, pumped

            # the halt left the rest of the fleet untouched and serving
            h = await (await client.get("/fleet/health")).json()
            assert h["agents"]["agent0"]["state"] == "DEAD"
            a1 = h["agents"]["agent1"]
            assert a1["state"] == "HEALTHY" and not a1["draining"], a1
            assert a1["epoch"] == 1
            m = await (await client.get("/metrics")).json()
            assert m["fleet_upgrade_halts_total"] == 1
            assert m.get("fleet_upgrades_total", 0) == 0
        finally:
            await http.close()
            await client.close()

    try:
        asyncio.run(go())
    finally:
        _kill(procs)
