"""Golden tests for the noise schedule vs an independent fp64 reference."""

import numpy as np
import jax.numpy as jnp

from ai_rtc_agent_tpu.ops import schedule as S


def test_scaled_linear_betas_match_fp64_reference():
    sch = S.make_schedule()
    # independent recomputation
    betas = np.linspace(0.00085**0.5, 0.012**0.5, 1000) ** 2
    np.testing.assert_allclose(sch.betas, betas, rtol=0, atol=0)
    np.testing.assert_allclose(sch.alphas_cumprod, np.cumprod(1 - betas), rtol=1e-12)
    assert sch.alphas_cumprod[0] > 0.999 - 1e-3
    assert sch.alphas_cumprod[-1] < 0.01  # nearly pure noise at t=999


def test_inference_timesteps_leading_50():
    ts = S.inference_timesteps(50)
    assert ts.shape == (50,)
    assert ts[0] == 980 and ts[-1] == 0  # 20*i descending
    assert (np.diff(ts) < 0).all()


def test_inference_timesteps_trailing_1_step_turbo():
    ts = S.inference_timesteps(1, spacing="trailing")
    assert ts.tolist() == [999]


def test_inference_timesteps_trailing_multi_step_descending():
    # regression: multi-step trailing ladders must stay most-noisy-first
    ts = S.inference_timesteps(4, spacing="trailing")
    assert ts.tolist() == [999, 749, 499, 249]
    assert (np.diff(ts) < 0).all()


def test_sub_timesteps_reference_default():
    # reference default t_index_list [18,26,35,45] of 50 (lib/pipeline.py:12)
    st = S.sub_timesteps([18, 26, 35, 45], 50)
    ladder = S.inference_timesteps(50)
    np.testing.assert_array_equal(st, ladder[[18, 26, 35, 45]])
    # larger t_index -> later in descending ladder -> smaller timestep
    assert (np.diff(st) < 0).all()


def test_sub_timesteps_validation():
    import pytest

    with pytest.raises(ValueError):
        S.sub_timesteps([], 50)
    with pytest.raises(ValueError):
        S.sub_timesteps([5, 3], 50)  # not increasing
    with pytest.raises(ValueError):
        S.sub_timesteps([0, 50], 50)  # out of range


def test_batched_sub_timesteps_repeat_interleave():
    st = S.batched_sub_timesteps([10, 20], 50, frame_buffer_size=3)
    base = S.sub_timesteps([10, 20], 50)
    np.testing.assert_array_equal(st, np.repeat(base, 3))


def test_add_noise_matches_closed_form(rng):
    sch = S.make_schedule()
    x0 = rng.standard_normal((4, 4, 8, 8)).astype(np.float32)
    noise = rng.standard_normal((4, 4, 8, 8)).astype(np.float32)
    t = np.array([0, 100, 500, 999])
    out = np.asarray(S.add_noise(sch, jnp.asarray(x0), jnp.asarray(noise), t))
    ac = sch.alphas_cumprod[t]
    want = (
        np.sqrt(ac)[:, None, None, None] * x0
        + np.sqrt(1 - ac)[:, None, None, None] * noise
    )
    np.testing.assert_allclose(out, want.astype(np.float32), rtol=2e-5, atol=2e-6)


def test_add_noise_clean_timestep():
    sch = S.make_schedule()
    x0 = np.ones((1, 4, 2, 2), np.float32)
    noise = np.full((1, 4, 2, 2), 7.0, np.float32)
    out = np.asarray(S.add_noise(sch, x0, noise, np.array([-1])))
    np.testing.assert_allclose(out, x0)
