"""w8a16 weight quantization (models/quant.py): correctness + integration.

Small-batch serving is weight-bandwidth bound on TPU; int8 kernel storage
halves the HBM reads.  These tests pin the dequant math, the pytree
transform, the layer-primitive dispatch, and an end-to-end quantized
stream on the tiny model.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ai_rtc_agent_tpu.models import quant as Q
from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.models.layers import conv2d, linear
from ai_rtc_agent_tpu.stream.engine import StreamEngine


def test_quantize_tensor_roundtrip(rng):
    w = rng.standard_normal((64, 128)).astype(np.float32)
    q, s = Q.quantize_tensor(w)
    assert q.dtype == np.int8 and s.shape == (1, 128)
    back = q.astype(np.float32) * s
    # per-channel symmetric int8: relative error bounded by the step size
    assert np.abs(back - w).max() <= (np.abs(w).max(axis=0) / 127.0 + 1e-7).max()


def test_quantized_linear_and_conv_close(rng):
    x = jnp.asarray(rng.standard_normal((2, 4096 // 16, 256)).astype(np.float32))
    w = rng.standard_normal((256, 128)).astype(np.float32)
    dense = {"kernel": jnp.asarray(w), "bias": jnp.zeros((128,), jnp.float32)}
    q, s = Q.quantize_tensor(w)
    quantized = {
        "kernel_q": jnp.asarray(q), "scale": jnp.asarray(s),
        "bias": jnp.zeros((128,), jnp.float32),
    }
    a, b = np.asarray(linear(dense, x)), np.asarray(linear(quantized, x))
    denom = np.abs(a).mean() + 1e-6
    assert np.abs(a - b).mean() / denom < 0.02  # ~int8 quantization noise

    xc = jnp.asarray(rng.standard_normal((1, 16, 16, 64)).astype(np.float32))
    wc = rng.standard_normal((3, 3, 64, 64)).astype(np.float32)
    dc = {"kernel": jnp.asarray(wc)}
    qc, sc = Q.quantize_tensor(wc)
    quantc = {"kernel_q": jnp.asarray(qc), "scale": jnp.asarray(sc)}
    a, b = np.asarray(conv2d(dc, xc)), np.asarray(conv2d(quantc, xc))
    assert np.abs(a - b).mean() / (np.abs(a).mean() + 1e-6) < 0.02


def test_quantize_params_skips_small_leaves(rng):
    tree = {
        "big": {"kernel": np.ones((256, 256), np.float32)},
        "small": {"kernel": np.ones((4, 4), np.float32)},
        "norm": {"scale": np.ones((8,), np.float32)},
    }
    out, n = Q.quantize_params(tree, min_size=1024)
    assert n == 1
    assert "kernel_q" in out["big"] and "kernel" not in out["big"]
    assert "kernel" in out["small"]  # too small: stays dense
    assert out["norm"]["scale"].shape == (8,)
    assert Q.quantized_bytes_saved(out) == 256 * 256


def test_quantized_stream_end_to_end(rng, monkeypatch):
    """QUANT_WEIGHTS=w8 through cast_params: the tiny engine streams and
    stays visually close to the dense stream."""
    bundle_d = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test")
    eng_d = StreamEngine(
        bundle_d.stream_models, bundle_d.params, cfg, bundle_d.encode_prompt
    ).prepare("quant parity", seed=7)

    monkeypatch.setenv("QUANT_WEIGHTS", "w8")
    monkeypatch.setenv("QUANT_MIN_SIZE", "256")  # tiny model kernels are small
    bundle_q = registry.load_model_bundle("tiny-test")
    qparams = registry.cast_params(bundle_q.params, cfg.dtype)
    assert Q.quantized_bytes_saved(qparams) > 0
    eng_q = StreamEngine(
        bundle_q.stream_models, qparams, cfg, bundle_q.encode_prompt
    ).prepare("quant parity", seed=7)

    frame = rng.integers(0, 256, (cfg.height, cfg.width, 3), dtype=np.uint8)
    for _ in range(3):
        od = eng_d(frame)
        oq = eng_q(frame)
    assert oq.shape == od.shape and oq.dtype == np.uint8
    # int8 weight noise moves pixels a little, not wholesale
    assert np.abs(od.astype(int) - oq.astype(int)).mean() < 24


def test_quantized_params_refuse_tp_mesh(monkeypatch):
    """QUANT_WEIGHTS + --tp would silently serve REPLICATED (sharding rules
    key on 'kernel' names, not 'kernel_q') — must fail loudly (ADVICE r2)."""
    import pytest

    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.parallel import mesh as M
    from ai_rtc_agent_tpu.stream.engine import StreamEngine

    monkeypatch.setenv("QUANT_WEIGHTS", "w8")
    monkeypatch.setenv("QUANT_MIN_SIZE", "16")  # tiny kernels quantize too
    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test")
    params = registry.cast_params(bundle.params, cfg.dtype)
    with pytest.raises(ValueError, match="tensor-parallel"):
        StreamEngine(
            bundle.stream_models, params, cfg, bundle.encode_prompt,
            mesh=M.make_mesh(tp=2),
        )
