"""FLOP-count invariants of the serving step (XLA cost analysis).

PERF.md's static audit puts the turbo512 bf16 step at ~1.05 TFLOP.  The MFU
gauge (bench._estimate_mfu) divides exactly this cost_analysis figure by
fps/peak, so a silent graph regression — e.g. an R-CFG branch accidentally
doubling the UNet, a VAE running twice, a lost fusion turning the stream
batch into per-index loops — would both corrupt the MFU number and burn
real fps.  Pin the step cost inside a loose band at the real served
geometry (lowering only: trace on CPU, no compile, no device).
"""

import jax
import pytest


def _step_flops(model_id: str, **overrides) -> float:
    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.stream.engine import StreamEngine, make_step_fn

    bundle = registry.load_model_bundle(model_id)
    cfg = registry.default_stream_config(model_id, **overrides)
    eng = StreamEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt
    )
    eng.prepare("cost analysis prompt", guidance_scale=1.0)
    import numpy as np

    frame = np.zeros((cfg.height, cfg.width, 3), np.uint8)
    step = make_step_fn(eng.models, eng.cfg)
    lowered = jax.jit(step).lower(eng.params, eng.state, frame)
    cost = lowered.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return float(cost.get("flops", 0.0))


@pytest.mark.slow
def test_turbo512_step_cost_band():
    """SD-Turbo 1-step img2img @512²: ~1.05 TFLOP/step (PERF.md static
    audit).  A 2x excursion in either direction means the graph changed
    shape, not just constants — fail loudly before it reaches hardware."""
    flops = _step_flops("stabilityai/sd-turbo")
    assert 0.6e12 < flops < 2.1e12, f"turbo512 step = {flops:.3e} FLOPs"


def test_tiny_step_cost_sane():
    """The hermetic tiny model's step must be orders of magnitude below the
    flagship — guards against the tiny family accidentally inheriting real
    geometry (which would silently blow up every CPU test's runtime)."""
    flops = _step_flops("tiny-test")
    assert 0 < flops < 5e9, f"tiny64 step = {flops:.3e} FLOPs"
