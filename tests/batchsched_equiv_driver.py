"""Subprocess driver for the batch-scheduler bit-identity test.

Runs OUTSIDE the test harness's ``--xla_force_host_platform_device_count=8``
simulation: under that flag XLA's CPU thread partitioning differs between
the batch-1 and batch-4 graphs, and a float rounding tie can flip one
uint8 by 1 — on a real single-device runtime (what serving runs) the
scheduler is bit-identical to dedicated engines, and THIS process asserts
exactly that.  Prints ``EQUIV_OK <n>`` (n = frame comparisons, all exact)
or raises on the first mismatch.

ISSUE 9 variant legs: the SAME scheduler-vs-dedicated comparison under
``QUANT_WEIGHTS=w8`` (int8 kernels + fused dequant) and the DeepCache
cadence (``unet_cache_interval``), each across bucket sizes k=4/2/1.
Same variant on both sides -> identical graphs -> the documented parity
tolerance is EXACT (0) on this single-device runtime; the per-leg counts
print as ``EQUIV_W8_OK <n>`` / ``EQUIV_DC_OK <n>``.

ISSUE 12 legs:

* ``--leg sharded`` runs a SEPARATE process UNDER the 8-virtual-device
  flag (the dp mesh needs devices): a dp=2-sharded scheduler vs
  dedicated engines across join/leave spanning the shard boundary,
  prompt/guidance/t-index updates, restart and rejoin.  Tolerance: the
  virtual-device simulation changes XLA's CPU thread partitioning
  between the sharded batch-k graph and the batch-1 engine graph, so a
  float rounding tie can flip one uint8 by 1 (exactly PR 7's documented
  tie class) — the leg asserts ``|diff| <= 1`` and prints the tie count
  (``EQUIV_SHARD_OK <n> ties=<t>``; observed 0 ties on this box).
* The fbs leg (in the default run): scheduler ``frame_buffer_size=2`` —
  sessions x consecutive frames as TWO batch dimensions of one bucket
  step — vs dedicated fbs=2 engines, bit-exact (``EQUIV_FBS_OK <n>``).

ISSUE 17 budget shave: ``--leg dense`` runs ONLY the dense drive (no
variant legs) — the lighter tier-1 sibling; the full composition (w8 +
DeepCache + fbs, each re-tracing k=4/2/1) runs in the slow tier.

ISSUE 20 adapter leg (in the full run): per-session LoRA factor banks
THROUGH the scheduler — each slot's style applied inside the shared
bucket step — vs dedicated engines with the SAME style offline-fused
(``models/lora.py``).  The factors path computes ``y + (x@down.T)@up.T``
where the fuse bakes ``kernel + down.T@up.T``: identical math up to
float association order, so the documented tolerance is PR 7's rounding
tie class (``|uint8 diff| <= 1``; ties reported — a couple observed per
run on this box).  A slot with NO adapter carries zero factors through the same
graph and must stay BIT-exact with a plain engine (zero-slot
exactness).  Prints ``EQUIV_ADAPTER_OK <n> ties=<t>``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
if "--leg" in sys.argv and "sharded" in sys.argv:
    # the dp mesh needs devices: force the SAME 8-virtual-device flag the
    # tier-1 harness runs under (this is the sharded serving simulation,
    # not the single-device exactness environment of the default run)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
else:
    os.environ.pop("XLA_FLAGS", None)

import numpy as np  # noqa: E402

from ai_rtc_agent_tpu.models import registry  # noqa: E402
from ai_rtc_agent_tpu.stream.engine import (  # noqa: E402
    SimilarityFilter,
    StreamEngine,
)
from ai_rtc_agent_tpu.stream.scheduler import BatchScheduler  # noqa: E402


def dedicated_engines(n, bundle, cfg, params=None):
    """n dedicated engines SHARING one set of jitted step callables.

    Every StreamEngine jits its own make_step_fn closure, so n identical
    engines pay n identical tiny-model compiles — the single biggest
    wall-time cost of this driver (tier-1 budget, ROADMAP standing
    constraint).  The step fn is pure in (params, state, frame), so
    engines over the same models/config are interchangeable at the
    executable level; sharing keeps the COMPARISON exact while paying
    each graph's compile once."""
    params = bundle.params if params is None else params
    engines = [
        StreamEngine(
            bundle.stream_models, params, cfg, bundle.encode_prompt
        )
        for _ in range(n)
    ]
    for eng in engines[1:]:
        eng._step = engines[0]._step
        if engines[0]._step_cached is not None:
            eng._step_cached = engines[0]._step_cached
    return engines


def drive_variant(label: str, bundle, cfg, params) -> int:
    """k=4 -> k=2 -> k=1 scheduler-vs-dedicated drive under one serving
    variant.  Three sessions claim up-front (every install resets the
    global DeepCache cadence, so the LAST claim leaves the tick at 0 —
    exactly the dedicated engines' fresh-prepare state), then release one
    by one: releases never touch the cadence, so both sides stay
    tick-aligned through every bucket transition."""
    rng = np.random.default_rng(hash(label) % (2**32))

    def frames(n):
        return [rng.integers(0, 256, (64, 64, 3), np.uint8) for _ in range(n)]

    # HUGE window: dispatch must happen ONLY when every live session has
    # a frame waiting (the inline full-batch path) — with a small window
    # a throttle hiccup between two submits lets the dispatcher fire a
    # PARTIAL batch, which advances the global DeepCache tick twice in
    # one comparison round and desyncs the cadence from the dedicated
    # engines (dense/w8 are cadence-free, so only the DC leg could flake)
    sched = BatchScheduler(
        bundle.stream_models, params, cfg, bundle.encode_prompt,
        max_sessions=4, window_ms=10_000.0, prewarm=False, dp=1,
    )
    prompts = ["a red cat", "a blue dog", "green hills"]
    sessions = [
        sched.claim(f"{label}-{i}", prompt=p, seed=40 + i)
        for i, p in enumerate(prompts)
    ]
    engines = dedicated_engines(3, bundle, cfg, params)
    for eng, (i, p) in zip(engines, enumerate(prompts)):
        eng.prepare(p, seed=40 + i)
    compared = 0

    def rounds(n, sess, engs):
        nonlocal compared
        for _ in range(n):
            fs = frames(len(sess))
            handles = [s.submit(f) for s, f in zip(sess, fs)]
            outs = [s.fetch(h) for s, h in zip(sess, handles)]
            for out, eng, f in zip(outs, engs, fs):
                np.testing.assert_array_equal(out, eng(f))
                compared += 1

    # 3 rounds per occupancy: with interval-3 DeepCache that is one full
    # capture + two cached steps at every bucket size — both graphs of
    # the pair execute and stay pinned at each k
    rounds(3, sessions, engines)            # k=4 (3 live rows, padded)
    sessions[2].release()
    rounds(3, sessions[:2], engines[:2])    # k=2
    sessions[1].release()
    rounds(3, sessions[:1], engines[:1])    # k=1 (solo-ultra inline path)
    sessions[0].release()
    sched.close()
    return compared


def drive_sharded():
    """ISSUE 12 parity leg: a dp=2 mesh-sharded scheduler vs dedicated
    engines, join/leave ACROSS the shard boundary (slots 0-1 live on
    shard 0, slots 2-3 on shard 1), per-session control-plane updates,
    restart and rejoin.  Runs under the 8-virtual-device flag (set at
    module import for ``--leg sharded``); the documented tolerance is a
    single uint8 rounding tie (see module docstring)."""
    import jax

    assert len(jax.devices()) >= 2, "sharded leg needs the device flag"
    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(2,), num_inference_steps=8,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
    )
    sched = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=4, window_ms=10_000.0, prewarm=False, dp=2,
    )
    assert sched.dp == 2 and sched._bucket_sizes == [2, 4]
    engines = dedicated_engines(3, bundle, cfg)
    rng = np.random.default_rng(12)
    compared = 0
    ties = 0

    def frames(n):
        return [rng.integers(0, 256, (64, 64, 3), np.uint8) for _ in range(n)]

    def step_pairs(sessions, dedicated, fs):
        nonlocal compared, ties
        handles = [s.submit(f) for s, f in zip(sessions, fs)]
        outs = [s.fetch(h) for s, h in zip(sessions, handles)]
        for out, eng, f in zip(outs, dedicated, fs):
            d = np.abs(out.astype(np.int16) - eng(f).astype(np.int16))
            assert d.max() <= 1, (
                f"sharded output diverged beyond a rounding tie "
                f"(max diff {d.max()})"
            )
            ties += int((d == 1).sum())
            compared += 1

    e1, e2, e3 = engines
    s1 = sched.claim("sh-a", prompt="a red cat", seed=11)     # slot 0, shard 0
    e1.prepare("a red cat", seed=11)
    # balanced claim() crosses the shard boundary HERE: the least-loaded
    # shard is 1, so the second session lands on slot 2 / shard 1
    s2 = sched.claim("sh-b", prompt="a blue dog", seed=22)
    e2.prepare("a blue dog", seed=22)
    assert s2.snapshot()["shard"] == 1, s2.snapshot()
    for _ in range(2):
        step_pairs([s1, s2], [e1, e2], frames(2))   # k=2, one row per shard

    # JOIN: balanced claim fills shard 0's second slot -> k=4
    s3 = sched.claim("sh-c", prompt="green hills", seed=33)
    e3.prepare("green hills", seed=33)
    assert s3.snapshot()["shard"] == 0, s3.snapshot()
    for _ in range(2):
        step_pairs([s1, s2, s3], [e1, e2, e3], frames(3))

    # per-session control plane across shards: only the target changes
    s2.update_prompt("a completely different prompt")
    e2.update_prompt("a completely different prompt")
    s3.update_guidance(guidance_scale=1.7, delta=0.8)
    e3.update_guidance(1.7, 0.8)
    s1.update_t_index_list([5])
    e1.update_t_index_list([5])
    for _ in range(2):
        step_pairs([s1, s2, s3], [e1, e2, e3], frames(3))

    # LEAVE empties shard 1 entirely; both survivors live on shard 0, so
    # the k=2 bucket spills one row onto the idle shard (the explicit
    # D2D straggler hop in _assemble_frames) — parity must hold through it
    s2.release()
    for _ in range(2):
        step_pairs([s1, s3], [e1, e3], frames(2))

    # restart() restores the live control plane on a fresh sharded row
    s1.restart()
    e1.prepare("a red cat", seed=11)
    e1.update_t_index_list([5])
    step_pairs([s1, s3], [e1, e3], frames(2))

    # rejoin: balanced claim re-fills the emptied shard 1 (freed slot 2)
    s2b = sched.claim("sh-d", prompt="a blue dog", seed=22)
    e2.prepare("a blue dog", seed=22)
    assert s2b.snapshot()["shard"] == 1, s2b.snapshot()
    step_pairs([s1, s2b, s3], [e1, e2, e3], frames(3))

    snap = sched.snapshot()
    assert snap["batchsched_dp"] == 2
    assert snap["batchsched_shard_sessions"] == {"0": 2, "1": 1}, snap
    sched.close()
    print(f"EQUIV_SHARD_OK {compared} ties={ties}")


def drive_fbs(bundle) -> int:
    """ISSUE 12 fbs leg: frame_buffer_size=2 THROUGH the scheduler —
    sessions x consecutive frames as two batch dimensions of one bucket
    step — vs dedicated fbs=2 engines.  Single-device exactness rules
    apply (same graphs both sides): tolerance 0."""
    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(2,), num_inference_steps=8,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        frame_buffer_size=2,
    )
    sched = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=2, window_ms=10_000.0, prewarm=False, dp=1,
    )
    engines = dedicated_engines(2, bundle, cfg)
    e1, e2 = engines
    s1 = sched.claim("fbs-a", prompt="a red cat", seed=11)
    e1.prepare("a red cat", seed=11)
    s2 = sched.claim("fbs-b", prompt="a blue dog", seed=22)
    e2.prepare("a blue dog", seed=22)
    rng = np.random.default_rng(21)
    compared = 0

    def group(n):
        return rng.integers(0, 256, (n, 64, 64, 3), np.uint8)

    def step_groups(sessions, dedicated):
        nonlocal compared
        gs = [group(2) for _ in sessions]
        handles = [s.submit_batch(list(g)) for s, g in zip(sessions, gs)]
        for s, h, eng, g in zip(sessions, handles, dedicated, gs):
            out = np.stack(s.fetch_batch(h))
            np.testing.assert_array_equal(out, eng(g))
            compared += 2

    for _ in range(3):
        step_groups([s1, s2], [e1, e2])   # k=2 x fbs=2 in one step
    s2.release()
    for _ in range(2):
        step_groups([s1], [e1])           # solo keeps the group batching
    s1.release()
    sched.close()
    return compared


def drive_adapter(bundle) -> int:
    """ISSUE 20 parity leg: per-session style adapters through the
    scheduler's stacked factor bank vs dedicated engines with the same
    LoRA offline-fused, across join/leave/bucket transitions, hot-swaps
    (mirrored as a params reassignment on the dedicated side — the step
    fn is pure in params) and restart.  See module docstring for the
    documented tolerance."""
    from ai_rtc_agent_tpu.adapters import AdapterRegistry
    from ai_rtc_agent_tpu.models import loader as LD
    from ai_rtc_agent_tpu.models import lora as LR

    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(2,), num_inference_steps=8,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
    )
    km = LD.unet_key_map(bundle.unet_cfg)
    MQ = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q"
    MV = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_v"
    grng = np.random.default_rng(77)

    def mk_groups(mods, r=2, din=8, dout=8):
        return {
            m: {
                "down": (grng.normal(size=(r, din)) * 0.2).astype(np.float32),
                "up": (grng.normal(size=(dout, r)) * 0.2).astype(np.float32),
                "alpha": float(r),
            }
            for m in mods
        }

    # styleA touches ONE module, styleB two: the bank's target set is the
    # union, so styleA's row carries explicit zeros at MV (zero-extension)
    gA = mk_groups([MQ])
    gB = mk_groups([MQ, MV])
    reg = AdapterRegistry(bundle.params["unet"], km)
    reg.add("styleA", gA)
    reg.add("styleB", gB)
    assert reg.bank_rank == 4, reg.bank_rank  # rank 2 pads to bucket 4

    def fused(groups):
        unet, applied, unmatched = LR.fuse_lora_into_unet(
            bundle.params["unet"], groups, km
        )
        assert applied == len(groups) and not unmatched
        p = dict(bundle.params)
        p["unet"] = unet
        return p

    pA, pB = fused(gA), fused(gB)

    sched = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=4, window_ms=10_000.0, prewarm=False, dp=1,
        adapters=reg,
    )
    # dedicated engines share ONE jitted step (pure in params); the plain
    # one doubles as the hot-swap mirror by reassigning .params
    e_base, eA, eB = dedicated_engines(3, bundle, cfg)
    base_params = e_base.params
    eA.params = pA
    eB.params = pB
    rng = np.random.default_rng(31)
    compared = 0
    ties = 0

    def frames(n):
        return [rng.integers(0, 256, (64, 64, 3), np.uint8) for _ in range(n)]

    def step_pairs(sessions, dedicated, exact, fs):
        nonlocal compared, ties
        handles = [s.submit(f) for s, f in zip(sessions, fs)]
        outs = [s.fetch(h) for s, h in zip(sessions, handles)]
        for out, eng, ex, f in zip(outs, dedicated, exact, fs):
            ref = eng(f)
            if ex:
                np.testing.assert_array_equal(out, ref)
            else:
                d = np.abs(out.astype(np.int16) - ref.astype(np.int16))
                assert d.max() <= 1, (
                    f"adapter parity beyond a rounding tie (max {d.max()})"
                )
                ties += int((d == 1).sum())
            compared += 1

    s1 = sched.claim("ad-a", prompt="a red cat", seed=11, adapter="styleA")
    eA.prepare("a red cat", seed=11)
    s2 = sched.claim("ad-b", prompt="a blue dog", seed=22)  # no adapter
    e_base.prepare("a blue dog", seed=22)
    # k=2: styled slot within the tie class, zero-factor slot BIT-exact
    for _ in range(2):
        step_pairs([s1, s2], [eA, e_base], [False, True], frames(2))

    # JOIN with a different style -> padded k=4, three styles live at once
    s3 = sched.claim("ad-c", prompt="green hills", seed=33, adapter="styleB")
    eB.prepare("green hills", seed=33)
    for _ in range(2):
        step_pairs([s1, s2, s3], [eA, e_base, eB],
                   [False, True, False], frames(3))

    # HOT-SWAP mid-stream: s2 None -> styleA; the dedicated mirror is a
    # params reassignment on the SAME engine (state history carries over
    # on both sides).  From here s2's pair is tie-class, not exact: its
    # pre-swap state already differs from the mirror's by association
    # rounding fed back through the latent ring.
    s2.update_adapter("styleA")
    e_base.params = pA
    for _ in range(2):
        step_pairs([s1, s2, s3], [eA, e_base, eB],
                   [False, False, False], frames(3))

    # swap BACK to no style + restart: a fresh zero-factor state against
    # a fresh plain engine state is bit-exact again
    s2.update_adapter(None)
    e_base.params = base_params
    s2.restart()
    e_base.prepare("a blue dog", seed=22)
    for _ in range(2):
        step_pairs([s1, s2, s3], [eA, e_base, eB],
                   [False, True, False], frames(3))

    # LEAVE -> k=2; the styled survivor stays pinned to its factors
    s3.release()
    for _ in range(2):
        step_pairs([s1, s2], [eA, e_base], [False, True], frames(2))

    # restart() rebuilds the styled session's state WITH its adapter
    s1.restart()
    eA.prepare("a red cat", seed=11)
    for _ in range(2):
        step_pairs([s1, s2], [eA, e_base], [False, True], frames(2))

    snap = sched.snapshot()
    assert snap["adapter_rank"] == 4, snap
    assert snap["adapter_swaps_total"] >= 2, snap
    sched.close()
    print(f"EQUIV_ADAPTER_OK {compared} ties={ties}")
    return compared


def main(variants=True):
    bundle = registry.load_model_bundle("tiny-test")
    # 8 sub-timesteps with a single stage so update_t_index_list([5]) is a
    # REAL coefficient change (a 1-step schedule only admits index 0)
    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(2,), num_inference_steps=8,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        similar_image_filter=True, similar_image_threshold=1.0,
    )
    sched = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=4, window_ms=2.0, prewarm=False, dp=1,
    )
    engines = dedicated_engines(3, bundle, cfg)
    rng = np.random.default_rng(0)
    compared = 0

    def frames(n):
        return [rng.integers(0, 256, (64, 64, 3), np.uint8) for _ in range(n)]

    def step_pairs(sessions, dedicated, fs):
        nonlocal compared
        handles = [s.submit(f) for s, f in zip(sessions, fs)]
        outs = [s.fetch(h) for s, h in zip(sessions, handles)]
        for out, eng, f in zip(outs, dedicated, fs):
            np.testing.assert_array_equal(out, eng(f))
            compared += 1

    e1, e2, e3 = engines
    s1 = sched.claim("sess-a", prompt="a red cat", seed=11)
    e1.prepare("a red cat", seed=11)
    s2 = sched.claim("sess-b", prompt="a blue dog", seed=22)
    e2.prepare("a blue dog", seed=22)

    # k=2 bucket
    for _ in range(3):
        step_pairs([s1, s2], [e1, e2], frames(2))

    # mid-stream JOIN -> padded k=4 bucket
    s3 = sched.claim("sess-c", prompt="green hills", seed=33)
    e3.prepare("green hills", seed=33)
    for _ in range(2):
        step_pairs([s1, s2, s3], [e1, e2, e3], frames(3))

    # per-session control plane: only the updated session changes
    s2.update_prompt("a completely different prompt")
    e2.update_prompt("a completely different prompt")
    s3.update_guidance(guidance_scale=1.7, delta=0.8)
    e3.update_guidance(1.7, 0.8)
    s1.update_t_index_list([5])
    e1.update_t_index_list([5])
    for _ in range(2):
        step_pairs([s1, s2, s3], [e1, e2, e3], frames(3))

    # mid-stream LEAVE: survivors stay bit-exact
    s2.release()
    for _ in range(2):
        step_pairs([s1, s3], [e1, e3], frames(2))

    # down to one: the solo inline fast path
    s3.release()
    for _ in range(3):
        f = frames(1)[0]
        np.testing.assert_array_equal(s1(f), e1(f))
        compared += 1

    # rejoin on the freed slot: a fresh state, not the old tenant's
    s2b = sched.claim("sess-d", prompt="a blue dog", seed=22)
    e2.prepare("a blue dog", seed=22)
    step_pairs([s1, s2b], [e1, e2], frames(2))

    # restart() restores the LIVE control plane (t_index [5], not the
    # config default) on a fresh stream state
    s1.restart()
    e1.prepare("a red cat", seed=11)
    e1.update_t_index_list([5])
    step_pairs([s1, s2b], [e1, e2], frames(2))

    # similarity skips: per-session filters in lockstep with dedicated
    # engines; one session's static scene never perturbs the other
    s1._sim = SimilarityFilter(0.9, 3, seed=0)
    e1._sim_filter = SimilarityFilter(0.9, 3, seed=0)
    s2b._sim = SimilarityFilter(0.9, 3, seed=0)
    e2._sim_filter = SimilarityFilter(0.9, 3, seed=0)
    static = frames(1)[0]
    for _ in range(8):
        fresh = frames(1)[0]
        step_pairs([s1, s2b], [e1, e2], [static, fresh])
    assert s1.frames_skipped_similar > 0, "static scene never skipped"
    assert s2b.frames_skipped_similar == 0, "live scene skipped"

    snap = sched.snapshot()
    assert snap["batchsched_steps_total"] > 0
    assert snap["batchsched_occupancy_hist"]
    sched.close()

    # --- ISSUE 9 variant legs: same drive, quantized + cached-cadence ---
    # (skipped for --leg dense: each variant re-traces the full k=4/2/1
    # geometry set, which is most of this driver's wall clock — the dense
    # leg alone is the tier-1 sibling, the composition runs in slow)
    if not variants:
        print(f"EQUIV_OK {compared}")
        return
    os.environ["QUANT_WEIGHTS"] = "w8"
    os.environ["QUANT_MIN_SIZE"] = "256"  # tiny-model kernels are small
    try:
        qparams = registry.cast_params(bundle.params, cfg.dtype)
    finally:
        del os.environ["QUANT_WEIGHTS"], os.environ["QUANT_MIN_SIZE"]
    from ai_rtc_agent_tpu.models.quant import quantized_bytes_saved

    assert quantized_bytes_saved(qparams) > 0, "quantization was a no-op"
    n_w8 = drive_variant("w8", bundle, cfg, qparams)
    compared += n_w8
    print(f"EQUIV_W8_OK {n_w8}")

    dc_cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(2,), num_inference_steps=8,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        unet_cache_interval=3,
    )
    n_dc = drive_variant("dc3", bundle, dc_cfg, bundle.params)
    compared += n_dc
    print(f"EQUIV_DC_OK {n_dc}")

    n_fbs = drive_fbs(bundle)
    compared += n_fbs
    print(f"EQUIV_FBS_OK {n_fbs}")

    compared += drive_adapter(bundle)

    print(f"EQUIV_OK {compared}")


if __name__ == "__main__":
    if "--leg" in sys.argv and "sharded" in sys.argv:
        drive_sharded()
    elif "--leg" in sys.argv and "dense" in sys.argv:
        main(variants=False)
    elif "--leg" in sys.argv and "adapter" in sys.argv:
        drive_adapter(registry.load_model_bundle("tiny-test"))
    else:
        main()
