"""Subprocess driver for the batch-scheduler bit-identity test.

Runs OUTSIDE the test harness's ``--xla_force_host_platform_device_count=8``
simulation: under that flag XLA's CPU thread partitioning differs between
the batch-1 and batch-4 graphs, and a float rounding tie can flip one
uint8 by 1 — on a real single-device runtime (what serving runs) the
scheduler is bit-identical to dedicated engines, and THIS process asserts
exactly that.  Prints ``EQUIV_OK <n>`` (n = frame comparisons, all exact)
or raises on the first mismatch.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)

import numpy as np  # noqa: E402

from ai_rtc_agent_tpu.models import registry  # noqa: E402
from ai_rtc_agent_tpu.stream.engine import (  # noqa: E402
    SimilarityFilter,
    StreamEngine,
)
from ai_rtc_agent_tpu.stream.scheduler import BatchScheduler  # noqa: E402


def main():
    bundle = registry.load_model_bundle("tiny-test")
    # 8 sub-timesteps with a single stage so update_t_index_list([5]) is a
    # REAL coefficient change (a 1-step schedule only admits index 0)
    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(2,), num_inference_steps=8,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        similar_image_filter=True, similar_image_threshold=1.0,
    )
    sched = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=4, window_ms=2.0, prewarm=False,
    )
    engines = [
        StreamEngine(
            bundle.stream_models, bundle.params, cfg, bundle.encode_prompt
        )
        for _ in range(3)
    ]
    rng = np.random.default_rng(0)
    compared = 0

    def frames(n):
        return [rng.integers(0, 256, (64, 64, 3), np.uint8) for _ in range(n)]

    def step_pairs(sessions, dedicated, fs):
        nonlocal compared
        handles = [s.submit(f) for s, f in zip(sessions, fs)]
        outs = [s.fetch(h) for s, h in zip(sessions, handles)]
        for out, eng, f in zip(outs, dedicated, fs):
            np.testing.assert_array_equal(out, eng(f))
            compared += 1

    e1, e2, e3 = engines
    s1 = sched.claim("sess-a", prompt="a red cat", seed=11)
    e1.prepare("a red cat", seed=11)
    s2 = sched.claim("sess-b", prompt="a blue dog", seed=22)
    e2.prepare("a blue dog", seed=22)

    # k=2 bucket
    for _ in range(3):
        step_pairs([s1, s2], [e1, e2], frames(2))

    # mid-stream JOIN -> padded k=4 bucket
    s3 = sched.claim("sess-c", prompt="green hills", seed=33)
    e3.prepare("green hills", seed=33)
    for _ in range(2):
        step_pairs([s1, s2, s3], [e1, e2, e3], frames(3))

    # per-session control plane: only the updated session changes
    s2.update_prompt("a completely different prompt")
    e2.update_prompt("a completely different prompt")
    s3.update_guidance(guidance_scale=1.7, delta=0.8)
    e3.update_guidance(1.7, 0.8)
    s1.update_t_index_list([5])
    e1.update_t_index_list([5])
    for _ in range(2):
        step_pairs([s1, s2, s3], [e1, e2, e3], frames(3))

    # mid-stream LEAVE: survivors stay bit-exact
    s2.release()
    for _ in range(2):
        step_pairs([s1, s3], [e1, e3], frames(2))

    # down to one: the solo inline fast path
    s3.release()
    for _ in range(3):
        f = frames(1)[0]
        np.testing.assert_array_equal(s1(f), e1(f))
        compared += 1

    # rejoin on the freed slot: a fresh state, not the old tenant's
    s2b = sched.claim("sess-d", prompt="a blue dog", seed=22)
    e2.prepare("a blue dog", seed=22)
    step_pairs([s1, s2b], [e1, e2], frames(2))

    # restart() restores the LIVE control plane (t_index [5], not the
    # config default) on a fresh stream state
    s1.restart()
    e1.prepare("a red cat", seed=11)
    e1.update_t_index_list([5])
    step_pairs([s1, s2b], [e1, e2], frames(2))

    # similarity skips: per-session filters in lockstep with dedicated
    # engines; one session's static scene never perturbs the other
    s1._sim = SimilarityFilter(0.9, 3, seed=0)
    e1._sim_filter = SimilarityFilter(0.9, 3, seed=0)
    s2b._sim = SimilarityFilter(0.9, 3, seed=0)
    e2._sim_filter = SimilarityFilter(0.9, 3, seed=0)
    static = frames(1)[0]
    for _ in range(8):
        fresh = frames(1)[0]
        step_pairs([s1, s2b], [e1, e2], [static, fresh])
    assert s1.frames_skipped_similar > 0, "static scene never skipped"
    assert s2b.frames_skipped_similar == 0, "live scene skipped"

    snap = sched.snapshot()
    assert snap["batchsched_steps_total"] > 0
    assert snap["batchsched_occupancy_hist"]
    sched.close()
    print(f"EQUIV_OK {compared}")


if __name__ == "__main__":
    main()
