"""RTCP SR/RR + NACK on the native tier (VERDICT r4 next-round #5).

The reference inherits sender reports, receiver-report stats and
NACK-driven retransmission from aiortc (reference agent.py:13-20); these
tests pin the in-repo equivalents (media/rtcp.py + rtc_native._RtcpState):
wire formats, the retransmission cache, and the live secure-session
behavior — an SR observable by the client, a NACK answered with the
original ciphertext, receiver-report gauges landing in /metrics.
"""

import asyncio
import struct

import numpy as np
import pytest

from ai_rtc_agent_tpu.media import rtcp
from ai_rtc_agent_tpu.media.rtcp import (
    RetransmissionCache,
    make_nack,
    make_rr,
    make_sr,
    parse_compound,
)


class TestWireFormats:
    def test_sr_roundtrip_with_sdes(self):
        sr = make_sr(0x5EED, rtp_ts=90000, packet_count=42, octet_count=4242)
        items = parse_compound(sr)
        assert len(items) == 1  # SDES walks but doesn't yield
        s = items[0]
        assert s["type"] == "sr" and s["ssrc"] == 0x5EED
        assert s["rtp_ts"] == 90000
        assert s["packet_count"] == 42 and s["octet_count"] == 4242
        # NTP timestamp is current wall time in the 1900 epoch
        import time

        assert abs(s["ntp_sec"] - rtcp.NTP_EPOCH_OFFSET - time.time()) < 5

    def test_sr_length_is_spec_shaped(self):
        sr = make_sr(1, 0, 0, 0, compound_sdes=False)
        assert len(sr) == 28
        (words,) = struct.unpack_from("!H", sr, 2)
        assert (words + 1) * 4 == len(sr)

    def test_rr_roundtrip(self):
        rr = make_rr(0xABC, 0x5EED, fraction_lost=25, cumulative_lost=7,
                     highest_seq=1234, jitter=99)
        (item,) = parse_compound(rr)
        assert item["type"] == "rr" and item["ssrc"] == 0xABC
        (blk,) = item["blocks"]
        assert blk["ssrc"] == 0x5EED
        assert blk["fraction_lost"] == 25 and blk["cumulative_lost"] == 7
        assert blk["highest_seq"] == 1234 and blk["jitter"] == 99

    def test_nack_pid_blp_encoding(self):
        # 5 and 9 fold into 3's bitmask; 100 starts a second FCI pair
        nack = make_nack(1, 2, [3, 5, 9, 100])
        (item,) = parse_compound(nack)
        assert item["type"] == "nack"
        assert sorted(item["seqs"]) == [3, 5, 9, 100]

    def test_nack_wraparound_seqs(self):
        nack = make_nack(1, 2, [65535, 0])
        (item,) = parse_compound(nack)
        assert 65535 in item["seqs"] and 0 in item["seqs"]

    def test_browser_style_compound_rr_plus_pli(self):
        rr = make_rr(0xABC, 0x5EED)
        pli = struct.pack("!BBH", 0x81, 206, 2) + struct.pack("!II", 0xABC, 0x5EED)
        items = parse_compound(rr + pli)
        assert [i["type"] for i in items] == ["rr", "pli"]

    def test_garbage_not_parsed(self):
        assert parse_compound(b"\x00" * 32) == []
        assert parse_compound(b"") == []


class TestRetransmissionCache:
    def _pkt(self, seq, ts=0):
        return struct.pack("!BBHII", 0x80, 102, seq, ts, 0x5EED) + b"payload"

    def test_add_get_and_eviction(self):
        c = RetransmissionCache(size=4)
        for seq in range(6):
            c.add(self._pkt(seq), b"wire%d" % seq)
        assert len(c) == 4
        assert c.get(0) is None and c.get(1) is None  # evicted
        assert c.get(5) == b"wire5"

    def test_rtcp_state_nack_resends_and_cache_miss_forces_idr(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState
        from ai_rtc_agent_tpu.utils.profiling import FrameStats

        stats = FrameStats()
        st = _RtcpState(stats=stats)
        st.sent(self._pkt(10, ts=777), b"wire10")
        resent = []
        force = st.on_rtcp(make_nack(1, 0x5EED, [10]), resent.append)
        assert resent == [b"wire10"] and force is False
        force = st.on_rtcp(make_nack(1, 0x5EED, [9999]), resent.append)
        assert force is True  # aged out -> IDR recovery
        snap = stats.snapshot()
        assert snap["rtcp_nacks_total"] == 2
        assert snap["rtcp_nack_retransmits_total"] == 1

    def test_rtcp_state_rr_gauges(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState
        from ai_rtc_agent_tpu.utils.profiling import FrameStats

        stats = FrameStats()
        st = _RtcpState(stats=stats)
        st.on_rtcp(make_rr(1, 0x5EED, fraction_lost=64, jitter=12), lambda w: None)
        snap = stats.snapshot()
        assert snap["rr_fraction_lost"] == 64 and snap["rr_jitter"] == 12
        assert snap["rtcp_rrs_total"] == 1

    def test_sr_counters_track_sends(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        st = _RtcpState()
        st.sent(self._pkt(1, ts=3000), b"w1")
        st.sent(self._pkt(2, ts=6000), b"w2")
        (item,) = [i for i in parse_compound(st.make_report()) if i["type"] == "sr"]
        assert item["packet_count"] == 2
        assert item["rtp_ts"] == 6000
        assert item["octet_count"] == 2 * len(b"payload")


class TestReportBlockSelection:
    """ISSUE 6 satellite: the RR gauge must select the report block about
    OUR media SSRC — a multi-block compound from a multi-stream peer must
    not gauge a stranger's loss, and blocks riding an SR (bidirectional
    peers, RFC 3550 s6.4.1) must feed the same gauges."""

    def _state(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState
        from ai_rtc_agent_tpu.utils.profiling import FrameStats

        stats = FrameStats()
        return _RtcpState(stats=stats), stats

    def _multiblock_rr(self, blocks):
        payload = struct.pack("!I", 0x1111)
        for b in blocks:
            payload += struct.pack(
                "!IIIIII",
                b["ssrc"],
                ((b["fraction_lost"] & 0xFF) << 24) | (b.get("lost", 0) & 0xFFFFFF),
                b.get("highest_seq", 0),
                b.get("jitter", 0),
                0, 0,
            )
        return (
            struct.pack("!BBH", 0x80 | len(blocks), 201, len(payload) // 4)
            + payload
        )

    def test_multiblock_rr_selects_our_ssrc_not_the_first_block(self):
        st, stats = self._state()
        # a stranger's catastrophic block comes FIRST; ours second
        rr = self._multiblock_rr([
            {"ssrc": 0xDEAD, "fraction_lost": 255, "jitter": 9999},
            {"ssrc": st.ssrc, "fraction_lost": 32, "jitter": 7},
        ])
        st.on_rtcp(rr, lambda w: None)
        snap = stats.snapshot()
        assert snap["rr_fraction_lost"] == 32 and snap["rr_jitter"] == 7

    def test_rr_without_our_block_gauges_nothing(self):
        st, stats = self._state()
        rr = self._multiblock_rr(
            [{"ssrc": 0xDEAD, "fraction_lost": 255, "jitter": 1}]
        )
        st.on_rtcp(rr, lambda w: None)
        snap = stats.snapshot()
        assert "rr_fraction_lost" not in snap
        assert snap.get("rtcp_rrs_total", 0) == 0

    def test_sr_embedded_report_block_feeds_gauges(self):
        st, stats = self._state()
        sr = rtcp.make_sr(
            0x2222, rtp_ts=0, packet_count=1, octet_count=1,
            compound_sdes=False,
            report_blocks=[
                {"ssrc": 0xBEEF, "fraction_lost": 200, "jitter": 5},
                {"ssrc": st.ssrc, "fraction_lost": 48, "jitter": 11},
            ],
        )
        st.on_rtcp(sr, lambda w: None)
        snap = stats.snapshot()
        assert snap["rr_fraction_lost"] == 48 and snap["rr_jitter"] == 11

    def test_blocks_feed_the_netadapt_ladder(self):
        st, _ = self._state()
        seen = []

        class Ladder:
            def on_receiver_report(self, blk):
                seen.append(blk)

            def on_tx_feedback(self, nacks=0, plis=0):
                seen.append(("fb", nacks, plis))

        st.netadapt = Ladder()
        st.on_rtcp(
            self._multiblock_rr([
                {"ssrc": 0xDEAD, "fraction_lost": 255, "jitter": 1},
                {"ssrc": st.ssrc, "fraction_lost": 64, "jitter": 3},
            ]),
            lambda w: None,
        )
        assert len(seen) == 1 and seen[0]["fraction_lost"] == 64
        # NACK + PLI feedback also lands, with the stranger's filtered out
        st.on_rtcp(make_nack(1, st.ssrc, [5, 6]), lambda w: None)
        st.on_rtcp(make_nack(1, 0xDEAD, [7]), lambda w: None)
        fb = [x for x in seen if isinstance(x, tuple)]
        assert fb == [("fb", 2, 0)]


@pytest.fixture(scope="module")
def native_lib():
    from ai_rtc_agent_tpu.media import native

    lib = native.load()
    if lib is None:
        pytest.skip("native lib unavailable")
    return lib


def test_live_secure_session_sr_nack_rr(native_lib, monkeypatch):
    """One encrypted session exercises all three: the client OBSERVES a
    sender report, a NACK is answered with the identical ciphertext
    packet, and a receiver report lands in /metrics."""
    # same gate as every test_secure_* file: the crypto backend is
    # optional at the package level — skip, don't fail, without it
    pytest.importorskip("cryptography", reason="secure tier needs cryptography")
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    from aiohttp.test_utils import TestClient, TestServer

    from ai_rtc_agent_tpu.media import native
    from ai_rtc_agent_tpu.media.frames import VideoFrame
    from ai_rtc_agent_tpu.media.plane import H264Sink
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider
    from tests.secure_client import SecureTestPeer, secure_offer
    from tests.test_secure_e2e import InvertPipeline

    use_h264 = native.h264_available()
    w = h = 64

    async def go():
        provider = NativeRtpProvider(
            default_width=w, default_height=h, use_h264=use_h264
        )
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        http = TestClient(TestServer(app))
        await http.start_server()
        peer = await SecureTestPeer("rtcp-client").open_socket()
        # distinct publish SSRC so the reception block about OUR stream
        # is distinguishable from the server's own 0x5EED
        out_sink = H264Sink(w, h, use_h264=use_h264, payload_type=102,
                            ssrc=0xCAFE)
        try:
            r = await http.post(
                "/offer",
                json={
                    "room_id": "rtcp-room",
                    "offer": {
                        "sdp": secure_offer(peer.cert.fingerprint),
                        "type": "offer",
                    },
                },
            )
            assert r.status == 200
            await peer.establish((await r.json())["sdp"])

            seen_wires: list = []
            rtcp_items: list = []
            # push frames until processed media returns, collecting RTCP
            for i in range(120):
                f = VideoFrame.from_ndarray(
                    np.full((h, w, 3), 180, np.uint8)
                )
                f.pts = i * 3000
                peer.send_rtp(out_sink.consume(f))
                rtp, items = peer.drain_classified()
                seen_wires.extend(rtp)
                rtcp_items.extend(items)
                if seen_wires and any(x["type"] == "sr" for x in rtcp_items):
                    break
                await asyncio.sleep(0.05)
            assert seen_wires, "no media came back"
            srs = [x for x in rtcp_items if x["type"] == "sr"]
            assert srs, "no sender report observed within the session"
            assert srs[-1]["ssrc"] == 0x5EED
            assert srs[-1]["packet_count"] > 0
            # the SR also REPORTS RECEPTION of our publish stream (r5:
            # ReceiverStats) — highest seq advances, ssrc is ours
            with_blocks = [x for x in srs if x.get("blocks")]
            assert with_blocks, "no reception block about our stream"
            blk = with_blocks[-1]["blocks"][0]
            assert blk["ssrc"] == 0xCAFE
            assert blk["highest_seq"] > 0

            # NACK the first media packet we saw: the identical ciphertext
            # must come back (cache hit — no re-encryption)
            target_wire = seen_wires[0]
            seq = (target_wire[2] << 8) | target_wire[3]
            peer.send_rtcp(make_nack(0xABC, 0x5EED, [seq]))
            got_dup = False
            for _ in range(40):
                await asyncio.sleep(0.05)
                rtp, items = peer.drain_classified()
                if any(wire == target_wire for wire in rtp):
                    got_dup = True
                    break
            assert got_dup, "NACK was not answered with a retransmission"

            # a receiver report lands in /metrics as gauges
            peer.send_rtcp(make_rr(0xABC, 0x5EED, fraction_lost=3, jitter=8))
            await asyncio.sleep(0.3)
            snap = await (await http.get("/metrics")).json()
            assert snap.get("rtcp_rrs_total", 0) >= 1
            assert snap.get("rr_fraction_lost") == 3
            assert snap.get("rr_jitter") == 8
        finally:
            out_sink.close()
            peer.close()
            await http.close()

    asyncio.run(go())


class TestReviewHardening:
    def _pkt(self, seq, ts=0):
        return struct.pack("!BBHII", 0x80, 102, seq, ts, 0x5EED) + b"p"

    def test_unknown_pt_does_not_terminate_compound_walk(self):
        # [RR][XR pt=207][NACK]: the NACK after the unknown XR must parse
        rr = make_rr(0xABC, 0x5EED)
        xr = struct.pack("!BBHI", 0x80, 207, 1, 0xABC)
        nack = make_nack(0xABC, 0x5EED, [7])
        types = [i["type"] for i in parse_compound(rr + xr + nack)]
        assert types == ["rr", "nack"]

    def test_nack_for_foreign_ssrc_ignored(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        st = _RtcpState()
        st.sent(self._pkt(10), b"wire10")
        resent = []
        # media SSRC is someone else's stream: no resend AND no IDR
        force = st.on_rtcp(make_nack(1, 0xDEAD, [10, 9999]), resent.append)
        assert resent == [] and force is False

    def test_rr_for_foreign_ssrc_does_not_pollute_gauges(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState
        from ai_rtc_agent_tpu.utils.profiling import FrameStats

        stats = FrameStats()
        st = _RtcpState(stats=stats)
        st.on_rtcp(make_rr(1, 0xDEAD, fraction_lost=99), lambda w: None)
        snap = stats.snapshot()
        assert "rr_fraction_lost" not in snap

    def test_retransmit_budget_caps_amplification(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        st = _RtcpState()
        for seq in range(200):
            st.sent(self._pkt(seq), b"w%d" % seq)
        resent = []
        st.on_rtcp(make_nack(1, 0x5EED, list(range(200))), resent.append)
        assert len(resent) == st.RTX_PER_SECOND  # one window's budget

    def test_feedback_idr_rate_limited(self):
        """A PLI/NACK flood must not turn every frame into a keyframe:
        feedback-driven IDRs are floored at IDR_MIN_INTERVAL_S."""
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        st = _RtcpState()
        pli = struct.pack("!BBH", 0x81, 206, 2) + struct.pack("!II", 1, 0x5EED)
        assert st.on_rtcp(pli, lambda w: None) is True
        for _ in range(10):  # immediate repeats are suppressed
            assert st.on_rtcp(pli, lambda w: None) is False
        st._last_idr -= 10.0  # interval elapsed -> allowed again
        assert st.on_rtcp(pli, lambda w: None) is True

    def test_wildcard_pli_ignored(self):
        """media_ssrc=0 is no longer a PLI wildcard — forged wildcard PLIs
        must not force keyframes (code review r5 pass 2)."""
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        st = _RtcpState()
        pli0 = struct.pack("!BBH", 0x81, 206, 2) + struct.pack("!II", 1, 0)
        assert st.on_rtcp(pli0, lambda w: None) is False


class TestReceiverStats:
    def _pkt(self, seq, ts, ssrc=0xCAFE):
        return struct.pack("!BBHII", 0x80, 102, seq, ts, ssrc) + b"d"

    def test_no_loss_clean_run(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        t = 100.0
        for i in range(50):
            rs.received(self._pkt(1000 + i, i * 3000), arrival=t + i / 30)
        blk = rs.report_block()
        assert blk["ssrc"] == 0xCAFE
        assert blk["fraction_lost"] == 0 and blk["cumulative_lost"] == 0
        assert blk["highest_seq"] == 1049
        # 30 fps arrivals vs 90 kHz ts: transit is constant -> jitter ~0
        assert blk["jitter"] == 0

    def test_loss_counted_and_interval_fraction(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        # drop every 4th, keeping the interval boundary (139) so the
        # second interval starts clean
        seqs = [s for s in range(100, 140) if s % 4 != 1]
        for s in seqs:
            rs.received(self._pkt(s, s * 3000), arrival=200.0 + s / 30)
        blk = rs.report_block()
        assert blk["cumulative_lost"] == 40 - len(seqs)
        assert blk["fraction_lost"] > 0
        # second interval with no loss -> fraction resets to 0
        for s in range(140, 160):
            rs.received(self._pkt(s, s * 3000), arrival=210.0 + s / 30)
        blk2 = rs.report_block()
        assert blk2["fraction_lost"] == 0
        assert blk2["cumulative_lost"] == blk["cumulative_lost"]

    def test_seq_wraparound_extends_highest(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        for s in (65533, 65534, 65535, 0, 1, 2):
            rs.received(self._pkt(s & 0xFFFF, s * 3000), arrival=300.0 + s / 30)
        blk = rs.report_block()
        assert blk["highest_seq"] == (1 << 16) | 2
        assert blk["cumulative_lost"] == 0

    def test_jittery_arrivals_show_jitter(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        rng = __import__("random").Random(4)
        for i in range(100):
            rs.received(
                self._pkt(i, i * 3000),
                arrival=400.0 + i / 30 + rng.uniform(0, 0.03),
            )
        assert rs.report_block()["jitter"] > 100  # RTP ts units (90 kHz)

    def test_sr_carries_reception_block_when_bidirectional(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        st = _RtcpState()
        out = struct.pack("!BBHII", 0x80, 102, 9, 1000, 0x5EED) + b"x"
        st.sent(out, out)
        for i in range(10):
            st.recv.received(self._pkt(50 + i, i * 3000), arrival=500.0 + i / 30)
        (sr,) = [i for i in parse_compound(st.make_report()) if i["type"] == "sr"]
        (blk,) = sr["blocks"]
        assert blk["ssrc"] == 0xCAFE and blk["highest_seq"] == 59

    def test_receive_only_emits_rr(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        st = _RtcpState()
        for i in range(5):
            st.recv.received(self._pkt(7 + i, i * 3000), arrival=600.0 + i / 30)
        (item,) = parse_compound(st.make_report())
        assert item["type"] == "rr"
        assert item["ssrc"] == st.ssrc
        assert item["blocks"][0]["ssrc"] == 0xCAFE

    def test_no_traffic_no_report(self):
        from ai_rtc_agent_tpu.server.rtc_native import _RtcpState

        assert _RtcpState().make_report() is None

    def test_rtp_timestamp_wrap_no_jitter_spike(self):
        """Code review r5: the sender's 32-bit rtp_ts wrap (~13h at 90kHz)
        must not register as a multi-thousand-second jitter spike."""
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        base_ts = (1 << 32) - 5 * 3000  # five frames before the wrap
        for i in range(10):
            ts = (base_ts + i * 3000) & 0xFFFFFFFF
            rs.received(self._pkt(i, ts), arrival=700.0 + i / 30)
        assert rs.report_block()["jitter"] < 100

    def test_foreign_ssrc_packets_ignored(self):
        """Code review r5: stray RTP from another SSRC on the same socket
        must not corrupt the publisher's loss accounting."""
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        for i in range(10):
            rs.received(self._pkt(100 + i, i * 3000), arrival=800.0 + i / 30)
            rs.received(
                self._pkt(40000 + i, i * 7000, ssrc=0xBAD), arrival=800.0 + i / 30
            )
        blk = rs.report_block()
        assert blk["ssrc"] == 0xCAFE
        assert blk["cumulative_lost"] == 0
        assert blk["highest_seq"] == 109

    def test_rr_compound_carries_sdes(self):
        from ai_rtc_agent_tpu.media.rtcp import make_rr

        rr = make_rr(1, 2)
        assert len(rr) > 32  # RR body is 32 bytes; the SDES chunk follows
        assert rr[33] == 202 and b"tpu-rtc-agent" in rr  # PT_SDES + CNAME


class TestReceiverStatsDuplicatesAndRelock:
    """ADVICE r5 regressions: duplicate/late packets must not inflate
    ``_received`` (RFC 3550 A.3 counts unique receptions), and a stats lock
    won by a stray datagram must release when the real stream keeps
    talking."""

    def _pkt(self, seq, ts=0, ssrc=0xCAFE):
        return struct.pack("!BBHII", 0x80, 102, seq, ts, ssrc) + b"d"

    def test_duplicates_do_not_mask_loss(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        # 20 unique packets with 5 lost (100..119 minus 5), every delivered
        # packet duplicated once — pre-fix the dups cancelled the loss
        lost = {103, 107, 111, 115, 119}
        for s in range(100, 120):
            if s in lost:
                continue
            rs.received(self._pkt(s, s * 3000), arrival=10.0 + s / 30)
            rs.received(self._pkt(s, s * 3000), arrival=10.0 + s / 30)
        blk = rs.report_block()
        assert blk["cumulative_lost"] == 4  # 119 lost is past highest_seq
        assert blk["fraction_lost"] > 0

    def test_reordered_first_arrival_still_counts(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        # 10..19 delivered with 14 arriving late (reordered, NOT lost)
        order = [10, 11, 12, 13, 15, 16, 17, 14, 18, 19]
        for s in order:
            rs.received(self._pkt(s, s * 3000), arrival=20.0 + s / 30)
        blk = rs.report_block()
        assert blk["cumulative_lost"] == 0
        assert blk["fraction_lost"] == 0

    def test_late_duplicate_rejected_late_original_accepted(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        for s in (50, 51, 52, 53):
            rs.received(self._pkt(s, s * 3000), arrival=30.0 + s / 30)
        rs.received(self._pkt(51, 51 * 3000), arrival=31.0)  # late DUP
        blk = rs.report_block()
        assert blk["cumulative_lost"] == 0
        assert rs._received == 4  # the replay did not count

    def test_relock_when_locked_stream_goes_silent(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        # one stray probe datagram wins the lock...
        rs.received(self._pkt(9, 0, ssrc=0xDEAD), arrival=40.0)
        assert rs.ssrc == 0xDEAD
        # ...then the real publisher talks and the ghost stays silent:
        # after RELOCK_AFTER consecutive foreign packets the stats re-lock
        for i in range(ReceiverStats.RELOCK_AFTER + 5):
            rs.received(
                self._pkt(200 + i, i * 3000, ssrc=0xCAFE), arrival=41.0 + i / 30
            )
        assert rs.ssrc == 0xCAFE
        blk = rs.report_block()
        assert blk["ssrc"] == 0xCAFE
        assert blk["cumulative_lost"] == 0  # fresh lock, clean accounting

    def test_no_relock_while_locked_stream_is_alive(self):
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        rs = ReceiverStats()
        for i in range(100):
            rs.received(self._pkt(10 + i, i * 3000), arrival=50.0 + i / 30)
            # interleaved foreign chatter never reaches RELOCK_AFTER in a row
            rs.received(self._pkt(7000 + i, 0, ssrc=0xBAD), arrival=50.0 + i / 30)
        assert rs.ssrc == 0xCAFE
        assert rs.report_block()["cumulative_lost"] == 0
