"""Multi-host distributed backend: 2 real processes, one global mesh.

SURVEY.md section 2c requires a distributed comm backend that "scales to
multi-host".  The suite's 8-virtual-device mesh is single-process; this test
is the stronger claim: TWO OS processes (4 virtual devices each) joined by
``jax.distributed``, the dp x tp x sp mesh spanning both, and a sharded
train step whose collectives cross the process boundary (Gloo — the CPU
stand-in for DCN).  Both processes must agree on the loss bit-for-bit.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_trainer_step_agrees():
    """`slow` tier since PR 9: a 17s two-subprocess TRAINING-path check
    (jax.distributed init x2 + collective step) — tier-1 wall-time goes
    to serving invariants first (ROADMAP standing constraint; the suite
    has twice been killed at the 870s timeout on throttled runs)."""
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # repo import path, WITHOUT any site hooks
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "tests/multihost_worker.py", str(port), str(i), "2"],
            env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        if (
            p.returncode != 0
            and "Multiprocess computations aren't implemented" in err
        ):
            # this jaxlib's CPU backend has no cross-process collectives
            # (platform capability, not a code bug) — the multi-host claim
            # is validated on builds that ship them
            for q in procs:
                q.kill()
            pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
        assert p.returncode == 0, f"worker failed:\n{err[-1500:]}"
        outs.append(out)

    losses = []
    for out in outs:
        line = next(ln for ln in out.splitlines() if ln.startswith("LOSS "))
        losses.append(line.split()[1:])
    # every host computes the SAME global loss (collectives agree)
    assert losses[0] == losses[1], losses
