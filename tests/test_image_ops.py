import numpy as np
import jax.numpy as jnp

from ai_rtc_agent_tpu.ops import image as I


def test_preprocess_round_trip(rng):
    frame = rng.integers(0, 256, (32, 48, 3), dtype=np.uint8)
    x = I.preprocess_uint8(frame)
    assert x.shape == (1, 32, 48, 3) and x.dtype == jnp.float32
    assert float(x.max()) <= 1.0 and float(x.min()) >= 0.0
    back = np.asarray(I.postprocess_uint8(x))[0]
    np.testing.assert_array_equal(back, frame)


def test_postprocess_clamps():
    x = jnp.asarray(np.array([-0.5, 0.0, 0.5, 1.0, 2.0], np.float32))
    x = x.reshape(1, 1, 5, 1).repeat(3, axis=3)
    out = np.asarray(I.postprocess_uint8(x))
    assert out.min() == 0 and out.max() == 255
    assert out[0, 0, 2, 0] == 128  # 0.5 -> round(127.5) = 128


def test_range_converters():
    x = jnp.asarray(np.linspace(0, 1, 5, dtype=np.float32))
    np.testing.assert_allclose(
        np.asarray(I.to_unit_range(I.to_sym_range(x))), np.asarray(x), atol=1e-6
    )


def test_resize_noop_and_shape(rng):
    x = jnp.asarray(rng.standard_normal((1, 16, 16, 3)).astype(np.float32))
    assert I.resize_bilinear(x, 16, 16) is x
    y = I.resize_bilinear(x, 8, 24)
    assert y.shape == (1, 8, 24, 3)


def test_similarity_identical_and_different(rng):
    a = jnp.asarray(rng.random((1, 32, 32, 3)).astype(np.float32))
    b = jnp.asarray(1.0 - np.asarray(a))
    s_same = float(I.similarity(a, a)[0])
    s_diff = float(I.similarity(a, b)[0])
    assert s_same > 0.999
    assert s_diff < s_same
