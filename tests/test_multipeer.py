"""Multi-peer batching tests (BASELINE configs[4])."""

import jax
import numpy as np
import pytest

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.parallel import mesh as M
from ai_rtc_agent_tpu.parallel.multipeer import MultiPeerEngine


@pytest.fixture(scope="module")
def bundle():
    return registry.load_model_bundle("tiny-test")


def _mp(bundle, mesh=None, max_peers=4):
    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
    )
    return MultiPeerEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_peers=max_peers, mesh=mesh,
    ).start("default prompt")


def test_multipeer_slots_and_step(bundle):
    mp = _mp(bundle)
    s0 = mp.connect("peer zero")
    s1 = mp.connect("peer one")
    assert (s0, s1) == (0, 1)
    rng = np.random.default_rng(0)
    frames = rng.integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (4, 64, 64, 3) and out.dtype == np.uint8
    # distinct inputs + per-peer state -> distinct outputs
    assert not np.array_equal(out[0], out[1])
    mp.disconnect(s0)
    assert mp.connect("replacement") == 0


def test_multipeer_per_peer_prompt_isolation(bundle):
    """Per-peer prompts: updating one slot must not disturb another —
    an upgrade over the reference's global prompt mutation (agent.py:423)."""
    mp = _mp(bundle)
    mp.connect("prompt A", seed=7)
    mp.connect("prompt A", seed=7)  # identical noise state for both slots
    rng = np.random.default_rng(1)
    frames = rng.integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)
    frames[1] = frames[0]  # identical inputs for slots 0/1
    base = mp.step_all(frames.copy())
    np.testing.assert_array_equal(base[0], base[1])  # same prompt+state+input

    mp.update_prompt(1, "a completely different prompt")
    out = mp.step_all(frames.copy())
    assert not np.array_equal(out[0], out[1])


def test_multipeer_sharded_over_dp(bundle):
    mesh = M.make_mesh(dp=4)
    mp = _mp(bundle, mesh=mesh)
    rng = np.random.default_rng(2)
    frames = rng.integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (4, 64, 64, 3)


def test_multipeer_wrong_slot_count(bundle):
    mp = _mp(bundle)
    with pytest.raises(ValueError):
        mp.step_all(np.zeros((3, 64, 64, 3), np.uint8))


def test_multipeer_aot_cache_roundtrip(bundle, tmp_path):
    """The vmapped all-peers step exports/reloads through the engine cache
    (peers-N key attribute); a mesh-sharded engine refuses (returns False)."""
    mp = _mp(bundle, max_peers=2)
    ok = mp.use_aot_cache("tiny-test", cache_dir=str(tmp_path), build_on_miss=True)
    assert ok
    rng = np.random.default_rng(0)
    frames = rng.integers(0, 256, (2, 64, 64, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (2, 64, 64, 3)

    # fresh engine adopts WITHOUT building
    mp2 = _mp(bundle, max_peers=2)
    assert mp2.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=False
    )
    out2 = mp2.step_all(frames)
    assert out2.shape == (2, 64, 64, 3)

    # different peer count = different key -> miss
    mp3 = _mp(bundle, max_peers=4)
    assert not mp3.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=False
    )

    # sharded engines are not exportable
    mp4 = _mp(bundle, mesh=M.make_mesh(dp=4))
    assert not mp4.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=True
    )


def test_multipeer_sdxl_extras_swap_on_prompt_update(rng):
    """Round-1 defect regression: per-slot prompt updates on an SDXL-style
    engine must swap the POOLED embeds (added_text), not just cond/uncond."""
    bundle = registry.load_model_bundle("tiny-xl-test")
    cfg = registry.default_stream_config("tiny-xl-test")
    mp = MultiPeerEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_peers=2,
    ).start("base prompt")
    before = np.asarray(mp.states["added_text"])
    mp.update_prompt(1, "a different sdxl prompt")
    after = np.asarray(mp.states["added_text"])
    assert np.array_equal(before[0], after[0])  # slot 0 untouched
    assert not np.array_equal(before[1], after[1])  # slot 1 swapped

    frames = rng.integers(0, 256, (2, cfg.height, cfg.width, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (2, cfg.height, cfg.width, 3)
