"""Multi-peer batching tests (BASELINE configs[4])."""

import jax
import numpy as np
import pytest

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.parallel import mesh as M
from ai_rtc_agent_tpu.parallel.multipeer import MultiPeerEngine


@pytest.fixture(scope="module")
def bundle():
    return registry.load_model_bundle("tiny-test")


def _mp(bundle, mesh=None, max_peers=4):
    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
    )
    return MultiPeerEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_peers=max_peers, mesh=mesh,
    ).start("default prompt")


def test_multipeer_slots_and_step(bundle):
    mp = _mp(bundle)
    s0 = mp.connect("peer zero")
    s1 = mp.connect("peer one")
    assert (s0, s1) == (0, 1)
    rng = np.random.default_rng(0)
    frames = rng.integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (4, 64, 64, 3) and out.dtype == np.uint8
    # distinct inputs + per-peer state -> distinct outputs
    assert not np.array_equal(out[0], out[1])
    mp.disconnect(s0)
    assert mp.connect("replacement") == 0


def test_multipeer_per_peer_prompt_isolation(bundle):
    """Per-peer prompts: updating one slot must not disturb another —
    an upgrade over the reference's global prompt mutation (agent.py:423)."""
    mp = _mp(bundle)
    mp.connect("prompt A", seed=7)
    mp.connect("prompt A", seed=7)  # identical noise state for both slots
    rng = np.random.default_rng(1)
    frames = rng.integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)
    frames[1] = frames[0]  # identical inputs for slots 0/1
    base = mp.step_all(frames.copy())
    np.testing.assert_array_equal(base[0], base[1])  # same prompt+state+input

    mp.update_prompt(1, "a completely different prompt")
    out = mp.step_all(frames.copy())
    assert not np.array_equal(out[0], out[1])


def test_multipeer_sharded_over_dp(bundle):
    mesh = M.make_mesh(dp=4)
    mp = _mp(bundle, mesh=mesh)
    rng = np.random.default_rng(2)
    frames = rng.integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (4, 64, 64, 3)


def test_multipeer_wrong_slot_count(bundle):
    mp = _mp(bundle)
    with pytest.raises(ValueError):
        mp.step_all(np.zeros((3, 64, 64, 3), np.uint8))


def test_multipeer_aot_cache_roundtrip(bundle, tmp_path):
    """The vmapped all-peers step exports/reloads through the engine cache
    (peers-N key attribute); a mesh-sharded engine refuses (returns False)."""
    mp = _mp(bundle, max_peers=2)
    ok = mp.use_aot_cache("tiny-test", cache_dir=str(tmp_path), build_on_miss=True)
    assert ok
    rng = np.random.default_rng(0)
    frames = rng.integers(0, 256, (2, 64, 64, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (2, 64, 64, 3)

    # fresh engine adopts WITHOUT building
    mp2 = _mp(bundle, max_peers=2)
    assert mp2.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=False
    )
    out2 = mp2.step_all(frames)
    assert out2.shape == (2, 64, 64, 3)
    # adoption turns buckets off: the serialized full-batch executable IS
    # the cold-start guarantee; a lazy bucket jit would stall it
    mp2.connect("solo")
    assert mp2._bucket_for(1) is None

    # different peer count = different key -> miss
    mp3 = _mp(bundle, max_peers=4)
    assert not mp3.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=False
    )

    # sharded engines are not exportable
    mp4 = _mp(bundle, mesh=M.make_mesh(dp=4))
    assert not mp4.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=True
    )


@pytest.mark.slow  # compile-heavy composition (own tiny-xl build + step):
# the tiny-model sibling test_multipeer_per_peer_prompt_isolation keeps
# per-slot prompt-update isolation in tier-1 (ISSUE 13 budget pairing)
def test_multipeer_sdxl_extras_swap_on_prompt_update(rng):
    """Round-1 defect regression: per-slot prompt updates on an SDXL-style
    engine must swap the POOLED embeds (added_text), not just cond/uncond."""
    bundle = registry.load_model_bundle("tiny-xl-test")
    cfg = registry.default_stream_config("tiny-xl-test")
    mp = MultiPeerEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_peers=2,
    ).start("base prompt")
    before = np.asarray(mp.states["added_text"])
    mp.update_prompt(1, "a different sdxl prompt")
    after = np.asarray(mp.states["added_text"])
    assert np.array_equal(before[0], after[0])  # slot 0 untouched
    assert not np.array_equal(before[1], after[1])  # slot 1 swapped

    frames = rng.integers(0, 256, (2, cfg.height, cfg.width, 3), dtype=np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (2, cfg.height, cfg.width, 3)


def test_bucket_selection(bundle):
    """_bucket_for: smallest covering power-of-two below capacity."""
    mp = _mp(bundle, max_peers=8)
    assert mp._bucket_sizes == [1, 2, 4]
    assert mp._bucket_for(0) is None  # nothing active: caller's problem
    assert mp._bucket_for(1) == 1
    assert mp._bucket_for(2) == 2
    assert mp._bucket_for(3) == 4
    assert mp._bucket_for(5) is None  # above largest bucket -> full step
    assert mp._bucket_for(8) is None


def test_bucket_step_matches_full_step(bundle, monkeypatch):
    """One active peer in an 8-slot engine: the bucketed step must produce
    the same output and state trajectory for that peer as the full-batch
    step (MULTIPEER_BUCKETS=0), while stepping ~1 slot of work."""
    rng = np.random.default_rng(3)
    frames = rng.integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)

    def run(buckets: bool):
        monkeypatch.setenv("MULTIPEER_BUCKETS", "1" if buckets else "0")
        mp = _mp(bundle, max_peers=4)
        mp.connect("peer zero")  # slot 0
        mp.connect("dropme")  # slot 1 -> released: active set is scattered? no
        mp.disconnect(1)
        outs = [mp.step_all(frames) for _ in range(3)]
        state0 = jax.tree.map(lambda a: np.asarray(a[0]), mp.states)
        return outs, state0

    outs_b, st_b = run(True)
    outs_f, st_f = run(False)
    for ob, of in zip(outs_b, outs_f):
        # batch-1 vs batch-4 executables may fuse differently: allow one
        # uint8 quantization step of drift
        np.testing.assert_allclose(
            ob[0].astype(np.int16), of[0].astype(np.int16), atol=1
        )
    for a, b in zip(jax.tree.leaves(st_b), jax.tree.leaves(st_f)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_bucket_step_flops_scale_with_occupancy(bundle):
    """Compiler-level proof of VERDICT r2 weak #5: the bucket executable
    for 1 active slot costs ~1/P of the full-capacity step's FLOPs."""
    import jax.numpy as jnp

    mp = _mp(bundle, max_peers=4)
    mp.connect("solo")
    frames = np.zeros((4, 64, 64, 3), np.uint8)
    # force both executables to exist
    out = mp.step_all(frames)
    assert out.shape[0] == 4

    def flops_of(jitted, *args):
        lowered = jitted.lower(*args)
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))

    idx = jnp.zeros((1,), jnp.int32)
    f1 = flops_of(
        mp._bucket_step(1), mp.params, mp.states,
        jnp.zeros((1, 64, 64, 3), jnp.uint8), idx,
    )
    ffull = flops_of(
        jax.jit(mp._vstep), mp.params, mp.states,
        jnp.zeros((4, 64, 64, 3), jnp.uint8),
    )
    assert f1 > 0 and ffull > 0
    # gather/scatter overhead is tiny; 1-of-4 occupancy must cost well
    # under half the full batch
    assert f1 < 0.5 * ffull, (f1, ffull)


@pytest.mark.slow  # prewarm x AOT-adoption composition (~8s; ISSUE 15
# budget pairing): test_multipeer_aot_cache_roundtrip keeps the AOT
# surface and test_bucket_step_matches_full_step the bucket math in
# tier-1; the scheduler twin (prewarm-ready executables, zero serving
# retraces) is pinned by test_sharded_churn_never_retraces
def test_prewarm_buckets_compiles_and_survives_aot(bundle, tmp_path):
    """prewarm_buckets must produce READY executables (jax.jit alone is
    lazy) and re-enable buckets on the AOT-adopted path."""
    mp = _mp(bundle, max_peers=4)
    assert mp.use_aot_cache("tiny-test", cache_dir=str(tmp_path), build_on_miss=True)
    mp.connect("solo")
    assert mp._bucket_for(1) is None  # adopted, not prewarmed -> full batch
    mp.prewarm_buckets()
    assert mp._prewarmed
    assert mp._bucket_for(1) == 1  # prewarmed buckets win again
    # the prewarmed object is a compiled executable, not a lazy jit wrapper
    # (bucket steps are keyed (size, variant) since buckets x DeepCache)
    assert not hasattr(mp._bucket_steps[(1, "full")], "lower")
    frames = np.zeros((4, 64, 64, 3), np.uint8)
    out = mp.step_all(frames)
    assert out.shape == (4, 64, 64, 3)


@pytest.mark.slow  # builds + serializes + re-adopts the capture/cached
# pair (~14s); test_multipeer_aot_cache_roundtrip keeps the multipeer AOT
# surface in tier-1 and the scheduler AOT tests pin the pair discipline
def test_multipeer_deepcache_aot_pair_adopts_and_reloads(tmp_path, monkeypatch):
    """VERDICT r3 item 7 follow-through: the multipeer DeepCache pair is
    exportable — both variants serialize per peer count and a FRESH engine
    adopts them atomically with build_on_miss=False."""
    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.parallel.multipeer import MultiPeerEngine

    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test", unet_cache_interval=2)

    def engine():
        return MultiPeerEngine(
            bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
            max_peers=2,
        ).start("aot pair")

    mp = engine()
    assert mp.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=True
    )
    assert mp._aot_adopted
    mp.connect("p")
    frames = np.zeros((2, cfg.height, cfg.width, 3), np.uint8)
    for _ in range(4):  # both cadence variants execute through AOT calls
        out = mp.step_all(frames)
        assert np.isfinite(out.astype(np.float64)).all()

    # fresh process analog: no build allowed, pair must load from disk
    mp2 = engine()
    assert mp2.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=False
    )
    mp2.connect("p")
    for _ in range(4):
        mp2.step_all(frames)

    # a HALF-present pair must refuse (atomicity): nuke one variant's blob
    import os
    import shutil

    entries = sorted(os.listdir(tmp_path))
    assert len(entries) >= 2
    victim = os.path.join(tmp_path, entries[0])
    (shutil.rmtree if os.path.isdir(victim) else os.remove)(victim)
    mp3 = engine()
    assert not mp3.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=False
    )
