"""Per-session style adapters (ai_rtc_agent_tpu/adapters/) — ISSUE 20.

Unit pins for the registry half of the subsystem: kohya/peft banks resolve
through models/lora.py's parser against the loader key map, pad to the
closed rank-bucket set, refuse above the largest bucket, DROP
text-encoder/conv/unmatched groups loudly, and emit bank-shaped factor
rows with zero-extension over the union target set.  The runtime half
(factors inside the vmapped bucket step, parity with offline fusion) is
pinned by the equivalence driver's adapter leg and the scheduler tests.
"""

import logging

import jax.numpy as jnp
import numpy as np
import pytest

from ai_rtc_agent_tpu.adapters import (
    AdapterRegistry,
    build_registry,
    graft_unet_params,
    zero_factor_rows,
)
from ai_rtc_agent_tpu.adapters.registry import targets_digest
from ai_rtc_agent_tpu.models import loader as LD
from ai_rtc_agent_tpu.models import registry as REG

# diffusers spelling (what the parser emits) for two tiny-test attn linears
MQ_DIFF = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q"
MV_DIFF = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_v"
# param-tree spelling (what bank rows / graft paths use)
MQ_TREE = "down_blocks.0.attentions.0.blocks.0.attn1.to_q"
MV_TREE = "down_blocks.0.attentions.0.blocks.0.attn1.to_v"


@pytest.fixture(scope="module")
def bundle():
    return REG.load_model_bundle("tiny-test")


@pytest.fixture()
def reg(bundle):
    return AdapterRegistry(
        bundle.params["unet"], LD.unet_key_map(bundle.unet_cfg)
    )


def _group(rng, r=2, din=8, dout=8, alpha=None):
    return {
        "down": (rng.normal(size=(r, din)) * 0.2).astype(np.float32),
        "up": (rng.normal(size=(dout, r)) * 0.2).astype(np.float32),
        "alpha": float(r) if alpha is None else float(alpha),
    }


def test_rank_bucketing_pads_and_refuses(reg, rng):
    # rank 2 -> smallest blessed bucket (4); rank 5 -> 8
    reg.add("small", {MQ_DIFF: _group(rng, r=2)})
    assert reg.rank_of("small") == 4
    reg.add("mid", {MQ_DIFF: _group(rng, r=5)})
    assert reg.rank_of("mid") == 8
    assert reg.bank_rank == 8  # largest bucket in use
    # above the largest bucket: REFUSED, never truncated
    with pytest.raises(ValueError, match="refusing to truncate"):
        reg.add("huge", {MQ_DIFF: _group(rng, r=17)})
    assert "huge" not in reg
    # padding is explicit zeros beyond the true rank
    rows = reg.factor_rows("small")
    down = np.asarray(rows[MQ_TREE]["down"])
    assert down.shape == (8, 8)  # bank rank 8 x in_dim 8
    assert np.all(down[2:] == 0) and np.any(down[:2] != 0)


def test_drops_te_conv_unmatched_loudly(reg, rng, caplog):
    groups = {
        MQ_DIFF: _group(rng),                      # good 2-D linear
        f"te.{MQ_DIFF}": _group(rng),              # text encoder: dropped
        "down_blocks.0.resnets.0.conv1": _group(rng, din=8),  # conv: dropped
        "mid_block.bogus.to_q": _group(rng),       # unmatched: dropped
    }
    with caplog.at_level(logging.WARNING, logger="ai_rtc_agent_tpu.adapters.registry"):
        applied, dropped = reg.add("partial", groups)
    assert applied == 1 and len(dropped) == 3
    assert "DROPPED" in caplog.text
    assert list(reg.targets) == [MQ_TREE]
    # a fully-unresolvable bank is a hard error, not a no-op style
    with pytest.raises(ValueError, match="matched 0 of"):
        reg.add("bogus", {"mid_block.bogus.to_q": _group(rng)})
    assert "bogus" not in reg


def test_shape_mismatch_is_wrong_base_model(reg, rng):
    with pytest.raises(ValueError, match="wrong base model"):
        reg.add("misfit", {MQ_DIFF: _group(rng, din=16)})


def test_factor_rows_zero_extension_and_refusals(reg, rng):
    reg.add("styleA", {MQ_DIFF: _group(rng)})
    reg.add("styleB", {MQ_DIFF: _group(rng), MV_DIFF: _group(rng)})
    assert set(reg.targets) == {MQ_TREE, MV_TREE}
    # styleA's row spans the UNION target set with zeros at MV
    rows = reg.factor_rows("styleA")
    assert set(rows) == {MQ_TREE, MV_TREE}
    assert np.any(np.asarray(rows[MQ_TREE]["down"]) != 0)
    assert not np.any(np.asarray(rows[MV_TREE]["down"]))
    assert not np.any(np.asarray(rows[MV_TREE]["up"]))
    # name=None is the all-zero row; dtype honoured
    z = reg.factor_rows(None, dtype=jnp.bfloat16)
    assert z[MQ_TREE]["down"].dtype == jnp.bfloat16
    assert not np.any(np.asarray(z[MQ_TREE]["down"], np.float32))
    with pytest.raises(KeyError, match="unknown adapter"):
        reg.factor_rows("nope")
    # a bank narrower than the adapter's bucket: rebuild, don't clip
    with pytest.raises(ValueError, match="rebuild the scheduler"):
        reg.factor_rows("styleA", rank=2)


def test_scale_alpha_folded_into_up(reg, rng):
    g = _group(rng, r=2, alpha=1.0)  # alpha/r = 0.5
    reg.add("scaled", {MQ_DIFF: g}, scale=2.0)  # s = 2.0 * 0.5 = 1.0
    rows = reg.factor_rows("scaled")
    np.testing.assert_allclose(
        np.asarray(rows[MQ_TREE]["up"])[:, :2], g["up"], rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(rows[MQ_TREE]["down"])[:2], g["down"], rtol=1e-6
    )


def test_graft_inserts_factors_beside_kernel(bundle, reg, rng):
    reg.add("styleA", {MQ_DIFF: _group(rng)})
    rows = reg.factor_rows("styleA")
    grafted = graft_unet_params(bundle.params["unet"], rows)
    mod = grafted["down_blocks"][0]["attentions"][0]["blocks"][0]["attn1"]["to_q"]
    assert "lora_down" in mod and "lora_up" in mod
    assert mod["kernel"] is bundle.params["unet"]["down_blocks"][0][
        "attentions"][0]["blocks"][0]["attn1"]["to_q"]["kernel"]
    # untouched subtrees keep identity (donation/sharding unaffected)
    assert grafted["mid_block"] is bundle.params["unet"]["mid_block"]
    # the factored linear equals base + (x @ down.T) @ up.T
    from ai_rtc_agent_tpu.models.layers import linear

    x = jnp.asarray(rng.normal(size=(3, 8)), jnp.float32)
    base = linear(bundle.params["unet"]["down_blocks"][0]["attentions"][0][
        "blocks"][0]["attn1"]["to_q"], x)
    got = linear(mod, x)
    want = base + (x @ rows[MQ_TREE]["down"].T) @ rows[MQ_TREE]["up"].T
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    # zero rows are a bitwise no-op through the SAME factored code path
    zmod = dict(mod)
    zrows = zero_factor_rows({MQ_TREE: (8, 8)}, 4)
    zmod["lora_down"], zmod["lora_up"] = (
        zrows[MQ_TREE]["down"], zrows[MQ_TREE]["up"],
    )
    np.testing.assert_array_equal(np.asarray(linear(zmod, x)), np.asarray(base))


def test_fingerprint_tracks_bank_shape_not_names(reg, rng):
    assert reg.fingerprint() == {
        "adapter_rank": 0, "adapter_targets": targets_digest({}),
    }
    reg.add("styleA", {MQ_DIFF: _group(rng)})
    fp1 = reg.fingerprint()
    assert fp1["adapter_rank"] == 4
    # a second style over the SAME targets/rank keeps the fingerprint
    reg.add("styleA2", {MQ_DIFF: _group(rng)})
    assert reg.fingerprint() == fp1
    # widening the target set changes it
    reg.add("styleB", {MV_DIFF: _group(rng)})
    assert reg.fingerprint() != fp1


def test_build_registry_scans_directory(bundle, rng, tmp_path):
    kohya = "lora_unet_down_blocks_0_attentions_0_transformer_blocks_0_attn1_to_q"
    for name in ("ghibli", "noir"):
        g = _group(rng)
        LD.write_safetensors(str(tmp_path / f"{name}.safetensors"), {
            f"{kohya}.lora_down.weight": g["down"],
            f"{kohya}.lora_up.weight": g["up"],
            f"{kohya}.alpha": np.array(g["alpha"], np.float32),
        })
    reg = build_registry(
        bundle.params["unet"], bundle.unet_cfg, str(tmp_path)
    )
    assert reg.names == ["ghibli", "noir"] and reg.bank_rank == 4
    # ADAPTER_DIR unset -> empty registry, factors path off
    empty = build_registry(bundle.params["unet"], bundle.unet_cfg, None)
    assert len(empty) == 0 and empty.bank_rank == 0
    # a broken bank refuses the boot instead of half-loading the catalog
    LD.write_safetensors(str(tmp_path / "broken.safetensors"), {
        "lora_unet_mid_block_bogus_to_q.lora_down.weight": _group(rng)["down"],
        "lora_unet_mid_block_bogus_to_q.lora_up.weight": _group(rng)["up"],
    })
    with pytest.raises(ValueError, match="matched 0 of"):
        build_registry(bundle.params["unet"], bundle.unet_cfg, str(tmp_path))


def test_env_rank_buckets_parsing(monkeypatch):
    from ai_rtc_agent_tpu.utils import env

    monkeypatch.delenv("ADAPTER_RANK_BUCKETS", raising=False)
    assert env.adapter_rank_buckets() == (4, 8, 16)
    monkeypatch.setenv("ADAPTER_RANK_BUCKETS", "2, 8,32")
    assert env.adapter_rank_buckets() == (2, 8, 32)
    monkeypatch.setenv("ADAPTER_RANK_BUCKETS", "8,zero")
    with pytest.raises(ValueError):
        env.adapter_rank_buckets()
    monkeypatch.delenv("ADAPTER_RANK_BUCKETS", raising=False)
    monkeypatch.delenv("ADAPTER_DIR", raising=False)
    assert env.adapter_dir() is None
    monkeypatch.setenv("ADAPTER_DIR", "/styles")
    assert env.adapter_dir() == "/styles"
