"""Worker for the multi-host (multi-process) trainer test.

Each process owns 4 virtual CPU devices; jax.distributed joins them into one
8-device fleet (2 "hosts"), the dp x tp x sp mesh spans BOTH processes, and
one sharded train step runs — collectives cross the process boundary over
the Gloo transport (the CPU stand-in for DCN).  Prints "LOSS <value>".
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

port, pid, n = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(
    f"127.0.0.1:{port}", num_processes=n, process_id=pid
)
assert jax.process_count() == n, jax.process_count()
assert len(jax.devices()) == 4 * n, len(jax.devices())

import numpy as np  # noqa: E402

from ai_rtc_agent_tpu.models import unet as U  # noqa: E402
from ai_rtc_agent_tpu.ops import schedule as S  # noqa: E402
from ai_rtc_agent_tpu.parallel import mesh as M  # noqa: E402
from ai_rtc_agent_tpu.parallel.trainer import (  # noqa: E402
    ShardedTrainer,
    TrainerConfig,
)

mesh = M.make_mesh(dp=2, tp=2, sp=2)  # spans both processes
cfg = U.UNetConfig.tiny()
params = U.init_unet(jax.random.PRNGKey(0), cfg)  # identical on every host


def unet_apply(p, x, t, ctx, added):
    return U.apply_unet(p, x, t, ctx, cfg, added_cond=added)


tr = ShardedTrainer(
    unet_apply, S.make_schedule(), mesh, params, TrainerConfig(learning_rate=1e-3)
)
rng = np.random.default_rng(0)  # identical batch on every host
batch = {
    "latents": rng.standard_normal((4, 8, 8, 4)).astype(np.float32),
    "context": rng.standard_normal((4, 7, 32)).astype(np.float32),
}
l0 = tr.step(batch, jax.random.PRNGKey(1))
l1 = tr.step(batch, jax.random.PRNGKey(1))
assert np.isfinite(l0) and np.isfinite(l1), (l0, l1)
assert l1 < l0, (l0, l1)  # same batch+key twice -> loss drops
print(f"LOSS {l0:.6f} {l1:.6f}", flush=True)
