"""Engine cache tests: build, persist, reload without retracing."""

import numpy as np
import jax.numpy as jnp
import pytest

from ai_rtc_agent_tpu.aot.cache import EngineCache, engine_key


def test_engine_key_discipline():
    k = engine_key("stabilityai/sd-turbo", "img2img", batch=4, hw="512x512", dtype="bf16")
    assert k.startswith("engines--stabilityai--sd-turbo")
    assert "mode-img2img" in k and "batch-4" in k and "hw-512x512" in k
    # distinct configs -> distinct keys (the reference's cache-key law)
    assert k != engine_key("stabilityai/sd-turbo", "img2img", batch=2, hw="512x512", dtype="bf16")


def test_build_and_reload(tmp_path):
    cache = EngineCache(cache_dir=str(tmp_path))
    trace_count = {"n": 0}

    def f(x, y):
        trace_count["n"] += 1
        return x @ y + 1.0

    x = np.ones((4, 8), np.float32)
    y = np.ones((8, 4), np.float32)
    call = cache.load_or_build("engines--test--mode-x", f, (x, y))
    out = np.asarray(call(x, y))
    np.testing.assert_allclose(out, x @ y + 1.0)
    assert trace_count["n"] == 1

    # second load: cache hit, no retrace of python fn
    call2 = cache.load_or_build("engines--test--mode-x", f, (x, y))
    out2 = np.asarray(call2(x, y))
    np.testing.assert_allclose(out2, out)
    assert trace_count["n"] == 1  # python fn never retraced

    entries = cache.entries()
    assert len(entries) == 1 and entries[0]["key"] == "engines--test--mode-x"


def test_shape_change_is_new_engine(tmp_path):
    cache = EngineCache(cache_dir=str(tmp_path))

    def f(x):
        return x * 2

    c1 = cache.load_or_build("engines--t", f, (np.ones((2, 2), np.float32),))
    c2 = cache.load_or_build("engines--t", f, (np.ones((4, 4), np.float32),))
    assert np.asarray(c1(np.ones((2, 2), np.float32))).shape == (2, 2)
    assert np.asarray(c2(np.ones((4, 4), np.float32))).shape == (4, 4)


def test_pytree_args(tmp_path):
    cache = EngineCache(cache_dir=str(tmp_path))

    def f(state, x):
        return {"a": state["a"] + x}

    state = {"a": jnp.ones((3,))}
    call = cache.load_or_build("engines--tree", f, (state, jnp.ones((3,))))
    out = call(state, jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(out["a"]), 2 * np.ones((3,)))


def test_entries_skips_corrupt_meta(tmp_path, caplog):
    """ISSUE 7 satellite: one truncated/corrupt meta JSON (crashed build,
    partial copy) must not crash the whole listing — the bad entry is
    skipped with a warning and every readable entry still reports."""
    import json
    import logging
    import os

    cache = EngineCache(cache_dir=str(tmp_path))
    call = cache.load_or_build(
        "engines--good", lambda x: x + 1, (jnp.ones((2,)),)
    )
    assert call is not None
    # a second key whose meta is truncated mid-write
    bad_dir = os.path.join(str(tmp_path), "engines--bad")
    os.makedirs(bad_dir)
    with open(os.path.join(bad_dir, "deadbeef.json"), "w") as f:
        f.write('{"key": "engines--bad", "plat')  # truncated
    with caplog.at_level(logging.WARNING):
        entries = cache.entries()
    assert [e["key"] for e in entries] == ["engines--good"]
    assert any("unreadable engine meta" in r.message for r in caplog.records)
    # and a corrupt meta does not block serving the (intact) blob either
    reload = cache.load_or_build(
        "engines--good", lambda x: x + 1, (jnp.ones((2,)),), build=False
    )
    assert reload is not None


def test_aot_call_donates_state(tmp_path):
    """ISSUE 9 donation audit: jax.export records the donation aliasing in
    the StableHLO but Exported.call re-enters jit WITHOUT donate_argnums —
    before the _donating_call wrapper, every AOT-adopted engine kept a
    hidden defensive copy of its whole state pytree alive per step.  Both
    the fresh-build and the deserialize paths must delete the donated
    input buffers."""
    cache = EngineCache(cache_dir=str(tmp_path))

    def step(params, state, x):
        return {"a": state["a"] * params["w"] + x}, state["a"][:2]

    params = {"w": jnp.full((4,), 2.0)}
    x = jnp.ones((4,))

    # build path
    state = {"a": jnp.arange(4.0)}
    call = cache.load_or_build(
        "engines--donate", step, (params, state, x), donate_argnums=(1,)
    )
    ns, out = call(params, state, x)
    assert state["a"].is_deleted(), "build-path call kept a defensive copy"
    np.testing.assert_allclose(np.asarray(ns["a"]), [1.0, 3.0, 5.0, 7.0])

    # deserialize path (fresh cache object -> cache HIT)
    call2 = EngineCache(cache_dir=str(tmp_path)).load_or_build(
        "engines--donate", step,
        (params, {"a": jnp.arange(4.0)}, x), donate_argnums=(1,),
    )
    state2 = {"a": jnp.arange(4.0)}
    ns2, _ = call2(params, state2, x)
    assert state2["a"].is_deleted(), "cache-hit call kept a defensive copy"
    np.testing.assert_allclose(np.asarray(ns2["a"]), np.asarray(ns["a"]))

    # no donation requested -> args stay alive (no over-aggressive wrap)
    plain = cache.load_or_build("engines--nodonate", lambda a: a + 1, (x,))
    y = jnp.ones((4,))
    plain(y)
    assert not y.is_deleted()
