"""Mesh/collectives/ring-attention/TP tests on the virtual 8-device CPU mesh
(SURVEY.md section 4 'Device tests' tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ai_rtc_agent_tpu.parallel import mesh as M
from ai_rtc_agent_tpu.parallel import ring_attention as RA
from ai_rtc_agent_tpu.parallel import sharding as SH


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_make_mesh_shapes():
    m = M.make_mesh(dp=2, tp=2, sp=2)
    assert m.shape == {"dp": 2, "tp": 2, "sp": 2}
    m2 = M.auto_mesh(prefer="sp")
    assert m2.shape["sp"] == 8
    with pytest.raises(ValueError):
        M.make_mesh(dp=16)


def test_session_axis_rules_and_knobs(monkeypatch):
    """ISSUE 12 units: the session-axis sharding recipe (shared by the
    dp scheduler and multipeer) and the MESH_SHAPE/BATCHSCHED_DP knob
    parsing — all compile-free."""
    from ai_rtc_agent_tpu.utils import env

    m = M.make_mesh(dp=4)
    assert SH.session_axis_spec(m) == P("dp")
    repl, row = SH.session_shardings(m)
    assert repl.spec == P() and row.spec == P("dp")
    devs = SH.dp_devices(m)
    assert len(devs) == 4 and len(set(devs)) == 4
    # shard d of a leading-axis sharded array lives on dp_devices[d]
    arr = jax.device_put(jnp.arange(8.0), row)
    by_start = {
        (s.index[0].start or 0): next(iter(s.data.devices()))
        for s in arr.addressable_shards
    }
    assert [by_start[i * 2] for i in range(4)] == devs
    # a trivial axis replicates (the single-device scheduler unchanged)
    assert SH.session_axis_spec(M.make_mesh(tp=2)) == P()

    # knob parsing: MESH_SHAPE feeds dp when BATCHSCHED_DP is unset
    monkeypatch.delenv("BATCHSCHED_DP", raising=False)
    monkeypatch.setenv("MESH_SHAPE", "8,1,1")
    assert env.mesh_shape() == (8, 1, 1)
    assert env.batchsched_dp() == 8
    monkeypatch.setenv("MESH_SHAPE", "4x2")
    assert env.mesh_shape() == (4, 2, 1)
    monkeypatch.setenv("BATCHSCHED_DP", "2")
    assert env.batchsched_dp() == 2  # explicit knob wins
    # explicit 0 is the per-box kill-switch even under a fleet MESH_SHAPE
    monkeypatch.setenv("MESH_SHAPE", "8,1,1")
    monkeypatch.setenv("BATCHSCHED_DP", "0")
    assert env.batchsched_dp() == 1
    monkeypatch.delenv("MESH_SHAPE")
    assert env.batchsched_dp() == 1  # off -> single-device
    monkeypatch.setenv("MESH_SHAPE", "bogus")
    with pytest.raises(ValueError):
        env.mesh_shape()
    monkeypatch.setenv("MESH_SHAPE", "1,2,3,4")
    with pytest.raises(ValueError):
        env.mesh_shape()


def test_collectives_in_shard_map(rng):
    from functools import partial
    from jax.experimental.shard_map import shard_map

    m = M.make_mesh(dp=8)
    x = jnp.arange(8.0)

    f = shard_map(
        lambda v: jax.lax.psum(v, axis_name="dp"),
        mesh=m,
        in_specs=P("dp"),
        out_specs=P("dp"),
    )
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full(8, x.sum()))

    def ring_shift(v):
        n = jax.lax.psum(1, "dp")  # portable axis size on jax 0.4.x
        return jax.lax.ppermute(
            v, axis_name="dp", perm=[(i, (i + 1) % n) for i in range(n)]
        )

    g = shard_map(
        ring_shift,
        mesh=m,
        in_specs=P("dp"),
        out_specs=P("dp"),
        check_rep=False,
    )
    np.testing.assert_allclose(np.asarray(g(x)), np.roll(np.arange(8.0), 1))


@pytest.mark.parametrize("n_sp", [2, 4, 8])
def test_ring_attention_matches_dense(rng, n_sp):
    m = M.make_mesh(sp=n_sp)
    B, L, H, D = 2, 32, 4, 8
    q = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    want = np.asarray(RA.dense_reference(q, k, v))
    got = np.asarray(RA.ring_attention(q, k, v, m))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ulysses_attention_matches_dense(rng):
    m = M.make_mesh(sp=4)
    B, L, H, D = 1, 16, 4, 8  # H divisible by sp
    q = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    want = np.asarray(RA.dense_reference(q, k, v))
    got = np.asarray(RA.ulysses_attention(q, k, v, m))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_tp_param_shardings_rules():
    m = M.make_mesh(tp=8)
    params = {
        "attn1": {"to_q": {"kernel": jnp.zeros((64, 64))}},
        "ff": {"out": {"kernel": jnp.zeros((64, 64)), "bias": jnp.zeros((64,))}},
        "norm1": {"scale": jnp.zeros((64,)), "bias": jnp.zeros((64,))},
        "odd": {"to_q": {"kernel": jnp.zeros((3, 5))}},  # indivisible
    }
    sh = SH.param_shardings(m, params)
    assert sh["attn1"]["to_q"]["kernel"].spec == P(None, "tp")  # column
    assert sh["ff"]["out"]["kernel"].spec == P("tp", None)  # row
    assert sh["norm1"]["scale"].spec == P()  # replicated
    assert sh["odd"]["to_q"]["kernel"].spec == P(None, None)  # fallback


def test_tp_sharded_unet_forward_matches_single(rng):
    """The TP-sharded UNet must compute the SAME function."""
    from ai_rtc_agent_tpu.models import unet as U

    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(rng.standard_normal((1, 8, 8, 4)).astype(np.float32))
    ctx = jnp.asarray(rng.standard_normal((1, 7, 32)).astype(np.float32))
    t = jnp.array([42])
    want = np.asarray(U.apply_unet(params, x, t, ctx, cfg))

    m = M.make_mesh(tp=2)
    sharded = SH.shard_params(m, params)
    f = jax.jit(lambda p, x, t, c: U.apply_unet(p, x, t, c, cfg))
    got = np.asarray(f(sharded, x, t, ctx))
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-5)


@pytest.mark.slow  # ~1 min of optimizer steps on the simulated 8-dev mesh
def test_sharded_trainer_loss_decreases(rng):
    """Full dp x tp x sp train step on the virtual mesh: loss is finite and
    params actually update."""
    from ai_rtc_agent_tpu.models import unet as U
    from ai_rtc_agent_tpu.ops import schedule as S
    from ai_rtc_agent_tpu.parallel.trainer import ShardedTrainer, TrainerConfig

    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(1), cfg)
    m = M.make_mesh(dp=2, tp=2, sp=2)

    def unet_apply(p, x, t, ctx, added):
        return U.apply_unet(p, x, t, ctx, cfg, added_cond=added)

    tr = ShardedTrainer(
        unet_apply, S.make_schedule(), m, params, TrainerConfig(learning_rate=1e-3)
    )
    batch = {
        "latents": rng.standard_normal((4, 8, 8, 4)).astype(np.float32),
        "context": rng.standard_normal((4, 7, 32)).astype(np.float32),
    }
    l0 = tr.step(batch, jax.random.PRNGKey(0))
    l1 = tr.step(batch, jax.random.PRNGKey(0))  # same batch+key: loss must drop
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0
    assert int(np.asarray(tr.state["step"])) == 2


def test_unet_ring_attention_matches_xla(rng):
    """sp>1 must change the attention code path, not just the test file
    (VERDICT r1 item 6): the full tiny UNet forward under an sp mesh with
    attn_impl="ring" must match the single-device dense result."""
    from ai_rtc_agent_tpu.models import unet as U
    from ai_rtc_agent_tpu.models.layers import sp_attention_mesh

    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(0), cfg)
    x = rng.standard_normal((2, 8, 8, 4)).astype(np.float32)
    t = np.array([5, 9], np.int32)
    ctx = rng.standard_normal((2, 7, 32)).astype(np.float32)

    ref = U.apply_unet(params, x, t, ctx, cfg, attn_impl="xla")

    mesh = M.make_mesh(sp=8)
    with sp_attention_mesh(mesh, axis="sp"):
        out_ring = jax.jit(
            lambda p, x, t, c: U.apply_unet(p, x, t, c, cfg, attn_impl="ring")
        )(params, x, t, ctx)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(ref), atol=2e-4)

    # ulysses needs heads % sp == 0 (tiny has 2 heads -> sp=2 mesh)
    mesh2 = M.make_mesh(sp=2)
    with sp_attention_mesh(mesh2, axis="sp"):
        out_uly = jax.jit(
            lambda p, x, t, c: U.apply_unet(p, x, t, c, cfg, attn_impl="ulysses")
        )(params, x, t, ctx)
    np.testing.assert_allclose(np.asarray(out_uly), np.asarray(ref), atol=2e-4)


def test_unet_ring_attention_no_mesh_falls_back(rng):
    """attn_impl="ring" without an active sp mesh = plain dense attention."""
    from ai_rtc_agent_tpu.models import unet as U

    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(0), cfg)
    x = rng.standard_normal((1, 8, 8, 4)).astype(np.float32)
    t = np.array([3], np.int32)
    ctx = rng.standard_normal((1, 7, 32)).astype(np.float32)
    a = U.apply_unet(params, x, t, ctx, cfg, attn_impl="ring")
    b = U.apply_unet(params, x, t, ctx, cfg, attn_impl="xla")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow  # ~1.5 min: two trainer builds + checkpoint IO on 1 core
def test_trainer_checkpoint_roundtrip(rng, tmp_path):
    """Save mid-training, keep stepping, restore -> identical continuation
    (bitwise state; SURVEY sec.5 'checkpoint/resume' for the training tier)."""
    from ai_rtc_agent_tpu.models import unet as U
    from ai_rtc_agent_tpu.ops import schedule as S
    from ai_rtc_agent_tpu.parallel.trainer import ShardedTrainer, TrainerConfig

    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(1), cfg)
    m = M.make_mesh(dp=2, tp=2, sp=2)

    def unet_apply(p, x, t, ctx, added):
        return U.apply_unet(p, x, t, ctx, cfg, added_cond=added)

    tr = ShardedTrainer(
        unet_apply, S.make_schedule(), m, params, TrainerConfig(learning_rate=1e-3)
    )
    batch = {
        "latents": rng.standard_normal((4, 8, 8, 4)).astype(np.float32),
        "context": rng.standard_normal((4, 7, 32)).astype(np.float32),
    }
    tr.step(batch, jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpts")
    tr.save(ckpt)

    # fresh trainer restores BITWISE-identical state (the checkpoint
    # guarantee that is actually deterministic)
    tr2 = ShardedTrainer(
        unet_apply, S.make_schedule(), m, params, TrainerConfig(learning_rate=1e-3)
    )
    assert tr2.restore(ckpt)
    assert int(np.asarray(tr2.state["step"])) == 1
    for a, b in zip(jax.tree.leaves(tr.state), jax.tree.leaves(tr2.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    l_continue = tr.step(batch, jax.random.PRNGKey(7))
    l_resumed = tr2.step(batch, jax.random.PRNGKey(7))
    # the continuation itself is NOT guaranteed bitwise: orbax-restored
    # arrays can carry different device layouts than step-produced ones,
    # so XLA may compile a second executable whose reduction order drifts
    # at float32 ulp scale (observed 6e-8 after an unrelated conv-padding
    # change re-fused the graph).  Identical state + tight tolerance is
    # the honest contract.
    np.testing.assert_allclose(
        float(l_resumed), float(l_continue), rtol=0, atol=5e-6
    )
    # restored leaves keep the mesh placement
    some_leaf = jax.tree.leaves(tr2.state["params"])[0]
    assert some_leaf.sharding.mesh.shape == m.shape

    # empty dir -> False
    assert not tr2.restore(str(tmp_path / "nope"))


def test_ring_attention_long_context(rng):
    """Long-context tier (SURVEY sec.5): ring + ulysses at the 8k-token
    scale of SDXL-like latents (SD@512 self-attn is 4096 tokens; SDXL@1024
    is 16k), sharded over the full 8-device mesh — exactness holds at
    scale, memory per device stays O(L/n)."""
    m = M.make_mesh(sp=8)
    B, L, H, D = 1, 8192, 1, 64
    q = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(np.float32))
    want = np.asarray(RA.dense_reference(q, k, v))
    got = np.asarray(RA.ring_attention(q, k, v, m))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
