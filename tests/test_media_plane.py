"""Media-plane serving-path tests (VERDICT r1 items 3+4+10).

Proves the full native path the reference gets from its NVDEC/NVENC aiortc
fork (reference lib/pipeline.py:76-96, README.md:11-15):

  H.264 bytes -> RTP -> depacketize -> decode -> FrameRing ->
  VideoStreamTrack -> pipeline -> encode -> RTP -> H.264 bytes

including over a REAL UDP socket pair against the agent's /offer endpoint
(NativeRtpProvider), with decode/encode/glass-to-glass gauges landing in
/metrics.
"""

import asyncio
import json
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.media import native
from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.media.plane import H264RingSource, H264Sink
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider
from ai_rtc_agent_tpu.utils.profiling import FrameStats


@pytest.fixture(scope="module")
def native_lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native lib unavailable")
    return lib


def _h264():
    return native.h264_available()


class InvertPipeline:
    """Metadata-preserving stand-in for StreamDiffusionPipeline."""

    def __call__(self, frame):
        arr = frame.to_ndarray(format="rgb24")
        out = VideoFrame.from_ndarray(255 - arr)
        out.pts = frame.pts
        out.time_base = frame.time_base
        out.wall_ts = frame.wall_ts
        return out


def test_source_sink_rtp_roundtrip(native_lib):
    """Encoder -> RTP packets -> source (depacketize+decode+ring) -> frames;
    constant-color frames survive the lossy H.264 trip within tolerance."""
    stats = FrameStats()
    w = h = 64
    sink = H264Sink(w, h, stats=stats, use_h264=_h264())
    src = H264RingSource(w, h, stats=stats, use_h264=_h264())
    vals = [30, 90, 150, 210, 60, 120, 180, 240]
    got = []
    for i, v in enumerate(vals):
        frame = VideoFrame.from_ndarray(np.full((h, w, 3), v, np.uint8))
        frame.pts = i * 3000
        # a real decode stamp (an epoch-zero stamp would read as infinitely
        # stale and be shed at the OVERLOAD_TX_DEADLINE_MS encode gate)
        frame.wall_ts = time.monotonic()
        for pkt in sink.consume(frame):
            src.feed_packet(pkt)
        item = src._ring.pop()
        if item is not None:
            got.append(item[0])
    # flush any encoder delay
    au = sink.flush()
    while au:
        src.feed_au(au)
        au = sink.flush()
    while (item := src._ring.pop()) is not None:
        got.append(item[0])
    assert len(got) >= len(vals) - 2, "decoder swallowed too many frames"
    for arr in got:
        assert arr.shape == (h, w, 3)
        spread = float(arr.astype(np.float32).std())
        assert spread < 25.0, "constant frame came back non-constant"
    snap = stats.snapshot()
    assert "decode_p50_ms" in snap and "encode_p50_ms" in snap
    sink.close()
    src.close()


def test_agent_native_rtp_e2e(native_lib, monkeypatch):
    """The full wire: a client encodes frames, sends RTP over UDP to the
    agent; the agent decodes -> pipeline -> encodes -> RTP back over UDP;
    the client decodes and checks the processed pixels + /metrics stages."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    use_h264 = _h264()
    w = h = 64

    async def go():
        provider = NativeRtpProvider(use_h264=use_h264)
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        loop = asyncio.get_event_loop()
        recv_q: asyncio.Queue = asyncio.Queue()

        class _ClientRecv(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                recv_q.put_nowait(data)

        client_transport, _ = await loop.create_datagram_endpoint(
            _ClientRecv, local_addr=("127.0.0.1", 0)
        )
        client_port = client_transport.get_extra_info("sockname")[1]
        try:
            offer = json.dumps(
                {
                    "native_rtp": True,
                    "video": True,
                    "client_addr": ["127.0.0.1", client_port],
                    "width": w,
                    "height": h,
                }
            )
            r = await client.post(
                "/offer",
                json={"room_id": "rtp-room", "offer": {"sdp": offer, "type": "offer"}},
            )
            assert r.status == 200
            answer = await r.json()
            server_port = json.loads(answer["sdp"])["server_port"]
            assert server_port

            # client-side media: encode constant frames -> RTP -> server
            out_sink = H264Sink(w, h, use_h264=use_h264)
            back_src = H264RingSource(w, h, use_h264=use_h264)
            send_transport, _ = await loop.create_datagram_endpoint(
                asyncio.DatagramProtocol,
                remote_addr=("127.0.0.1", server_port),
            )
            try:
                val = 200
                decoded = []
                for i in range(12):
                    f = VideoFrame.from_ndarray(np.full((h, w, 3), val, np.uint8))
                    f.pts = i * 3000
                    for pkt in out_sink.consume(f):
                        send_transport.sendto(pkt)
                    # drain whatever came back so far
                    try:
                        while True:
                            data = recv_q.get_nowait()
                            back_src.feed_packet(data)
                    except asyncio.QueueEmpty:
                        pass
                    while (item := back_src._ring.pop()) is not None:
                        decoded.append(item[0])
                    await asyncio.sleep(0.05)
                # grace period for in-flight frames
                for _ in range(40):
                    if decoded:
                        break
                    await asyncio.sleep(0.05)
                    try:
                        while True:
                            back_src.feed_packet(recv_q.get_nowait())
                    except asyncio.QueueEmpty:
                        pass
                    while (item := back_src._ring.pop()) is not None:
                        decoded.append(item[0])

                assert decoded, "no processed frames made it back over UDP"
                mean = float(decoded[-1].astype(np.float32).mean())
                # pipeline inverts: 200 -> 55 (lossy codec tolerance)
                assert abs(mean - (255 - val)) < 20, mean

                m = await client.get("/metrics")
                snap = await m.json()
                assert snap.get("decode_p50_ms") is not None
                assert snap.get("encode_p50_ms") is not None
                if use_h264:
                    assert snap.get("glass_p50_ms") is not None
            finally:
                out_sink.close()
                back_src.close()
                send_transport.close()
        finally:
            client_transport.close()
            await client.close()

    asyncio.run(go())


def test_agent_native_rtp_real_engine_e2e(native_lib, monkeypatch):
    """H.264 bytes -> agent -> REAL StreamEngine (tiny hermetic model) ->
    H.264 bytes: the decode->diffuse->encode path the reference's headline
    is about (lib/pipeline.py:76-96), over real UDP."""
    monkeypatch.setenv("WARMUP_FRAMES", "1")
    # this test measures the compile-then-serve path: early frames age for
    # seconds behind the CPU jit compile by design, and must NOT be shed
    # at the encode-hop overload deadline
    monkeypatch.setenv("OVERLOAD_TX_DEADLINE_MS", "0")
    # ONE session is served here: cap the scheduler at one slot so
    # startup prewarm compiles only the k=1 bucket instead of {1,2,4,8}
    # (~20s of tier-1 wall-time; multi-bucket compile coverage lives in
    # test_batch_scheduler.py)
    monkeypatch.setenv("BATCHSCHED_MAX_SESSIONS", "1")
    use_h264 = _h264()

    async def go():
        provider = NativeRtpProvider(use_h264=use_h264)
        app = build_app(model_id="tiny-test", provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()  # builds the tiny pipeline (jit compile)
        pipe_cfg = app["pipeline"].config
        w, h = pipe_cfg.width, pipe_cfg.height
        loop = asyncio.get_event_loop()
        recv_q: asyncio.Queue = asyncio.Queue()

        class _ClientRecv(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                recv_q.put_nowait(data)

        client_transport, _ = await loop.create_datagram_endpoint(
            _ClientRecv, local_addr=("127.0.0.1", 0)
        )
        client_port = client_transport.get_extra_info("sockname")[1]
        try:
            offer = json.dumps(
                {
                    "native_rtp": True,
                    "video": True,
                    "client_addr": ["127.0.0.1", client_port],
                    "width": w,
                    "height": h,
                }
            )
            r = await client.post(
                "/offer",
                json={"room_id": "real", "offer": {"sdp": offer, "type": "offer"}},
            )
            assert r.status == 200
            server_port = json.loads((await r.json())["sdp"])["server_port"]

            out_sink = H264Sink(w, h, use_h264=use_h264)
            back_src = H264RingSource(w, h, use_h264=use_h264)
            send_transport, _ = await loop.create_datagram_endpoint(
                asyncio.DatagramProtocol,
                remote_addr=("127.0.0.1", server_port),
            )
            try:
                decoded = []
                rng = np.random.default_rng(0)
                for i in range(60):
                    arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
                    f = VideoFrame.from_ndarray(arr)
                    f.pts = i * 3000
                    for pkt in out_sink.consume(f):
                        send_transport.sendto(pkt)
                    try:
                        while True:
                            back_src.feed_packet(recv_q.get_nowait())
                    except asyncio.QueueEmpty:
                        pass
                    while (item := back_src._ring.pop()) is not None:
                        decoded.append(item[0])
                    if decoded:
                        break
                    # tiny-model step takes a moment on CPU; keep feeding
                    await asyncio.sleep(0.1)
                for _ in range(100):
                    if decoded:
                        break
                    await asyncio.sleep(0.1)
                    try:
                        while True:
                            back_src.feed_packet(recv_q.get_nowait())
                    except asyncio.QueueEmpty:
                        pass
                    while (item := back_src._ring.pop()) is not None:
                        decoded.append(item[0])

                assert decoded, "no diffused frames made it back"
                assert decoded[0].shape == (h, w, 3)
                m = await client.get("/metrics")
                snap = await m.json()
                assert snap["frames_total"] >= 1
            finally:
                out_sink.close()
                back_src.close()
                send_transport.close()
        finally:
            client_transport.close()
            await client.close()

    asyncio.run(go())


def test_rtp_reorder_buffer_orders_and_recovers():
    """Out-of-order delivery and single-packet loss through the reorder
    stage (real UDP reorders; FU-A assembly needs order)."""
    from ai_rtc_agent_tpu.media.rtp import RtpReorderBuffer

    def pkt(seq):
        return bytes([0x80, 96, (seq >> 8) & 0xFF, seq & 0xFF]) + b"x" * 8

    rb = RtpReorderBuffer(window=4)
    # in-order passes straight through
    assert rb.push(pkt(100)) == [pkt(100)]
    # gap: 102 buffered until 101 arrives, then both release in order
    assert rb.push(pkt(102)) == []
    assert rb.push(pkt(101)) == [pkt(101), pkt(102)]
    # late duplicate dropped
    assert rb.push(pkt(101)) == []
    # loss: the gap is abandoned once the window overflows
    out = []
    for s in (104, 105, 106, 107, 108):  # 103 never arrives
        out += rb.push(pkt(s))
    assert out == [pkt(s) for s in (104, 105, 106, 107, 108)]
    # wraparound
    rb2 = RtpReorderBuffer()
    assert rb2.push(pkt(65535)) == [pkt(65535)]
    assert rb2.push(pkt(0)) == [pkt(0)]


def test_source_survives_shuffled_packets(native_lib):
    """A frame's RTP packets delivered out of order still decode."""
    stats = FrameStats()
    w = h = 64
    sink = H264Sink(w, h, stats=stats, use_h264=_h264())
    src = H264RingSource(w, h, stats=stats, use_h264=_h264())
    got = 0
    for i, v in enumerate((40, 110, 180, 250, 70, 140)):
        frame = VideoFrame.from_ndarray(np.full((h, w, 3), v, np.uint8))
        frame.pts = i * 3000
        pkts = sink.consume(frame)
        # swap adjacent pairs within the AU (stays inside the reorder
        # window); leave the very first packet of the stream in place —
        # cold-start ordering before any reference point is unknowable
        start = 1 if i == 0 else 0
        for j in range(start, len(pkts) - 1, 2):
            pkts[j], pkts[j + 1] = pkts[j + 1], pkts[j]
        for p in pkts:
            src.feed_packet(p)
        while src._ring.pop() is not None:
            got += 1
    assert got >= 3, f"only {got} frames decoded from shuffled packets"
    sink.close()
    src.close()


def test_whip_whep_over_native_rtp(native_lib, monkeypatch):
    """Publisher (WHIP) and viewer (WHEP) over the native RTP wire: OBS-style
    ingest -> pipeline -> relay fan-out -> RTP back out to the subscriber."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    use_h264 = _h264()
    w = h = 64

    async def go():
        provider = NativeRtpProvider(use_h264=use_h264)
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        loop = asyncio.get_event_loop()
        recv_q: asyncio.Queue = asyncio.Queue()

        class _ViewerRecv(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                recv_q.put_nowait(data)

        viewer_tr, _ = await loop.create_datagram_endpoint(
            _ViewerRecv, local_addr=("127.0.0.1", 0)
        )
        viewer_port = viewer_tr.get_extra_info("sockname")[1]
        try:
            # publish: WHIP with a video ingest leg only
            whip_offer = json.dumps(
                {"native_rtp": True, "video": True, "width": w, "height": h}
            )
            r = await client.post(
                "/whip", data=whip_offer,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            ingest_port = json.loads(await r.text())["server_port"]
            assert app["state"]["source_track"] is not None

            # subscribe: WHEP, media flows OUT to the viewer's UDP port
            whep_offer = json.dumps(
                {
                    "native_rtp": True,
                    "video": False,
                    "client_addr": ["127.0.0.1", viewer_port],
                    "width": w,
                    "height": h,
                }
            )
            r = await client.post(
                "/whep", data=whep_offer,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201

            pub_sink = H264Sink(w, h, use_h264=use_h264)
            back_src = H264RingSource(w, h, use_h264=use_h264)
            pub_tr, _ = await loop.create_datagram_endpoint(
                asyncio.DatagramProtocol,
                remote_addr=("127.0.0.1", ingest_port),
            )
            try:
                val = 180
                decoded = []
                for i in range(60):
                    f = VideoFrame.from_ndarray(np.full((h, w, 3), val, np.uint8))
                    f.pts = i * 3000
                    for pkt in pub_sink.consume(f):
                        pub_tr.sendto(pkt)
                    await asyncio.sleep(0.05)
                    try:
                        while True:
                            back_src.feed_packet(recv_q.get_nowait())
                    except asyncio.QueueEmpty:
                        pass
                    while (item := back_src._ring.pop()) is not None:
                        decoded.append(item[0])
                    if decoded:
                        break
                assert decoded, "viewer got no frames over WHIP->WHEP native RTP"
                mean = float(decoded[-1].astype(np.float32).mean())
                assert abs(mean - (255 - val)) < 20, mean
            finally:
                pub_sink.close()
                back_src.close()
                pub_tr.close()
        finally:
            viewer_tr.close()
            await client.close()

    asyncio.run(go())


def test_rtp_client_drain_survives_bursts(native_lib):
    """NativeRtpClient.drain interleaves feed and poll: a burst of frames
    larger than the 4-slot latest-wins ring must all be counted, none
    evicted (code-review r3 — batch-feeding undercounted healthy streams)."""
    from ai_rtc_agent_tpu.media.rtp_client import NativeRtpClient

    async def go():
        c = await NativeRtpClient(64, 64, use_h264=_h264()).open()
        sink = H264Sink(64, 64, use_h264=_h264())
        try:
            for i in range(10):
                f = VideoFrame.from_ndarray(np.full((64, 64, 3), 20 * i, np.uint8))
                f.pts = i * 3000
                for pkt in sink.consume(f):
                    # queued across frames: outlives the packetizer pool
                    # window, so take a stable copy (pool contract,
                    # media/rtp.py module docstring)
                    c._recv_q.push(bytes(pkt))
            got = c.drain()
            assert got >= 8, got  # codec delay may hold back 1-2 frames
            assert c.back.dropped == 0
        finally:
            sink.close()
            c.close()

    asyncio.run(go())


def test_rtcp_on_media_port_does_not_desync_depacketizer(native_lib):
    """rtcp-mux regression (r5): a compound RR/SR interleaved with RTP on
    the media port must be ignored by the depacketizer — feeding it into
    the reorder buffer desyncs the seq window (its bytes 2:4 are a LENGTH
    field, not a seq) and every later frame drops."""
    from ai_rtc_agent_tpu.media.rtcp import make_rr, make_sr

    use_h264 = _h264()
    sink = H264Sink(64, 64, use_h264=use_h264)
    src = H264RingSource(64, 64, use_h264=use_h264)
    rng = np.random.default_rng(3)
    decoded = 0
    try:
        for i in range(8):
            f = VideoFrame.from_ndarray(
                rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
            )
            f.pts = i * 3000
            pkts = sink.consume(f)
            # interleave reports exactly where a muxed wire would carry them
            src.feed_packet(make_rr(0xABC, 0x5EED, fraction_lost=1))
            for pkt in pkts:
                src.feed_packet(pkt)
            src.feed_packet(make_sr(0x5EED, i * 3000, i + 1, 1000))
            while src.poll() is not None:
                decoded += 1
    finally:
        sink.close()
        src.close()
    assert decoded >= 6, f"only {decoded} frames survived muxed RTCP"


def test_sink_reconfigure_profile_and_scale(native_lib):
    """ISSUE 6: the session-level encoder mutation surface.  On the
    NullCodec tier the profile is still recorded (quality rungs stay
    observable without libavcodec) and the reduce-resolution decimation
    actually shrinks the frames on the wire."""
    sink = H264Sink(32, 32, use_h264=False)
    src = H264RingSource(32, 32, use_h264=False)
    try:
        frame = np.arange(32 * 32 * 3, dtype=np.uint8).reshape(32, 32, 3)
        for pkt in sink.consume(frame):
            src.feed_packet(bytes(pkt))
        got = src.poll()
        assert got is not None and got[0].shape == (32, 32, 3)

        sink.reconfigure(bitrate=500_000, gop=30, scale=2)
        assert sink.profile["bitrate"] == 500_000
        assert sink.profile["gop"] == 30
        assert sink.profile["scale"] == 2
        for pkt in sink.consume(frame):
            src.feed_packet(bytes(pkt))
        got = src.poll()
        assert got is not None and got[0].shape == (16, 16, 3), (
            "reduce-resolution rung must shrink the encoded geometry"
        )

        sink.reconfigure(scale=1)  # recovery restores full resolution
        assert sink.profile["bitrate"] == 500_000  # rate profile survives
        for pkt in sink.consume(frame):
            src.feed_packet(bytes(pkt))
        got = src.poll()
        assert got is not None and got[0].shape == (32, 32, 3)

        # odd decimated geometry is cropped to EVEN dims (yuv420 encoders
        # reject odd sizes — the degradation rung must never kill the
        # send path; review fix)
        sink.reconfigure(scale=2)
        odd = np.zeros((54, 42, 3), np.uint8)  # 54/2=27, 42/2=21: both odd
        for pkt in sink.consume(odd):
            src.feed_packet(bytes(pkt))
        got = src.poll()
        assert got is not None and got[0].shape == (26, 20, 3)
    finally:
        sink.close()
        src.close()


def test_pc_keyframe_governor_coalesces_pli_storm(native_lib):
    """rtc_native wiring: with a netadapt ladder attached, a PLI storm at
    _force_sink_keyframe costs ONE IDR per coalescing window."""
    from ai_rtc_agent_tpu.resilience.netadapt import NetworkAdaptLadder
    from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider

    provider = NativeRtpProvider()
    pc = provider.peer_connection()
    forced = []

    class FakeSink:
        def force_keyframe(self):
            forced.append(1)

        def reconfigure(self, **kw):
            pass

    try:
        pc._sink = FakeSink()
        na = NetworkAdaptLadder("s", pli_coalesce_s=60.0)
        pc.attach_netadapt(na)
        assert pc._rtcp_state.netadapt is na  # RR blocks feed the ladder
        for _ in range(25):
            pc._force_sink_keyframe()
        assert sum(forced) == 1, "PLI storm must cost one IDR per window"
        assert pc.kf_governor.coalesced == 24
    finally:
        provider.unregister_plane_session(pc.pc_id)
