"""Secure-tier per-packet cost pins (VERDICT r4 next-round #6).

docs/security.md claims SRTP crypto is <5% of one core at streaming rates;
scripts/secure_rate_profile.py measured it (committed in PERF.md).  These
tests keep the claim honest without a flaky absolute wall-clock bound:
each profile is normalized against ITS OWN underlying primitives from the
same crypto library (AES-GCM vs a raw AESGCM seal; CM vs raw AES-CTR +
HMAC-SHA1), so hardware where AES and SHA throughput scale differently
(AES-NI / SHA extensions) moves both sides together.  A Python-level
regression (accidental per-packet allocs, a lost fast path) shows up as
a ratio blowup.
"""

import pytest

# the secure tier's crypto backend is optional at the package level
# (signaling degrades to loopback without it) — these tests must SKIP,
# not fail collection, on a box without it (resilience PR satellite)
pytest.importorskip("cryptography", reason="secure tier needs cryptography")

import struct
import time

from ai_rtc_agent_tpu.server.secure.srtp import (
    PROFILE_AEAD_AES_128_GCM,
    PROFILE_AES128_CM_SHA1_80,
    derive_srtp_contexts,
)

PKT_SIZE = 1200
N = 500
REPEATS = 3  # best-of-N: the MIN is robust to scheduler noise on a
# contended box (a full-suite run competes for this 1-core host)


def _pkts():
    return [
        struct.pack("!BBHII", 0x80, 102, seq, seq * 3000, 0x5EED)
        + b"\x7c" * (PKT_SIZE - 12)
        for seq in range(1, N + 1)
    ]


def _best_of(fn) -> float:
    return min(fn() for _ in range(REPEATS))


def _baseline_cm_us() -> float:
    """Raw AES-128-CTR + HMAC-SHA1 over one packet — the same primitives
    one CM protect leg uses, minus the SRTP framing logic under test."""
    import hashlib
    import hmac as hmac_mod

    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    key = b"k" * 16
    mac_key = b"m" * 20
    buf = b"\x7c" * PKT_SIZE

    def run():
        t0 = time.perf_counter()
        for i in range(N):
            enc = Cipher(
                algorithms.AES(key), modes.CTR(i.to_bytes(16, "big"))
            ).encryptor()
            ct = enc.update(buf) + enc.finalize()
            hmac_mod.new(mac_key, ct, hashlib.sha1).digest()
        return 1e6 * (time.perf_counter() - t0) / N

    return _best_of(run)


def _baseline_gcm_us() -> float:
    """Raw AESGCM seal over one packet — the GCM profile's primitive."""
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    aead = AESGCM(b"k" * 16)
    buf = b"\x7c" * PKT_SIZE

    def run():
        t0 = time.perf_counter()
        for i in range(N):
            aead.encrypt(i.to_bytes(12, "big"), buf, b"")
        return 1e6 * (time.perf_counter() - t0) / N

    return _best_of(run)


def _roundtrip_us(profile) -> float:
    km = b"\x5a" * 60

    def run():
        tx, _ = derive_srtp_contexts(km, is_server=True, profile=profile)
        _, rx = derive_srtp_contexts(km, is_server=False, profile=profile)
        pkts = _pkts()
        t0 = time.perf_counter()
        for p in pkts:
            rx.unprotect(tx.protect(p))
        return 1e6 * (time.perf_counter() - t0) / N

    return _best_of(run)


def test_cm_profile_per_packet_cost_bounded():
    base = _baseline_cm_us()
    cost = _roundtrip_us(PROFILE_AES128_CM_SHA1_80)
    # roundtrip = 2x the primitive leg + SRTP framing; generous fence
    assert cost < 12 * base, f"CM roundtrip {cost:.1f}us vs base {base:.1f}us"


def test_gcm_profile_per_packet_cost_bounded():
    base = _baseline_gcm_us()
    cost = _roundtrip_us(PROFILE_AEAD_AES_128_GCM)
    # the one-shot AESGCM primitive is so fast (~0.7us) that the roundtrip
    # ratio mostly measures the Python SRTP framing (~13x on the build
    # box); 25x is the regression fence for that framing cost
    assert cost < 25 * base, f"GCM roundtrip {cost:.1f}us vs base {base:.1f}us"


def test_core_share_claim_at_streaming_rate():
    """The docs/security.md '<5% of a core' claim, with slack for slow CI
    boxes: even at 25% the order of magnitude documented is right."""
    cost_s = _roundtrip_us(PROFILE_AES128_CM_SHA1_80) / 1e6
    core_share = 400 * cost_s  # 400 pkts/s each way at 30 fps 512²
    assert core_share < 0.25, f"core share {core_share:.3f}"
