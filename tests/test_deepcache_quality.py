"""DeepCache QUALITY measurement (VERDICT r3 item 6).

The wiring tests (test_deepcache.py) pin that capture-then-use is exact on
IDENTICAL inputs and that the cached step costs 0.54x FLOPs.  The actual
risk of the approximation is different: on MOVING content the deep
features grow stale between refreshes.  This file measures it — PSNR/SSIM
of the cached-interval stream against the full-UNet stream on a synthetic
moving scene — and pins the floor so a regression in the splice point
or cadence shows up as a quality number, not a vibe.

The measured curve (hermetic tiny geometry, random weights) lives in
PERF.md §DeepCache; the real-weight curve must be re-measured when
weights are available (scripts/deepcache_quality.py prints the table).
"""

import numpy as np
import pytest

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.stream.engine import StreamEngine
from ai_rtc_agent_tpu.utils.quality import moving_scene, psnr, ssim

WARMUP = 6  # ring depth 4 + slack: compare steady-state outputs only
N_FRAMES = 18


def _moving_scene(n, h=64, w=64):
    return moving_scene(n, h, w)  # shared generator (utils/quality.py)


def _stream_outputs(interval: int):
    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config(
        "tiny-test", unet_cache_interval=interval
    )
    eng = StreamEngine(
        models=bundle.stream_models,
        params=bundle.params,
        cfg=cfg,
        encode_prompt=bundle.encode_prompt,
    )
    eng.prepare("a moving scene", seed=7)
    return [eng(f) for f in _moving_scene(N_FRAMES)][WARMUP:]


@pytest.fixture(scope="module")
def curves():
    full = _stream_outputs(0)
    rows = {}
    # intervals 3 (the shipped default, floor-pinned below) and 5 (the
    # far point of the curve) carry every assertion; interval 2 carried
    # none and cost a full engine build — dropped for the tier-1 wall-time
    # budget (ROADMAP standing constraints).  The full curve incl. 2 stays
    # measurable via scripts/deepcache_quality.py.
    for interval in (3, 5):
        cached = _stream_outputs(interval)
        ps = [psnr(a, b) for a, b in zip(full, cached)]
        ss = [ssim(a, b) for a, b in zip(full, cached)]
        rows[interval] = (float(np.mean(ps)), float(np.mean(ss)))
    return rows


def test_quality_curve_reported_and_floored(curves):
    for interval, (p, s) in sorted(curves.items()):
        print(f"DEEPCACHE interval={interval} psnr={p:.2f}dB ssim={s:.4f}")
    # floors pinned from the measured hermetic curve (see PERF.md) with
    # slack; a splice-point regression craters these
    assert curves[3][0] > curves[5][0] - 3.0  # shorter interval not worse
    for interval, (p, s) in curves.items():
        assert np.isfinite(p) and 0.0 <= s <= 1.0


def test_interval3_tracks_full_stream(curves):
    """The default cadence (3) must stay close to the full stream — the
    justification for shipping it as the bench default.  Floors pinned
    with slack from the measured hermetic curve (57.1 dB / 1.0000,
    PERF.md §DeepCache quality)."""
    p3, s3 = curves[3]
    assert p3 >= 40.0, f"interval-3 PSNR collapsed: {p3:.2f} dB"
    assert s3 >= 0.99, f"interval-3 SSIM collapsed: {s3:.4f}"
