"""STUN (server/secure/stun.py) pinned against RFC 5769 test vectors.

The reference's STUN/ICE lives in aiortc (reference agent.py:13-20); these
vectors pin our wire format against the IETF's published byte-exact
samples, not against our own encoder.
"""

import pytest

# the secure tier's crypto backend is optional at the package level
# (signaling degrades to loopback without it) — these tests must SKIP,
# not fail collection, on a box without it (resilience PR satellite)
pytest.importorskip("cryptography", reason="secure tier needs cryptography")

import struct

from ai_rtc_agent_tpu.server.secure import stun

def _rfc5769_request() -> bytes:
    # s2.1 — sample request: SOFTWARE "STUN test client", PRIORITY,
    # ICE-CONTROLLED, USERNAME "evtj:h6vY", MESSAGE-INTEGRITY keyed
    # "VOkJxbRl1RmTxUk/WvJxBt", FINGERPRINT
    return bytes.fromhex(
        "000100582112a442b7e7a701bc34d686fa87dfae"
        "80220010"
        "5354554e207465737420636c69656e74"
        "00240004"
        "6e0001ff"
        "80290008"
        "932ff9b151263b36"
        "00060009"
        "6576746a3a68367659202020"
        "00080014"
        "9aeaa70cbfd8cb56781ef2b5b2d3f249c1b571a2"
        "80280004"
        "e57a3bcf"
    )


def _rfc5769_response() -> bytes:
    # s2.2 — sample IPv4 response (XOR-MAPPED-ADDRESS 192.0.2.1:32853,
    # SOFTWARE "test vector")
    return bytes.fromhex(
        "0101003c2112a442b7e7a701bc34d686fa87dfae"
        "8022000b"
        "7465737420766563746f7220"
        "00200008"
        "0001a147e112a643"
        "00080014"
        "2b91f599fd9e90c38c7489f92af9ba53f06be7d7"
        "80280004"
        "c07d4c96"
    )


def test_rfc5769_request_decodes_and_verifies():
    raw = _rfc5769_request()
    assert stun.is_stun(raw)
    msg = stun.StunMessage.decode(raw)
    assert msg.message_type == stun.BINDING_REQUEST
    assert msg.get(stun.ATTR_USERNAME) == b"evtj:h6vY"
    assert msg.verify_integrity(b"VOkJxbRl1RmTxUk/WvJxBt", raw)
    # wrong key must fail
    assert not msg.verify_integrity(b"wrong-password", raw)


def test_rfc5769_response_xor_mapped_address():
    msg = stun.StunMessage.decode(_rfc5769_response())
    assert msg.message_type == stun.BINDING_SUCCESS
    assert msg.xor_mapped_address() == ("192.0.2.1", 32853)


def test_xor_address_roundtrip():
    val = stun.StunMessage.xor_address_value("203.0.113.7", 61000)
    msg = stun.StunMessage(stun.BINDING_SUCCESS)
    msg.attributes.append((stun.ATTR_XOR_MAPPED_ADDRESS, val))
    raw = msg.encode()
    back = stun.StunMessage.decode(raw)
    assert back.xor_mapped_address() == ("203.0.113.7", 61000)


def test_encode_with_integrity_verifies():
    msg = stun.StunMessage(stun.BINDING_REQUEST)
    msg.attributes.append((stun.ATTR_USERNAME, b"abcd:efgh"))
    raw = msg.encode(integrity_key=b"secret-pwd")
    back = stun.StunMessage.decode(raw)
    assert back.verify_integrity(b"secret-pwd", raw)
    # fingerprint attribute must be last and valid per RFC 5389 s15.5
    assert back.attributes[-1][0] == stun.ATTR_FINGERPRINT


def test_tampered_message_fails_integrity():
    msg = stun.StunMessage(stun.BINDING_REQUEST)
    msg.attributes.append((stun.ATTR_USERNAME, b"abcd:efgh"))
    raw = bytearray(msg.encode(integrity_key=b"secret-pwd"))
    raw[25] ^= 0xFF  # flip a bit inside USERNAME
    back = stun.StunMessage.decode(bytes(raw))
    assert not back.verify_integrity(b"secret-pwd", bytes(raw))


class TestIceLiteResponder:
    def _bind_request(self, resp: stun.IceLiteResponder, use_candidate=True):
        msg = stun.StunMessage(stun.BINDING_REQUEST)
        msg.attributes.append(
            (stun.ATTR_USERNAME, f"{resp.ufrag}:clientfrag".encode())
        )
        msg.attributes.append((stun.ATTR_PRIORITY, struct.pack("!I", 12345)))
        if use_candidate:
            msg.attributes.append((stun.ATTR_USE_CANDIDATE, b""))
        return msg.encode(integrity_key=resp.pwd.encode())

    def test_authenticated_binding_gets_success_and_latches(self):
        resp = stun.IceLiteResponder()
        raw = self._bind_request(resp)
        reply = resp.handle(raw, ("198.51.100.9", 50000))
        assert reply is not None
        back = stun.StunMessage.decode(reply)
        assert back.message_type == stun.BINDING_SUCCESS
        assert back.transaction_id == stun.StunMessage.decode(raw).transaction_id
        assert back.xor_mapped_address() == ("198.51.100.9", 50000)
        # reply is integrity-protected with our pwd (RFC 8445 s7.3)
        assert back.verify_integrity(resp.pwd.encode(), reply)
        assert resp.nominated_addr == ("198.51.100.9", 50000)

    def test_wrong_password_is_dropped(self):
        resp = stun.IceLiteResponder()
        msg = stun.StunMessage(stun.BINDING_REQUEST)
        msg.attributes.append(
            (stun.ATTR_USERNAME, f"{resp.ufrag}:x".encode())
        )
        raw = msg.encode(integrity_key=b"not-the-password")
        assert resp.handle(raw, ("198.51.100.9", 50000)) is None
        assert resp.nominated_addr is None

    def test_wrong_ufrag_is_dropped(self):
        resp = stun.IceLiteResponder()
        msg = stun.StunMessage(stun.BINDING_REQUEST)
        msg.attributes.append((stun.ATTR_USERNAME, b"someoneelse:x"))
        raw = msg.encode(integrity_key=resp.pwd.encode())
        assert resp.handle(raw, ("198.51.100.9", 50000)) is None

    def test_credentialless_probe_answered_but_never_latches(self):
        """A spoofed credential-less Binding Request must not steer media
        (code-review r4): it still gets its XOR-MAPPED-ADDRESS reply, but
        only MESSAGE-INTEGRITY-verified requests may latch the peer addr."""
        resp = stun.IceLiteResponder()
        probe = stun.StunMessage(stun.BINDING_REQUEST).encode()
        reply = resp.handle(probe, ("203.0.113.66", 4444))
        assert reply is not None
        assert stun.StunMessage.decode(reply).xor_mapped_address() == (
            "203.0.113.66",
            4444,
        )
        assert resp.nominated_addr is None
        assert resp.seen_addr is None
        # an authenticated request from the real peer then wins the latch
        raw = self._bind_request(resp)
        resp.handle(raw, ("198.51.100.9", 50000))
        assert resp.nominated_addr == ("198.51.100.9", 50000)

    def test_non_stun_and_malformed_ignored(self):
        resp = stun.IceLiteResponder()
        assert resp.handle(b"\x80\x60aaaa", ("1.2.3.4", 5)) is None
        assert resp.handle(b"\x00\x01", ("1.2.3.4", 5)) is None

    def test_ice_string_alphabet(self):
        s = stun.random_ice_string(22)
        assert len(s) == 22
        allowed = set(
            "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/"
        )
        assert set(s) <= allowed
