"""In-process stand-in for the ``aiortc`` package.

The real aiortc is not installable in this environment (zero egress), so
``AiortcProvider`` (server/signaling.py) would otherwise never execute.
This module implements the EXACT API surface the reference drives —
documented at reference agent.py:13-20 (imports), :72-77 (force_codec,
``codec.mimeType``), :149-152 (``RTCRtpSender.getCapabilities`` /
``codec.name`` / ``setCodecPreferences``), :256-263 (the name-mangled
``pc._RTCPeerConnection__gather()`` OBS workaround), :123-395 (pc event
decorators, setRemoteDescription/createAnswer/setLocalDescription,
localDescription, connectionState) — so the provider and the agent's
aiortc-specific wiring run for real, pinned against that contract.

``install()`` registers ``aiortc`` and ``aiortc.rtcrtpsender`` in
sys.modules and returns this module for introspection (created pcs are
recorded in ``PEER_CONNECTIONS``).
"""

from __future__ import annotations

import asyncio
import sys
import types

import numpy as np

PEER_CONNECTIONS: list["RTCPeerConnection"] = []


class RTCSessionDescription:
    def __init__(self, sdp: str, type: str):
        self.sdp = sdp
        self.type = type


class RTCIceServer:
    def __init__(self, urls, username=None, credential=None):
        self.urls = urls
        self.username = username
        self.credential = credential


class RTCConfiguration:
    def __init__(self, iceServers=None):
        self.iceServers = iceServers or []


class RTCRtpCodecCapability:
    """Real aiortc capabilities expose BOTH mimeType ("video/H264") and a
    short name ("H264") — the reference filters on each in different spots
    (agent.py:76 vs :151)."""

    def __init__(self, mimeType: str, clockRate: int = 90000):
        self.mimeType = mimeType
        self.clockRate = clockRate

    @property
    def name(self) -> str:
        return self.mimeType.split("/", 1)[1]

    def __repr__(self):
        return f"Codec({self.mimeType})"


class _Capabilities:
    def __init__(self, codecs):
        self.codecs = codecs


class RTCRtpSender:
    _VIDEO_CODECS = [
        RTCRtpCodecCapability("video/VP8"),
        RTCRtpCodecCapability("video/rtx"),
        RTCRtpCodecCapability("video/H264"),
        RTCRtpCodecCapability("video/VP9"),
    ]

    def __init__(self, track=None):
        self.track = track

    @classmethod
    def getCapabilities(cls, kind: str):
        if kind != "video":
            return _Capabilities([])
        return _Capabilities(list(cls._VIDEO_CODECS))


class _Transceiver:
    def __init__(self, kind: str, sender: RTCRtpSender):
        self.kind = kind
        self.sender = sender
        self.codec_preferences = None

    def setCodecPreferences(self, prefs):
        if not prefs:
            raise ValueError("codec preferences must not be empty")
        self.codec_preferences = list(prefs)


class _EventEmitter:
    def __init__(self):
        self._handlers: dict[str, list] = {}

    def on(self, event: str, f=None):
        def register(fn):
            self._handlers.setdefault(event, []).append(fn)
            return fn

        return register(f) if f else register

    def _emit(self, event: str, *args):
        """Run handlers; async handlers are scheduled like aiortc's
        AsyncIOEventEmitter does."""
        for fn in self._handlers.get(event, []):
            r = fn(*args)
            if asyncio.iscoroutine(r):
                asyncio.ensure_future(r)


class RemoteVideoTrack(_EventEmitter):
    """Remote media track announced by setRemoteDescription; recv() yields
    bare uint8 HWC ndarrays — one of the duck-typed frame forms the
    pipeline's coerce_frame accepts (reference frame contract
    lib/tracks.py:34-37; av.VideoFrame-shaped objects are exercised by the
    loopback/native tiers)."""

    kind = "video"

    def __init__(self, width=64, height=64):
        super().__init__()
        self._w, self._h = width, height
        self._i = 0

    async def recv(self):
        self._i += 1
        frame = np.full((self._h, self._w, 3), self._i % 255, np.uint8)
        return frame


class FakeDataChannel(_EventEmitter):
    label = "control"

    def __init__(self):
        super().__init__()
        self.sent: list = []

    def send(self, m):
        self.sent.append(m)

    async def deliver(self, message: str):
        """Test hook: run the registered on('message') handlers to
        completion (the agent's handler is async)."""
        for fn in self._handlers.get("message", []):
            r = fn(message)
            if asyncio.iscoroutine(r):
                await r


class RTCPeerConnection(_EventEmitter):
    """NOTE: the class must be named exactly ``RTCPeerConnection`` — the
    agent's OBS workaround calls the name-mangled private
    ``pc._RTCPeerConnection__gather()`` (reference agent.py:256-263), which
    only resolves against this class name."""

    def __init__(self, configuration=None):
        super().__init__()
        self.configuration = configuration
        self.connectionState = "new"
        self.iceConnectionState = "new"
        self.localDescription = None
        self.remoteDescription = None
        self.gather_calls = 0
        self._transceivers: list[_Transceiver] = []
        self.data_channels: list[FakeDataChannel] = []
        self.remote_tracks: list[RemoteVideoTrack] = []
        PEER_CONNECTIONS.append(self)

    # -- media plumbing ----------------------------------------------------
    def addTransceiver(self, kind: str):
        t = _Transceiver(kind, RTCRtpSender())
        self._transceivers.append(t)
        return t

    def getTransceivers(self):
        return list(self._transceivers)

    def addTrack(self, track):
        sender = RTCRtpSender(track)
        self._transceivers.append(_Transceiver("video", sender))
        return sender

    # -- signaling ---------------------------------------------------------
    async def setRemoteDescription(self, desc):
        if "m=" not in desc.sdp:
            # aiortc raises ValueError on an offer with no media sections;
            # the agent maps this to HTTP 400
            raise ValueError("offer has no media sections")
        self.remoteDescription = desc
        if "m=video" in desc.sdp:
            track = RemoteVideoTrack()
            self.remote_tracks.append(track)
            self._emit("track", track)
        if "m=application" in desc.sdp:
            ch = FakeDataChannel()
            self.data_channels.append(ch)
            self._emit("datachannel", ch)

    async def __gather(self):  # mangles to _RTCPeerConnection__gather
        self.gather_calls += 1

    async def createAnswer(self):
        if self.remoteDescription is None:
            raise ValueError("no remote description set")
        lines = ["v=0", "o=- 0 0 IN IP4 127.0.0.1", "s=-", "t=0 0"]
        if "m=video" in self.remoteDescription.sdp or any(
            t.kind == "video" for t in self._transceivers
        ):
            lines += [
                "m=video 9 UDP/TLS/RTP/SAVPF 102",
                "a=rtpmap:102 H264/90000",
            ]
            if self.gather_calls:
                # non-trickle: candidates inline (the point of __gather)
                lines.append(
                    "a=candidate:1 1 udp 2130706431 127.0.0.1 40000 typ host"
                )
        return RTCSessionDescription(sdp="\r\n".join(lines) + "\r\n", type="answer")

    async def setLocalDescription(self, desc):
        self.localDescription = desc
        self.connectionState = "connecting"

    async def close(self):
        if self.connectionState == "closed":
            return
        self.connectionState = "closed"
        self.iceConnectionState = "closed"
        self._emit("connectionstatechange")

    # -- test hooks --------------------------------------------------------
    async def simulate_state(self, state: str):
        """Drive connectionstatechange handlers to completion."""
        self.connectionState = state
        for fn in self._handlers.get("connectionstatechange", []):
            r = fn()
            if asyncio.iscoroutine(r):
                await r


def install() -> types.ModuleType:
    """Register fake 'aiortc' + 'aiortc.rtcrtpsender' modules and return
    the aiortc module object.  Idempotent; clears PEER_CONNECTIONS."""
    PEER_CONNECTIONS.clear()
    mod = types.ModuleType("aiortc")
    mod.RTCConfiguration = RTCConfiguration
    mod.RTCIceServer = RTCIceServer
    mod.RTCPeerConnection = RTCPeerConnection
    mod.RTCSessionDescription = RTCSessionDescription
    sub = types.ModuleType("aiortc.rtcrtpsender")
    sub.RTCRtpSender = RTCRtpSender
    mod.rtcrtpsender = sub
    sys.modules["aiortc"] = mod
    sys.modules["aiortc.rtcrtpsender"] = sub
    return mod


def uninstall() -> None:
    sys.modules.pop("aiortc", None)
    sys.modules.pop("aiortc.rtcrtpsender", None)
