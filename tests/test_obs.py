"""obs/ subsystem (ISSUE 5): per-frame tracing, flight recorder, exports.

Four layers, all hermetic and fast:

* trace.py unit contract — zero-cost-when-off attach, span/mark stamping,
  first-terminal-wins sealing, bounded rings, the capture-window clamp;
* recorder.py unit contract — always-on event log, bounded snapshot
  store, snapshot survival past session teardown;
* export.py validity — the Chrome trace-event rendering parses, its
  ``ph``/``ts``/``pid``/``tid`` fields conform, per-track spans stay
  disjoint (lane spill), a shed frame renders with its terminal marker,
  and the JSONL rendering round-trips;
* the chaos acceptance — a seeded FAULT_PLAN drives a live loopback
  session to DEGRADED: the flight recorder auto-captures a snapshot whose
  event log holds the supervisor transition and whose frame timelines
  carry shed/passthrough terminals; ``GET /debug/flight`` serves it and
  the Chrome-trace export of it validates.
"""

import asyncio
import json
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.obs.export import stop_jax_bridge, to_chrome_trace, to_jsonl
from ai_rtc_agent_tpu.obs.recorder import FlightRecorder
from ai_rtc_agent_tpu.obs.trace import (
    STAGES,
    FrameTrace,
    SessionTracer,
    TraceController,
    get_trace,
)
from ai_rtc_agent_tpu.resilience import faults
from ai_rtc_agent_tpu.resilience.faults import FaultPlan, FaultSpec
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.events import StreamEventHandler
from ai_rtc_agent_tpu.server.signaling import (
    LoopbackProvider,
    make_loopback_offer,
)
from ai_rtc_agent_tpu.utils.profiling import FrameStats


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.deactivate()
    yield
    faults.deactivate()


def _on_controller() -> TraceController:
    c = TraceController()
    c.enabled = True
    return c


# ---------------------------------------------------------------------------
# trace.py
# ---------------------------------------------------------------------------

def test_attach_off_is_none_and_leaves_frame_untouched():
    ctrl = TraceController()
    ctrl.stop()
    tracer = SessionTracer("s", ctrl)
    f = VideoFrame.from_ndarray(np.zeros((4, 4, 3), dtype=np.uint8))
    assert tracer.attach(f) is None
    assert f.trace is None
    assert get_trace(f) is None
    assert get_trace(np.zeros(3)) is None  # bare ndarray: guard, no raise


def test_attach_on_mints_binds_and_reuses():
    tracer = SessionTracer("s", _on_controller())
    f = VideoFrame.from_ndarray(np.zeros((4, 4, 3), dtype=np.uint8))
    tr = tracer.attach(f)
    assert tr is not None and f.trace is tr
    assert tracer.attach(f) is tr  # second attach returns the same trace
    # ndarrays cannot carry the attribute: no downstream hop could ever
    # stamp such a trace, so attach declines to mint one (no per-frame
    # allocation for timelines that can only leak uncompleted)
    assert tracer.attach(np.zeros((4, 4, 3), dtype=np.uint8)) is None


def test_span_mark_finish_and_first_terminal_wins():
    tracer = SessionTracer("s", _on_controller())
    tr = tracer.mint()
    with tr.span("encode"):
        pass
    tr.add_span("ingest", 1.0, 2.0)
    tr.mark("similar_skip")
    tr.finish("shed")
    assert tr.done and tr.terminal == "shed"
    # sealed: further stamps and terminals are no-ops
    tr.add_span("send", 3.0, 4.0)
    tr.mark("late")
    tr.finish("sent")
    assert tr.terminal == "shed"
    names = [n for n, *_ in tr.spans]
    assert names == ["encode", "ingest"]
    assert ("similar_skip",) == tuple(n for n, _ in tr.marks if n == "similar_skip")
    assert any(n == "terminal:shed" for n, _ in tr.marks)
    # completion published it to the session ring
    assert tracer.frames_completed == 1
    assert tracer.snapshot_frames()[0]["terminal"] == "shed"


def test_begin_end_pairing_and_dangling_begin_closes_at_finish():
    tr = FrameTrace(1)
    tr.begin("submit", t=1.0)
    tr.begin("fetch", t=2.0)
    tr.end(t=3.0)  # bare end closes the innermost (fetch)
    tr.begin("engine_step", t=3.5)
    tr.end("submit", t=4.0)  # named end closes by name
    tr.finish("sent", t=5.0)  # dangling engine_step closes at the terminal
    spans = {n: (t0, t1) for n, t0, t1 in tr.spans}
    assert spans["fetch"] == (2.0, 3.0)
    assert spans["submit"] == (1.0, 4.0)
    assert spans["engine_step"] == (3.5, 5.0)
    assert tr.span_end("submit") == 4.0
    assert tr.span_end("never") is None


def test_ring_is_bounded_oldest_evicted():
    tracer = SessionTracer("s", _on_controller(), ring_frames=3)
    for i in range(7):
        tracer.mint(frame_id=i).finish("sent")
    snap = tracer.snapshot_frames()
    assert [d["frame_id"] for d in snap] == [4, 5, 6]
    assert tracer.frames_completed == 7  # the counter is not windowed


def test_controller_window_clamps_and_expires():
    now = [100.0]
    ctrl = TraceController(clock=lambda: now[0])
    ctrl.max_capture_s = 30.0
    granted = ctrl.start(10_000.0)
    assert granted == 30.0  # clamped to TRACE_MAX_CAPTURE_S
    assert ctrl.active()
    now[0] += 31.0
    assert not ctrl.active()  # lazy expiry flipped it off
    assert ctrl.enabled is False
    assert ctrl.status()["enabled"] is False


def test_trace_enable_env_turns_tracing_on(monkeypatch):
    monkeypatch.setenv("TRACE_ENABLE", "1")
    assert TraceController().active()  # unbounded startup enable
    monkeypatch.setenv("TRACE_ENABLE", "0")
    assert not TraceController().active()


# ---------------------------------------------------------------------------
# recorder.py
# ---------------------------------------------------------------------------

def test_event_log_is_bounded_and_always_on(monkeypatch):
    monkeypatch.setenv("FLIGHT_EVENTS", "4")
    flight = FlightRecorder()  # tracing OFF: the event log records anyway
    rec = flight.register("s1")
    for i in range(10):
        rec.event("supervisor", old="HEALTHY", new="DEGRADED", i=i)
    assert len(rec.events) == 4
    assert rec.recent_events(2)[-1]["i"] == 9
    assert all(e["kind"] == "supervisor" for e in rec.events)


def test_snapshot_store_bounded_and_survives_unregister(monkeypatch):
    monkeypatch.setenv("FLIGHT_SNAPSHOTS", "2")
    stats = FrameStats()
    flight = FlightRecorder(stats=stats)
    flight.register("s1").event("webhook", event="StreamDegraded")
    ids = [flight.take_snapshot("s1", reason=f"r{i}") for i in range(3)]
    assert all(ids)
    assert flight.get_snapshot(ids[0]) is None  # evicted (bounded store)
    assert flight.get_snapshot(ids[2])["reason"] == "r2"
    assert flight.take_snapshot("nope") is None  # unknown session
    flight.unregister("s1")
    # the black box outlives the session it recorded
    assert flight.get_snapshot(ids[2]) is not None
    assert flight.session("s1") is None
    assert stats.snapshot()["flight_snapshots_total"] == 3
    idx = flight.index()
    assert [s["id"] for s in idx["snapshots"]] == ids[1:]
    assert idx["trace"]["enabled"] is False


def test_snapshot_carries_frames_and_events():
    flight = FlightRecorder()
    flight.controller.enabled = True
    rec = flight.register("s1")
    tr = rec.tracer.mint(frame_id=7)
    tr.add_span("ingest", 1.0, 2.0)
    tr.finish("passthrough")
    rec.event("overload_rung", old="normal", new="skip2")
    snap_id = flight.take_snapshot("s1", reason="DEGRADED: test")
    snap = flight.get_snapshot(snap_id)
    assert snap["session"] == "s1" and snap["reason"] == "DEGRADED: test"
    assert snap["frames"][0]["terminal"] == "passthrough"
    assert snap["events"][0]["kind"] == "overload_rung"
    assert json.loads(json.dumps(snap)) == snap  # json-safe by construction


# ---------------------------------------------------------------------------
# export.py — Chrome trace validity
# ---------------------------------------------------------------------------

def _validate_chrome(doc: dict):
    """The satellite's conformance gate: parses, fields conform, spans per
    track are well-formed (disjoint — nesting is spilled to lanes).
    Tracks are identified by (pid, tid): a merged multi-agent export
    (obs/export.merge_chrome_traces) renders each source under its own
    process id, and two processes' identically-numbered tids are
    DIFFERENT tracks in the trace-event format."""
    doc = json.loads(json.dumps(doc))  # must survive a JSON round-trip
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    by_track: dict = {}
    for ev in events:
        assert ev["ph"] in ("M", "X", "i"), ev
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        assert isinstance(ev["name"], str) and ev["name"]
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
            assert "name" in ev["args"]
            continue
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0.0
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0.0
            by_track.setdefault((ev["pid"], ev["tid"]), []).append(
                (ev["ts"], ev["ts"] + ev["dur"])
            )
        if ev["ph"] == "i":
            assert ev["s"] in ("t", "p", "g")
    for track, spans in by_track.items():
        spans.sort()
        for (_, end0), (start1, _) in zip(spans, spans[1:]):
            assert start1 >= end0, f"overlapping spans on {track}: {spans}"
    return events


def _synthetic_snapshot() -> dict:
    """Three frames: pipelined overlap on engine_step (lane spill), one
    shed at ingest, one passthrough — plus a supervisor event-log entry."""
    return {
        "id": "flt-1",
        "session": "s1",
        "reason": "DEGRADED: step timeout",
        "taken_at": 110.0,
        "events": [
            {"t": 103.0, "kind": "supervisor", "old": "HEALTHY",
             "new": "DEGRADED", "reason": "step timeout"},
            {"t": 103.5, "kind": "webhook", "event": "StreamDegraded"},
        ],
        "frames": [
            {"frame_id": 1, "session": "s1", "born": 100.0, "terminal": "sent",
             "spans": [["ingest", 100.0, 100.1], ["submit", 100.1, 100.2],
                       ["engine_step", 100.2, 101.5], ["send", 101.6, 101.7]],
             "marks": [["terminal:sent", 101.7]]},
            {"frame_id": 2, "session": "s1", "born": 100.5, "terminal": "sent",
             # engine_step overlaps frame 1's (two frames in flight)
             "spans": [["ingest", 100.5, 100.6], ["engine_step", 100.7, 102.0]],
             "marks": [["terminal:sent", 102.1]]},
            {"frame_id": 3, "session": "s1", "born": 102.5, "terminal": "shed",
             "spans": [],
             "marks": [["ingest_shed", 102.6], ["terminal:shed", 102.6]]},
            {"frame_id": 4, "session": "s1", "born": 103.0,
             "terminal": "passthrough",
             "spans": [["ingest", 103.0, 103.1]],
             "marks": [["terminal:passthrough", 103.2]]},
        ],
    }


def test_chrome_trace_export_validates_and_renders_terminals():
    snap = _synthetic_snapshot()
    events = _validate_chrome(to_chrome_trace(snap))
    # the shed frame renders with its terminal marker (instant event)
    terminals = [e for e in events if e["ph"] == "i" and e["name"].startswith("terminal:")]
    assert any(e["name"] == "terminal:shed" for e in terminals)
    assert any(e["name"] == "terminal:passthrough" for e in terminals)
    shed = next(e for e in terminals if e["name"] == "terminal:shed")
    assert shed["args"]["frame_id"] == 3 and shed["args"]["terminal"] == "shed"
    # the event log renders on the events track
    sup = [e for e in events if e["ph"] == "i" and e["name"] == "supervisor"]
    assert sup and sup[0]["args"]["new"] == "DEGRADED"
    # overlapping engine_step spans spilled onto an overflow lane
    step_tids = {
        e["tid"] for e in events if e["ph"] == "X" and e["name"] == "engine_step"
    }
    assert len(step_tids) == 2
    lane_names = {
        e["args"]["name"] for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "engine_step #2" in lane_names
    # ts normalized: the viewport opens on the data, not at hours offset
    assert min(e["ts"] for e in events if "ts" in e) == 0.0


def test_chrome_trace_handles_empty_and_unknown_stage():
    _validate_chrome(to_chrome_trace(
        {"session": "s", "reason": "r", "events": [], "frames": []}
    ))
    events = _validate_chrome(to_chrome_trace({
        "session": "s", "reason": "r", "events": [],
        "frames": [{"frame_id": 1, "terminal": "sent",
                    "spans": [["weird_stage", 1.0, 2.0]],
                    "marks": []}],
    }))
    assert any(e["ph"] == "X" and e["name"] == "weird_stage" for e in events)
    # unknown stages park on tids past the taxonomy's reserved range
    weird = next(e for e in events if e["ph"] == "X")
    assert weird["tid"] >= 16 * (len(STAGES) + 1)


def test_deep_lane_spill_keeps_tracks_disjoint():
    """20 frames in flight on one stage — deeper than the 16 reserved
    lanes.  Spill past lane 16 must allocate UNIQUE tids (folding onto a
    shared tid renders overlapping X events, a malformed track)."""
    frames = [
        {"frame_id": i, "session": "s", "born": 0.0, "terminal": "sent",
         # all 20 ingest spans overlap: [i, 30+i) — 20 lanes required
         "spans": [["ingest", float(i), 30.0 + i]],
         "marks": []}
        for i in range(20)
    ]
    events = _validate_chrome(to_chrome_trace(
        {"session": "s", "reason": "r", "events": [], "frames": frames}
    ))  # the validator itself asserts per-tid disjointness
    tids = [e["tid"] for e in events if e["ph"] == "X"]
    assert len(tids) == 20 and len(set(tids)) == 20
    labels = {
        e["args"]["name"] for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "ingest #20" in labels


def test_safe_list_retries_past_concurrent_mutation():
    from ai_rtc_agent_tpu.obs.trace import safe_list

    class _FlakyDeque:
        """iter() raises like a deque mutated mid-copy, twice, then yields."""

        def __init__(self):
            self.attempts = 0

        def __iter__(self):
            self.attempts += 1
            if self.attempts <= 2:
                raise RuntimeError("deque mutated during iteration")
            return iter([1, 2, 3])

    assert safe_list(_FlakyDeque()) == [1, 2, 3]

    class _Hostile:
        def __iter__(self):
            raise RuntimeError("deque mutated during iteration")

    assert safe_list(_Hostile()) == []  # never raises on the incident path


def test_snapshot_survives_concurrent_ring_appends():
    """The review-found race, as a smoke: worker threads hammer both
    rings while snapshots run — no 'deque mutated during iteration'
    escapes (the DEGRADED auto-snapshot path must never raise)."""
    import threading

    flight = FlightRecorder()
    flight.controller.enabled = True
    rec = flight.register("s1")
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            rec.tracer.mint(frame_id=i).finish("sent")
            rec.event("overload_rung", i=i)
            i += 1

    def reader():
        try:
            for _ in range(300):
                snap = rec.snapshot()
                assert isinstance(snap["frames"], list)
                flight.take_snapshot("s1", reason="race")
                flight.index()
        except Exception as e:  # pragma: no cover - failure diagnostics
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(2)]
    r = threading.Thread(target=reader)
    for t in threads:
        t.start()
    r.start()
    r.join()
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors


def test_last_submit_was_skip_is_thread_local():
    """Sessions share ONE engine outside --multipeer: a concurrent
    session's submit on another thread must not cross-contaminate this
    thread's similar_skip trace mark."""
    import threading

    from ai_rtc_agent_tpu.stream.engine import StreamEngine

    eng = StreamEngine.__new__(StreamEngine)  # flag mechanics only
    eng._submit_skip_flag = threading.local()
    eng.last_submit_was_skip = True  # this thread's submit skipped

    seen = {}

    def other_session():
        seen["before"] = eng.last_submit_was_skip  # fresh thread: False
        eng.last_submit_was_skip = False  # its own submit, not a skip
        seen["after"] = eng.last_submit_was_skip

    t = threading.Thread(target=other_session)
    t.start()
    t.join()
    assert seen == {"before": False, "after": False}
    assert eng.last_submit_was_skip is True  # ours is untouched


def test_jsonl_roundtrip():
    snap = _synthetic_snapshot()
    lines = to_jsonl(snap).strip().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert recs[0]["record"] == "header" and recs[0]["id"] == "flt-1"
    kinds = [r["record"] for r in recs]
    assert kinds.count("event") == 2 and kinds.count("frame") == 4
    sheds = [r for r in recs if r["record"] == "frame" and r["terminal"] == "shed"]
    assert sheds and sheds[0]["frame_id"] == 3


def test_stop_jax_bridge_without_start_is_noop():
    assert stop_jax_bridge() is None


# ---------------------------------------------------------------------------
# webhook payload (ISSUE 5 satellite: events.py)
# ---------------------------------------------------------------------------

def test_stream_degraded_webhook_carries_flight_fields():
    posted = []

    class _Resp:
        status = 200

    class _Sess:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return _Resp()

    async def go():
        h = StreamEventHandler(session_factory=_Sess)
        h.webhook_url, h.token = "http://orch/webhook", "tok"
        emitted = []
        h.on_emit = lambda name, sid: emitted.append((name, sid))
        recent = [{"t": 1.0, "kind": "supervisor", "new": "DEGRADED"}]
        t = h.handle_session_state(
            "s1", "room", "DEGRADED", "step timeout",
            flight_snapshot_id="flt-9", recent_events=recent,
        )
        await t
        # recovery carries no flight fields (nothing broke)
        t2 = h.handle_session_state("s1", "room", "HEALTHY", "recovered")
        await t2
        return emitted

    emitted = asyncio.run(go())
    degraded = next(p for p in posted if p["event"] == "StreamDegraded")
    assert degraded["flight_snapshot_id"] == "flt-9"
    assert degraded["recent_events"][0]["kind"] == "supervisor"
    assert degraded["state"] == "DEGRADED"
    recovered = next(p for p in posted if p["event"] == "StreamRecovered")
    assert "flight_snapshot_id" not in recovered
    # the black box is told what the outside world was told
    assert ("StreamDegraded", "s1") in emitted


# ---------------------------------------------------------------------------
# /debug endpoints + the chaos acceptance
# ---------------------------------------------------------------------------

class ChaosPipeline:
    """Invert-colors pipeline consulting the engine fault scope the way
    StreamEngine.submit does (same stand-in as test_chaos_session)."""

    def __init__(self):
        self._fault_scope = faults.scope("engine")
        self.restarts = 0

    def __call__(self, frame):
        if self._fault_scope is not None:
            self._fault_scope.step()
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def restart(self):
        self.restarts += 1


def _vframe(fill: int, age_s: float = 0.0) -> VideoFrame:
    f = VideoFrame.from_ndarray(np.full((8, 8, 3), fill, dtype=np.uint8))
    f.wall_ts = time.monotonic() - age_s
    return f


def test_debug_trace_endpoint_start_stop(monkeypatch):
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("TRACE_MAX_CAPTURE_S", "60")

    async def go():
        app = build_app(pipeline=ChaosPipeline(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/debug/trace")
            assert (await r.json())["enabled"] is False
            r = await client.post(
                "/debug/trace", json={"action": "start", "duration_s": 9000}
            )
            body = await r.json()
            assert body["tracing"] is True
            assert body["duration_s"] == 60.0  # clamped to TRACE_MAX_CAPTURE_S
            assert (await (await client.get("/debug/trace")).json())["enabled"]
            m = await (await client.get("/metrics")).json()
            assert m["trace_enabled"] == 1
            r = await client.post("/debug/trace", json={"action": "stop"})
            assert (await r.json())["tracing"] is False
            r = await client.post("/debug/trace", json={"action": "bogus"})
            assert r.status == 400
            r = await client.post(
                "/debug/trace", json={"action": "start", "duration_s": "abc"}
            )
            assert r.status == 400  # validated, not a 500 from float()
            r = await client.post("/debug/trace", data=b"not json")
            assert r.status == 400
        finally:
            await client.close()

    asyncio.run(go())


def test_flight_recorder_kill_switch_404s_debug_surface(monkeypatch):
    monkeypatch.setenv("FLIGHT_RECORDER", "0")

    async def go():
        app = build_app(pipeline=ChaosPipeline(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            assert (await client.get("/debug/flight")).status == 404
            assert (await client.get("/debug/trace")).status == 404
            m = await (await client.get("/metrics")).json()
            assert "trace_enabled" not in m
        finally:
            await client.close()

    asyncio.run(go())


def test_chaos_degrade_autocaptures_flight_snapshot(monkeypatch):
    """The ISSUE's chaos acceptance: a seeded FAULT_PLAN wedges the engine
    mid-stream; the session degrades to passthrough; the flight recorder
    auto-snapshots at the transition with the supervisor event in its log
    and shed/passthrough terminals in its timelines; GET /debug/flight
    serves it in all three formats and the Chrome export validates."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("RESILIENCE_STEP_TIMEOUT_S", "0.25")
    monkeypatch.setenv("RESILIENCE_FIRST_STEP_TIMEOUT_S", "0.25")
    monkeypatch.setenv("SUPERVISOR_STALL_AFTER_S", "30")
    monkeypatch.setenv("TRACE_ENABLE", "1")  # timelines from frame one

    # steps 3-4 wedge far past the 0.25 s budget
    faults.activate(
        FaultPlan(
            specs=(
                FaultSpec(
                    target="engine", kind="slow_step",
                    start=3, stop=5, delay_s=4.0,
                ),
            ),
            seed=7,
        )
    )
    pipe = ChaosPipeline()

    async def go():
        app = build_app(pipeline=pipe, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "chaos-obs",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
            )
            assert r.status == 200
            pc = next(iter(app["pcs"]))
            viewer = pc.out_tracks[0]
            (sup,) = app["supervisors"].values()

            # phase 1: a stale burst — three frames aged 10 s with a fresh
            # one queued behind them.  The ingest hop sheds all three
            # (freshest-frame-wins), terminal-marking their timelines —
            # completed BEFORE the degrade, so the auto-snapshot holds them.
            for fill in (10, 11, 12):
                await pc.in_track.push(_vframe(fill, age_s=10.0))
            await pc.in_track.push(_vframe(40))
            out = await asyncio.wait_for(viewer.recv(), timeout=3.0)
            assert np.array_equal(
                out if isinstance(out, np.ndarray) else out.to_ndarray(),
                255 - np.full((8, 8, 3), 40, dtype=np.uint8),
            )

            # phase 2: pump into the stall window until the supervisor
            # leaves HEALTHY and a passthrough frame is delivered (its
            # timeline seals with terminal:passthrough)
            deadline = time.monotonic() + 20.0
            saw_passthrough = False
            fill = 50
            while time.monotonic() < deadline:
                await pc.in_track.push(_vframe(fill))
                fill += 1
                out = await asyncio.wait_for(viewer.recv(), timeout=3.0)
                if not isinstance(out, np.ndarray):
                    saw_passthrough = True  # VideoFrame passed through raw
                states = {t["to"] for t in sup.snapshot()["transitions"]}
                if saw_passthrough and "DEGRADED" in states:
                    break
            assert saw_passthrough, "no passthrough frame during the stall"
            assert "DEGRADED" in {
                t["to"] for t in sup.snapshot()["transitions"]
            }

            # the auto-captured snapshot: index lists it...
            idx = await (await client.get("/debug/flight")).json()
            assert idx["trace"]["enabled"] is True
            degrades = [
                s for s in idx["snapshots"] if s["reason"].startswith("DEGRADED")
            ]
            assert degrades, idx
            snap_id = degrades[-1]["id"]

            # ...the JSON body holds the supervisor transition + terminals
            r = await client.get("/debug/flight", params={"id": snap_id})
            assert r.status == 200
            snap = await r.json()
            sups = [e for e in snap["events"] if e["kind"] == "supervisor"]
            assert any(e["new"] == "DEGRADED" for e in sups), snap["events"]
            terminals = [f["terminal"] for f in snap["frames"]]
            assert "shed" in terminals, terminals  # the phase-1 burst
            assert all(t is not None for t in terminals)

            # live capture (by now passthrough timelines have completed too)
            r = await client.get(
                "/debug/flight", params={"session": next(iter(idx["sessions"]))}
            )
            live = await r.json()
            assert "passthrough" in {f["terminal"] for f in live["frames"]}

            # ...the Chrome export of the snapshot validates, shed visible
            r = await client.get(
                "/debug/flight", params={"id": snap_id, "format": "chrome"}
            )
            events = _validate_chrome(await r.json())
            assert any(
                e["ph"] == "i" and e["name"] == "terminal:shed" for e in events
            )
            assert any(
                e["ph"] == "i" and e["name"] == "supervisor"
                and e["args"].get("new") == "DEGRADED"
                for e in events
            )

            # ...and the JSONL export parses line by line
            r = await client.get(
                "/debug/flight", params={"id": snap_id, "format": "jsonl"}
            )
            recs = [json.loads(ln) for ln in (await r.text()).splitlines()]
            assert recs[0]["record"] == "header"

            # error surfaces stay crisp
            assert (
                await client.get("/debug/flight", params={"id": "flt-none"})
            ).status == 404
            assert (
                await client.get("/debug/flight", params={"session": "nope"})
            ).status == 404
            assert (
                await client.get(
                    "/debug/flight", params={"id": snap_id, "format": "bogus"}
                )
            ).status == 400
            # format without a capture selector (a tooling URL whose id
            # variable expanded empty) fails loudly, not index-as-200
            assert (
                await client.get("/debug/flight", params={"format": "chrome"})
            ).status == 400
        finally:
            await client.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# fleet journey correlation (ISSUE 13): header threading, the ?journey=
# fragment selector, JSON error bodies, and the multi-source Chrome merge
# ---------------------------------------------------------------------------

def test_merge_chrome_traces_per_agent_pids_and_stamps():
    """Two agents' captures merge into ONE Perfetto doc: disjoint pids,
    journey/agent/leg stamped into process metadata and span args —
    identically-numbered stage tids no longer collide across agents."""
    from ai_rtc_agent_tpu.obs.export import merge_chrome_traces

    snap_a = _synthetic_snapshot()
    snap_b = _synthetic_snapshot()
    snap_b["session"] = "s2"
    doc = merge_chrome_traces(
        [
            (snap_a, {"journey_id": "j-1", "agent": "agent0", "leg": 1}),
            (snap_b, {"journey_id": "j-1", "agent": "agent1", "leg": 2}),
        ],
        journey="j-1",
    )
    events = _validate_chrome(doc)
    pids = {e["pid"] for e in events}
    assert pids == {1, 2}
    # per-agent disjoint pids: every event of one agent shares one pid
    by_pid_agent = {}
    for e in events:
        if e["ph"] == "M" and e["name"] == "process_name":
            by_pid_agent[e["pid"]] = e["args"]["agent"]
            assert e["args"]["journey_id"] == "j-1"
    assert by_pid_agent == {1: "agent0", 2: "agent1"}
    # span args carry the stamp (Perfetto's "which leg is this" answer)
    spans = [e for e in events if e["ph"] == "X"]
    assert spans
    assert all(e["args"]["journey_id"] == "j-1" for e in spans)
    assert {e["args"]["leg"] for e in spans} == {1, 2}
    assert doc["otherData"]["journey_id"] == "j-1"
    assert len(doc["otherData"]["sources"]) == 2


def test_agent_threads_journey_headers_and_serves_fragment(monkeypatch):
    """The agent half of the tentpole: X-Journey-Id on /offer binds the
    session's recorder/tracer/supervisor context, every snapshot +
    sealed timeline carries it, and GET /debug/flight?journey= serves
    the one-pull fragment the router's bundle fan-out consumes."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("TRACE_ENABLE", "1")
    monkeypatch.setenv("WORKER_ID", "agent-frag")

    async def go():
        app = build_app(pipeline=ChaosPipeline(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "jr",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
                headers={"X-Journey-Id": "j-abc", "X-Journey-Leg": "2"},
            )
            assert r.status == 200
            # the signaling answer echoes the binding
            assert r.headers["X-Journey-Id"] == "j-abc"
            assert r.headers["X-Journey-Leg"] == "2"
            sid = r.headers["X-Stream-Id"]

            # /health session snapshot carries the journey context
            h = await (await client.get("/health")).json()
            ctx = h["sessions"][sid]["context"]["journey"]
            assert ctx["journey_id"] == "j-abc" and ctx["leg"] == 2

            # stream a stale burst so timelines seal (the ingest hop
            # sheds the aged frames, terminal-marking their traces —
            # the loopback tier has no send hop to seal "sent" on)
            pc = next(iter(app["pcs"]))
            viewer = pc.out_tracks[0]
            for fill in (10, 11):
                await pc.in_track.push(_vframe(fill, age_s=10.0))
            await pc.in_track.push(_vframe(20))
            await asyncio.wait_for(viewer.recv(), timeout=3.0)

            # an auto/on-demand snapshot carries the journey binding
            snap_id = app["flight"].take_snapshot(sid, reason="test")
            snap = app["flight"].get_snapshot(snap_id)
            assert snap["journey"]["journey_id"] == "j-abc"
            assert snap["journey"]["agent"] == "agent-frag"
            # sealed timelines carry it too (the merged export's stamp)
            assert snap["frames"]
            assert all(
                f["journey_id"] == "j-abc" and f["leg"] == 2
                for f in snap["frames"]
            )
            # the black box logged the leg start
            assert any(e["kind"] == "journey" for e in snap["events"])
            # the index names the journey per stored snapshot
            idx = await (await client.get("/debug/flight")).json()
            assert any(
                s["id"] == snap_id and s["journey_id"] == "j-abc"
                for s in idx["snapshots"]
            )

            # the fragment: live capture + stored snapshot + devtel
            r = await client.get(
                "/debug/flight", params={"journey": "j-abc"}
            )
            assert r.status == 200
            frag = await r.json()
            assert frag["agent"] == "agent-frag"
            assert sid in frag["sessions"]
            assert [s["id"] for s in frag["snapshots"]] == [snap_id]
            assert "recent_compiles" in frag["devtel"]

            # unknown journey: 404 with a JSON error body (never an
            # empty 200 a jq pipeline reads as success)
            r = await client.get(
                "/debug/flight", params={"journey": "j-none"}
            )
            assert r.status == 404
            assert "error" in await r.json()
            # journey fragments are JSON-only; merge happens router-side
            r = await client.get(
                "/debug/flight",
                params={"journey": "j-abc", "format": "chrome"},
            )
            assert r.status == 400 and "error" in await r.json()
            # unknown query params are rejected, not silently ignored
            r = await client.get(
                "/debug/flight", params={"sessoin": "typo"}
            )
            assert r.status == 400
            assert "sessoin" in (await r.json())["error"]
            # mixed selectors are ambiguous
            r = await client.get(
                "/debug/flight", params={"journey": "j-abc", "id": snap_id}
            )
            assert r.status == 400 and "error" in await r.json()
        finally:
            await client.close()

    asyncio.run(go())
