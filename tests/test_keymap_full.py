"""Full-architecture key-map completeness — no weights needed.

VERDICT r1 item 8: tiny configs can't catch mapping drift at real geometry
(SD1.5 / SD2.1 / SDXL / ControlNet).  Here we synthesize a COMPLETE state
dict from the param tree itself (zeros via eval_shape — no RNG cost), then
strict-load it back: every key-map path must resolve in the tree, shapes
must round-trip through the OIHW<->HWIO / [O,I]<->[I,O] conventions, and —
the completeness half — every array leaf of the tree must be covered by the
map (reference load surface: lib/wrapper.py:645-669).
"""

import jax
import numpy as np
import pytest

from ai_rtc_agent_tpu.models import clip as C
from ai_rtc_agent_tpu.models import controlnet as CN
from ai_rtc_agent_tpu.models import loader as LD
from ai_rtc_agent_tpu.models import taesd as T
from ai_rtc_agent_tpu.models import unet as U


def _zeros_tree(init_fn):
    """Materialize the init tree as numpy zeros (calloc — fast at any size)."""
    shapes = jax.eval_shape(init_fn)
    return jax.tree.map(lambda s: np.zeros(s.shape, np.float32), shapes)


def _roundtrip(params, km):
    sd = LD.tree_to_state_dict(params, km)
    out, n = LD.load_into_tree(params, sd, km, strict=True)
    total = len(jax.tree.leaves(params))
    assert n == len(sd), f"loaded {n} != synthesized {len(sd)}"
    assert n == total, (
        f"key map covers {n}/{total} leaves — "
        f"{total - n} tree leaves unreachable from the checkpoint"
    )
    return out


@pytest.mark.slow  # full-geometry UNet builds: ~2.5 min on the 1-core box
@pytest.mark.parametrize("fam", ["sd15", "sd21", "sdxl"])
def test_unet_keymap_full_geometry(fam):
    cfg = getattr(U.UNetConfig, fam)()
    params = _zeros_tree(lambda: U.init_unet(jax.random.PRNGKey(0), cfg))
    _roundtrip(params, LD.unet_key_map(cfg))


@pytest.mark.parametrize(
    "cfg_name",
    [
        "sd15",
        # the big text towers cost ~14s EACH of pure host tree-building
        # on this box; sd15 stays as the tier-1 representative (same map
        # code, same conventions), the rest ride the slow tier like the
        # full-geometry UNet variant above (tier-1 budget, ISSUE 10)
        pytest.param("sd21", marks=pytest.mark.slow),
        pytest.param("sdxl_g", marks=pytest.mark.slow),
    ],
)
def test_clip_keymap_full_geometry(cfg_name):
    cfg = getattr(C.CLIPTextConfig, cfg_name)()
    params = _zeros_tree(lambda: C.init_clip_text(jax.random.PRNGKey(0), cfg))
    _roundtrip(params, LD.clip_key_map(cfg))


def test_taesd_keymap_full_geometry():
    cfg = T.TAESDConfig()
    params = _zeros_tree(lambda: T.init_taesd(jax.random.PRNGKey(0), cfg))
    _roundtrip(params, LD.taesd_key_map(cfg))


@pytest.mark.slow  # SD1.5-geometry ControlNet build (~40s on the 1-core
# box), same reason its UNet full-geometry family is slow; the tiny-
# geometry sibling (test_controlnet_stream.py::
# test_controlnet_key_map_covers_params) keeps the keymap surface tier-1
def test_controlnet_keymap_full_geometry():
    cfg = U.UNetConfig.sd15()
    params = _zeros_tree(
        lambda: CN.init_controlnet(jax.random.PRNGKey(0), cfg, num_down=3)
    )
    _roundtrip(params, LD.controlnet_key_map(cfg, num_down=3))
