"""Stage-latency SLO plane (obs/slo.py): histograms, budgets, burn-rate
windows, breach hysteresis, and the tracer feed path (ISSUE 8 tentpole).

All clockless: ticks are driven directly (the SloPlane.tick discipline
shared with the overload/netadapt ladders), so nothing here sleeps.
"""

import pytest

from ai_rtc_agent_tpu.obs.slo import (
    BUCKET_BOUNDS_MS,
    STATE_BREACH,
    STATE_OK,
    SloPlane,
    StageHistogram,
    stage_budgets_ms,
)
from ai_rtc_agent_tpu.obs.trace import STAGES, SessionTracer, TraceController


class _Frame:
    pass


def _plane(monkeypatch=None, **env):
    if monkeypatch is not None:
        for k, v in env.items():
            monkeypatch.setenv(k, str(v))
    return SloPlane()


def _tracer(plane, session="s1", tracing=False):
    ctrl = TraceController()
    ctrl.enabled = bool(tracing)
    return SessionTracer(session, ctrl, slo=plane)


def _feed(tracer, n, stage="engine_step", ms=20.0, terminal="sent"):
    for _ in range(n):
        f = _Frame()
        tr = tracer.attach(f)
        assert tr is not None
        tr.add_span(stage, 0.0, ms / 1e3)
        tr.finish(terminal)


# -- histogram ---------------------------------------------------------------

def test_histogram_buckets_cumulative_and_inf_terminal():
    h = StageHistogram(budget_ms=10.0)
    for ms in (0.05, 0.3, 3.0, 30.0, 30.0, 9999.0):
        h.observe(ms)
    cum = h.cumulative()
    # strictly the prom shape: one entry per bound + the +Inf terminal
    assert len(cum) == len(BUCKET_BOUNDS_MS) + 1
    assert cum[-1] == ("+Inf", 6)
    counts = [c for _, c in cum]
    assert counts == sorted(counts), "buckets must be cumulative"
    # a value past the last bound lands ONLY in +Inf
    assert cum[-2][1] == 5
    assert h.count == 6 and h.over == 3  # 30, 30, 9999 past the 10ms budget
    assert h.sum_ms == pytest.approx(0.05 + 0.3 + 3.0 + 30.0 + 30.0 + 9999.0)


def test_histogram_boundary_value_lands_in_its_le_bucket():
    # le is INCLUSIVE: an observation exactly at a bound belongs in it
    h = StageHistogram(budget_ms=10.0)
    h.observe(1.0)
    cum = dict(h.cumulative())
    assert cum["1"] == 1
    assert cum["0.5"] == 0


def test_histogram_quantiles():
    h = StageHistogram(budget_ms=10.0)
    assert h.quantile_ms(0.5) is None  # no data yet
    for _ in range(90):
        h.observe(3.0)  # -> le=5 bucket
    for _ in range(10):
        h.observe(400.0)  # -> le=500 bucket
    assert h.quantile_ms(0.5) == 5.0
    assert h.quantile_ms(0.99) == 500.0


def test_histogram_quantile_past_last_bound_is_json_safe():
    """A tail past the last bucket (compile stall) must CENSOR to the top
    finite bound, never float('inf') — json.dumps would emit bare
    `Infinity`, invalid JSON, breaking /health mid-incident."""
    import json

    h = StageHistogram(budget_ms=10.0)
    for _ in range(10):
        h.observe(60_000.0)  # one minute: past every bound
    q = h.quantile_ms(0.99)
    assert q == BUCKET_BOUNDS_MS[-1]
    json.loads(json.dumps({"p99_ms": q}))  # round-trips as legal JSON


# -- budgets -----------------------------------------------------------------

def test_budgets_cover_every_stage_and_read_env(monkeypatch):
    assert set(stage_budgets_ms()) == set(STAGES)
    monkeypatch.setenv("SLO_ENGINE_STEP_BUDGET_MS", "123.5")
    assert stage_budgets_ms()["engine_step"] == 123.5


def test_bad_objective_refused(monkeypatch):
    monkeypatch.setenv("SLO_OBJECTIVE", "1.5")
    with pytest.raises(ValueError, match="SLO_OBJECTIVE"):
        SloPlane()


# -- feed path (SessionTracer integration) -----------------------------------

def test_slo_only_mint_feeds_histograms_but_not_ring():
    plane = _plane()
    tracer = _tracer(plane, tracing=False)
    _feed(tracer, 5, stage="decode", ms=2.0)
    assert plane.frames_observed == 5
    assert plane.global_hist["decode"].count == 5
    assert plane.sessions["s1"].stages["decode"].hist.count == 5
    # timelines are only RETAINED while tracing proper is on
    assert len(tracer.ring) == 0 and tracer.frames_completed == 0


def test_tracing_on_keeps_ring_and_feeds_slo():
    plane = _plane()
    tracer = _tracer(plane, tracing=True)
    _feed(tracer, 3)
    assert plane.frames_observed == 3
    assert len(tracer.ring) == 3 and tracer.frames_completed == 3


def test_both_off_is_a_no_mint_fast_path():
    plane = _plane()
    plane.enabled = False
    tracer = _tracer(plane, tracing=False)
    f = _Frame()
    assert tracer.attach(f) is None
    assert not hasattr(f, "trace")
    assert plane.frames_observed == 0


def test_disabled_plane_observe_is_noop():
    plane = _plane()
    plane.enabled = False
    tracer = _tracer(plane, tracing=True)  # tracing without SLO
    _feed(tracer, 2)
    assert plane.frames_observed == 0
    assert len(tracer.ring) == 2  # tracing itself unaffected


def test_non_stage_spans_are_ignored():
    plane = _plane()
    tracer = _tracer(plane)
    f = _Frame()
    tr = tracer.attach(f)
    tr.add_span("not_a_stage", 0.0, 1.0)
    tr.finish("sent")
    assert plane.frames_observed == 1
    assert all(plane.global_hist[s].count == 0 for s in STAGES)


def test_unregister_drops_session_keeps_global():
    plane = _plane()
    tracer = _tracer(plane)
    _feed(tracer, 4)
    assert "s1" in plane.sessions
    plane.unregister("s1")
    assert "s1" not in plane.sessions
    assert plane.global_hist["engine_step"].count == 4
    assert plane.session_snapshot("s1") is None


# -- burn rate + breach hysteresis -------------------------------------------

def _breach_plane(monkeypatch, **extra):
    env = {
        "SLO_TICK_S": "1.0",
        "SLO_FAST_WINDOW_S": "3",      # 3 ticks
        "SLO_SLOW_WINDOW_S": "10",     # 10 ticks
        "SLO_OBJECTIVE": "0.99",
        "SLO_BURN_THRESHOLD": "2.0",
        "SLO_UP_TICKS": "2",
        "SLO_DOWN_TICKS": "3",
        "SLO_ENGINE_STEP_BUDGET_MS": "50",
    }
    env.update({k: str(v) for k, v in extra.items()})
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    return SloPlane()


def test_breach_requires_both_windows_and_up_ticks(monkeypatch):
    plane = _breach_plane(monkeypatch)
    moves = []
    plane.on_breach = lambda sid, stage, state, info: moves.append(
        (sid, stage, state, info)
    )
    tracer = _tracer(plane)
    # sustained over-budget traffic: burn = 1.0/0.01 = 100 >> threshold
    _feed(tracer, 10, ms=200.0)
    plane.tick()
    st = plane.sessions["s1"].stages["engine_step"]
    assert st.state == STATE_OK, "one firing tick must not breach (up=2)"
    _feed(tracer, 10, ms=200.0)
    plane.tick()
    assert st.state == STATE_BREACH
    assert moves == [
        ("s1", "engine_step", STATE_BREACH, {
            "budget_ms": 50.0,
            "burn_fast": round(st.burn_fast, 3),
            "burn_slow": round(st.burn_slow, 3),
        })
    ]
    assert plane.breaches_total == 1


def test_breach_clears_on_quiet_fast_window_after_down_ticks(monkeypatch):
    plane = _breach_plane(monkeypatch)
    moves = []
    plane.on_breach = lambda sid, stage, state, info: moves.append(state)
    tracer = _tracer(plane)
    for _ in range(2):
        _feed(tracer, 10, ms=200.0)
        plane.tick()
    st = plane.sessions["s1"].stages["engine_step"]
    assert st.state == STATE_BREACH
    # clean traffic: the fast window (3 ticks) must drain, then 3 quiet
    # ticks clear the breach — the slow window may still remember the burn
    ticks_to_clear = 0
    for _ in range(20):
        _feed(tracer, 10, ms=5.0)
        plane.tick()
        ticks_to_clear += 1
        if st.state == STATE_OK:
            break
    assert st.state == STATE_OK
    # fast window (3) must drain the over-samples + 3 down ticks
    assert 3 <= ticks_to_clear <= 7
    assert moves == [STATE_BREACH, STATE_OK]


def test_idle_session_never_breaches(monkeypatch):
    """No frames = no evidence: burn must read 0, not NaN or breach."""
    plane = _breach_plane(monkeypatch)
    tracer = _tracer(plane)
    _feed(tracer, 1, ms=200.0)  # one bad frame, then silence
    for _ in range(10):
        plane.tick()
    st = plane.sessions["s1"].stages["engine_step"]
    assert st.state == STATE_OK
    # fast window saw no NEW frames once the old sample aged out
    assert st.burn_fast == 0.0


def test_breach_counts_frames_before_first_tick(monkeypatch):
    """Lazy registration: a burst observed before the plane's first tick
    (the seed sample) still counts toward burn."""
    plane = _breach_plane(monkeypatch)
    tracer = _tracer(plane)
    _feed(tracer, 50, ms=200.0)
    plane.tick()
    plane.tick()
    assert plane.sessions["s1"].stages["engine_step"].state == STATE_BREACH


def test_stats_counter_and_snapshot(monkeypatch):
    from ai_rtc_agent_tpu.utils.profiling import FrameStats

    stats = FrameStats()
    plane = _breach_plane(monkeypatch)
    plane.stats = stats
    tracer = _tracer(plane)
    for _ in range(2):
        _feed(tracer, 10, ms=200.0)
        plane.tick()
    assert stats.snapshot()["slo_breaches_total"] == 1
    snap = plane.snapshot()
    assert snap["slo_enabled"] == 1
    assert snap["slo_sessions"] == 1
    assert snap["slo_stages_breached"] == 1
    assert snap["slo_frames_observed"] == 20
    stage = snap["slo_stages"]["engine_step"]
    assert stage["count"] == 20 and stage["over"] == 20
    assert stage["budget_ms"] == 50.0
    # untouched stages are omitted (bounded, not padded)
    assert "decode" not in snap["slo_stages"]


def test_session_snapshot_shape(monkeypatch):
    plane = _breach_plane(monkeypatch)
    tracer = _tracer(plane)
    _feed(tracer, 10, ms=5.0)
    plane.tick()
    snap = plane.session_snapshot("s1")
    assert set(snap) == {"engine_step"}
    s = snap["engine_step"]
    assert s["state"] == STATE_OK
    assert s["count"] == 10 and s["over"] == 0
    assert s["budget_ms"] == 50.0
    assert isinstance(s["burn_fast"], float)
    assert s["p50_ms"] == 5.0


def test_agent_breach_rides_webhook_and_event_log(monkeypatch):
    """The agent wiring (server/agent.py on_startup): an SLO breach lands
    in the flight-recorder event log AND fires the StreamDegraded webhook
    path with state=SLO_BREACH + the session's recent black-box events."""
    import asyncio

    for k, v in {
        "SLO_TICK_S": "1.0", "SLO_FAST_WINDOW_S": "3",
        "SLO_SLOW_WINDOW_S": "10", "SLO_UP_TICKS": "2",
        "SLO_ENGINE_STEP_BUDGET_MS": "50",
    }.items():
        monkeypatch.setenv(k, v)

    from aiohttp.test_utils import TestClient, TestServer

    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    class Pipe:
        def __call__(self, frame):
            return frame

        def restart(self):
            pass

    async def go():
        app = build_app(pipeline=Pipe(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            plane = app["slo"]
            flight = app["flight"]
            assert plane is not None and flight.slo is plane
            rec = flight.register("sess-1")
            # arm the webhook with a fake transport (no real HTTP)
            posted = []

            class _Resp:
                status = 200

            class _Sess:
                async def post(self, url, headers=None, json=None):
                    posted.append(json)
                    return _Resp()

            handler = app["stream_event_handler"]
            handler.webhook_url = "http://orchestrator/hook"
            handler.token = "tok"
            handler._session_factory = lambda: _Sess()

            for _ in range(2):
                _feed(rec.tracer, 10, ms=200.0)
                plane.tick()
            for _ in range(10):  # call_soon_threadsafe + webhook task
                await asyncio.sleep(0.01)
                if posted:
                    break
            slo_events = [e for e in rec.events if e["kind"] == "slo"]
            assert slo_events and slo_events[0]["stage"] == "engine_step"
            assert slo_events[0]["state"] == STATE_BREACH
            assert posted, "breach did not reach the webhook"
            body = posted[0]
            assert body["event"] == "StreamDegraded"
            assert body["state"] == "SLO_BREACH"
            assert "engine_step" in body["reason"]
            assert body["stream_id"] == "sess-1"
            assert body["recent_events"], "black-box context missing"
            # /health carries the per-session burn state... for supervised
            # sessions; the plane's own snapshot always has it
            snap = plane.session_snapshot("sess-1")
            assert snap["engine_step"]["state"] == STATE_BREACH
            # /metrics counts the breach
            r = await client.get("/metrics")
            j = await r.json()
            assert j["slo_breaches_total"] == 1
            assert j["slo_stages_breached"] == 1
        finally:
            await client.close()

    asyncio.run(go())


def test_breach_callback_failure_never_breaks_tick(monkeypatch):
    plane = _breach_plane(monkeypatch)

    def boom(*a):
        raise RuntimeError("handler bug")

    plane.on_breach = boom
    tracer = _tracer(plane)
    for _ in range(2):
        _feed(tracer, 10, ms=200.0)
        plane.tick()  # must not raise
    assert plane.sessions["s1"].stages["engine_step"].state == STATE_BREACH
