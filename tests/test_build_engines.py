"""AOT engine-build CLI on the tiny family (reference build.py parity)."""

import os

from ai_rtc_agent_tpu.assets.build_engines import build


def test_build_engine_tiny(tmp_path, monkeypatch):
    key = build("tiny-test", cache_dir=str(tmp_path))
    d = os.path.join(tmp_path, key)
    assert os.path.isdir(d)
    blobs = [f for f in os.listdir(d) if f.endswith(".jaxexport")]
    metas = [f for f in os.listdir(d) if f.endswith(".json")]
    assert len(blobs) == 1 and len(metas) == 1

    # second build: cache hit (no new blob)
    build("tiny-test", cache_dir=str(tmp_path))
    assert len([f for f in os.listdir(d) if f.endswith(".jaxexport")]) == 1
