"""AOT engine-build CLI on the tiny family (reference build.py parity)."""

import os

import pytest

from ai_rtc_agent_tpu.assets.build_engines import build


@pytest.mark.slow
def test_build_engine_tiny(tmp_path, monkeypatch):
    """`slow` tier (ISSUE 12 budget satellite, ~16s of CLI build): the
    serving-side adoption of a prebuilt engine stays tier-1
    (test_serving_adopts_prebuilt_engine), as do the EngineCache
    build/load/donation pins in tests/test_aot_cache.py — this is the
    CLI-driver composition over the same machinery."""
    (key,), _ = build("tiny-test", cache_dir=str(tmp_path))
    d = os.path.join(tmp_path, key)
    assert os.path.isdir(d)
    blobs = [f for f in os.listdir(d) if f.endswith(".jaxexport")]
    metas = [f for f in os.listdir(d) if f.endswith(".json")]
    assert len(blobs) == 1 and len(metas) == 1

    # second build: cache hit (no new blob)
    build("tiny-test", cache_dir=str(tmp_path))
    assert len([f for f in os.listdir(d) if f.endswith(".jaxexport")]) == 1


def test_serving_adopts_prebuilt_engine(tmp_path, monkeypatch):
    """The pipeline must hit the deserialize fast path when the CLI built an
    engine (reference _load_trt_model fast path, lib/wrapper.py:409-512)."""
    import numpy as np

    from ai_rtc_agent_tpu.stream.pipeline import StreamDiffusionPipeline

    monkeypatch.setenv("XLA_ENGINES_CACHE", str(tmp_path))
    build("tiny-test", cache_dir=str(tmp_path))

    pipe = StreamDiffusionPipeline("tiny-test")
    assert pipe.engine.use_aot_cache("tiny-test", build_on_miss=False)
    frame = np.random.default_rng(0).integers(0, 256, (64, 64, 3), np.uint8)
    out = pipe(frame)
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8


def test_no_adoption_without_prebuilt_engine(tmp_path, monkeypatch):
    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.stream.engine import StreamEngine

    monkeypatch.setenv("XLA_ENGINES_CACHE", str(tmp_path))
    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test")
    eng = StreamEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        jit_compile=False,
    )
    eng.prepare("x")
    assert not eng.use_aot_cache("tiny-test", build_on_miss=False)


@pytest.mark.slow  # a second full build with the ControlNet graph
# (~11s); the tiny build + serving-adoption tests keep the CLI covered
# in tier-1, and the variant keying itself is pinned by stream_engine_key
# unit tests
def test_build_controlnet_engine_variant(tmp_path):
    """ControlNet engine variant gets its own cache key (reference compiles a
    separate UNet+ControlNet engine, lib/wrapper.py:870-877)."""
    (key_plain,), _ = build("tiny-test", cache_dir=str(tmp_path))
    (key_cnet,), _ = build("tiny-test", cache_dir=str(tmp_path), controlnet="tiny-cnet")
    assert key_plain != key_cnet
    assert os.path.isdir(os.path.join(tmp_path, key_cnet))


def test_build_deepcache_pair(tmp_path, monkeypatch):
    """UNET_CACHE config builds BOTH variants (capture + cached) with
    distinct keys — serve-time adoption is pair-atomic."""
    monkeypatch.setenv("UNET_CACHE", "2")
    keys, _ = build("tiny-test", cache_dir=str(tmp_path))
    assert len(keys) == 2 and keys[0] != keys[1]
    assert any("capture" in k for k in keys)
    assert any("cached" in k for k in keys)
    for k in keys:
        d = os.path.join(tmp_path, k)
        assert [f for f in os.listdir(d) if f.endswith(".jaxexport")]


def test_build_engines_peers_flag(tmp_path, monkeypatch):
    """--peers N prebuilds the multipeer engine through the serving
    adoption path (keys can't drift); a fresh MultiPeerEngine then loads
    without building."""
    from ai_rtc_agent_tpu.assets import build_engines
    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.parallel.multipeer import MultiPeerEngine

    build_engines.main([
        "--model-id", "tiny-test", "--cache-dir", str(tmp_path),
        "--peers", "2",
    ])
    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test")
    mp = MultiPeerEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_peers=2,
    ).start("adopt prebuilt")
    assert mp.use_aot_cache(
        "tiny-test", cache_dir=str(tmp_path), build_on_miss=False
    )
