"""Serverless worker sidecar (reference runpod/handler.py parity)."""

import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from ai_rtc_agent_tpu.server import worker


def _serve_health(port, status=200, n_requests=10):
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(status)
            self.end_headers()
            self.wfile.write(b"OK")

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", port), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def test_handler_publishes_and_holds():
    srv = _serve_health(18931)
    published = []
    rc = worker.handler(
        18931, publish=published.append, sleep=lambda s: published.append(("slept", s))
    )
    srv.shutdown()
    assert rc == 0
    info = published[0]
    assert info["status"] == "ready"
    assert info["public_port"] == "18931"
    assert published[1][0] == "slept"


def test_handler_fails_when_agent_down(monkeypatch):
    monkeypatch.setattr(worker, "HEALTH_BUDGET_S", 1.5)
    rc = worker.handler(18999, publish=lambda i: None, sleep=lambda s: None)
    assert rc == 1


def _serve_publish(status, hits):
    """-> (server, os-assigned port) answering every POST with ``status``."""
    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            hits.append(self.path)
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(status)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


def test_publish_404_is_terminal_single_attempt(monkeypatch):
    """ROADMAP open item 3: urlopen raises HTTPError BEFORE the status
    check and retry_on used to catch it as URLError, re-POSTing a
    permanent 404 through the whole backoff budget.  4xx must fail after
    EXACTLY one attempt."""
    hits = []
    srv, port = _serve_publish(404, hits)
    monkeypatch.setenv("WORKER_PUBLISH_URL", f"http://127.0.0.1:{port}/pub")
    try:
        ok = worker.default_publish({"status": "ready"})
    finally:
        srv.shutdown()
    assert ok is False
    assert len(hits) == 1


def test_publish_2xx_succeeds(monkeypatch):
    hits = []
    srv, port = _serve_publish(204, hits)
    monkeypatch.setenv("WORKER_PUBLISH_URL", f"http://127.0.0.1:{port}/pub")
    try:
        ok = worker.default_publish({"status": "ready"})
    finally:
        srv.shutdown()
    assert ok is True
    assert len(hits) == 1


def test_check_server_times_out():
    t0 = __import__("time").monotonic()
    assert not worker.check_server("http://127.0.0.1:18998/", budget_s=1.0)
    assert __import__("time").monotonic() - t0 < 5
