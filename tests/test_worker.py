"""Serverless worker sidecar (reference runpod/handler.py parity)."""

import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from ai_rtc_agent_tpu.server import worker


def _serve_health(port, status=200, n_requests=10):
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(status)
            self.end_headers()
            self.wfile.write(b"OK")

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", port), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def test_handler_publishes_and_holds():
    srv = _serve_health(18931)
    published = []
    rc = worker.handler(
        18931, publish=published.append, sleep=lambda s: published.append(("slept", s))
    )
    srv.shutdown()
    assert rc == 0
    info = published[0]
    assert info["status"] == "ready"
    assert info["public_port"] == "18931"
    assert published[1][0] == "slept"


def test_handler_fails_when_agent_down(monkeypatch):
    monkeypatch.setattr(worker, "HEALTH_BUDGET_S", 1.5)
    rc = worker.handler(18999, publish=lambda i: None, sleep=lambda s: None)
    assert rc == 1


def test_check_server_times_out():
    t0 = __import__("time").monotonic()
    assert not worker.check_server("http://127.0.0.1:18998/", budget_s=1.0)
    assert __import__("time").monotonic() - t0 < 5
