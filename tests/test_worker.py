"""Serverless worker sidecar (reference runpod/handler.py parity)."""

import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from ai_rtc_agent_tpu.server import worker


def _serve_health(port, status=200, n_requests=10):
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(status)
            self.end_headers()
            self.wfile.write(b"OK")

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", port), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def test_handler_publishes_and_holds(monkeypatch):
    monkeypatch.setenv("AGENT_TIMEOUT", "10")
    monkeypatch.setenv("WORKER_REPUBLISH_S", "5")
    srv = _serve_health(18931)
    published = []
    t = {"now": 0.0}

    def fake_sleep(s):
        published.append(("slept", s))
        t["now"] += s

    rc = worker.handler(
        18931, publish=published.append, sleep=fake_sleep,
        clock=lambda: t["now"],
    )
    srv.shutdown()
    assert rc == 0
    info = published[0]
    assert info["status"] == "ready"
    assert info["public_port"] == "18931"
    assert published[1][0] == "slept"
    # the lease is held to its full AGENT_TIMEOUT across republish ticks
    assert sum(s for tag, s in published[1:] if tag == "slept") == 10


def test_handler_republish_legacy_single_sleep(monkeypatch):
    """WORKER_REPUBLISH_S<=0 restores the original one-sleep lease."""
    monkeypatch.setenv("AGENT_TIMEOUT", "600")
    monkeypatch.setenv("WORKER_REPUBLISH_S", "0")
    srv = _serve_health(18932)
    slept = []
    rc = worker.handler(
        18932, publish=lambda i: None, sleep=slept.append,
        clock=lambda: 0.0,
    )
    srv.shutdown()
    assert rc == 0
    assert slept == [600]


def test_handler_republishes_on_capacity_change(monkeypatch):
    """ISSUE 11 satellite: a box that fills up mid-lease must republish
    its shrunken capacity instead of advertising the stale number for
    the rest of AGENT_TIMEOUT; an unchanged capacity republishes
    NOTHING (bounded cadence, no publish storm)."""
    monkeypatch.setenv("AGENT_TIMEOUT", "20")
    monkeypatch.setenv("WORKER_REPUBLISH_S", "5")
    monkeypatch.setattr(worker, "check_server", lambda url, budget_s: True)
    caps = [
        {"capacity": 4, "saturated": False},   # initial publish
        {"capacity": 4, "saturated": False},   # tick 1: unchanged
        {"capacity": 0, "saturated": True},    # tick 2: box filled up
        {"capacity": 0, "saturated": True},    # tick 3: unchanged again
    ]
    monkeypatch.setattr(worker, "fetch_capacity", lambda url: caps.pop(0))
    published = []
    t = {"now": 0.0}

    def fake_sleep(s):
        t["now"] += s

    rc = worker.handler(
        0, publish=published.append, sleep=fake_sleep,
        clock=lambda: t["now"],
    )
    assert rc == 0
    assert len(published) == 2
    assert published[0]["capacity"] == 4
    assert published[0]["saturated"] is False
    assert published[1]["capacity"] == 0
    assert published[1]["saturated"] is True
    # identity fields ride every republish (the orchestrator keys on them)
    assert published[1]["worker_id"] == published[0]["worker_id"]


def test_handler_failed_republish_retries_on_next_tick(monkeypatch):
    """A republish that fails terminally (publish -> False) must not
    burn the change: the next tick sees the same delta and tries
    again."""
    monkeypatch.setenv("AGENT_TIMEOUT", "15")
    monkeypatch.setenv("WORKER_REPUBLISH_S", "5")
    monkeypatch.setattr(worker, "check_server", lambda url, budget_s: True)
    caps = [
        {"capacity": 4, "saturated": False},
        {"capacity": 1, "saturated": False},  # change; publish fails
        {"capacity": 1, "saturated": False},  # unchanged vs LAST PUBLISHED
    ]
    monkeypatch.setattr(worker, "fetch_capacity", lambda url: caps.pop(0))
    calls = []
    outcomes = iter([None, False, None])  # initial ok, republish fails, retry ok

    def flaky_publish(info):
        calls.append(info)
        return next(outcomes)

    t = {"now": 0.0}

    def fake_sleep(s):
        t["now"] += s

    rc = worker.handler(
        0, publish=flaky_publish, sleep=fake_sleep, clock=lambda: t["now"]
    )
    assert rc == 0
    assert len(calls) == 3
    assert calls[1]["capacity"] == 1 and calls[2]["capacity"] == 1


def test_handler_fails_when_agent_down(monkeypatch):
    monkeypatch.setattr(worker, "HEALTH_BUDGET_S", 1.5)
    rc = worker.handler(18999, publish=lambda i: None, sleep=lambda s: None)
    assert rc == 1


def _serve_publish(status, hits):
    """-> (server, os-assigned port) answering every POST with ``status``."""
    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            hits.append(self.path)
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(status)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


def test_publish_404_is_terminal_single_attempt(monkeypatch):
    """ROADMAP open item 3: urlopen raises HTTPError BEFORE the status
    check and retry_on used to catch it as URLError, re-POSTing a
    permanent 404 through the whole backoff budget.  4xx must fail after
    EXACTLY one attempt."""
    hits = []
    srv, port = _serve_publish(404, hits)
    monkeypatch.setenv("WORKER_PUBLISH_URL", f"http://127.0.0.1:{port}/pub")
    try:
        ok = worker.default_publish({"status": "ready"})
    finally:
        srv.shutdown()
    assert ok is False
    assert len(hits) == 1


def test_publish_2xx_succeeds(monkeypatch):
    hits = []
    srv, port = _serve_publish(204, hits)
    monkeypatch.setenv("WORKER_PUBLISH_URL", f"http://127.0.0.1:{port}/pub")
    try:
        ok = worker.default_publish({"status": "ready"})
    finally:
        srv.shutdown()
    assert ok is True
    assert len(hits) == 1


def test_check_server_times_out():
    t0 = __import__("time").monotonic()
    assert not worker.check_server("http://127.0.0.1:18998/", budget_s=1.0)
    assert __import__("time").monotonic() - t0 < 5
