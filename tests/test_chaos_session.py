"""Scripted chaos: live sessions driven through seeded fault schedules.

Fast tier (runs everywhere, deterministic, no long sleeps): a loopback
end-to-end session hits an injected engine stall mid-stream and must
degrade to passthrough (the stream NEVER freezes), restart the engine in
the background, climb back to HEALTHY, and expose every transition at
GET /health — the ISSUE's chaos acceptance on the hermetic tier.

Slow tier (full boxes: native lib + cryptography): the same schedule plus
a 30% datagram loss burst against a real SECURE session over UDP.
"""

import asyncio
import json
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.resilience import faults
from ai_rtc_agent_tpu.resilience.faults import FaultPlan, FaultSpec
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.signaling import (
    LoopbackProvider,
    make_loopback_offer,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.deactivate()
    yield
    faults.deactivate()


class ChaosPipeline:
    """Invert-colors pipeline that consults the engine fault scope exactly
    the way StreamEngine.submit does — the test's stand-in for a real
    engine under an injected schedule."""

    def __init__(self):
        self._fault_scope = faults.scope("engine")
        self.restarts = 0
        self.calls = 0

    def __call__(self, frame):
        self.calls += 1
        if self._fault_scope is not None:
            self._fault_scope.step()
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def restart(self):
        self.restarts += 1


async def _pump_until(pc, viewer_recv, pred, frames, deadline_s=20.0):
    """Push frames and collect outputs until pred() or deadline.  Every
    recv is bounded — a stream freeze fails the test immediately."""
    outs = []
    deadline = time.monotonic() + deadline_s
    i = 0
    while time.monotonic() < deadline and not pred(outs):
        f = frames[i % len(frames)]
        i += 1
        await pc.in_track.push(f)
        out = await asyncio.wait_for(viewer_recv(), timeout=3.0)
        outs.append((f, out))
    return outs


def test_chaos_engine_stall_degrades_to_passthrough_then_recovers(monkeypatch):
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("RESILIENCE_STEP_TIMEOUT_S", "0.25")
    monkeypatch.setenv("RESILIENCE_FIRST_STEP_TIMEOUT_S", "0.25")
    monkeypatch.setenv("SUPERVISOR_STALL_AFTER_S", "30")  # step watchdog drives

    # the schedule: steps 3-4 wedge far past the step budget
    faults.activate(
        FaultPlan(
            specs=(
                FaultSpec(
                    target="engine", kind="slow_step",
                    start=3, stop=5, delay_s=4.0,
                ),
            ),
            seed=7,
        )
    )
    pipe = ChaosPipeline()

    async def go():
        app = build_app(pipeline=pipe, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "chaos",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
            )
            assert r.status == 200
            pc = next(iter(app["pcs"]))
            viewer = pc.out_tracks[0]
            frames = [
                np.full((8, 8, 3), 40 + i, dtype=np.uint8) for i in range(4)
            ]

            # phase 1: healthy — outputs inverted
            outs = await _pump_until(
                pc, viewer.recv, lambda o: len(o) >= 2, frames
            )
            assert all(np.array_equal(o, 255 - f) for f, o in outs)

            (sup,) = app["supervisors"].values()

            # phase 2: the stall window.  The stream must keep flowing —
            # passthrough frames (NOT inverted) instead of a freeze —
            # and the supervisor must leave HEALTHY.
            outs = await _pump_until(
                pc,
                viewer.recv,
                lambda o: any(np.array_equal(f, o_) for f, o_ in o),
                frames,
            )
            assert any(np.array_equal(f, o) for f, o in outs), (
                "no passthrough frame seen during the injected stall"
            )
            states = {t["to"] for t in sup.snapshot()["transitions"]}
            assert "DEGRADED" in states

            # phase 3: recovery — background restart ran, state returns to
            # HEALTHY, outputs are inverted again
            outs = await _pump_until(
                pc,
                viewer.recv,
                lambda o: sup.state == "HEALTHY"
                and len(o) > 0
                and np.array_equal(o[-1][1], 255 - o[-1][0]),
                frames,
                deadline_s=30.0,
            )
            assert sup.state == "HEALTHY"
            assert pipe.restarts >= 1
            assert np.array_equal(outs[-1][1], 255 - outs[-1][0])

            # the whole ride is visible at the health endpoint
            r = await client.get("/health")
            body = await r.json()
            assert body["status"] == "HEALTHY"
            (snap,) = body["sessions"].values()
            seen = {t["to"] for t in snap["transitions"]}
            assert {"DEGRADED", "RECOVERING", "HEALTHY"} <= seen
            assert snap["passthrough_frames"] >= 1
            assert snap["restarts"] >= 1

            # ... and in /metrics counters
            m = await (await client.get("/metrics")).json()
            assert m.get("supervisor_degraded_total", 0) >= 1
            assert m.get("supervisor_healthy_total", 0) >= 1
        finally:
            await client.close()

    asyncio.run(go())


def test_chaos_nan_poisoning_recovers_via_restart(monkeypatch):
    """Injected NaN outputs (poisoned latents) burst past the error
    threshold, the supervisor restarts the engine, the stream stays up and
    NaN frames never reach the viewer."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("RESILIENCE_STEP_TIMEOUT_S", "1.0")
    monkeypatch.setenv("RESILIENCE_FIRST_STEP_TIMEOUT_S", "1.0")
    monkeypatch.setenv("SUPERVISOR_STALL_AFTER_S", "30")

    faults.activate(
        FaultPlan(
            specs=(
                FaultSpec(target="engine", kind="nan", start=2, stop=5),
            ),
            seed=3,
        )
    )

    class NanChaosPipeline(ChaosPipeline):
        def __call__(self, frame):
            self.calls += 1
            action = (
                self._fault_scope.step() if self._fault_scope is not None else None
            )
            arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
            if action == "nan":
                return np.full(arr.shape, np.nan, np.float32)
            return 255 - arr

    pipe = NanChaosPipeline()

    async def go():
        app = build_app(pipeline=pipe, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "nan-chaos",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
            )
            assert r.status == 200
            pc = next(iter(app["pcs"]))
            viewer = pc.out_tracks[0]
            frames = [np.full((8, 8, 3), 90, dtype=np.uint8)]
            (sup,) = app["supervisors"].values()

            outs = await _pump_until(
                pc,
                viewer.recv,
                lambda o: sup.state == "HEALTHY" and pipe.restarts >= 1,
                frames,
                deadline_s=30.0,
            )
            # no NaN ever reached the wire-facing track
            for _, o in outs:
                assert o.dtype == np.uint8
            assert pipe.restarts >= 1
            assert sup.state == "HEALTHY"
        finally:
            await client.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# full-box tier: loss burst + engine stall against a real SECURE session
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_secure_session_loss_burst_plus_engine_stall(monkeypatch):
    pytest.importorskip("cryptography", reason="secure tier needs cryptography")
    from ai_rtc_agent_tpu.media import native

    if native.load() is None:
        pytest.skip("native lib unavailable")

    from ai_rtc_agent_tpu.media.frames import VideoFrame
    from ai_rtc_agent_tpu.media.plane import H264RingSource, H264Sink
    from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider
    from tests.secure_client import SecureTestPeer, secure_offer

    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("RESILIENCE_STEP_TIMEOUT_S", "0.25")
    monkeypatch.setenv("RESILIENCE_FIRST_STEP_TIMEOUT_S", "0.25")
    monkeypatch.setenv("SUPERVISOR_STALL_AFTER_S", "30")
    use_h264 = native.h264_available()
    w = h = 64

    # the ISSUE's schedule: a 30% loss burst on inbound datagrams plus an
    # engine stall, all from one seeded plan
    faults.activate(
        FaultPlan(
            specs=(
                FaultSpec(target="rx", kind="drop", p=0.3, start=40, stop=200),
                FaultSpec(
                    target="engine", kind="slow_step",
                    start=10, stop=12, delay_s=4.0,
                ),
            ),
            seed=5,
        )
    )
    pipe = ChaosPipeline()

    async def go():
        provider = NativeRtpProvider(
            default_width=w, default_height=h, use_h264=use_h264
        )
        app = build_app(pipeline=pipe, provider=provider)
        http = TestClient(TestServer(app))
        await http.start_server()
        peer = await SecureTestPeer("chaos-client").open_socket()
        out_sink = H264Sink(w, h, use_h264=use_h264, payload_type=102)
        back_src = H264RingSource(w, h, use_h264=use_h264)
        try:
            r = await http.post(
                "/offer",
                json={
                    "room_id": "secure-chaos",
                    "offer": {
                        "sdp": secure_offer(peer.cert.fingerprint),
                        "type": "offer",
                    },
                },
            )
            assert r.status == 200
            await peer.establish((await r.json())["sdp"])

            decoded = []

            def pop_all():
                while (item := back_src.poll()) is not None:
                    decoded.append(item[0])

            # drive 240 frames through the faulted session; the server
            # receive socket drops 30% of datagrams in the burst window and
            # the engine wedges at steps 10-11
            for i in range(240):
                f = VideoFrame.from_ndarray(
                    np.full((h, w, 3), 30 + (i % 50), np.uint8)
                )
                f.pts = i * 3000
                peer.send_rtp(out_sink.consume(f))
                peer.drain_into(back_src)
                pop_all()
                await asyncio.sleep(0.02)

            sups = list(app["supervisors"].values())
            assert sups, "secure session was never supervised"
            sup = sups[0]
            for _ in range(200):
                if sup.state == "HEALTHY" and pipe.restarts >= 1:
                    break
                await asyncio.sleep(0.05)
                peer.drain_into(back_src)
                pop_all()

            # the process survived, frames flowed despite the loss burst,
            # and the session recovered to HEALTHY
            assert decoded, "no frames made it through the chaos schedule"
            assert pipe.restarts >= 1
            assert sup.state == "HEALTHY"
            states = {t["to"] for t in sup.snapshot()["transitions"]}
            assert "DEGRADED" in states

            r = await http.get("/health")
            assert (await r.json())["status"] == "HEALTHY"
        finally:
            out_sink.close()
            back_src.close()
            peer.close()
            await http.close()

    asyncio.run(go())
