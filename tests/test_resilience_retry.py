"""Unified retry/backoff policy (resilience/retry.py) — deterministic,
injected clocks only, no wall-time sleeps."""

import asyncio
import random

import pytest

from ai_rtc_agent_tpu.resilience.retry import (
    RetryError,
    RetryPolicy,
    poll_policy,
    transient_policy,
)


def test_backoff_schedule_grows_and_caps():
    p = RetryPolicy(
        attempts=10, base_delay_s=1.0, multiplier=2.0, max_delay_s=8.0, jitter=0.0
    )
    g = p.delays()
    assert [next(g) for _ in range(6)] == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]


def test_jitter_is_bounded_and_seeded():
    p = RetryPolicy(attempts=3, base_delay_s=1.0, jitter=0.2)
    a = [next(p.delays(random.Random(7))) for _ in range(1)]
    b = [next(p.delays(random.Random(7))) for _ in range(1)]
    assert a == b  # same seed, same schedule
    for _ in range(100):
        d = next(p.delays(random.Random()))
        assert 0.8 <= d <= 1.2


def test_full_jitter_decorrelates_and_pins_when_seeded():
    """ISSUE 4 satellite: full jitter draws each delay from U[0, core] so a
    fleet retrying one control plane cannot synchronize into a retry storm.
    Deterministic when seeded (like faults.py plans): the exact schedule
    for seed 7 is pinned."""
    p = RetryPolicy(
        attempts=6, base_delay_s=1.0, multiplier=2.0, max_delay_s=8.0,
        full_jitter=True,
    )
    g = p.delays(random.Random(7))
    sched = [round(next(g), 6) for _ in range(5)]
    assert sched == [0.323833, 0.301698, 2.603738, 0.57949, 4.287056]
    # same seed -> same schedule; envelope respected for any seed
    g2 = p.delays(random.Random(7))
    assert [round(next(g2), 6) for _ in range(5)] == sched
    caps = [1.0, 2.0, 4.0, 8.0, 8.0]
    for seed in range(20):
        g = p.delays(random.Random(seed))
        for cap in caps:
            d = next(g)
            assert 0.0 <= d <= cap


def test_full_jitter_takes_precedence_over_fractional():
    p = RetryPolicy(attempts=3, base_delay_s=1.0, jitter=0.2, full_jitter=True)
    # fractional jitter would bound delays to [0.8, 1.2]; full jitter uses
    # the whole [0, 1] interval
    seen = [next(p.delays(random.Random(s))) for s in range(50)]
    assert min(seen) < 0.8


def test_transient_policy_uses_full_jitter():
    """The fleet-facing shape (worker publish, Twilio, Civitai, example
    signaling) is full-jitter by default — the anti-storm satellite."""
    p = transient_policy(attempts=3, base_delay_s=2.0)
    assert p.full_jitter
    slept = []
    p.run(
        lambda: (_ for _ in ()).throw(OSError("x")),
        sleep=slept.append, rng=random.Random(7), default=None,
    )
    assert slept == [pytest.approx(2 * 0.32383276483316237, rel=1e-9),
                     pytest.approx(4 * 0.15084917392450192, rel=1e-9)]


def test_poll_policy_stays_unjittered():
    """The health poll is a fixed-interval deadline-bound probe against
    localhost — jitter would only blur its budget accounting."""
    p = poll_policy(budget_s=5.0, interval_s=1.0)
    assert not p.full_jitter and p.jitter == 0.0


def test_run_retries_then_succeeds():
    calls = []
    slept = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    p = RetryPolicy(attempts=5, base_delay_s=0.5, jitter=0.0)
    out = p.run(fn, sleep=slept.append)
    assert out == "ok"
    assert len(calls) == 3
    assert slept == [0.5, 1.0]


def test_run_exhausts_raises_retryerror_with_cause():
    def fn():
        raise ValueError("nope")

    p = RetryPolicy(attempts=3, base_delay_s=0.1, jitter=0.0)
    with pytest.raises(RetryError) as ei:
        p.run(fn, sleep=lambda s: None)
    assert isinstance(ei.value.last, ValueError)


def test_run_default_instead_of_raise():
    p = RetryPolicy(attempts=2, base_delay_s=0.1, jitter=0.0)
    out = p.run(lambda: 1 / 0, retry_on=(ZeroDivisionError,),
                sleep=lambda s: None, default="fallback")
    assert out == "fallback"


def test_non_retryable_exception_propagates_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise KeyError("fatal")

    p = RetryPolicy(attempts=5, base_delay_s=0.1, jitter=0.0)
    with pytest.raises(KeyError):
        p.run(fn, retry_on=(OSError,), sleep=lambda s: None)
    assert len(calls) == 1


def test_deadline_stops_unbounded_poll():
    """poll_policy: fixed interval, deadline-bound — the health-poll shape."""
    now = [0.0]

    def clock():
        return now[0]

    def sleep(s):
        now[0] += s

    calls = []

    def fn():
        calls.append(now[0])
        raise OSError("still down")

    p = poll_policy(budget_s=5.0, interval_s=1.0)
    out = p.run(fn, sleep=sleep, clock=clock, default=False)
    assert out is False
    # one probe per second until the budget: no backoff growth
    assert calls == [0.0, 1.0, 2.0, 3.0, 4.0]


def test_deadline_clamps_final_sleep():
    now = [0.0]
    slept = []

    def clock():
        return now[0]

    def sleep(s):
        slept.append(s)
        now[0] += s

    p = RetryPolicy(
        attempts=None, base_delay_s=10.0, multiplier=1.0, jitter=0.0, deadline_s=4.0
    )
    p.run(lambda: (_ for _ in ()).throw(OSError()), sleep=sleep, clock=clock,
          default=None)
    assert slept == [4.0]  # clamped to the remaining budget, then stop


def test_unbounded_requires_deadline():
    with pytest.raises(ValueError):
        RetryPolicy(attempts=None)


def test_arun_async_retry():
    calls = []

    async def fn():
        calls.append(1)
        if len(calls) < 2:
            raise OSError("transient")
        return 42

    async def go():
        p = transient_policy(attempts=3, base_delay_s=0.001)
        return await p.arun(fn)

    assert asyncio.run(go()) == 42
    assert len(calls) == 2


def test_on_retry_observability_hook():
    seen = []
    p = RetryPolicy(attempts=3, base_delay_s=0.5, jitter=0.0)
    p.run(
        lambda: (_ for _ in ()).throw(OSError("x")),
        sleep=lambda s: None,
        on_retry=lambda i, exc, d: seen.append((i, type(exc).__name__, d)),
        default=None,
    )
    assert seen == [(1, "OSError", 0.5), (2, "OSError", 1.0)]
