"""Native media runtime tests: ring, RTP, H.264 roundtrip.

These run without JAX (pure host-side), so they're fast.  The H.264 tests
skip when the distro libavcodec isn't the gated 5.x ABI.
"""

import numpy as np
import pytest

from ai_rtc_agent_tpu.media import native
from ai_rtc_agent_tpu.media.codec import NullCodec
from ai_rtc_agent_tpu.media.ring import FrameRing


def test_frame_ring_fifo(rng):
    ring = FrameRing((4, 4, 3), n_slots=4)
    frames = [rng.integers(0, 256, (4, 4, 3), dtype=np.uint8) for _ in range(3)]
    for i, f in enumerate(frames):
        assert ring.push_latest(f, meta=i)
    assert ring.size == 3
    for i, f in enumerate(frames):
        got, meta = ring.pop()
        np.testing.assert_array_equal(got, f)
        assert meta == i
    assert ring.pop() is None
    ring.close()


def test_frame_ring_latest_wins(rng):
    ring = FrameRing((2, 2, 3), n_slots=2)
    frames = [np.full((2, 2, 3), i, np.uint8) for i in range(5)]
    for i, f in enumerate(frames):
        ring.push_latest(f, meta=i)
    # capacity 2: oldest evicted, newest retained
    metas = []
    while (item := ring.pop()) is not None:
        metas.append(item[1])
    assert metas[-1] == 4
    assert len(metas) <= 2
    assert ring.dropped >= 1
    ring.close()


def test_null_codec_roundtrip(rng):
    f = rng.integers(0, 256, (16, 24, 3), dtype=np.uint8)
    enc = NullCodec.encode(f, pts=77)
    back, pts = NullCodec.decode(enc)
    np.testing.assert_array_equal(back, f)
    assert pts == 77


@pytest.fixture(scope="module")
def native_lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native lib unavailable")
    return lib


def test_rtp_roundtrip_small_and_fua(native_lib):
    from ai_rtc_agent_tpu.media.rtp import RtpDepacketizer, RtpPacketizer

    rng = np.random.default_rng(0)
    # fake annex-B AU: one small NAL + one large NAL (forces FU-A)
    small = bytes([0x67]) + bytes(rng.integers(0, 255, 30, dtype=np.uint8))
    large = bytes([0x65]) + bytes(rng.integers(0, 255, 5000, dtype=np.uint8))
    au = b"\x00\x00\x00\x01" + small + b"\x00\x00\x01" + large

    ptz = RtpPacketizer(mtu=1200)
    packets = ptz.packetize(au, timestamp=9000)
    assert len(packets) >= 1 + 5  # small NAL + >=5 FU-A fragments
    # marker only on the last packet
    markers = [bool(p[1] & 0x80) for p in packets]
    assert markers[-1] and not any(markers[:-1])

    dpz = RtpDepacketizer()
    out = None
    for p in packets:
        r = dpz.push(p)
        if r:
            out = r
    assert out is not None
    got_au, ts = out
    assert ts == 9000
    # reassembled AU uses 4-byte start codes throughout
    want = b"\x00\x00\x00\x01" + small + b"\x00\x00\x00\x01" + large
    assert got_au == want


def test_h264_encode_decode_roundtrip(native_lib):
    if not native_lib.tr_h264_available():
        pytest.skip("libavcodec 5.x not present")
    from ai_rtc_agent_tpu.media.codec import H264Decoder, H264Encoder

    w, h = 128, 96
    enc = H264Encoder(w, h, fps=30)
    dec = H264Decoder()

    # moving gradient frames
    frames = []
    for i in range(8):
        y, x = np.mgrid[0:h, 0:w]
        f = np.stack(
            [(x + 4 * i) % 256, (y + 2 * i) % 256, np.full_like(x, 128)], axis=-1
        ).astype(np.uint8)
        frames.append(f)

    decoded = []
    for i, f in enumerate(frames):
        data = enc.encode(f, pts=i)
        if data:
            out = dec.decode(data, pts=i)
            if out is not None:
                decoded.append(out[0])
    # drain both pipelines
    data = enc.flush()
    if data:
        out = dec.decode(data)
        if out is not None:
            decoded.append(out[0])
    while (out := dec.flush()) is not None:
        decoded.append(out[0])

    assert len(decoded) >= 4
    d0 = decoded[0].astype(np.int16)
    f0 = frames[0].astype(np.int16)
    assert d0.shape == f0.shape
    # lossy codec: mean abs error small on a smooth gradient
    assert np.abs(d0 - f0).mean() < 16
    enc.close()
    dec.close()


def test_full_media_plane_e2e(native_lib, rng):
    """Glass-to-glass slice: RGB -> H.264 encode -> RTP packetize -> depacketize
    -> decode -> diffusion pipeline -> stylized RGB (the complete TPU-side
    replacement for the reference's NVDEC->diffuse->NVENC loop,
    reference lib/tracks.py:33-38 + lib/pipeline.py:76-96)."""
    import pytest

    from ai_rtc_agent_tpu.media import native as N
    from ai_rtc_agent_tpu.media.codec import H264Decoder, H264Encoder
    from ai_rtc_agent_tpu.media.rtp import RtpDepacketizer, RtpPacketizer
    from ai_rtc_agent_tpu.stream.pipeline import StreamDiffusionPipeline

    if N.load() is None or not N.h264_available():
        pytest.skip("native h264 unavailable")

    pipe = StreamDiffusionPipeline("tiny-test")
    h = w = pipe.config.height
    enc = H264Encoder(w, h, fps=30)
    dec = H264Decoder()
    pkt = RtpPacketizer(ssrc=0x1234, payload_type=96, mtu=600)
    depkt = RtpDepacketizer()

    delivered = 0
    for i in range(6):
        frame = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        au = enc.encode(frame, i)
        if not au:
            continue  # encoder still buffering
        packets = pkt.packetize(au, timestamp=i * 3000)
        assert packets and all(len(p) <= 600 for p in packets)
        for p in packets:
            ready = depkt.push(p)
            if ready:
                out_au, _ts = ready
                got = dec.decode(out_au, i)
                if got is None:
                    continue
                decoded, _pts = got
                assert decoded.shape == (h, w, 3)
                styled = pipe(decoded)
                assert styled.shape == (h, w, 3) and styled.dtype == np.uint8
                delivered += 1
    assert delivered >= 3  # codec latency may hold back a few frames


def test_depacketizer_survives_adversarial_packets(native_lib):
    """The RTP depacketizer parses REMOTELY-SUPPLIED bytes (the agent's
    UDP media port): 2k seeded adversarial packets (empty, truncated
    headers, forced FU-A indicators, random garbage) must never crash the
    native parser (memory-safety regression gate; a 20k-packet run of the
    same corpus passed during round 3)."""
    from ai_rtc_agent_tpu.media.rtp import RtpDepacketizer, RtpReorderBuffer

    rng = np.random.default_rng(0)
    d = RtpDepacketizer()
    rb = RtpReorderBuffer()
    cases = [b"", b"\x80", b"\x80\x60", b"\x80" * 11, b"\xff" * 12, b"\x00" * 13]
    # the reorder buffer filters <4-byte runts in python — hit the NATIVE
    # parser directly with every truncated shape too
    for c in cases:
        d.push(c)
    aus = 0
    for i in range(2000):
        if i < len(cases):
            pkt = cases[i]
        else:
            ln = int(rng.integers(0, 1500))
            pkt = rng.integers(0, 256, ln, dtype=np.uint8).tobytes()
            if rng.random() < 0.5 and ln >= 13:
                b = bytearray(pkt)
                b[0] = 0x80
                b[1] = (b[1] & 0x80) | 96
                if rng.random() < 0.5:
                    b[12] = (b[12] & 0xE0) | 28  # FU-A indicator
                pkt = bytes(b)
        for p2 in rb.push(pkt):
            if d.push(p2) is not None:
                aus += 1
    d.close()
    assert aus >= 0  # surviving is the assertion


def test_packetizer_boundary_au_sizes(native_lib):
    """NAL sizes straddling the single-NAL/FU-A threshold (max_payload =
    mtu 1200 - 12-byte header = 1188) and large payloads: every emitted
    packet respects the MTU and fragmentation kicks in exactly past the
    threshold."""
    from ai_rtc_agent_tpu.media.rtp import MAX_AU, RtpPacketizer

    rng = np.random.default_rng(1)
    p = RtpPacketizer()
    max_payload = 1200 - 12
    for nal_len in (1, 2, max_payload - 1, max_payload, max_payload + 1,
                    max_payload + 2, 65536, MAX_AU // 2):
        nal = bytes([0x65]) + rng.integers(0, 256, nal_len - 1, dtype=np.uint8).tobytes()
        pkts = p.packetize(b"\x00\x00\x00\x01" + nal, 1234)
        assert pkts, nal_len  # a start-coded NAL must produce packets
        assert all(len(x) <= 1200 for x in pkts), nal_len
        if nal_len <= max_payload:
            assert len(pkts) == 1, (nal_len, len(pkts))  # single NAL packet
        else:
            assert len(pkts) >= 2, nal_len  # FU-A fragmentation engaged
    p.close()


def test_h264_rate_control_bounds(native_lib, monkeypatch):
    """ENC_MIN/MAX_BITRATE (NVENC_* accepted as aliases — reference
    docs/environment.md:17-25): the rc-bound encoder must open via
    tr_h264_encoder_create_rc and still produce a decodable stream."""
    if not native_lib.tr_h264_available():
        pytest.skip("libavcodec 5.x not present")
    assert hasattr(native_lib, "tr_h264_encoder_create_rc")
    from ai_rtc_agent_tpu.media.codec import H264Decoder, H264Encoder

    monkeypatch.setenv("NVENC_MAX_BITRATE", "800000")  # alias spelling
    monkeypatch.setenv("ENC_MIN_BITRATE", "100000")
    w, h = 128, 96
    enc = H264Encoder(w, h, fps=30)
    dec = H264Decoder()
    rng = np.random.default_rng(5)
    decoded = 0
    for i in range(8):
        f = rng.integers(0, 256, (h, w, 3), np.uint8)
        data = enc.encode(f, pts=i)
        if data and dec.decode(data, pts=i) is not None:
            decoded += 1
    data = enc.flush()
    if data and dec.decode(data) is not None:
        decoded += 1
    assert decoded >= 1
    enc.close()
    dec.close()


def test_is_pli_walks_compound_rtcp():
    """Browsers send PLI inside compound RTCP (RR first, RFC 3550) — the
    detector must walk the compound, not just test the first packet
    (code-review r4)."""
    import struct

    from ai_rtc_agent_tpu.media import rtp as R

    pli = R.make_pli()
    assert R.is_pli(pli)
    # RR (PT 201, no report blocks) prepended — the Chrome shape
    rr = struct.pack("!BBH", 0x80, 201, 1) + struct.pack("!I", 0xAAA)
    assert R.is_pli(rr + pli)
    # compound without a PLI
    sdes = struct.pack("!BBH", 0x81, 202, 1) + b"\x00" * 4
    assert not R.is_pli(rr + sdes)
    # plain RTP must never read as PLI
    rtp_pkt = struct.pack("!BBHII", 0x80, 96, 7, 0, 0x1234) + b"\x00" * 20
    assert not R.is_pli(rtp_pkt)
