"""Execute AiortcProvider + the agent's aiortc-specific wiring for real.

aiortc cannot be installed here (zero egress), so these tests install
tests/fake_aiortc.py — a stand-in pinned to the exact API surface the
reference drives (see that module's docstring for the reference citations).
This closes the 'AiortcProvider is dead code in every test' gap (VERDICT r2
item 3): the provider's codec filtering, the name-mangled __gather OBS
workaround, event-decorator wiring, teardown, and the 400-on-bad-SDP path
all execute through the real agent handlers.
"""

import asyncio
import json
import os

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests import fake_aiortc

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "sdp")


class FakePipeline:
    def __init__(self):
        self.prompt = None
        self.calls = 0

    def __call__(self, frame):
        self.calls += 1
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def update_prompt(self, p):
        self.prompt = p

    def update_t_index_list(self, t):
        self.t_index_list = list(t)


@pytest.fixture()
def aiortc_app(monkeypatch):
    """build_app wired to a REAL AiortcProvider over the fake aiortc."""
    fake_aiortc.install()
    try:
        monkeypatch.setenv("WARMUP_FRAMES", "0")
        monkeypatch.delenv("WEBRTC_PROVIDER", raising=False)
        from ai_rtc_agent_tpu.server.agent import build_app
        from ai_rtc_agent_tpu.server.signaling import (
            AiortcProvider,
            get_provider,
        )

        provider = get_provider()
        assert isinstance(provider, AiortcProvider)  # importable -> real tier
        pipe = FakePipeline()
        app = build_app(pipeline=pipe, provider=provider)
        yield app, pipe
    finally:
        # a leaked fake would hijack 'import aiortc' for the whole session
        fake_aiortc.uninstall()


def run(coro):
    return asyncio.run(coro)


async def _client(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


OFFER_SDP = (
    "v=0\r\no=- 1 1 IN IP4 127.0.0.1\r\ns=-\r\nt=0 0\r\n"
    "m=video 9 UDP/TLS/RTP/SAVPF 96 102\r\na=rtpmap:102 H264/90000\r\n"
    "m=application 9 UDP/DTLS/SCTP webrtc-datachannel\r\n"
)


def test_offer_flow_codec_forcing_and_datachannel(aiortc_app):
    app, pipe = aiortc_app

    async def go():
        client = await _client(app)
        try:
            r = await client.post(
                "/offer",
                json={"room_id": "r1",
                      "offer": {"sdp": OFFER_SDP, "type": "offer"}},
            )
            assert r.status == 200, await r.text()
            ans = await r.json()
            assert ans["type"] == "answer" and "H264" in ans["sdp"]

            (pc,) = fake_aiortc.PEER_CONNECTIONS
            # receive preference: H264-only on the video transceiver
            # (reference agent.py:149-152)
            recv_t = pc.getTransceivers()[0]
            assert [c.name for c in recv_t.codec_preferences] == ["H264"]
            # remote video track arrived and was wired back out through
            # addTrack + force_codec (reference agent.py:176-179): the
            # send transceiver's preferences are mimeType-filtered
            send_t = pc.getTransceivers()[-1]
            assert send_t.sender.track is not None
            assert [c.mimeType for c in send_t.codec_preferences] == ["video/H264"]

            # datachannel config routing -> pipeline.update_prompt
            (ch,) = pc.data_channels
            await ch.deliver(json.dumps({"prompt": "neon city"}))
            assert pipe.prompt == "neon city"

            # processed frames flow through the provider's track type
            vt = pc.getTransceivers()[-1].sender.track
            out = await vt.recv()
            arr = out if isinstance(out, np.ndarray) else out.to_ndarray()
            assert arr.shape == (64, 64, 3) and pipe.calls >= 1

            # connection close releases the pc from the app set
            await pc.simulate_state("closed")
            assert pc not in app["pcs"]
        finally:
            await client.close()

    run(go())


def test_whip_whep_with_real_browser_sdp(aiortc_app):
    """The committed real-browser WHIP offer (tests/fixtures/sdp) through
    the aiortc tier: 201 + Location, answer present, and the OBS
    non-trickle gather workaround actually invoked (name-mangled private —
    only works if the provider hands back a genuine RTCPeerConnection)."""
    app, _ = aiortc_app
    with open(os.path.join(FIXDIR, "browser_whip_offer.sdp")) as f:
        browser_offer = f.read()

    async def go():
        client = await _client(app)
        try:
            r = await client.post(
                "/whip", data=browser_offer,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201, await r.text()
            loc = r.headers["Location"]
            assert loc.startswith("/whip/")
            whip_pc = fake_aiortc.PEER_CONNECTIONS[-1]
            assert whip_pc.gather_calls == 1  # OBS workaround executed

            r = await client.post(
                "/whep", data=OFFER_SDP,
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201, await r.text()
            whep_pc = fake_aiortc.PEER_CONNECTIONS[-1]
            assert whep_pc.gather_calls == 1
            # non-trickle answer carries inline candidates
            assert "a=candidate" in await r.text()

            # session-scoped teardown
            r = await client.delete(loc)
            assert r.status == 200
            assert whip_pc.connectionState == "closed"
        finally:
            await client.close()

    run(go())


def test_whip_bad_sdp_maps_to_400_and_leaks_nothing(aiortc_app):
    app, _ = aiortc_app

    async def go():
        client = await _client(app)
        try:
            r = await client.post(
                "/whip", data="v=0\r\ns=-\r\n",  # no media sections
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 400
            assert not app["pcs"]
            assert not app["state"].get("whip_pcs")
        finally:
            await client.close()

    run(go())
