"""Safety checker (reference lib/wrapper.py:930-942 parity).

Covers: CLIP vision tower shapes, HF key-map round trip, flagging logic
(threshold crossing incl. the special-care adjustment), frame blanking, and
the never-flag property of a random-weight checker.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ai_rtc_agent_tpu.models import clip_vision as CV
from ai_rtc_agent_tpu.models import loader as LD
from ai_rtc_agent_tpu.models.safety import (
    SafetyChecker,
    check_images,
    init_safety_checker,
    safety_key_map,
)

CFG = CV.CLIPVisionConfig.tiny()


def _checker(seed=0):
    params = init_safety_checker(jax.random.PRNGKey(seed), CFG)
    return SafetyChecker(params=params, cfg=CFG)


def test_clip_vision_shapes():
    p = CV.init_clip_vision(jax.random.PRNGKey(0), CFG)
    img = jnp.zeros((2, CFG.image_size, CFG.image_size, 3))
    out = CV.apply_clip_vision(p, img, CFG)
    assert out["hidden"].shape == (2, CFG.num_patches + 1, CFG.width)
    assert out["pooled"].shape == (2, CFG.width)


def test_preprocess_resizes_and_normalizes():
    img = jnp.ones((1, 64, 48, 3)) * 0.5
    x = CV.preprocess_clip(img, CFG)
    assert x.shape == (1, CFG.image_size, CFG.image_size, 3)
    expect = (0.5 - np.array(CV.CLIP_MEAN)) / np.array(CV.CLIP_STD)
    np.testing.assert_allclose(np.asarray(x[0, 0, 0]), expect, atol=1e-5)


def test_random_checker_flags_nothing():
    chk = _checker()
    rng = np.random.default_rng(0)
    frames = rng.integers(0, 256, (3, 40, 40, 3), dtype=np.uint8)
    out = chk(frames)
    np.testing.assert_array_equal(out, frames)  # untouched


def test_threshold_crossing_flags_and_blanks():
    chk = _checker()
    rng = np.random.default_rng(1)
    frame = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
    # aim concept 0 at this exact frame's embedding -> cosine sim = 1
    img01 = jnp.asarray(frame[None], jnp.float32) / 255.0
    x = CV.preprocess_clip(img01, CFG)
    pooled = CV.apply_clip_vision(chk.params["vision"], x, CFG)["pooled"]
    from ai_rtc_agent_tpu.models.layers import linear

    emb = linear(chk.params["visual_projection"], pooled)[0]
    chk.params["concept_embeds"] = (
        chk.params["concept_embeds"].at[0].set(emb / jnp.linalg.norm(emb))
    )
    chk.params["concept_embeds_weights"] = (
        chk.params["concept_embeds_weights"].at[0].set(0.5)  # sim 1 > 0.5
    )
    out = chk(frame)
    assert (out == 0).all()  # blanked
    # restoring the threshold above max cosine sim (1.0) must un-flag it
    chk.params["concept_embeds_weights"] = (
        chk.params["concept_embeds_weights"].at[0].set(1.5)
    )
    np.testing.assert_array_equal(chk(frame), frame)


def test_special_care_adjustment():
    params = init_safety_checker(jax.random.PRNGKey(0), CFG)
    img = jnp.zeros((1, CFG.image_size, CFG.image_size, 3))
    # compute the actual embedding, then set special embed to match it with
    # a threshold it barely crosses, and a concept at exactly threshold-0.005
    x = CV.preprocess_clip(img, CFG)
    pooled = CV.apply_clip_vision(params["vision"], x, CFG)["pooled"]
    from ai_rtc_agent_tpu.models.layers import linear

    emb = linear(params["visual_projection"], pooled)[0]
    embn = emb / jnp.linalg.norm(emb)
    params["special_care_embeds"] = params["special_care_embeds"].at[0].set(embn)
    params["special_care_embeds_weights"] = (
        params["special_care_embeds_weights"].at[0].set(0.9)
    )
    params["concept_embeds"] = params["concept_embeds"].at[0].set(embn)
    # sim = 1.0; threshold 1.005: only the +0.01 special adjustment crosses
    params["concept_embeds_weights"] = (
        params["concept_embeds_weights"].at[0].set(1.005)
    )
    flags = check_images(params, img, CFG)
    assert bool(flags[0])
    # without the special hit it must NOT flag
    params["special_care_embeds_weights"] = (
        params["special_care_embeds_weights"].at[0].set(2.0)
    )
    flags = check_images(params, img, CFG)
    assert not bool(flags[0])


def test_safety_key_map_round_trip(tmp_path):
    params = init_safety_checker(jax.random.PRNGKey(2), CFG)
    km = safety_key_map(CFG)
    sd = LD.tree_to_state_dict(params, km)
    assert "visual_projection.weight" in sd
    assert sd["vision_model.vision_model.embeddings.patch_embedding.weight"].shape == (
        CFG.width, 3, CFG.patch_size, CFG.patch_size,
    )
    p2, n = LD.load_into_tree(params, sd, km, strict=False)
    assert n == len(sd)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
