"""Tiny-config model tests on CPU (SURVEY.md section 4 'Integration' tier)."""

import jax
import jax.numpy as jnp
import numpy as np

from ai_rtc_agent_tpu.models import clip as C
from ai_rtc_agent_tpu.models import controlnet as CN
from ai_rtc_agent_tpu.models import taesd as T
from ai_rtc_agent_tpu.models import unet as U


def test_taesd_shapes_and_range(rng):
    cfg = T.TAESDConfig.tiny()
    params = T.init_taesd(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(rng.random((2, 32, 32, 3)).astype(np.float32))
    z = T.encode(params["encoder"], x, cfg)
    assert z.shape == (2, 8, 8, 4)  # 2 stages -> /4
    y = T.decode(params["decoder"], z, cfg)
    assert y.shape == (2, 32, 32, 3)
    assert float(y.min()) >= 0.0 and float(y.max()) <= 1.0


def test_taesd_jit_compiles(rng):
    cfg = T.TAESDConfig.tiny()
    params = T.init_taesd(jax.random.PRNGKey(0), cfg)
    f = jax.jit(lambda p, x: T.decode(p["decoder"], T.encode(p["encoder"], x, cfg), cfg))
    y = f(params, jnp.zeros((1, 16, 16, 3)))
    assert y.shape == (1, 16, 16, 3)


def test_unet_tiny_forward(rng):
    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(1), cfg)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 4)).astype(np.float32))
    t = jnp.array([999, 10])
    ctx = jnp.asarray(rng.standard_normal((2, 7, 32)).astype(np.float32))
    out = U.apply_unet(params, x, t, ctx, cfg)
    assert out.shape == (2, 8, 8, 4)
    assert np.isfinite(np.asarray(out)).all()


def test_unet_sdxl_style_added_cond(rng):
    cfg = U.UNetConfig.tiny_xl()
    params = U.init_unet(jax.random.PRNGKey(2), cfg)
    x = jnp.zeros((1, 8, 8, 4))
    ctx = jnp.zeros((1, 7, 32))
    added = {
        "time_ids": jnp.asarray(np.array([[32, 32, 0, 0, 32, 32]], np.float32)),
        "text_embeds": jnp.zeros((1, 16)),
    }
    out = U.apply_unet(params, x, jnp.array([999]), ctx, cfg, added_cond=added)
    assert out.shape == (1, 8, 8, 4)
    # missing added_cond must raise for text_time configs
    import pytest

    with pytest.raises(ValueError):
        U.apply_unet(params, x, jnp.array([999]), ctx, cfg)


def test_unet_timestep_sensitivity(rng):
    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(3), cfg)
    x = jnp.asarray(rng.standard_normal((1, 8, 8, 4)).astype(np.float32))
    ctx = jnp.asarray(rng.standard_normal((1, 7, 32)).astype(np.float32))
    o1 = U.apply_unet(params, x, jnp.array([10]), ctx, cfg)
    o2 = U.apply_unet(params, x, jnp.array([900]), ctx, cfg)
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


def test_controlnet_zero_init_is_noop(rng):
    cfg = U.UNetConfig.tiny()
    unet_p = U.init_unet(jax.random.PRNGKey(4), cfg)
    cn_p = CN.init_controlnet(jax.random.PRNGKey(5), cfg)
    x = jnp.asarray(rng.standard_normal((1, 8, 8, 4)).astype(np.float32))
    t = jnp.array([500])
    ctx = jnp.asarray(rng.standard_normal((1, 7, 32)).astype(np.float32))
    cond = jnp.asarray(rng.random((1, 64, 64, 3)).astype(np.float32))

    down_res, mid_res = CN.apply_controlnet(cn_p, x, t, ctx, cond, cfg)
    # zero convs: every residual must be exactly zero at init
    for r in down_res + [mid_res]:
        assert float(jnp.abs(r).max()) == 0.0

    base = U.apply_unet(unet_p, x, t, ctx, cfg)
    controlled = U.apply_unet(
        unet_p, x, t, ctx, cfg, down_residuals=down_res, mid_residual=mid_res
    )
    np.testing.assert_allclose(np.asarray(base), np.asarray(controlled), atol=0)


def test_canny_soft_edges(rng):
    img = np.zeros((1, 32, 32, 3), np.float32)
    img[:, :, 16:] = 1.0  # vertical step edge
    e = np.asarray(CN.canny_soft(jnp.asarray(img)))
    assert e.shape == (1, 32, 32, 3)
    assert e[0, 16, 16, 0] > 0.9  # strong response at the edge
    assert e[0, 16, 4, 0] < 0.1  # flat region quiet


def test_clip_text_shapes_and_pooled(rng):
    cfg = C.CLIPTextConfig.tiny()
    params = C.init_clip_text(jax.random.PRNGKey(6), cfg)
    ids = np.zeros((2, 16), np.int32)
    ids[0, :5] = [10, 40, 30, 20, 255]  # eot = argmax = position 4
    ids[1, :3] = [7, 255, 9]
    out = C.apply_clip_text(params, jnp.asarray(ids), cfg)
    assert out["hidden"].shape == (2, 16, 32)
    assert out["pooled"].shape == (2, 32)


def test_clip_causality(rng):
    """Changing a later token must not affect earlier hidden states."""
    cfg = C.CLIPTextConfig.tiny()
    params = C.init_clip_text(jax.random.PRNGKey(7), cfg)
    ids1 = np.ones((1, 8), np.int32) * 3
    ids2 = ids1.copy()
    ids2[0, 6] = 99
    h1 = np.asarray(C.apply_clip_text(params, jnp.asarray(ids1), cfg)["hidden"])
    h2 = np.asarray(C.apply_clip_text(params, jnp.asarray(ids2), cfg)["hidden"])
    np.testing.assert_allclose(h1[0, :6], h2[0, :6], atol=1e-5)
    assert not np.allclose(h1[0, 6:], h2[0, 6:])


def test_clip_skip_penultimate():
    cfg0 = C.CLIPTextConfig.tiny()
    cfg1 = C.CLIPTextConfig(
        vocab_size=256, max_length=16, width=32, layers=2, heads=4, clip_skip=1
    )
    params = C.init_clip_text(jax.random.PRNGKey(8), cfg0)
    ids = jnp.asarray(np.ones((1, 8), np.int32))
    h0 = np.asarray(C.apply_clip_text(params, ids, cfg0)["hidden"])
    h1 = np.asarray(C.apply_clip_text(params, ids, cfg1)["hidden"])
    assert not np.allclose(h0, h1)


def test_default_stream_config_families():
    """Config routing: turbo ids get the 1-step turbo schedule; UNDISTILLED
    SD2.x gets the stream-batch LCM schedule (a 1-step schedule on a
    non-distilled checkpoint produces noise), with 768/v-prediction for
    stable-diffusion-2-1 and 512/epsilon for -base."""
    from ai_rtc_agent_tpu.models import registry

    turbo = registry.default_stream_config("stabilityai/sd-turbo")
    assert turbo.scheduler == "turbo" and turbo.t_index_list == (0,)

    sd21 = registry.default_stream_config("stabilityai/stable-diffusion-2-1")
    assert sd21.scheduler == "lcm" and len(sd21.t_index_list) == 4
    assert sd21.prediction_type == "v_prediction"
    assert sd21.height == 768

    sd21b = registry.default_stream_config("stabilityai/stable-diffusion-2-1-base")
    assert sd21b.prediction_type == "epsilon" and sd21b.height == 512

    xl = registry.default_stream_config("stabilityai/sdxl-turbo")
    assert xl.height == 1024 and xl.use_added_cond

    sd15 = registry.default_stream_config("lykon/dreamshaper-8")
    assert sd15.scheduler == "lcm" and sd15.cfg_type == "self"


def test_v_prediction_stream_end_to_end(rng):
    """The v-prediction path (SD2.1-768 family) streams end to end."""
    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.stream.engine import StreamEngine

    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config(
        "tiny-test", prediction_type="v_prediction"
    )
    eng = StreamEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt
    ).prepare("v-pred stream", seed=4)
    frame = rng.integers(0, 256, (cfg.height, cfg.width, 3), dtype=np.uint8)
    for _ in range(3):
        out = eng(frame)
    assert out.shape == frame.shape and out.dtype == np.uint8
