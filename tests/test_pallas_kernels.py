"""Pallas kernels vs XLA references (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ai_rtc_agent_tpu.ops import lcm as L
from ai_rtc_agent_tpu.ops import rcfg as R
from ai_rtc_agent_tpu.ops import schedule as S
from ai_rtc_agent_tpu.ops.pallas import attention as PA
from ai_rtc_agent_tpu.ops.pallas import fused_scheduler as FS


def _coeffs():
    sch = S.make_schedule()
    bt = S.batched_sub_timesteps([18, 26, 35, 45], 50)
    return L.make_step_coeffs(sch, bt).as_jnp()


@pytest.mark.parametrize("cfg_type", ["self", "none"])
def test_fused_epilogue_matches_composed_ops(rng, cfg_type):
    c = _coeffs()
    shape = (4, 8, 8, 4)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    eps_c = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    stock = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    noise = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    g, d = 1.5, 0.9

    den, adv, stock_new = FS.fused_stream_epilogue(
        x, eps_c, stock, noise, c, g, d, cfg_type, interpret=True
    )

    # composed reference path (ops/lcm + ops/rcfg)
    if cfg_type == "self":
        eps = R.combine_residual(eps_c, stock, g, d)
    else:
        eps = eps_c
    den_ref = L.lcm_denoise(x, eps, c)
    adv_ref = L.renoise_next(den_ref, noise, c)
    np.testing.assert_allclose(np.asarray(den), np.asarray(den_ref), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_ref), rtol=1e-4, atol=1e-5)
    if cfg_type == "self":
        stock_ref = R.update_stock_noise(stock, eps_c, c.alpha, c.sigma)
        np.testing.assert_allclose(
            np.asarray(stock_new), np.asarray(stock_ref), rtol=1e-4, atol=1e-5
        )
    else:
        np.testing.assert_allclose(np.asarray(stock_new), np.asarray(stock))


def test_flash_attention_matches_dense(rng):
    B, L_, H, D = 2, 64, 2, 16
    q = jnp.asarray(rng.standard_normal((B, L_, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, L_, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, L_, H, D)).astype(np.float32))
    got = np.asarray(PA.flash_attention(q, k, v, block_q=32, block_k=32, interpret=True))
    want = np.asarray(PA._xla_attention(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_flash_attention_ragged_falls_back(rng):
    B, Lq, Lk, H, D = 1, 10, 7, 2, 8  # not divisible by blocks
    q = jnp.asarray(rng.standard_normal((B, Lq, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, Lk, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, Lk, H, D)).astype(np.float32))
    got = np.asarray(PA.flash_attention(q, k, v, block_q=8, block_k=8, interpret=True))
    want = np.asarray(PA._xla_attention(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_flash_attention_rejects_mask(rng):
    q = jnp.zeros((1, 8, 1, 4))
    with pytest.raises(NotImplementedError):
        PA.flash_attention(q, q, q, mask=jnp.zeros((8, 8)))
