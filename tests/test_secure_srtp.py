"""SRTP (server/secure/srtp.py) pinned against RFC 3711 test vectors.

The key-derivation vectors are Appendix B.3 of the RFC — byte-exact
published values, so the KDF is pinned independently of our own code.
"""

import pytest

# the secure tier's crypto backend is optional at the package level
# (signaling degrades to loopback without it) — these tests must SKIP,
# not fail collection, on a box without it (resilience PR satellite)
pytest.importorskip("cryptography", reason="secure tier needs cryptography")

import struct

import pytest

from ai_rtc_agent_tpu.server.secure import srtp

B3_MASTER_KEY = bytes.fromhex("E1F97A0D3E018BE0D64FA32C06DE4139")
B3_MASTER_SALT = bytes.fromhex("0EC675AD498AFEEBB6960B3AABE6")


def test_rfc3711_b3_cipher_key():
    out = srtp.kdf(B3_MASTER_KEY, B3_MASTER_SALT, srtp.LABEL_RTP_ENCRYPTION, 16)
    assert out == bytes.fromhex("C61E7A93744F39EE10734AFE3FF7A087")


def test_rfc3711_b3_cipher_salt():
    out = srtp.kdf(B3_MASTER_KEY, B3_MASTER_SALT, srtp.LABEL_RTP_SALT, 14)
    assert out == bytes.fromhex("30CBBC08863D8C85D49DB34A9AE1")


def test_rfc3711_b3_auth_key():
    out = srtp.kdf(B3_MASTER_KEY, B3_MASTER_SALT, srtp.LABEL_RTP_AUTH, 20)
    assert out == bytes.fromhex("CEBE321F6FF7716B6FD4AB49AF256A156D38BAA4")


def _rtp_packet(seq: int, ssrc: int = 0x1234, payload: bytes = b"\xab" * 160):
    return (
        struct.pack("!BBHII", 0x80, 96, seq & 0xFFFF, 1000 + seq, ssrc)
        + payload
    )


class TestSrtpRoundtrip:
    def _pair(self):
        key, salt = b"k" * 16, b"s" * 14
        return srtp.SrtpContext(key, salt), srtp.SrtpContext(key, salt)

    def test_protect_unprotect(self):
        tx, rx = self._pair()
        pkt = _rtp_packet(1)
        wire = tx.protect(pkt)
        assert len(wire) == len(pkt) + srtp.AUTH_TAG_LEN
        assert wire[:12] == pkt[:12]  # header in clear
        assert wire[12 : len(pkt)] != pkt[12:]  # payload encrypted
        assert rx.unprotect(wire) == pkt

    def test_tamper_detected(self):
        tx, rx = self._pair()
        wire = bytearray(tx.protect(_rtp_packet(1)))
        wire[20] ^= 0x01
        with pytest.raises(ValueError, match="auth"):
            rx.unprotect(bytes(wire))

    def test_wrong_key_detected(self):
        tx = srtp.SrtpContext(b"k" * 16, b"s" * 14)
        rx = srtp.SrtpContext(b"K" * 16, b"s" * 14)
        with pytest.raises(ValueError, match="auth"):
            rx.unprotect(tx.protect(_rtp_packet(1)))

    def test_sequence_rollover_keeps_decrypting(self):
        """ROC advances at the 16-bit seq wrap; both ends stay in sync
        (RFC 3711 s3.3.1 index estimation)."""
        tx, rx = self._pair()
        for seq in [65533, 65534, 65535, 0, 1, 2]:
            pkt = _rtp_packet(seq)
            assert rx.unprotect(tx.protect(pkt)) == pkt
        assert tx._roc[0x1234][0] == 1  # rolled over exactly once

    def test_distinct_ssrc_independent_roc(self):
        tx, rx = self._pair()
        for ssrc in (0x111, 0x222):
            pkt = _rtp_packet(7, ssrc=ssrc)
            assert rx.unprotect(tx.protect(pkt)) == pkt

    def test_replayed_packet_rejected(self):
        """RFC 3711 s3.3.2 replay list (code-review r4): a captured packet
        re-sent verbatim must not decrypt twice."""
        tx, rx = self._pair()
        wire = tx.protect(_rtp_packet(5))
        rx.unprotect(wire)
        with pytest.raises(ValueError, match="replay"):
            rx.unprotect(wire)
        # later packets still flow
        w2 = tx.protect(_rtp_packet(6))
        assert rx.unprotect(w2)

    def test_out_of_order_within_window_ok_once(self):
        tx, rx = self._pair()
        wires = [tx.protect(_rtp_packet(s)) for s in (10, 11, 12)]
        rx.unprotect(wires[0])
        rx.unprotect(wires[2])
        assert rx.unprotect(wires[1])  # late but fresh: fine
        with pytest.raises(ValueError, match="replay"):
            rx.unprotect(wires[1])  # replayed late packet: rejected

    def test_csrc_and_extension_headers_stay_clear(self):
        tx, rx = self._pair()
        # CC=1 (one CSRC), X=1 (4-byte extension with 1 word)
        hdr = struct.pack("!BBHII", 0x80 | 0x10 | 0x01, 96, 5, 99, 0x77)
        hdr += struct.pack("!I", 0xDEADBEEF)  # CSRC
        hdr += struct.pack("!HH", 0xBEDE, 1) + b"\x00" * 4  # extension
        pkt = hdr + b"payload-bytes"
        wire = tx.protect(pkt)
        assert wire[: len(hdr)] == hdr
        assert rx.unprotect(wire) == pkt


class TestSrtcp:
    def test_rtcp_roundtrip(self):
        key, salt = b"q" * 16, b"z" * 14
        tx, rx = srtp.SrtpContext(key, salt), srtp.SrtpContext(key, salt)
        # RTCP PLI-shaped packet: V=2 PT=206 FMT=1, sender+media ssrc
        pkt = struct.pack("!BBHII", 0x81, 206, 2, 0xAAA, 0xBBB)
        wire = tx.protect_rtcp(pkt)
        assert len(wire) == len(pkt) + 4 + srtp.AUTH_TAG_LEN
        assert wire[:8] == pkt[:8]
        assert rx.unprotect_rtcp(wire) == pkt

    def test_rtcp_tamper_detected(self):
        key, salt = b"q" * 16, b"z" * 14
        tx, rx = srtp.SrtpContext(key, salt), srtp.SrtpContext(key, salt)
        wire = bytearray(tx.protect_rtcp(struct.pack("!BBHII", 0x81, 206, 2, 1, 2)))
        wire[9] ^= 0x01
        with pytest.raises(ValueError, match="auth"):
            rx.unprotect_rtcp(bytes(wire))

    def test_rtcp_replay_rejected(self):
        """A replayed (captured) SRTCP PLI must not re-trigger keyframes."""
        key, salt = b"q" * 16, b"z" * 14
        tx, rx = srtp.SrtpContext(key, salt), srtp.SrtpContext(key, salt)
        wire = tx.protect_rtcp(struct.pack("!BBHII", 0x81, 206, 2, 1, 2))
        rx.unprotect_rtcp(wire)
        with pytest.raises(ValueError, match="replay"):
            rx.unprotect_rtcp(wire)

    def test_rtcp_index_increments(self):
        key, salt = b"q" * 16, b"z" * 14
        tx, rx = srtp.SrtpContext(key, salt), srtp.SrtpContext(key, salt)
        pkt = struct.pack("!BBHII", 0x81, 206, 2, 1, 2)
        w1, w2 = tx.protect_rtcp(pkt), tx.protect_rtcp(pkt)
        assert w1 != w2  # index (and so keystream) differs
        assert rx.unprotect_rtcp(w1) == pkt
        assert rx.unprotect_rtcp(w2) == pkt


def test_derive_srtp_contexts_roles_mirror():
    km = bytes(range(60))
    srv_tx, srv_rx = srtp.derive_srtp_contexts(km, is_server=True)
    cli_tx, cli_rx = srtp.derive_srtp_contexts(km, is_server=False)
    pkt = _rtp_packet(3)
    # server-sent packet decrypts with the client's rx context
    assert cli_rx.unprotect(srv_tx.protect(pkt)) == pkt
    assert srv_rx.unprotect(cli_tx.protect(pkt)) == pkt
    with pytest.raises(ValueError):
        srtp.derive_srtp_contexts(km[:30], is_server=True)


class TestAeadSrtp:
    """RFC 7714 AEAD AES-128-GCM profile (single-pass; Chrome's preferred
    family).  KDF caveat documented in srtp.py/docs/security.md."""

    def _pair(self):
        from ai_rtc_agent_tpu.server.secure.srtp import AeadSrtpContext

        key, salt = b"K" * 16, b"S" * 12
        return AeadSrtpContext(key, salt), AeadSrtpContext(key, salt)

    def test_roundtrip_and_header_in_clear(self):
        tx, rx = self._pair()
        pkt = _rtp_packet(9)
        wire = tx.protect(pkt)
        assert wire[:12] == pkt[:12]
        assert len(wire) == len(pkt) + 16  # GCM tag
        assert rx.unprotect(wire) == pkt

    def test_tamper_header_detected(self):
        """AEAD covers the HEADER too (AAD) — a flipped header bit fails,
        which plain CM+HMAC also catches but via the separate tag."""
        tx, rx = self._pair()
        wire = bytearray(tx.protect(_rtp_packet(9)))
        wire[4] ^= 0x01  # timestamp bit
        with pytest.raises(ValueError, match="auth"):
            rx.unprotect(bytes(wire))

    def test_tamper_payload_detected(self):
        tx, rx = self._pair()
        wire = bytearray(tx.protect(_rtp_packet(9)))
        wire[-1] ^= 0x01
        with pytest.raises(ValueError, match="auth"):
            rx.unprotect(bytes(wire))

    def test_replay_rejected(self):
        tx, rx = self._pair()
        wire = tx.protect(_rtp_packet(3))
        rx.unprotect(wire)
        with pytest.raises(ValueError, match="replay"):
            rx.unprotect(wire)

    def test_rollover_and_distinct_ssrc(self):
        tx, rx = self._pair()
        for seq in (65534, 65535, 0, 1):
            pkt = _rtp_packet(seq)
            assert rx.unprotect(tx.protect(pkt)) == pkt
        for ssrc in (0x1, 0x2):
            pkt = _rtp_packet(50, ssrc=ssrc)
            assert rx.unprotect(tx.protect(pkt)) == pkt

    def test_rtcp_roundtrip_and_replay(self):
        tx, rx = self._pair()
        pkt = struct.pack("!BBHII", 0x81, 206, 2, 0xAAA, 0xBBB)
        wire = tx.protect_rtcp(pkt)
        assert wire[:8] == pkt[:8]
        assert rx.unprotect_rtcp(wire) == pkt
        with pytest.raises(ValueError, match="replay"):
            rx.unprotect_rtcp(wire)

    def test_keying_lengths(self):
        from ai_rtc_agent_tpu.server.secure.srtp import (
            PROFILE_AEAD_AES_128_GCM,
            PROFILE_AES128_CM_SHA1_80,
            keying_material_length,
        )

        assert keying_material_length(PROFILE_AES128_CM_SHA1_80) == 60
        assert keying_material_length(PROFILE_AEAD_AES_128_GCM) == 56
