"""Fault injection (resilience/faults.py): deterministic seeded plans,
window gating, media-path hooks, engine-path hooks, and the zero-cost-
when-disabled guarantee.  No wall-clock sleeps — injected sleep fns."""

import asyncio
import json

import numpy as np
import pytest

from ai_rtc_agent_tpu.resilience import faults
from ai_rtc_agent_tpu.resilience.faults import (
    DeviceLostError,
    FaultPlan,
    FaultSpec,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.deactivate()
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# plan parsing + determinism
# ---------------------------------------------------------------------------

def test_plan_json_roundtrip_and_validation():
    plan = FaultPlan.from_json(
        json.dumps(
            {
                "seed": 11,
                "faults": [
                    {"target": "rx", "kind": "drop", "p": 0.5, "start": 2, "stop": 6},
                    {"target": "engine", "kind": "nan", "start": 1, "stop": 2},
                ],
            }
        )
    )
    assert plan.seed == 11
    assert len(plan.specs) == 2
    assert plan.for_target("rx")[0].kind == "drop"
    with pytest.raises(ValueError):
        FaultSpec(target="rx", kind="nan")  # engine kind on a net target
    with pytest.raises(ValueError):
        FaultSpec(target="bogus", kind="drop")
    with pytest.raises(ValueError):
        FaultSpec(target="rx", kind="drop", p=1.5)


def test_seeded_plan_replays_identically():
    plan = FaultPlan(
        specs=(FaultSpec(target="rx", kind="drop", p=0.3),), seed=42
    )

    def run():
        faults.activate(plan)
        s = faults.scope("rx")
        return [len(s.apply(bytes([i % 256]) * 16)) for i in range(200)]

    assert run() == run()


def test_window_gating_exact():
    plan = FaultPlan(
        specs=(FaultSpec(target="rx", kind="drop", p=1.0, start=3, stop=6),),
        seed=0,
    )
    faults.activate(plan)
    s = faults.scope("rx")
    kept = [len(s.apply(b"p" * 16)) for i in range(10)]
    # packets 3,4,5 dropped, everything else passes
    assert kept == [1, 1, 1, 0, 0, 0, 1, 1, 1, 1]
    assert s.stats["drop"] == 3


def test_loss_burst_duty_cycle_exact():
    """ISSUE 6 satellite: a deterministic on/off duty cycle over an index
    window — the first `burst` of every `period` packets drop, everything
    outside the window passes, and the same plan replays packet-for-packet
    (no per-packet probability to tune)."""
    plan = FaultPlan(
        specs=(
            FaultSpec(
                target="rx", kind="loss_burst",
                period=5, burst=2, start=3, stop=13,
            ),
        ),
        seed=9,
    )

    def run():
        faults.activate(plan)
        s = faults.scope("rx")
        return [len(s.apply(b"p" * 16)) for _ in range(20)]

    kept = run()
    # window [3,13): cycles start at 3 — drop 3,4 / pass 5,6,7 / drop 8,9 /
    # pass 10,11,12; outside the window everything passes
    expect = [1] * 3 + [0, 0, 1, 1, 1, 0, 0, 1, 1, 1] + [1] * 7
    assert kept == expect
    assert kept == run()  # reactivation replays identically

    faults.activate(plan)
    s = faults.scope("rx")
    for _ in range(20):
        s.apply(b"p" * 16)
    assert s.stats["loss_burst"] == 4


def test_loss_burst_sustained_loss_fraction():
    """period/burst express a target loss rate directly: burst=5 of
    period=10 over a long window loses exactly half the packets."""
    plan = FaultPlan(
        specs=(FaultSpec(target="rx", kind="loss_burst", period=10, burst=5),),
        seed=1,
    )
    faults.activate(plan)
    s = faults.scope("rx")
    kept = sum(len(s.apply(b"x" * 16)) for _ in range(400))
    assert kept == 200


def test_loss_burst_validation():
    with pytest.raises(ValueError):
        FaultSpec(target="rx", kind="loss_burst", period=0)
    with pytest.raises(ValueError):
        FaultSpec(target="rx", kind="loss_burst", period=4, burst=5)
    # JSON plan spelling parses
    plan = FaultPlan.from_json(
        '{"seed": 3, "faults": [{"target": "rx", "kind": "loss_burst", '
        '"period": 20, "burst": 10, "start": 100, "stop": 500}]}'
    )
    (spec,) = plan.specs
    assert spec.period == 20 and spec.burst == 10


def test_dup_delay_truncate_reorder_transforms():
    faults.activate(
        FaultPlan(specs=(FaultSpec(target="rx", kind="dup", p=1.0),), seed=0)
    )
    s = faults.scope("rx")
    out = s.apply(b"abc")
    assert [d for d, _ in out] == [b"abc", b"abc"]

    faults.activate(
        FaultPlan(
            specs=(FaultSpec(target="rx", kind="delay", p=1.0, delay_s=0.2),),
            seed=0,
        )
    )
    s = faults.scope("rx")
    ((d, delay),) = s.apply(b"abc")
    assert d == b"abc" and delay == pytest.approx(0.2)

    faults.activate(
        FaultPlan(
            specs=(FaultSpec(target="rx", kind="truncate", p=1.0, keep=2),),
            seed=0,
        )
    )
    s = faults.scope("rx")
    assert s.apply(b"abcdef")[0][0] == b"ab"

    # reorder: pkt0 held, released after pkt1 — order on the wire is 1, 0
    faults.activate(
        FaultPlan(
            specs=(FaultSpec(target="rx", kind="reorder", p=1.0, stop=1),),
            seed=0,
        )
    )
    s = faults.scope("rx")
    assert s.apply(b"first") == []
    out = s.apply(b"second")
    assert [d for d, _ in out] == [b"second", b"first"]


# ---------------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------------

def test_disabled_injection_is_free():
    """No active plan -> scope() is None, so hook sites carry exactly one
    is-None test and never touch fault code."""
    assert faults.active() is None
    assert faults.scope("rx") is None
    assert faults.scope("tx") is None
    assert faults.scope("engine") is None
    # a plan with only engine faults keeps the media hooks free too
    faults.activate(
        FaultPlan(specs=(FaultSpec(target="engine", kind="nan"),), seed=0)
    )
    assert faults.scope("rx") is None
    assert faults.scope("engine") is not None


def test_rtp_receiver_hook_absent_when_disabled():
    from ai_rtc_agent_tpu.server.rtc_native import (
        _RtcpState,
        _RtpReceiverProtocol,
    )

    class FakeSource:
        def __init__(self):
            self.fed = []

        def depacketize(self, pkt):
            self.fed.append(pkt)
            return []

        def on(self, *a, **k):
            pass

    async def go():
        proto = _RtpReceiverProtocol(FakeSource(), _RtcpState())
        assert proto._rx_faults is None  # zero-cost path
        proto.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# media-path hook (server receive socket)
# ---------------------------------------------------------------------------

def _rtp_packet(seq: int, ssrc: int = 0xABC, pt: int = 96) -> bytes:
    return bytes(
        [0x80, pt, (seq >> 8) & 0xFF, seq & 0xFF]
    ) + (0).to_bytes(4, "big") + ssrc.to_bytes(4, "big") + b"payload"


def test_rtp_receiver_drop_burst_is_deterministic():
    from ai_rtc_agent_tpu.server.rtc_native import (
        _RtcpState,
        _RtpReceiverProtocol,
    )

    class FakeSource:
        def __init__(self):
            self.fed = []

        def depacketize(self, pkt):
            self.fed.append(pkt)
            return []

        def on(self, *a, **k):
            pass

    faults.activate(
        FaultPlan(
            specs=(FaultSpec(target="rx", kind="drop", p=1.0, start=5, stop=10),),
            seed=9,
        )
    )

    async def go():
        src = FakeSource()
        proto = _RtpReceiverProtocol(src, _RtcpState())
        for i in range(20):
            proto.datagram_received(_rtp_packet(i), ("127.0.0.1", 1))
        proto.close()
        return src.fed

    fed = asyncio.run(go())
    assert len(fed) == 15  # 5 packets of the burst never reached the stack
    seqs = [(p[2] << 8) | p[3] for p in fed]
    assert seqs == [0, 1, 2, 3, 4, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19]


# ---------------------------------------------------------------------------
# engine-path hook
# ---------------------------------------------------------------------------

def _tiny_engine():
    from ai_rtc_agent_tpu.models import registry
    from ai_rtc_agent_tpu.stream.engine import StreamEngine

    bundle = registry.load_model_bundle("tiny-test")
    cfg = registry.default_stream_config("tiny-test")
    eng = StreamEngine(
        models=bundle.stream_models,
        params=bundle.params,
        cfg=cfg,
        encode_prompt=bundle.encode_prompt,
    )
    eng.prepare("chaos", seed=0)
    return eng


@pytest.fixture(scope="module")
def tiny_engine():
    """ONE compiled engine for the whole file (PR 6 tier-1 wall-time
    shave: three builds -> one).  Built with no plan active, so the ctor
    binds no fault scope — test_engine_without_plan_has_no_scope (first
    consumer, and the autouse fixture above guarantees no plan leaks in)
    pins the ctor-binding contract; the fault tests then rebind the scope
    exactly as a construction under an active plan would, and restore."""
    return _tiny_engine()


def test_engine_without_plan_has_no_scope(tiny_engine):
    assert tiny_engine._fault_scope is None
    out = tiny_engine(np.zeros((64, 64, 3), np.uint8))
    assert out.dtype == np.uint8


@pytest.fixture
def _engine_scope(tiny_engine):
    """Bind the active plan's engine scope onto the shared engine (what
    the ctor does when a plan is live at construction), restore after."""

    def bind():
        tiny_engine._fault_scope = faults.scope("engine")
        return tiny_engine

    yield bind
    tiny_engine._fault_scope = None


def test_engine_nan_fault_yields_non_finite_output(_engine_scope):
    faults.activate(
        FaultPlan(
            specs=(FaultSpec(target="engine", kind="nan", start=1, stop=2),),
            seed=0,
        )
    )
    eng = _engine_scope()
    frame = np.zeros((64, 64, 3), np.uint8)
    out0 = eng(frame)
    assert out0.dtype == np.uint8  # step 0 clean
    out1 = eng(frame)
    assert out1.dtype.kind == "f" and not np.isfinite(out1).all()
    out2 = eng(frame)
    assert out2.dtype == np.uint8  # window closed


def test_engine_device_lost_fault_raises(_engine_scope):
    faults.activate(
        FaultPlan(
            specs=(FaultSpec(target="engine", kind="device_lost", start=0),),
            seed=0,
        )
    )
    eng = _engine_scope()
    with pytest.raises(DeviceLostError):
        eng(np.zeros((64, 64, 3), np.uint8))


def test_engine_slow_step_uses_injected_sleep():
    from ai_rtc_agent_tpu.resilience.faults import EngineFaultScope

    slept = []
    scope = EngineFaultScope(
        (FaultSpec(target="engine", kind="slow_step", delay_s=2.5),),
        __import__("random").Random(0),
        sleep=slept.append,
    )
    assert scope.step() == "slow_step"
    assert slept == [2.5]


