"""Golden-output validation with real weights (VERDICT r2 missing #5).

Skipped unless BOTH the committed golden file and the model's real local
weights exist (zero-egress CI boxes have neither).  On a weights-bearing
host this replays the deterministic capture procedure and compares
fingerprints — the operational validation the reference relies on
(reference docs/connect.md:3-5), made reproducible.

The fingerprint/compare machinery itself is unit-tested hermetically below
so the skip never hides a broken comparator.
"""

import glob
import os

import numpy as np
import pytest

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.utils import golden

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def _goldens():
    return sorted(glob.glob(os.path.join(GOLDEN_DIR, "*.json")))


@pytest.mark.parametrize(
    "path", _goldens() or [pytest.param(None, marks=pytest.mark.skip(
        reason="no committed goldens yet (scripts/golden_capture.py on a "
        "weights-bearing host)"))],
)
def test_golden_output_matches(path):
    import json

    with open(path) as f:
        gold = json.load(f)
    model_id = gold["model_id"]
    snap = registry.resolve_snapshot_dir(model_id)
    hermetic = registry.family_of(model_id) in ("tiny", "tinyxl")
    if snap is None and not hermetic:
        pytest.skip(f"no local weights for {model_id}")
    got = golden.capture(model_id)  # raises if weights turn out unloadable
    problems = golden.compare(gold, got)
    assert not problems, "; ".join(problems)


# -- hermetic comparator checks (always run) --------------------------------

def test_fingerprint_detects_noise_output():
    """A random-noise frame must NOT match a structured golden — this is
    exactly the failure mode (key-map/scale bug -> noise) being guarded."""
    structured = golden.golden_input(64, 64)
    noise = np.random.default_rng(0).integers(0, 256, (64, 64, 3), np.uint8)
    gold = {"fingerprint": golden.fingerprint(structured)}
    assert golden.compare(gold, {"fingerprint": golden.fingerprint(noise)})


def test_fingerprint_tolerates_small_drift():
    """bf16-level drift (±2 uint8 levels of noise) passes."""
    base = golden.golden_input(64, 64).astype(np.int16)
    drift = base + np.random.default_rng(1).integers(-2, 3, base.shape)
    gold = {"fingerprint": golden.fingerprint(base.astype(np.uint8))}
    got = {"fingerprint": golden.fingerprint(np.clip(drift, 0, 255).astype(np.uint8))}
    assert golden.compare(gold, got) == []


def test_golden_input_deterministic():
    np.testing.assert_array_equal(golden.golden_input(32, 32), golden.golden_input(32, 32))
