"""scripts/tpu_smoke.py contract guarantees.

The TPU watcher (scripts/tpu_watch.sh) parses exactly ONE JSON line from the
smoke script and banks it into PERF_LOG.jsonl only when it proves real TPU
contact (backend=="tpu" and ok==true).  These tests pin the contract on the
paths runnable without hardware: the CPU backend must still emit the line,
report ok:false, and exit non-zero so the watcher's attempt cap engages.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "tpu_smoke.py")


def _run_smoke(extra_env: dict, timeout=300):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # hermetic: no axon site hook
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, SCRIPT],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


def _contract_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, f"expected exactly one JSON line, got: {stdout!r}"
    return json.loads(lines[0])


def test_cpu_backend_emits_line_not_ok():
    """On a CPU backend the script must measure (proving the timing path
    runs anywhere) but report ok:false with rc!=0 — the watcher must never
    bank a non-TPU smoke result."""
    r = _run_smoke({"JAX_PLATFORMS": "cpu"})
    assert r.returncode != 0
    d = _contract_line(r.stdout)
    assert d["ok"] is False
    assert d["backend"] == "cpu"
    # the measurement itself ran: dispatch/matmul numbers are present
    assert d["dispatch_ms"] > 0 and d["matmul_ms"] > 0


def test_init_error_emits_line():
    """A backend that cannot initialize at all still produces the contract
    line (with error detail) instead of a bare traceback."""
    r = _run_smoke({"JAX_PLATFORMS": "bogus-platform"})
    assert r.returncode != 0
    d = _contract_line(r.stdout)
    assert d["ok"] is False
    assert "error" in d


def test_watcher_filter_accepts_only_tpu_ok():
    """Pin the EXACT acceptance predicate run_item pipes through
    (scripts/watch_filter.py — the watcher invokes the same file, so there
    is no transcription to drift): banked iff backend=='tpu' and ok==true,
    or value>0 with live:true; a replayed live:false line is never banked,
    and the watcher's invocation contract is exit-code based."""
    filt = os.path.join(REPO, "scripts", "watch_filter.py")
    # tpu_watch.sh must actually invoke this file, not an inline copy
    with open(os.path.join(REPO, "scripts", "tpu_watch.sh")) as f:
        assert "watch_filter.py" in f.read()

    def accept(d):
        r = subprocess.run(
            [sys.executable, filt], input=json.dumps(d),
            capture_output=True, text=True, timeout=30,
        )
        return r.returncode == 0

    assert accept({"backend": "tpu", "ok": True})
    assert accept({"backend": "tpu", "value": 18.0, "live": True})
    assert not accept({"backend": "cpu", "ok": True})
    assert not accept({"backend": "tpu", "ok": False})
    assert not accept({"backend": "tpu", "value": 18.0, "live": False})
    assert not accept({"backend": "tpu", "value": 0.0, "live": True})
    assert not accept({"backend": "tpu"})  # malformed/empty-ish line


def test_watcher_cpu_fallback_classifier():
    """--cpu-fallback mode: flap (cpu line) vs real wedge (tpu line,
    empty, or garbage) — drives the cache-forfeit and smoke-try-cap
    decisions in tpu_watch.sh."""
    filt = os.path.join(REPO, "scripts", "watch_filter.py")
    with open(os.path.join(REPO, "scripts", "tpu_watch.sh")) as f:
        assert "watch_filter.py --cpu-fallback" in f.read()

    def is_flap(text):
        r = subprocess.run(
            [sys.executable, filt, "--cpu-fallback"], input=text,
            capture_output=True, text=True, timeout=30,
        )
        return r.returncode == 0

    assert is_flap(json.dumps({"backend": "cpu", "ok": False}))
    assert not is_flap(json.dumps({"backend": "tpu", "ok": False}))
    assert not is_flap("")          # timeout/KILL: no line
    assert not is_flap('{"backe')   # partial line
