"""Round-trip tests: our pytree -> diffusers-style state dict -> fresh pytree.

Real HF weights are unavailable hermetically, so these tests prove the name
mapping + layout conversion machinery is self-consistent: exporting a tiny
model's params under diffusers names and re-importing into a fresh init must
reproduce the exact forward output.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ai_rtc_agent_tpu.models import clip as C
from ai_rtc_agent_tpu.models import loader as LD
from ai_rtc_agent_tpu.models import lora as LR
from ai_rtc_agent_tpu.models import taesd as T
from ai_rtc_agent_tpu.models import unet as U


def test_safetensors_round_trip(tmp_path, rng):
    tensors = {
        "a": rng.standard_normal((3, 4)).astype(np.float32),
        "b": rng.integers(0, 10, (2,)).astype(np.int32),
    }
    p = os.path.join(tmp_path, "t.safetensors")
    LD.write_safetensors(p, tensors)
    back = LD.read_safetensors(p)
    assert set(back) == {"a", "b"}
    np.testing.assert_array_equal(back["a"], tensors["a"])
    np.testing.assert_array_equal(back["b"], tensors["b"])

    # interop check with the reference safetensors package if available
    try:
        from safetensors.numpy import load_file

        theirs = load_file(p)
        np.testing.assert_array_equal(theirs["a"], tensors["a"])
    except ImportError:
        pass


def test_unet_state_dict_round_trip(rng):
    cfg = U.UNetConfig.tiny()
    p1 = U.init_unet(jax.random.PRNGKey(0), cfg)
    p2 = U.init_unet(jax.random.PRNGKey(99), cfg)  # different weights
    km = LD.unet_key_map(cfg)
    sd = LD.tree_to_state_dict(p1, km)
    assert any(k.startswith("down_blocks.0.attentions") for k in sd)
    p2_loaded, n = LD.load_into_tree(p2, sd, km)
    assert n == len(sd)

    x = jnp.asarray(rng.standard_normal((1, 8, 8, 4)).astype(np.float32))
    ctx = jnp.asarray(rng.standard_normal((1, 7, 32)).astype(np.float32))
    t = jnp.array([123])
    o1 = np.asarray(U.apply_unet(p1, x, t, ctx, cfg))
    o2 = np.asarray(U.apply_unet(p2_loaded, x, t, ctx, cfg))
    np.testing.assert_allclose(o1, o2, atol=1e-6)


def test_taesd_state_dict_round_trip(rng):
    cfg = T.TAESDConfig.tiny()
    p1 = T.init_taesd(jax.random.PRNGKey(1), cfg)
    p2 = T.init_taesd(jax.random.PRNGKey(2), cfg)
    km = LD.taesd_key_map(cfg)
    sd = LD.tree_to_state_dict(p1, km)
    # encoder sequential indices must be dense from 0
    p2_loaded, _ = LD.load_into_tree(p2, sd, km)
    x = jnp.asarray(rng.random((1, 16, 16, 3)).astype(np.float32))
    o1 = np.asarray(T.encode(p1["encoder"], x, cfg))
    o2 = np.asarray(T.encode(p2_loaded["encoder"], x, cfg))
    np.testing.assert_allclose(o1, o2, atol=1e-6)


def test_clip_state_dict_round_trip(rng):
    cfg = C.CLIPTextConfig.tiny()
    p1 = C.init_clip_text(jax.random.PRNGKey(3), cfg)
    p2 = C.init_clip_text(jax.random.PRNGKey(4), cfg)
    km = LD.clip_key_map(cfg)
    sd = LD.tree_to_state_dict(p1, km)
    p2_loaded, _ = LD.load_into_tree(p2, sd, km)
    ids = jnp.asarray(np.ones((1, 8), np.int32) * 5)
    h1 = np.asarray(C.apply_clip_text(p1, ids, cfg)["hidden"])
    h2 = np.asarray(C.apply_clip_text(p2_loaded, ids, cfg)["hidden"])
    np.testing.assert_allclose(h1, h2, atol=1e-6)


def test_loader_missing_key_raises(rng):
    cfg = C.CLIPTextConfig.tiny()
    p = C.init_clip_text(jax.random.PRNGKey(5), cfg)
    km = LD.clip_key_map(cfg)
    sd = LD.tree_to_state_dict(p, km)
    sd.pop("text_model.final_layer_norm.weight")
    import pytest

    with pytest.raises(KeyError):
        LD.load_into_tree(p, sd, km, strict=True)


def test_lora_fuse_linear_changes_output(rng):
    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(6), cfg)
    km = LD.unet_key_map(cfg)

    r, din = 2, 8  # attn1 to_q of down block 0: ch=8
    down = rng.standard_normal((r, din)).astype(np.float32)
    up = rng.standard_normal((din, r)).astype(np.float32)
    sd = {
        "lora_unet_down_blocks_0_attentions_0_transformer_blocks_0_attn1_to_q.lora_down.weight": down,
        "lora_unet_down_blocks_0_attentions_0_transformer_blocks_0_attn1_to_q.lora_up.weight": up,
        "lora_unet_down_blocks_0_attentions_0_transformer_blocks_0_attn1_to_q.alpha": np.array(
            r, np.float32
        ),
    }
    groups = LR.parse_lora_state_dict(sd)
    assert len(groups) == 1
    fused, applied, unmatched = LR.fuse_lora_into_unet(params, groups, km, scale=1.0)
    assert applied == 1 and unmatched == []

    old = np.asarray(
        params["down_blocks"][0]["attentions"][0]["blocks"][0]["attn1"]["to_q"]["kernel"]
    )
    new = np.asarray(
        fused["down_blocks"][0]["attentions"][0]["blocks"][0]["attn1"]["to_q"]["kernel"]
    )
    want = old + down.T @ up.T  # alpha/r = 1
    np.testing.assert_allclose(new, want, rtol=1e-5, atol=1e-6)

    # untouched leaf shares identity (shallow copy semantics preserved)
    x = jnp.asarray(rng.standard_normal((1, 8, 8, 4)).astype(np.float32))
    ctx = jnp.asarray(rng.standard_normal((1, 7, 32)).astype(np.float32))
    o1 = np.asarray(U.apply_unet(params, x, jnp.array([100]), ctx, cfg))
    o2 = np.asarray(U.apply_unet(fused, x, jnp.array([100]), ctx, cfg))
    assert not np.allclose(o1, o2)


def test_lora_fuse_miskeyed_state_dict_is_loud(rng, caplog):
    """ISSUE 20 satellite: unmatched LoRA paths must be RETURNED and warned,
    and a fully-miskeyed adapter (applied == 0) must be a hard error at the
    registry call site — not a silent no-op style."""
    import logging

    cfg = U.UNetConfig.tiny()
    params = U.init_unet(jax.random.PRNGKey(7), cfg)
    km = LD.unet_key_map(cfg)

    r, din = 2, 8
    down = rng.standard_normal((r, din)).astype(np.float32)
    up = rng.standard_normal((din, r)).astype(np.float32)
    # deliberately miskeyed: a module path that exists in no SD UNet
    sd = {
        "lora_unet_mid_block_bogus_module_to_q.lora_down.weight": down,
        "lora_unet_mid_block_bogus_module_to_q.lora_up.weight": up,
    }
    groups = LR.parse_lora_state_dict(sd)
    assert len(groups) == 1
    with caplog.at_level(logging.WARNING, logger="ai_rtc_agent_tpu.models.lora"):
        fused, applied, unmatched = LR.fuse_lora_into_unet(params, groups, km)
    assert applied == 0
    assert unmatched == list(groups)
    assert any("DROPPED" in rec.message for rec in caplog.records)
    # untouched tree: the shallow-copy result still shares every leaf
    assert fused["mid_block"] is params["mid_block"]

    # registry call site refuses an all-miss fuse
    from ai_rtc_agent_tpu.models import registry as REG

    lora_path = None
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        lora_path = os.path.join(td, "bogus_style.safetensors")
        LD.write_safetensors(lora_path, sd)
        import pytest

        with pytest.raises(ValueError, match="matched 0 of"):
            REG.load_model_bundle("tiny-test", lora_dict={lora_path: 1.0})


def test_real_weights_with_missing_vocab_is_hard_error(tmp_path, monkeypatch):
    """VERDICT r3 weak #6: real weights + no tokenizer files must refuse to
    serve (hash token ids over a real embedding table are garbage-in) —
    reference analog fails loudly too (lib/wrapper.py:468-473)."""
    import json as _json

    from ai_rtc_agent_tpu.models import registry

    # tiny geometry everywhere, but a REAL (non-tiny) family so the
    # tokenizer guard applies; weight loading itself is faked as successful
    monkeypatch.setattr(registry, "family_of", lambda mid: "sd15")
    orig_configs = registry._model_configs
    monkeypatch.setattr(
        registry, "_model_configs", lambda fam: orig_configs("tiny")
    )
    monkeypatch.setattr(
        registry, "resolve_snapshot_dir", lambda mid: str(tmp_path)
    )
    monkeypatch.setattr(
        registry, "_try_load_weights", lambda *a, **k: True
    )
    with pytest.raises(FileNotFoundError, match="HashTokenizer"):
        registry.load_model_bundle("fake/real-model")

    # with vocab files present the same bundle builds fine
    tok_dir = tmp_path / "tokenizer"
    tok_dir.mkdir()
    vocab = {"<|startoftext|>": 0, "<|endoftext|>": 1, "a</w>": 2, "cat</w>": 3}
    (tok_dir / "vocab.json").write_text(_json.dumps(vocab))
    (tok_dir / "merges.txt").write_text("#version: 0.2\nc at</w>\n")
    bundle = registry.load_model_bundle("fake/real-model")
    assert bundle.loaded_real_weights
    # the prompt path works end-to-end with the real BPE files
    cond, uncond, _ = (lambda r: r if len(r) == 3 else (*r, {}))(
        bundle.encode_prompt("a cat")
    )
    assert np.isfinite(np.asarray(cond)).all()
