"""A faithful-semantics stand-in for the ``cryptography`` package.

The image this repo grows on does not always ship ``cryptography`` (the
secure tier degrades and its tests skip).  That would leave the batched
SRTP path (srtp.protect_frame — ISSUE 2) completely unexercised on such
boxes, so this fake implements the exact *mode semantics* the batch
logic depends on while replacing the block function with a keyed hash:

* CTR keystream block j == ECB(counter_block_0 + j) with 128-bit
  big-endian increment — the identity protect_frame's precomputed
  counter blocks rely on.  If the batch layout/IV math is wrong, batch
  vs per-packet outputs diverge under this fake exactly as they would
  under OpenSSL.
* AESGCM: deterministic stream + hash tag over (key, iv, aad, ct).

This is NOT cryptography and must never ship outside tests: install()
only ever runs when the real package is absent, and uninstall() removes
every injected module again so later tests see the true environment.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import sys
import types

_MASK128 = (1 << 128) - 1


def _ecb_block(key: bytes, block: bytes) -> bytes:
    return hashlib.sha256(b"ECB" + key + block).digest()[:16]


class _Encryptor:
    def __init__(self, key: bytes, mode):
        self._key = key
        self._mode = mode
        self._ctr = (
            int.from_bytes(mode.iv, "big") if mode.kind == "ctr" else None
        )

    def update(self, data) -> bytes:
        data = bytes(data)
        if self._mode.kind == "ecb":
            assert len(data) % 16 == 0, "ECB input must be block-aligned"
            return b"".join(
                _ecb_block(self._key, data[i : i + 16])
                for i in range(0, len(data), 16)
            )
        n = (len(data) + 15) // 16
        c = self._ctr
        ks = b"".join(
            _ecb_block(self._key, ((c + j) & _MASK128).to_bytes(16, "big"))
            for j in range(n)
        )
        self._ctr = c + n
        return bytes(bytearray(a ^ b for a, b in zip(data, ks)))

    def finalize(self) -> bytes:
        return b""


class Cipher:
    def __init__(self, algorithm, mode):
        self._algorithm = algorithm
        self._mode = mode

    def encryptor(self):
        return _Encryptor(self._algorithm.key, self._mode)

    decryptor = encryptor  # CTR/ECB are symmetric here


class AES:
    def __init__(self, key):
        self.key = bytes(key)


class ECB:
    kind = "ecb"


class CTR:
    kind = "ctr"

    def __init__(self, iv):
        self.iv = bytes(iv)


class AESGCM:
    def __init__(self, key):
        self._key = bytes(key)

    def _keystream(self, iv: bytes, n: int) -> bytes:
        base = int.from_bytes(iv + b"\x00\x00\x00\x02", "big")
        return b"".join(
            _ecb_block(self._key, ((base + j) & _MASK128).to_bytes(16, "big"))
            for j in range(n)
        )

    def _tag(self, iv: bytes, aad: bytes, ct: bytes) -> bytes:
        return hashlib.sha256(
            b"GCM" + self._key + iv + (aad or b"") + ct
        ).digest()[:16]

    def encrypt(self, iv, data, aad):
        data = bytes(data)
        ks = self._keystream(bytes(iv), (len(data) + 15) // 16)
        ct = bytes(a ^ b for a, b in zip(data, ks))
        return ct + self._tag(bytes(iv), bytes(aad or b""), ct)

    def decrypt(self, iv, data, aad):
        data = bytes(data)
        ct, tag = data[:-16], data[-16:]
        if self._tag(bytes(iv), bytes(aad or b""), ct) != tag:
            raise ValueError("fake-GCM tag mismatch")
        ks = self._keystream(bytes(iv), (len(ct) + 15) // 16)
        return bytes(a ^ b for a, b in zip(ct, ks))


_INJECTED: list[str] = []


def install() -> None:
    """Register the fake under the ``cryptography`` names.  Refuses to
    shadow a real installation."""
    if importlib.util.find_spec("cryptography") is not None:
        raise RuntimeError("real cryptography present; refusing to shadow")
    mods = {
        "cryptography": types.ModuleType("cryptography"),
        "cryptography.hazmat": types.ModuleType("cryptography.hazmat"),
        "cryptography.hazmat.primitives": types.ModuleType(
            "cryptography.hazmat.primitives"
        ),
        "cryptography.hazmat.primitives.ciphers": types.ModuleType(
            "cryptography.hazmat.primitives.ciphers"
        ),
        "cryptography.hazmat.primitives.ciphers.aead": types.ModuleType(
            "cryptography.hazmat.primitives.ciphers.aead"
        ),
    }
    algorithms = types.SimpleNamespace(AES=AES)
    modes = types.SimpleNamespace(ECB=ECB, CTR=CTR)
    ciphers = mods["cryptography.hazmat.primitives.ciphers"]
    ciphers.Cipher = Cipher
    ciphers.algorithms = algorithms
    ciphers.modes = modes
    mods["cryptography.hazmat.primitives.ciphers.aead"].AESGCM = AESGCM
    for name, mod in mods.items():
        sys.modules[name] = mod
        _INJECTED.append(name)


def uninstall() -> None:
    """Remove every injected module so later imports see the truth."""
    while _INJECTED:
        sys.modules.pop(_INJECTED.pop(), None)


def load_srtp():
    """Import server/secure/srtp.py as a PRIVATE module instance bound to
    whatever ``cryptography`` is currently importable (the fake, inside
    an install()/uninstall() window).  The real package-level module is
    never touched, so nothing leaks into other tests."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ai_rtc_agent_tpu", "server", "secure", "srtp.py",
    )
    spec = importlib.util.spec_from_file_location("_srtp_under_fake_crypto", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
