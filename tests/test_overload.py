"""Overload control plane units (resilience/overload.py): bounded deadline
queues, admission, lag watchdog, shedding ladder, O(sessions) snapshots —
all on injected clocks, no wall-time sleeps."""

import asyncio
import json
import threading

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.resilience.overload import (
    RUNG_FROZEN,
    RUNG_PASSTHROUGH,
    AdmissionController,
    DeadlineQueue,
    OverloadControlPlane,
    OverloadLadder,
)
from ai_rtc_agent_tpu.resilience.supervisor import (
    DEGRADED,
    HEALTHY,
    RECOVERING,
    ResilientPipeline,
    SessionSupervisor,
)


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# DeadlineQueue
# ---------------------------------------------------------------------------

def test_deadline_queue_sheds_oldest_on_overflow():
    clock = Clock()
    sheds = []
    q = DeadlineQueue(bound=3, clock=clock, on_shed=lambda r, n: sheds.append((r, n)))
    for i in range(5):
        q.push(i)
    assert q.depth == 3
    assert q.shed_overflow == 2
    assert sheds == [("overflow", 1), ("overflow", 1)]
    # freshest-frame-wins: the two OLDEST entries (0, 1) were shed
    assert [q.pop()[0] for _ in range(3)] == [2, 3, 4]
    assert q.pop() is None


def test_deadline_queue_pop_sheds_stale_entries():
    clock = Clock()
    q = DeadlineQueue(bound=8, deadline_s=0.5, clock=clock)
    q.push("old")
    clock.tick(0.6)  # "old" is now past its deadline
    q.push("fresh")
    item, stamp = q.pop()
    assert item == "fresh"
    assert q.shed_stale == 1
    assert q.shed_overflow == 0


def test_deadline_queue_all_stale_returns_none():
    clock = Clock()
    q = DeadlineQueue(bound=4, deadline_s=0.1, clock=clock)
    q.push("a")
    q.push("b")
    clock.tick(1.0)
    assert q.pop() is None
    assert q.shed_stale == 2
    assert q.depth == 0


def test_deadline_queue_never_blocks_push():
    q = DeadlineQueue(bound=1)
    for i in range(100):
        q.push(i)  # returns immediately, sheds synchronously
    assert q.depth == 1
    assert q.shed_overflow == 99


# ---------------------------------------------------------------------------
# AdmissionController
# ---------------------------------------------------------------------------

def test_admission_pressure_is_max_of_signals():
    a = AdmissionController(step_budget_s=1.0, lag_budget_s=0.1)
    assert a.pressure() == 0.0
    a.note_step_latency(0.5)
    assert a.pressure() == pytest.approx(0.5)
    a.note_loop_lag(0.2)  # 2x the lag budget dominates
    assert a.pressure() == pytest.approx(2.0)


def test_admission_refuses_over_budget_with_retry_after():
    a = AdmissionController(step_budget_s=0.1, retry_after_s=2.0)
    ok, _ = a.admit()
    assert ok
    a.note_step_latency(0.4)  # 4x budget
    ok, retry_after = a.admit()
    assert not ok
    assert retry_after == pytest.approx(8.0)  # base * pressure, capped at 8x
    assert a.rejected == 1


def test_admission_retry_after_clamps():
    a = AdmissionController(step_budget_s=0.01, retry_after_s=2.0)
    a.note_step_latency(10.0)  # 1000x over budget
    assert a.retry_after_s() == pytest.approx(16.0)  # 8x cap


def test_admission_session_cap():
    a = AdmissionController(max_sessions=2)
    assert a.admit(live_sessions=1)[0]
    ok, retry_after = a.admit(live_sessions=2)
    assert not ok and retry_after > 0


def test_admission_freeze_holds_compose():
    a = AdmissionController()
    a.hold_freeze()
    a.hold_freeze()
    assert not a.admit()[0]
    a.release_freeze()
    assert a.frozen  # one hold still out
    a.release_freeze()
    assert a.admit()[0]
    a.release_freeze()  # over-release never goes negative
    assert not a.frozen


def test_admission_step_timeout_registers_as_severe():
    a = AdmissionController(step_budget_s=1.0)
    a.note_step_timeout(1.5)
    assert a.pressure() == pytest.approx(3.0)  # 2x the blown budget


def test_capacity_shapes():
    a = AdmissionController(max_sessions=4)
    assert a.capacity(live_sessions=1) == {
        "capacity": 3, "saturated": False, "retry_after_s": 0.0,
    }
    # the TIGHTEST structural bound wins: advertising engine slots beyond
    # the session-cap headroom would oversell (admit() 503s the excess)
    assert a.capacity(live_sessions=1, free_slots=7)["capacity"] == 3
    assert a.capacity(live_sessions=1, free_slots=2)["capacity"] == 2
    # at the structural cap: admit() refuses, so /capacity must say
    # saturated too (an orchestrator reading it never routes to a 503)
    cap = a.capacity(live_sessions=4)
    assert cap == {
        "capacity": 0, "saturated": True,
        "retry_after_s": a.retry_after_base_s,
    }
    a.note_loop_lag(1e9)
    cap = a.capacity(live_sessions=1, free_slots=7)
    assert cap["capacity"] == 0 and cap["saturated"]
    assert cap["retry_after_s"] > 0
    # unbounded box: -1, not a made-up number
    b = AdmissionController()
    assert b.capacity()["capacity"] == -1


def test_capacity_slot_exhaustion_is_saturated():
    """Review finding: a slot-exhausted multipeer box (free_slots=0) with
    pressure under budget and no session cap reported saturated=False —
    an orchestrator routing on the flag would send a session straight
    into /offer's 'all peer slots in use' 503."""
    a = AdmissionController()  # no cap, no pressure
    cap = a.capacity(live_sessions=4, free_slots=0)
    assert cap["capacity"] == 0
    assert cap["saturated"] is True
    assert cap["retry_after_s"] == a.retry_after_base_s
    # headroom left -> not saturated
    assert a.capacity(live_sessions=3, free_slots=1)["saturated"] is False


# ---------------------------------------------------------------------------
# OverloadLadder
# ---------------------------------------------------------------------------

def _ladder(sup=None, clock=None, **kw):
    a = AdmissionController(step_budget_s=1.0)
    kw.setdefault("up_after", 2)
    kw.setdefault("down_after", 3)
    return OverloadLadder("s", a, sup, clock=clock or Clock(), **kw), a


def test_ladder_escalates_with_hysteresis():
    ladder, _ = _ladder()
    ladder.tick(True)
    assert ladder.rung == 0  # one hot tick is not sustained pressure
    ladder.tick(True)
    assert ladder.rung == 1
    ladder.tick(True)
    ladder.tick(True)
    assert ladder.rung == 2
    # a single quiet tick resets the climb but does not descend
    ladder.tick(False)
    assert ladder.rung == 2
    ladder.tick(False)
    ladder.tick(False)
    assert ladder.rung == 1  # down_after=3 quiet ticks -> one rung down


def test_ladder_passthrough_rung_degrades_supervisor_once():
    clock = Clock()
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    ladder, _ = _ladder(sup=sup, clock=clock)
    for _ in range(2 * RUNG_PASSTHROUGH):
        ladder.tick(True)
    assert ladder.rung == RUNG_PASSTHROUGH
    assert sup.state == DEGRADED
    assert "overload" in sup.snapshot()["reason"]
    # no restart budget was spent — this is capacity, not a fault
    assert sup.snapshot()["restarts"] == 0


def test_ladder_top_rung_freezes_admission_and_close_releases():
    ladder, adm = _ladder()
    for _ in range(2 * RUNG_FROZEN):
        ladder.tick(True)
    assert ladder.rung == RUNG_FROZEN
    assert adm.frozen
    ladder.close()
    assert not adm.frozen


def test_ladder_unfreezes_on_deescalation():
    ladder, adm = _ladder()
    for _ in range(2 * RUNG_FROZEN):
        ladder.tick(True)
    assert adm.frozen
    for _ in range(3):
        ladder.tick(False)
    assert ladder.rung == RUNG_FROZEN - 1
    assert not adm.frozen


def test_ladder_skip_ratios_and_probe_rung():
    clock = Clock()
    ladder, _ = _ladder(clock=clock, probe_interval_s=1.0)
    ladder.rung = 1  # skip2: every 2nd frame processes
    admitted = sum(ladder.admit_frame() for _ in range(10))
    assert admitted == 5
    assert ladder.frames_skipped == 5
    ladder.rung = RUNG_PASSTHROUGH  # probe-only
    assert ladder.admit_frame()  # first probe fires immediately
    assert not ladder.admit_frame()  # inside the probe interval
    clock.tick(1.1)
    assert ladder.admit_frame()


def test_supervisor_recovers_from_overload_degrade_via_ok_steps():
    clock = Clock()
    sup = SessionSupervisor(
        "s", clock=clock, sleep=lambda s: None, healthy_after=2
    )
    sup.note_overload("overload shedding: passthrough")
    assert sup.state == DEGRADED
    # probe steps succeed while shedding continues: the hold keeps the
    # session DEGRADED — a fast probe proves nothing about capacity
    sup.on_step_ok(0.01)
    assert sup.state == DEGRADED
    # ladder de-escalates below passthrough: hold released, real steps
    # walk the session back through RECOVERING to HEALTHY
    sup.note_overload_clear()
    sup.on_step_ok(0.01)
    assert sup.state == RECOVERING  # frames flowing again
    sup.on_step_ok(0.01)
    sup.on_step_ok(0.01)
    assert sup.state == HEALTHY


def test_passthrough_probe_cadence_not_halved_by_supervisor_throttle():
    """Review finding: at the passthrough rung the ladder's probe token
    (one per OVERLOAD_PROBE_S) was consumed by the pipeline's _admit_frame
    and then discarded by the supervisor's own DEGRADED probe throttle
    (2s default) — every probe landing inside the supervisor's window was
    burned, halving the cadence to exactly the stale-decay threshold and
    starving the step EWMA the probes exist to feed.  While the overload
    hold is set, the ladder owns the probe cadence and the supervisor
    gate must admit."""
    clock = Clock(100.0)
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    ladder, adm = _ladder(sup=sup, clock=clock, up_after=1)
    while ladder.rung < RUNG_PASSTHROUGH:
        ladder.tick(True)
    assert sup.state == DEGRADED  # overload hold set by note_overload
    probes = 0
    for _ in range(10):
        clock.tick(ladder.probe_interval_s)
        if ladder.admit_frame() and sup.should_try_engine():
            probes += 1
    assert probes == 10  # every ladder probe reaches the engine
    # a REAL wedge during shedding: recovery owns the engine — the
    # pipeline-level gate refuses BEFORE the ladder token is consumed,
    # so the probe fires the moment recovery releases instead of
    # waiting out a fresh interval
    rp = ResilientPipeline(lambda f: f, sup, warm_steps=0)
    rp.throttle = ladder
    try:
        sup._recovery_pending = True
        clock.tick(ladder.probe_interval_s)
        token_at = ladder._next_probe
        assert rp("src") == "src"  # passthrough, engine untouched
        assert ladder._next_probe == token_at  # probe token preserved
        sup._recovery_pending = False
        assert rp._admit_frame() and sup.should_try_engine()
    finally:
        rp.close()


# ---------------------------------------------------------------------------
# ResilientPipeline x throttle
# ---------------------------------------------------------------------------

def test_resilient_pipeline_throttle_sheds_to_passthrough():
    clock = Clock()
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    calls = []
    rp = ResilientPipeline(
        lambda f: calls.append(f) or ("processed", f), sup, warm_steps=0
    )
    ladder, adm = _ladder(clock=clock)
    rp.throttle = ladder
    try:
        ladder.rung = 1  # skip2
        outs = [rp(i) for i in range(4)]
        assert len(calls) == 2  # half the frames ran the engine
        assert ("processed", 1) in outs and 0 in outs  # passthrough = source
        assert sup.passthrough_frames == 2
        # processed steps fed the admission EWMA
        assert adm.step_ewma.samples == 2
    finally:
        rp.close()


def test_resilient_pipeline_timeout_feeds_admission():
    clock = Clock()
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    wedge = threading.Event()
    rp = ResilientPipeline(
        lambda f: wedge.wait(5), sup, step_timeout_s=0.05,
        first_step_timeout_s=0.05, warm_steps=0,
    )
    ladder, adm = _ladder(clock=clock)
    rp.throttle = ladder
    try:
        out = rp("src")
        assert out == "src"  # passthrough, not a hang
        assert adm.step_ewma.value == pytest.approx(0.1)  # 2x the budget
    finally:
        wedge.set()
        rp.close()


def test_warm_up_steps_never_feed_admission():
    """Review finding: the first steps of a session carry the JAX compile
    (tens of seconds by design — first_step_timeout_s exists for them);
    feeding them to the admission EWMA pinned pressure far over budget on
    EVERY cold start, 503ing concurrent offers.  Only steady-state steps
    measure capacity — for both completed steps and blown ones."""
    clock = Clock()
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    rp = ResilientPipeline(lambda f: ("processed", f), sup, warm_steps=2)
    ladder, adm = _ladder(clock=clock)
    rp.throttle = ladder
    try:
        rp(0)
        rp(1)
        assert adm.step_ewma.samples == 0  # compile-sized, not capacity
        rp(2)
        assert adm.step_ewma.samples == 1  # steady state measures
    finally:
        rp.close()

    # a blown WARM-UP step is a fault (restart), not a capacity signal
    sup2 = SessionSupervisor("s2", clock=clock, sleep=lambda s: None)
    wedge = threading.Event()
    rp2 = ResilientPipeline(
        lambda f: wedge.wait(5), sup2, step_timeout_s=0.05,
        first_step_timeout_s=0.05, warm_steps=2,
    )
    ladder2, adm2 = _ladder(clock=clock)
    rp2.throttle = ladder2
    try:
        assert rp2("src") == "src"
        assert adm2.step_ewma.samples == 0
    finally:
        wedge.set()
        rp2.close()


# ---------------------------------------------------------------------------
# OverloadControlPlane: registry, tick, O(sessions) snapshot
# ---------------------------------------------------------------------------

def test_plane_tick_drives_all_ladders(monkeypatch):
    monkeypatch.setenv("OVERLOAD_UP_TICKS", "1")
    plane = OverloadControlPlane()
    a = plane.register_session("a")
    b = plane.register_session("b")
    plane.admission.note_step_latency(1e9)  # pressure >> 1
    plane.tick()
    assert a.rung == 1 and b.rung == 1
    plane.unregister_session("a")
    plane.tick()
    assert a.rung == 0  # closed ladders reset and stop moving
    assert b.rung == 2


def test_stale_step_pressure_decays_when_sessions_leave(monkeypatch):
    """Review finding: the step EWMA's only feed is live-session steps, so
    a wedged step followed by the session disconnecting used to pin
    pressure >= 1 FOREVER — an idle box 503ing every new session until
    restart.  The tick loop now decays the signal once samples stop
    arriving."""
    monkeypatch.setenv("OVERLOAD_STEP_BUDGET_MS", "100")
    clock = Clock()
    plane = OverloadControlPlane(clock=clock)
    ladder = plane.register_session("s")
    ladder.note_step_timeout(0.8)  # wedged step: EWMA pinned at 1.6s
    assert not plane.admission.admit()[0]
    plane.unregister_session("s")
    # no sessions, no samples: pressure must drain, not persist
    for _ in range(60):
        clock.tick(0.25)
        plane.tick()
    ok, _ = plane.admission.admit()
    assert ok, f"idle box still refusing (pressure={plane.admission.pressure()})"


def test_fresh_step_samples_hold_off_decay():
    """Decay fires only on stale evidence: while samples keep arriving the
    EWMA is live data and must not be eroded under it."""
    clock = Clock()
    a = AdmissionController(step_budget_s=0.1, clock=clock)
    a.note_step_latency(0.4)
    before = a.step_ewma.value
    clock.tick(0.5)
    a.decay_stale_step_signal(stale_after_s=2.0)  # sample only 0.5s old
    assert a.step_ewma.value == before
    clock.tick(2.0)
    a.decay_stale_step_signal(stale_after_s=2.0)  # now stale
    assert a.step_ewma.value < before


def test_admission_gate_counts_inflight_reservations(monkeypatch):
    """Review finding: OVERLOAD_MAX_SESSIONS was checked against
    len(ladders), which only grows when on_track fires (inside the awaited
    setRemoteDescription) — a burst of concurrent offers all saw zero
    ladders and sailed past the cap.  The gate now takes the session key
    as a counted reservation."""
    monkeypatch.setenv("OVERLOAD_MAX_SESSIONS", "2")
    plane = OverloadControlPlane(clock=Clock())
    assert plane.admission_gate(key="a")[0]
    assert plane.admission_gate(key="b")[0]
    ok, retry_after = plane.admission_gate(key="c")
    assert not ok and retry_after > 0  # zero ladders, cap still enforced
    # registration converts the reservation — no double count
    plane.register_session("a")
    assert plane.snapshot()["overload_admission_pending"] == 1
    assert not plane.admission_gate(key="c")[0]  # 1 ladder + 1 pending
    # a failed offer releases its reservation before any ladder exists
    plane.release_admission("b")
    assert plane.admission_gate(key="c")[0]
    # unregister clears a stray reservation too (failed-offer _end_supervision)
    plane.unregister_session("c")
    assert plane.snapshot()["overload_admission_pending"] == 0


def test_admission_reservations_expire(monkeypatch):
    """A session admitted but never delivering a video track must not
    shrink the cap forever: reservations expire after the setup-sized
    TTL (swept by the tick loop and by the gate itself)."""
    monkeypatch.setenv("OVERLOAD_MAX_SESSIONS", "1")
    clock = Clock()
    plane = OverloadControlPlane(clock=clock)
    assert plane.admission_gate(key="ghost")[0]
    assert not plane.admission_gate(key="next")[0]
    clock.tick(plane._pending_ttl_s + 1.0)
    plane.tick()
    assert plane.admission_gate(key="next")[0]


def test_plane_unregister_releases_freeze(monkeypatch):
    monkeypatch.setenv("OVERLOAD_UP_TICKS", "1")
    plane = OverloadControlPlane()
    plane.register_session("a")
    plane.admission.note_step_latency(1e9)
    for _ in range(RUNG_FROZEN):
        plane.tick()
    assert plane.admission.frozen
    plane.unregister_session("a")
    assert not plane.admission.frozen


class _OpaqueQueue:
    """Queue stub whose CONTENTS cannot be observed — proves the snapshot
    reads counters only, never traverses frames."""

    bound = 8
    shed_overflow = 3
    shed_stale = 1
    depth = 5

    def __iter__(self):
        raise AssertionError("snapshot traversed a frame queue")

    def __getitem__(self, i):
        raise AssertionError("snapshot indexed a frame queue")


def test_snapshot_is_counter_reads_only():
    plane = OverloadControlPlane()
    for i in range(32):
        plane.register_session(f"s{i}")
    plane.register_queue("rx", _OpaqueQueue())
    for _ in range(100):
        plane.note_delivered(0.01)
    snap = plane.snapshot()  # must not touch queue contents
    assert snap["overload_sessions"] == 32
    assert snap["overload_admission_pending"] == 0
    assert snap["overload_queues"]["rx"] == {
        "depth": 5, "bound": 8, "shed_overflow": 3, "shed_stale": 1,
    }
    assert snap["overload_freshness_p50_ms"] == pytest.approx(10.0)
    assert snap["overload_freshness_p99_ms"] == pytest.approx(10.0)
    assert snap["overload_pressure"] == 0.0


def test_queue_probe_adapts_foreign_queues_and_unregisters_with_session():
    from ai_rtc_agent_tpu.resilience.overload import QueueProbe

    async def go():
        q = asyncio.Queue(maxsize=16)
        await q.put(1)
        await q.put(2)
        plane = OverloadControlPlane()
        plane.register_session("sess")
        plane.register_queue("ingest:sess", QueueProbe(q))
        snap = plane.snapshot()["overload_queues"]["ingest:sess"]
        assert snap == {"depth": 2, "bound": 16,
                        "shed_overflow": 0, "shed_stale": 0}
        plane.unregister_session("sess")
        assert plane.snapshot()["overload_queues"] == {}

    asyncio.run(go())


def test_deadline_queue_satisfies_snapshot_surface():
    plane = OverloadControlPlane()
    q = plane.register_queue("q", DeadlineQueue(bound=2))
    q.push(b"a")
    q.push(b"b")
    q.push(b"c")
    snap = plane.snapshot()["overload_queues"]["q"]
    assert snap == {"depth": 2, "bound": 2, "shed_overflow": 1, "shed_stale": 0}


# ---------------------------------------------------------------------------
# agent surface: /capacity, admission 503 + Retry-After, /metrics keys
# ---------------------------------------------------------------------------

def _offer_body():
    from ai_rtc_agent_tpu.server.signaling import make_loopback_offer

    return {"room_id": "r", "offer": {"sdp": make_loopback_offer(), "type": "offer"}}


def test_agent_admission_503_and_capacity(monkeypatch):
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    monkeypatch.setenv("WARMUP_FRAMES", "0")

    async def go():
        app = build_app(pipeline=lambda f: f, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            ov = app["overload"]
            assert ov is not None

            r = await client.get("/capacity")
            body = await r.json()
            assert body["capacity"] == -1 and body["saturated"] is False

            # saturate the step signal -> admission refuses BEFORE any claim
            ov.admission.note_step_latency(1e9)
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 503
            assert int(r.headers["Retry-After"]) >= 1
            r = await client.post(
                "/whip",
                data=json.dumps({"loopback": True, "video": True}),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 503
            assert "Retry-After" in r.headers

            body = await (await client.get("/capacity")).json()
            assert body["capacity"] == 0 and body["saturated"] is True

            m = await (await client.get("/metrics")).json()
            assert m["overload_pressure"] >= 1.0
            assert m.get("overload_admission_rejected_total", 0) >= 2

            # pressure clears -> admitted again (EWMA washes down)
            for _ in range(64):
                ov.admission.note_step_latency(0.001)
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 200
        finally:
            await client.close()

    asyncio.run(go())


def test_metrics_and_health_never_traverse_frame_queues(monkeypatch):
    """The observability endpoints themselves must survive overload: with
    a live session and an opaque (untraversable) queue registered, GET
    /metrics and GET /health still answer — any per-request traversal of
    frame-queue contents would 500."""
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    monkeypatch.setenv("WARMUP_FRAMES", "0")

    async def go():
        app = build_app(pipeline=lambda f: f, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 200
            app["overload"].register_queue("opaque", _OpaqueQueue())
            m = await client.get("/metrics")
            assert m.status == 200
            body = await m.json()
            assert body["overload_queues"]["opaque"]["depth"] == 5
            h = await client.get("/health")
            assert h.status == 200
            (snap,) = (await h.json())["sessions"].values()
            assert snap["overload_rung"] == 0
        finally:
            await client.close()

    asyncio.run(go())


def test_agent_session_cap(monkeypatch):
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("OVERLOAD_MAX_SESSIONS", "1")

    async def go():
        app = build_app(pipeline=lambda f: f, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 200
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 503
            cap = await (await client.get("/capacity")).json()
            assert cap["capacity"] == 0
        finally:
            await client.close()

    asyncio.run(go())


def test_overload_control_kill_switch(monkeypatch):
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    monkeypatch.setenv("OVERLOAD_CONTROL", "0")

    async def go():
        app = build_app(pipeline=lambda f: f, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            assert app["overload"] is None
            m = await (await client.get("/metrics")).json()
            assert "overload_pressure" not in m
            body = await (await client.get("/capacity")).json()
            assert body["capacity"] == -1
        finally:
            await client.close()

    asyncio.run(go())


def test_worker_publishes_capacity(monkeypatch):
    """The sidecar publish carries remaining capacity, not a boolean."""
    import threading as _threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from ai_rtc_agent_tpu.server import worker

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            body = (
                json.dumps({"capacity": 3, "saturated": False,
                            "retry_after_s": 0.0})
                if self.path == "/capacity"
                else "OK"
            ).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    port = srv.server_address[1]
    t = _threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    published = []
    # the republish lease loop (ISSUE 11) is wall-clock driven: a fake
    # sleep MUST advance a fake clock or the loop spins the real lease
    now = {"t": 0.0}

    def fake_sleep(s):
        now["t"] += s

    try:
        rc = worker.handler(
            port, publish=published.append, sleep=fake_sleep,
            clock=lambda: now["t"],
        )
    finally:
        srv.shutdown()
    assert rc == 0
    info = published[0]
    assert info["capacity"] == 3
    assert info["saturated"] is False
    assert info["status"] == "ready"  # kept for orchestrator compat


def test_fetch_capacity_tolerates_garbled_response(monkeypatch):
    """Review finding: a truncated/garbled /capacity response raises
    http.client.HTTPException (BadStatusLine, IncompleteRead) — not
    URLError/OSError/ValueError — and used to escape the best-effort
    helper, killing the worker handler before publish() ran: the lease
    burned unpublished behind a perfectly healthy agent."""
    import http.client as _http_client
    import urllib.request as _urllib_request

    from ai_rtc_agent_tpu.server import worker

    def garbled(url, timeout=None):
        raise _http_client.BadStatusLine("HTP/1.1 garbage")

    monkeypatch.setattr(_urllib_request, "urlopen", garbled)
    assert worker.fetch_capacity("http://127.0.0.1:1/capacity") is None


def test_multipeer_slot_queue_sheds_oldest_as_passthrough():
    """Bounded per-slot queues: a peer outrunning the batch step gets its
    oldest frame back as passthrough instead of unbounded queueing."""
    from concurrent.futures import Future

    from ai_rtc_agent_tpu.server.multipeer_serving import MultiPeerPipeline

    mp = MultiPeerPipeline.__new__(MultiPeerPipeline)  # no engine build
    mp.queue_bound = 2
    mp.frames_shed = 0
    mp._lock = threading.Lock()
    mp._has_work = threading.Condition(mp._lock)
    from collections import deque

    mp._queues = [deque(maxlen=2)]
    frames = [np.full((2, 2, 3), i, np.uint8) for i in range(4)]
    futs = [mp._enqueue(0, f) for f in frames]
    assert len(mp._queues[0]) == 2
    assert mp.frames_shed == 2
    # the two shed futures resolved as passthrough with their own pixels,
    # ShedFrame-marked so the wrapper never mistakes them for engine output
    from ai_rtc_agent_tpu.resilience.overload import ShedFrame

    assert futs[0].done() and isinstance(futs[0].result(), ShedFrame)
    assert np.array_equal(futs[0].result().frame, frames[0])
    assert futs[1].done() and np.array_equal(futs[1].result().frame, frames[1])
    assert not futs[2].done() and not futs[3].done()
    assert isinstance(futs[2], Future)


def test_shed_frames_do_not_feed_admission_ewma():
    """Review finding: a shed multipeer frame used to resolve its Future
    with raw source pixels, which the resilience wrapper counted as a
    ~0ms healthy engine step — diluting the step EWMA exactly when the
    shed condition (slow batch steps) was evidence of overload.  The
    ShedFrame marker makes the wrapper deliver passthrough and feed
    nothing."""
    from ai_rtc_agent_tpu.resilience.overload import ShedFrame

    class _SheddingInner:
        def __call__(self, frame):
            raise AssertionError("pipelined surface expected")

        def submit(self, frame):
            return ("h", frame)

        def fetch(self, handle, src_frame=None):
            return ShedFrame(handle[1])  # queue shed it: source pixels back

    clock = Clock()
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    rp = ResilientPipeline(_SheddingInner(), sup, warm_steps=0)
    ladder, adm = _ladder(clock=clock)
    rp.throttle = ladder
    try:
        out = rp.fetch(rp.submit("px"), "src")
        assert out == "src"  # passthrough delivery of the source frame
        assert adm.step_ewma.samples == 0  # shed never measures capacity
        assert sup.passthrough_frames == 1
        assert sup.processed_frames == 0
    finally:
        rp.close()


def test_shed_in_batch_delivers_passthrough_per_position():
    """ISSUE 12 review finding: routing fbs>1 through the BatchScheduler
    made a per-position batch shed reachable (the scheduler's bounded
    window can evict part of a group).  The batched wrapper must deliver
    source pixels for the shed position, the stepped output for the
    rest, and feed only the stepped frames to the counters — a raw
    ShedFrame object must never escape toward the encoder."""
    from ai_rtc_agent_tpu.resilience.overload import ShedFrame

    class _PartialShedInner:
        def __call__(self, frame):
            raise AssertionError("batched surface expected")

        def submit_batch(self, frames):
            return list(frames)

        def fetch_batch(self, handles, src_frames=None):
            return ["out0", ShedFrame(handles[1])]

    clock = Clock()
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    rp = ResilientPipeline(_PartialShedInner(), sup, warm_steps=0)
    ladder, adm = _ladder(clock=clock)
    rp.throttle = ladder
    try:
        outs = rp.fetch_batch(rp.submit_batch(["f0", "f1"]), ["s0", "s1"])
        assert outs == ["out0", "s1"]
        assert sup.passthrough_frames == 1
        assert sup.processed_frames == 1
    finally:
        rp.close()


def test_shed_marker_sync_path_delivers_passthrough():
    """Same invariant on the sync (depth-1) surface: __call__ returning a
    ShedFrame marker must deliver passthrough and feed neither the step
    EWMA nor the processed-frame counter."""
    from ai_rtc_agent_tpu.resilience.overload import ShedFrame

    class _SheddingSync:
        def __call__(self, frame):
            return ShedFrame(frame)

    clock = Clock()
    sup = SessionSupervisor("s", clock=clock, sleep=lambda s: None)
    rp = ResilientPipeline(_SheddingSync(), sup, warm_steps=0)
    ladder, adm = _ladder(clock=clock)
    rp.throttle = ladder
    try:
        out = rp("px")
        assert out == "px"
        assert adm.step_ewma.samples == 0
        assert sup.passthrough_frames == 1
        assert sup.processed_frames == 0
    finally:
        rp.close()


def test_track_ingest_sheds_stale_frames(monkeypatch):
    """Freshest-frame-wins at the track: stale stamped frames with fresher
    ones queued behind are shed and counted; the fresh frame is delivered."""
    from ai_rtc_agent_tpu.media.frames import VideoFrame
    from ai_rtc_agent_tpu.server.signaling import LoopbackTrack
    from ai_rtc_agent_tpu.server.tracks import VideoStreamTrack

    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("OVERLOAD_FRAME_DEADLINE_MS", "100")

    from ai_rtc_agent_tpu.utils.profiling import FrameStats

    stats = FrameStats()
    plane = OverloadControlPlane(stats)

    async def go():
        src = LoopbackTrack()
        vt = VideoStreamTrack(src, lambda f: f, overload=plane)
        now = plane._clock()
        for i in range(5):
            f = VideoFrame.from_ndarray(np.full((4, 4, 3), i, np.uint8))
            f.wall_ts = now - 10.0  # ancient
            await src.push(f)
        fresh = VideoFrame.from_ndarray(np.full((4, 4, 3), 99, np.uint8))
        fresh.wall_ts = now
        await src.push(fresh)
        out = await vt.recv()
        assert out.to_ndarray()[0, 0, 0] == 99
        assert stats.snapshot().get("overload_shed_ingest_total") == 5
        snap = plane.snapshot()
        assert snap["overload_freshness_p99_ms"] < 100.0

    asyncio.run(go())
