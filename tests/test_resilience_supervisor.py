"""Session supervisor + ResilientPipeline (resilience/supervisor.py):
state machine transitions, passthrough degradation, background recovery,
watchdog ticks — driven with injected clocks and tiny timeouts so the
whole file runs in a few seconds of wall time."""

import threading
import time

import numpy as np
import pytest

from ai_rtc_agent_tpu.resilience.faults import DeviceLostError
from ai_rtc_agent_tpu.resilience.supervisor import (
    DEGRADED,
    FAILED,
    HEALTHY,
    RECOVERING,
    ResilientPipeline,
    SessionSupervisor,
    worst_state,
)


class ScriptedPipeline:
    """Pipeline whose per-call behavior is a script: numbers are sleeps,
    exceptions raise, 'nan' returns a poisoned frame, None is a clean step."""

    def __init__(self, script=()):
        self.script = list(script)
        self.calls = 0
        self.restarts = 0

    def __call__(self, frame):
        self.calls += 1
        action = self.script.pop(0) if self.script else None
        if action is None:
            return 255 - frame
        if isinstance(action, (int, float)):
            time.sleep(action)
            return 255 - frame
        if action == "nan":
            return np.full(frame.shape, np.nan, np.float32)
        raise action

    def restart(self):
        self.restarts += 1


def _sup(**kw):
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("probe_interval_s", 0.0)
    return SessionSupervisor("test-session", **kw)


def _rp(pipe, sup, timeout=0.2):
    return ResilientPipeline(
        pipe, sup, step_timeout_s=timeout, first_step_timeout_s=timeout
    )


FRAME = np.zeros((4, 4, 3), np.uint8)


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_healthy_steps_pass_through_engine():
    pipe = ScriptedPipeline()
    sup = _sup()
    rp = _rp(pipe, sup)
    out = rp(FRAME)
    assert out.max() == 255  # inverted — the engine ran
    assert sup.state == HEALTHY
    assert sup.processed_frames == 1


def test_stall_degrades_to_passthrough_and_recovers():
    pipe = ScriptedPipeline(script=[10.0])  # first step wedges
    sup = _sup(healthy_after=2)
    rp = _rp(pipe, sup, timeout=0.05)

    out = rp(FRAME)
    # stream did NOT freeze: the source frame came back instead
    assert out is FRAME
    assert sup.state in (DEGRADED, RECOVERING)
    assert sup.passthrough_frames == 1

    # background restart (pipe.restart) completes -> RECOVERING
    assert _wait_for(lambda: sup.state == RECOVERING)
    assert pipe.restarts == 1

    # healthy steps climb back to HEALTHY
    rp(FRAME)
    out = rp(FRAME)
    assert sup.state == HEALTHY
    assert out.max() == 255


def test_error_burst_triggers_recovery_single_error_does_not():
    pipe = ScriptedPipeline(
        script=[RuntimeError("x"), None, RuntimeError("a"),
                RuntimeError("b"), RuntimeError("c")]
    )
    sup = _sup(error_burst=3, healthy_after=1)
    rp = _rp(pipe, sup)
    assert rp(FRAME) is FRAME  # error 1 -> passthrough, still HEALTHY
    assert sup.state == HEALTHY
    rp(FRAME)  # clean step resets the burst counter
    assert sup.state == HEALTHY
    for _ in range(3):
        rp(FRAME)
    assert sup.state in (DEGRADED, RECOVERING)
    assert _wait_for(lambda: pipe.restarts >= 1)


def test_device_lost_degrades_immediately():
    pipe = ScriptedPipeline(script=[DeviceLostError("gone")])
    sup = _sup()
    rp = _rp(pipe, sup)
    assert rp(FRAME) is FRAME
    assert sup.state in (DEGRADED, RECOVERING)


def test_nan_output_counts_as_step_error():
    pipe = ScriptedPipeline(script=["nan", "nan", "nan"])
    sup = _sup(error_burst=3)
    rp = _rp(pipe, sup)
    for _ in range(3):
        out = rp(FRAME)
        assert out is FRAME  # poisoned frames never reach the encoder
    assert sup.state in (DEGRADED, RECOVERING)


def test_restart_budget_exhaustion_fails_session_but_stream_flows():
    class AlwaysBroken:
        def __call__(self, frame):
            raise RuntimeError("dead engine")

        def restart(self):
            raise RuntimeError("restart also dead")

    sup = _sup(error_burst=1, max_restarts=2)
    rp = _rp(AlwaysBroken(), sup)
    rp(FRAME)
    assert _wait_for(lambda: sup.state == FAILED)
    # FAILED is terminal for the engine, not the stream
    out = rp(FRAME)
    assert out is FRAME
    assert sup.snapshot()["state"] == FAILED


def test_watchdog_detects_output_stall_and_fires_resync():
    now = [0.0]
    resyncs = []
    sup = SessionSupervisor(
        "wd",
        stall_after_s=2.0,
        clock=lambda: now[0],
        resync=lambda: resyncs.append(now[0]),
    )
    sup.note_frame_out()
    assert sup.check(now[0]) == HEALTHY
    now[0] = 1.0
    assert sup.check() == HEALTHY
    now[0] = 3.5  # frame age 3.5s > 2s
    assert sup.check() == DEGRADED
    assert resyncs == [3.5]
    # frames resume -> probe succeeds -> RECOVERING -> HEALTHY
    sup.on_step_ok()
    assert sup.state == RECOVERING
    for _ in range(3):
        sup.on_step_ok()
    assert sup.state == HEALTHY


def test_transitions_are_observable():
    seen = []
    pipe = ScriptedPipeline(script=[10.0])
    sup = _sup(on_transition=lambda a, b, r: seen.append((a, b)),
               healthy_after=1)
    rp = _rp(pipe, sup, timeout=0.05)
    rp(FRAME)
    assert _wait_for(lambda: sup.state == RECOVERING)
    rp(FRAME)
    assert (HEALTHY, DEGRADED) in seen
    assert (DEGRADED, RECOVERING) in seen
    assert (RECOVERING, HEALTHY) in seen
    snap = sup.snapshot()
    assert snap["restarts"] == 1
    assert len(snap["transitions"]) >= 3


def test_pipelined_surface_passthrough_on_stall():
    class PipelinedStall:
        def __init__(self):
            self.stall = False

        def submit(self, frame):
            return ("h", frame)

        def fetch(self, handle, src=None):
            if self.stall:
                time.sleep(10.0)
            return 255 - handle[1]

        def restart(self):
            self.stall = False

    inner = PipelinedStall()
    sup = _sup(healthy_after=1)
    rp = _rp(inner, sup, timeout=0.05)
    h = rp.submit(FRAME)
    assert rp.fetch(h, FRAME).max() == 255
    inner.stall = True
    h = rp.submit(FRAME)
    out = rp.fetch(h, FRAME)
    assert out is FRAME  # stalled fetch -> source frame, stream alive
    assert sup.state in (DEGRADED, RECOVERING)
    assert _wait_for(lambda: sup.state == RECOVERING)
    h = rp.submit(FRAME)
    assert rp.fetch(h, FRAME).max() == 255
    assert sup.state == HEALTHY


def test_pipelined_probe_carries_grant_through_fetch():
    """ROADMAP open item 1: the DEGRADED probe token is consumed at
    submit time; with the old fetch-side re-check (`should_try_engine`
    again, a frame later) the window was always closed by then, every
    probe was discarded as passthrough, on_step_ok never fired, and a
    pipelined session without a restart hook could NEVER leave DEGRADED.
    The grant must ride with the in-flight frame."""
    now = [0.0]

    class ProbePipeline:
        def __init__(self):
            self.fail = False
            self.fetches = 0

        def submit(self, frame):
            return ("h", frame)

        def fetch(self, handle, src=None):
            if self.fail:
                raise RuntimeError("wedged")
            self.fetches += 1
            return 255 - handle[1]

        # NO restart attr: DEGRADED recovers via throttled probes only

    inner = ProbePipeline()
    sup = SessionSupervisor(
        "probe", probe_interval_s=2.0, error_burst=1, healthy_after=1,
        clock=lambda: now[0],
    )
    rp = _rp(inner, sup, timeout=1.0)
    inner.fail = True
    h = rp.submit(FRAME)
    assert rp.fetch(h, FRAME) is FRAME  # error burst of 1 -> DEGRADED
    assert sup.state == DEGRADED
    inner.fail = False
    # probe window still closed: submit passthroughs, nothing consumed
    assert rp.submit(FRAME)[0] == "passthrough"
    now[0] = 2.5  # window open: this submit consumes the probe token
    h = rp.submit(FRAME)
    assert h[0] == "live"
    out = rp.fetch(h, FRAME)  # the regression: fetch must HONOR the grant
    assert out is not FRAME and out.max() == 255
    assert inner.fetches == 1
    assert sup.state in (RECOVERING, HEALTHY)
    # next frame runs normally (RECOVERING is unthrottled) -> HEALTHY
    h = rp.submit(FRAME)
    assert rp.fetch(h, FRAME).max() == 255
    assert sup.state == HEALTHY


def test_failed_session_revokes_inflight_fetch():
    """The probe grant survives DEGRADED but not FAILED — a handle
    submitted before the session died must come back as passthrough."""
    class P:
        def submit(self, frame):
            return ("h", frame)

        def fetch(self, handle, src=None):
            raise AssertionError("engine must not run after FAILED")

    sup = _sup()
    rp = _rp(P(), sup)
    h = rp.submit(FRAME)
    assert h[0] == "live"
    with sup._lock:
        sup._transition_locked(FAILED, "test")
    assert rp.fetch(h, FRAME) is FRAME


def test_resync_marshalled_to_loop_when_bound():
    import asyncio

    fired = {}

    async def go():
        sup = SessionSupervisor(
            "loop-bound",
            resync=lambda: fired.setdefault(
                "thread", threading.current_thread().name
            ),
        )
        sup.start_watchdog()
        # resync requested from a worker thread must land on the loop
        t = threading.Thread(target=sup._fire_resync)
        t.start()
        t.join()
        await asyncio.sleep(0.05)
        sup.stop()

    asyncio.run(go())
    assert fired["thread"] == "MainThread"


def test_worst_state_rollup():
    assert worst_state([]) == HEALTHY
    assert worst_state([HEALTHY, RECOVERING]) == RECOVERING
    assert worst_state([HEALTHY, DEGRADED, RECOVERING]) == DEGRADED
    assert worst_state([FAILED, DEGRADED]) == FAILED


def test_control_plane_delegation():
    class WithControls:
        frame_buffer_size = 4

        def __call__(self, f):
            return f

        def update_prompt(self, p):
            self.prompt = p

    inner = WithControls()
    rp = ResilientPipeline(inner, _sup())
    rp.update_prompt("hello")
    assert inner.prompt == "hello"
    assert rp.frame_buffer_size == 4
    assert not hasattr(rp, "submit")  # no pipelined surface to forward
