"""bench.py contract guarantees (the round-1 failure mode: rc=1, no JSON).

The driver parses exactly ONE JSON line from bench.py; these tests pin the
two failure paths that previously produced none: an unreachable accelerator
backend and an outright init error.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env: dict, args=(), config="turbo512", timeout=180):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # keep the subprocess hermetic
    # never coordinate with (or stop!) a real watcher running on this box —
    # tests opt in via an explicit TPU_WATCH_PID
    env.setdefault("TPU_WATCH_PID", os.devnull)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, "bench.py", "--config", config, *args],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


def _contract_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, f"expected exactly one JSON line, got: {stdout!r}"
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, f"contract key {k} missing: {d}"
    return d


def test_accelerator_tier_refuses_cpu_fallback():
    """ISSUE 8 acceptance: an accelerator-tier record (--expect-backend
    tpu) running on a CPU-fallback backend must exit NONZERO with NO
    contract line — nothing bankable, loudly (BENCH_r05 banked 0.04 fps
    from exactly this silent fallback).  Fast: the probe path refuses
    before any model builds."""
    r = _run_bench(
        {"JAX_PLATFORMS": "cpu", "PERF_LOG_PATH": os.devnull},
        args=("--frames", "2", "--probe-timeout", "120",
              "--expect-backend", "tpu"),
        config="tiny64",
    )
    assert r.returncode == 3, (r.returncode, r.stderr[-400:])
    assert not [ln for ln in r.stdout.splitlines() if ln.startswith("{")], (
        "a refusal must not emit a contract line: " + r.stdout
    )
    assert "BENCH REFUSED" in r.stderr and "tpu" in r.stderr

    # env spelling, and an UNREACHABLE accelerator with a declared tier
    # is also a refusal (replaying a stale number would defeat the gate)
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": os.devnull,
         "BENCH_EXPECT_BACKEND": "tpu"},
    )
    assert r.returncode == 3, (r.returncode, r.stderr[-400:])
    assert not [ln for ln in r.stdout.splitlines() if ln.startswith("{")]


def test_contract_line_when_backend_unreachable():
    """A bogus platform makes the subprocess probe fail -> the bench must
    still print the parseable contract line and exit 0.  PERF_LOG_PATH is
    pointed at an empty file so a committed PERF_LOG.jsonl (written by the
    TPU watcher) can't substitute a replayed number here."""
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": os.devnull}
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 0.0
    assert "error" in d and "unreachable" in d["error"]


def test_unreachable_backend_replays_committed_tpu_number(tmp_path):
    """VERDICT r2 item 1: a TPU number committed mid-round by the watcher
    must survive into the driver's artifact even when the tunnel is dead at
    bench time — emitted with live:false + the original measurement's
    fields, plus the live attempt's error for honesty."""
    log = tmp_path / "PERF_LOG.jsonl"
    entry = {
        "metric": "e2e_fps_turbo512_singlechip", "value": 31.4, "unit": "fps",
        "vs_baseline": 1.047, "backend": "tpu", "latency_p50_ms": 41.0,
        "stage_ms": {"upload": 1.0, "compute": 20.0, "readback": 2.0},
        "mfu": 0.21, "recorded_at": "2026-07-30T12:00:00+00:00",
    }
    log.write_text(json.dumps({"metric": "other", "backend": "tpu", "value": 1})
                   + "\n" + json.dumps(entry) + "\n")
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": str(log)}
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 31.4 and d["backend"] == "tpu"
    assert d["live"] is False
    assert d["recorded_at"] == "2026-07-30T12:00:00+00:00"
    assert "unreachable" in d["live_attempt"]["error"]
    assert d["stage_ms"]["compute"] == 20.0 and d["mfu"] == 0.21


@pytest.mark.slow
def test_contract_line_happy_path_tiny():
    """The full bench pipeline on the hermetic tiny model emits exactly one
    well-formed contract line with a positive fps and stage breakdown.

    `slow` tier (ISSUE 12 budget satellite, ~50s of live tiny-bench):
    the contract MACHINERY keeps tier-1 teeth via the refusal/replay/
    fence tests in this file, and the live-bench smoke shape is the same
    one the (also slow-tier) batchsched/meshsched smokes exercise."""
    r = _run_bench(
        {"JAX_PLATFORMS": "cpu"},
        args=("--frames", "4", "--probe-timeout", "120"),
        config="tiny64",
        timeout=420,
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["metric"] == "e2e_fps_tiny64_singlechip"
    assert d["value"] > 0
    # live, not replayed: the repo PERF_LOG now contains a matching CPU
    # entry, and a silently-replaying broken pipeline must still fail here
    assert d["live"] is True
    assert "stage_ms" in d and set(d["stage_ms"]) == {
        "upload", "compute", "readback"
    }


def test_wedged_child_still_replays_committed_number(tmp_path):
    """r3 failure mode: the measurement wedges in an uninterruptible remote
    call.  The parent process (which never imports jax) must kill the child
    at BENCH_CHILD_TIMEOUT_S and still emit the committed replay line."""
    log = tmp_path / "PERF_LOG.jsonl"
    entry = {
        "metric": "e2e_fps_sdxl1024_singlechip", "value": 12.3, "unit": "fps",
        "vs_baseline": 0.41, "backend": "tpu",
        "recorded_at": "2026-07-31T04:00:00+00:00",
    }
    log.write_text(json.dumps(entry) + "\n")
    # sdxl1024: the child cannot even finish imports + SDXL param init
    # within 3s on any machine, so the kill path is deterministic (a tiny
    # config could legitimately finish before the timeout on a warm box)
    r = _run_bench(
        {"JAX_PLATFORMS": "cpu", "PERF_LOG_PATH": str(log),
         "BENCH_CHILD_TIMEOUT_S": "3"},
        args=("--frames", "1", "--probe-timeout", "60"),
        config="sdxl1024", timeout=180,
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 12.3 and d["live"] is False
    assert "wedged" in d["live_attempt"]["error"]


def test_replay_prefers_same_variant_then_falls_back_labeled(tmp_path):
    """Two-tier replay: a same-variant entry wins; with only a safe-path
    (xla/unfused) entry committed, the default-variant request still emits
    it — the line self-describes its variant, which beats value 0.0."""
    log = tmp_path / "PERF_LOG.jsonl"
    safe = {
        "metric": "e2e_fps_turbo512_singlechip", "value": 17.9, "unit": "fps",
        "vs_baseline": 0.597, "backend": "tpu", "attn_impl": "xla",
        "fused_epilogue": False, "recorded_at": "2026-07-31T05:00:00+00:00",
    }
    log.write_text(json.dumps(safe) + "\n")
    # pin the wanted variant to the TPU defaults: an exported ATTN_IMPL or
    # FUSED_EPILOGUE on the host would otherwise turn the fallback phase
    # into a tier-1 match
    env = {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": str(log),
           "ATTN_IMPL": "", "FUSED_EPILOGUE": ""}
    r = _run_bench(env)
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 17.9 and d["live"] is False
    assert d["attn_impl"] == "xla" and d["fused_epilogue"] is False

    # same-variant entry present -> it wins over the safe one
    default = dict(safe, value=29.0, attn_impl="pallas", fused_epilogue=True)
    log.write_text(json.dumps(safe) + "\n" + json.dumps(default) + "\n")
    r = _run_bench(env)
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 29.0 and d["attn_impl"] == "pallas"


@pytest.mark.slow
def test_bench_yields_to_watcher_item_lock(tmp_path):
    """Coordination: with a LIVE watcher pid and a fresh item lock, the
    non-watcher bench writes the stop file and waits for the lock's
    release before claiming; the watcher's own items (TPU_WATCH_OWNER=1)
    skip coordination entirely.  Deterministic: the lock is released only
    AFTER the bench's stop file appears, so subprocess startup time can't
    race the release.

    `slow` tier (ISSUE 12 budget satellite, ~14s): the OTHER half of the
    watcher-lock contract — refusing to double-claim an unreleased lock
    — stays tier-1 (test_bench_refuses_to_contend_with_unreleased_claim),
    which is the wedge mode with teeth."""
    import threading
    import time as _time

    lock = tmp_path / "tpu_item.lock"
    lock.write_text("123\n")
    stop = tmp_path / "watch_stop"
    pidfile = tmp_path / "watch.pid"
    pidfile.write_text(f"{os.getpid()}\n")  # "watcher" = this live process

    def release_after_stop_seen():
        deadline = _time.time() + 60
        while _time.time() < deadline and not stop.exists():
            _time.sleep(0.2)
        _time.sleep(2)  # bench is now provably inside its wait loop
        lock.unlink()

    threading.Thread(target=release_after_stop_seen, daemon=True).start()
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": os.devnull,
         "TPU_ITEM_LOCK": str(lock), "TPU_WATCH_STOP": str(stop),
         "TPU_WATCH_PID": str(pidfile), "BENCH_CLAIM_WAIT_S": "60"},
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert "unreachable" in d["error"]  # proceeded after release
    # PAUSE protocol (advisor r3): the stand-down file is written during
    # the run and REAPED in the bench's finally so the watcher resumes
    assert not stop.exists()
    assert not lock.exists()  # proceeded only after the release
    assert "claim_contention" not in d

    # owner path: same fresh lock + live pid, no waiting, no stop file
    lock.write_text("123\n")
    stop2 = tmp_path / "watch_stop2"
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": os.devnull,
         "TPU_ITEM_LOCK": str(lock), "TPU_WATCH_STOP": str(stop2),
         "TPU_WATCH_PID": str(pidfile), "TPU_WATCH_OWNER": "1",
         "BENCH_CLAIM_WAIT_S": "60"},
    )
    assert _contract_line(r.stdout)
    assert not stop2.exists()


def test_bench_refuses_to_contend_with_unreleased_claim(tmp_path):
    """A watcher item that never releases within the wait budget means the
    bench must NOT double-claim (the lease-leak wedge mode): it emits the
    contract line (or a replay) labeled with the contention error instead."""
    lock = tmp_path / "tpu_item.lock"
    lock.write_text("123\n")
    stop = tmp_path / "watch_stop"
    pidfile = tmp_path / "watch.pid"
    pidfile.write_text(f"{os.getpid()}\n")
    r = _run_bench(
        {"JAX_PLATFORMS": "cpu", "PERF_LOG_PATH": os.devnull,
         "TPU_ITEM_LOCK": str(lock), "TPU_WATCH_STOP": str(stop),
         "TPU_WATCH_PID": str(pidfile), "BENCH_CLAIM_WAIT_S": "6"},
        args=("--frames", "2", "--probe-timeout", "30"), config="tiny64",
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 0.0
    assert "not contending" in d["error"]
    assert not stop.exists()  # pause file reaped even on the refusal path


def test_host_plane_bench_contract_and_speedup(tmp_path):
    """Host-plane microbench smoke (ISSUE 2): runs in seconds on CPU,
    emits exactly one contract line, BANKS it into PERF_LOG_PATH, and the
    batched path must not be slower than per-packet.  The ratio fence is
    deliberately loose (the ≥3x acceptance number is measured by a full
    run on an uncontended box); a regression that makes batching SLOWER
    than the per-packet loop still fails here."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "PERF_LOG_PATH": str(log),
            "HOST_PLANE_BENCH_FRAMES": "60",
            "JAX_PLATFORMS": "cpu",
        }
    )
    r = subprocess.run(
        [sys.executable, "scripts/host_plane_bench.py"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert d["metric"] == "host_plane_batched_speedup"
    assert d["pkts_per_frame"] >= 15  # 512²-rate FU-A shape at 1200 MTU
    # honest-bench fingerprint (ISSUE 8): shared utils/hwfp.py dict
    assert d["fingerprint"]["host_cpus"] >= 1
    assert d["fingerprint"]["jax_backend"] == "unprobed"  # pure-host bench
    # not-slower fence with headroom for a contended 1-core CI box
    assert d["value"] >= 0.9, d
    # banked: the same entry landed in the log
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "host_plane_batched_speedup"


def test_broadcast_bench_contract(tmp_path):
    """Broadcast fan-out bench smoke (ISSUE 17): runs in seconds on CPU,
    emits exactly TWO contract lines (amortization + single-viewer
    overhead), BANKS both, and the bench contract pin holds: the PLI
    storm fired inside the fan-out leg produced exactly one GOP replay
    and zero encoder IDRs.  No ratio fence here beyond sanity — the
    amortization claim is measured by a full run (perf_compare fences
    the banked numbers); what this catches is the harness rotting."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "PERF_LOG_PATH": str(log),
            "BROADCAST_BENCH_FRAMES": "4",
            "BROADCAST_BENCH_VIEWERS": "4",
            "BROADCAST_BENCH_DIM": "64",
            "BROADCAST_BENCH_PAIRS": "2",
            "JAX_PLATFORMS": "cpu",
        }
    )
    r = subprocess.run(
        [sys.executable, "scripts/broadcast_bench.py"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 2, r.stdout
    by_metric = {json.loads(ln)["metric"]: json.loads(ln) for ln in lines}
    assert set(by_metric) == {
        "broadcast_viewers_per_core_30fps",
        "broadcast_single_viewer_overhead_ratio",
    }
    for d in by_metric.values():
        for k in ("metric", "value", "unit", "vs_baseline"):
            assert k in d, d
        assert "error" not in d, d
        assert d["value"] > 0, d
        assert d["fingerprint"]["jax_backend"] == "unprobed"  # host bench
    assert by_metric["broadcast_viewers_per_core_30fps"]["unit"] == "viewers"
    # the bench-contract half of the acceptance pin: the in-harness PLI
    # storm coalesced to ONE gop replay, ZERO encoder/engine IDRs
    d = by_metric["broadcast_viewers_per_core_30fps"]
    assert d["pli_storm"] == {"replays": 1, "encoder_idrs": 0}
    banked = {json.loads(x)["metric"] for x in log.read_text().splitlines()}
    assert banked == set(by_metric)


def test_trace_overhead_bench_contract(tmp_path):
    """Tracing-overhead microbench smoke (ISSUE 5): runs in seconds on
    CPU, emits exactly one contract line, BANKS it into PERF_LOG_PATH,
    and the zero-cost-when-off promise holds as a guarded ratio.  The
    fence is deliberately loose for contended CI boxes — what it catches
    is a regression that puts allocation/locking/clock reads back on the
    trace-off hot path (that is a multi-x blowup, not a few percent)."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "PERF_LOG_PATH": str(log),
            "TRACE_BENCH_FRAMES": "400",
            "JAX_PLATFORMS": "cpu",
        }
    )
    r = subprocess.run(
        [sys.executable, "scripts/trace_overhead_bench.py"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    # three contract lines: the trace/SLO quartet + the devtel leg
    # (ISSUE 10) + the fleet journey leg (ISSUE 13)
    assert len(lines) == 3, r.stdout
    by_metric = {json.loads(ln)["metric"]: json.loads(ln) for ln in lines}
    d = by_metric["trace_off_overhead_ratio"]
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert 0 < d["value"] <= 1.5, d  # off-mode must stay within noise
    # tracing ON costs more than OFF (the bench actually traced), and the
    # absolute off-mode residue stays in single-digit µs per frame
    assert d["trace_on_us_per_frame"] >= d["trace_off_us_per_frame"], d
    assert d["off_overhead_us_per_frame"] < 25.0, d
    # the SLO plane's off-mode contract (ISSUE 8 acceptance: ≤5% over the
    # trace-off ratio on an uncontended box; this CI fence is loose the
    # same way the trace one is — what it catches is allocation/locking
    # landing back on the SLO_ENABLE=0 hot path, a multi-x blowup)
    assert 0 < d["slo_off_overhead_ratio"] <= 1.5, d
    assert d["slo_off_overhead_us_per_frame"] < 25.0, d
    # slo-on actually aggregated (the bench fed real timelines)
    assert d["slo_frames_observed"] > 0, d
    assert d["fingerprint"]["jax_backend"] == "unprobed"
    # the devtel plane's off-mode contract (ISSUE 10 acceptance: ≤1.05 on
    # an uncontended box; this CI fence is loose the same way — it
    # catches allocation/locking landing back on the DEVTEL_ENABLE=0
    # hook path, a multi-x blowup, not a few percent)
    dt = by_metric["devtel_off_overhead_ratio"]
    assert "error" not in dt, dt
    assert 0 < dt["value"] <= 1.5, dt
    assert dt["devtel_off_overhead_us_per_frame"] < 25.0, dt
    # the on-leg actually counted every hook (2 per frame x frames x reps)
    assert dt["devtel_transfers_counted"] > 0, dt
    # the fleet journey plane's off-mode contract (ISSUE 13: the
    # JOURNEY_ENABLE=0 note() residue is one attribute read — same loose
    # CI fence, same multi-x failure mode it exists to catch)
    jt = by_metric["journey_off_overhead_ratio"]
    assert "error" not in jt, jt
    assert 0 < jt["value"] <= 1.5, jt
    assert jt["journey_off_overhead_us_per_frame"] < 25.0, jt
    # the on-leg actually recorded into the bounded ring
    assert jt["journey_events_counted"] > 0, jt
    # banked: all THREE entries landed in the log
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert {b["metric"] for b in banked[-3:]} == {
        "trace_off_overhead_ratio", "devtel_off_overhead_ratio",
        "journey_off_overhead_ratio",
    }


def test_unet_cache_prefix_validated():
    """advisor r3: 'foo:3' must not parse as a valid UNET_CACHE spelling."""
    import pytest

    from ai_rtc_agent_tpu.models import registry

    import os
    os.environ["UNET_CACHE"] = "foo:3"
    try:
        with pytest.raises(ValueError, match="deepcache"):
            registry.default_stream_config("tiny-test")
    finally:
        del os.environ["UNET_CACHE"]
    os.environ["UNET_CACHE"] = "deepcache:3"
    try:
        assert registry.default_stream_config("tiny-test").unet_cache_interval == 3
    finally:
        del os.environ["UNET_CACHE"]


def test_bench_child_timeout_scales_with_config(monkeypatch):
    """advisor r3: heavy configs get a bigger default child budget."""
    import sys

    import bench

    monkeypatch.delenv("BENCH_CHILD_TIMEOUT_S", raising=False)
    captured = {}

    class _P:
        returncode = 0

        def communicate(self, timeout=None):
            captured["tmo"] = timeout
            return '{"ok": true}', ""

    # _run_measurement_child imports subprocess locally — patch via module
    import subprocess as _sp

    monkeypatch.setattr(_sp, "Popen", lambda *a, **k: _P())
    monkeypatch.setattr(
        sys, "argv", ["bench.py", "--config", "x", "--frames", "3"]
    )
    for cfg, expect in [("turbo512", 1500), ("sdxl1024", 3600)]:
        bench._run_measurement_child({}, config=cfg)
        assert captured["tmo"] == expect, (cfg, captured["tmo"])


def test_clear_watcher_pause_removes_file(tmp_path):
    """advisor r3: a one-off bench pauses (not kills) the watcher — the
    pause file must be reaped in the bench's finally."""
    import bench

    import os as _os

    stop = tmp_path / "stopfile"
    stop.write_text(f"pause {_os.getpid()} test\n")
    bench._PAUSED_WATCHER_STOPFILE = str(stop)
    bench._clear_watcher_pause()
    assert not stop.exists()
    assert bench._PAUSED_WATCHER_STOPFILE is None
    bench._clear_watcher_pause()  # idempotent

    # someone else's pause (or a manual stop) is NEVER reaped by us
    stop.write_text("pause 999999 other bench\n")
    bench._PAUSED_WATCHER_STOPFILE = str(stop)
    bench._clear_watcher_pause()
    assert stop.exists()


def test_watcher_check_stop_protocol(tmp_path):
    """The shell side: 'pause <dead-pid>' reaps and resumes; a manual stop
    file exits."""
    import subprocess

    harness = r'''
STOP="$1"
LOG=/dev/null
note() { :; }
'''
    # extract check_stop from the watcher script verbatim so the test pins
    # the real code
    src = open("scripts/tpu_watch.sh").read()
    start = src.index("check_stop() {")
    end = src.index("\n}", start) + 2
    harness += src[start:end] + "\ncheck_stop\necho RESUMED\n"

    stop = tmp_path / "stop"
    # dead pid -> reap and resume
    stop.write_text("pause 999999 bench\n")
    out = subprocess.run(
        ["bash", "-c", harness, "bash", str(stop)],
        capture_output=True, text=True, timeout=30,
    )
    assert "RESUMED" in out.stdout
    assert not stop.exists()
    # manual stop -> exit without resuming
    stop.write_text("manual stop\n")
    out = subprocess.run(
        ["bash", "-c", harness, "bash", str(stop)],
        capture_output=True, text=True, timeout=30,
    )
    assert "RESUMED" not in out.stdout


def test_unreachable_backend_falls_back_to_cpu_entry(tmp_path):
    """VERDICT r4 item 3: with NO TPU entry banked, a committed CPU-backend
    measurement must replay (clearly labeled backend:"cpu", live:false)
    rather than emitting value 0.0 with an error object — and a TPU entry,
    when present, must always win over it."""
    log = tmp_path / "PERF_LOG.jsonl"
    cpu_entry = {
        "metric": "e2e_fps_turbo512_singlechip", "value": 0.9, "unit": "fps",
        "vs_baseline": 0.03, "backend": "cpu", "label": "turbo512_cpu",
        "recorded_at": "2026-08-01T05:00:00+00:00",
    }
    log.write_text(json.dumps(cpu_entry) + "\n")
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": str(log)}
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 0.9 and d["backend"] == "cpu"
    assert d["live"] is False
    assert "unreachable" in d["live_attempt"]["error"]
    # TPU tier still wins when present
    tpu_entry = dict(cpu_entry, backend="tpu", value=31.4, vs_baseline=1.047)
    log.write_text(json.dumps(cpu_entry) + "\n" + json.dumps(tpu_entry) + "\n")
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": str(log)}
    )
    assert r.returncode == 0, r.stderr[-800:]
    d = _contract_line(r.stdout)
    assert d["value"] == 31.4 and d["backend"] == "tpu"


@pytest.mark.slow
def test_batch_scheduler_bench_contract(tmp_path):
    """Batch-scheduler amortization microbench smoke (ISSUE 7): emits
    exactly one contract line, BANKS it, and batching must not be SLOWER
    than serializing sessions through the shared engine.  Runs at 2
    sessions (half the bucket compiles); `slow` tier — ISSUE 7's budget
    satellite trades this ~30s of compiles for tier-1 headroom (the
    scheduler itself is tier-1-covered by tests/test_batch_scheduler.py,
    and the committed 4-session PERF_LOG line carries the ≥1.5x / ≤5%
    acceptance numbers).  What this fence catches is a scheduler
    regression that makes coalescing a pessimization."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "PERF_LOG_PATH": str(log),
            "BATCHSCHED_BENCH_FRAMES": "6",
            "BATCHSCHED_BENCH_PAIRS": "4",
            "BATCHSCHED_BENCH_SESSIONS": "2",
            "JAX_PLATFORMS": "cpu",
        }
    )
    r = subprocess.run(
        [sys.executable, "scripts/batch_scheduler_bench.py"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert d["metric"] == "batchsched_amortization_2s"
    assert d["sessions"] == 2
    # pessimization fences with headroom for a contended 1-core CI box
    # (at 2 sessions with tiny reps the median ratio wobbles around ~1.2;
    # a real regression that makes coalescing slower reads ~0.5): the
    # committed PERF_LOG line carries the real 4-session ≥1.5x / ≤5%
    assert d["value"] >= 0.8, d
    assert d["single_session_overhead_pct"] <= 40.0, d
    # full fingerprint: this bench initializes jax for the measurement
    assert d["fingerprint"]["jax_backend"] == "cpu"
    assert d["fingerprint"]["device_count"] >= 1
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "batchsched_amortization_2s"


@pytest.mark.slow
def test_adapter_bench_contract(tmp_path):
    """Per-session style adapter bench smoke (ISSUE 20): emits exactly
    one contract line with the NxN metric + bank-rank/swap labels and
    BANKS it, and the factor-bank path must not be grossly slower than
    the fused dedicated engines it replaces.  Runs at 2x2 (half the
    compiles — two fused engines + one 2-slot prewarm); `slow` tier like
    its batchsched sibling; the committed 4x4 PERF_LOG line carries the
    acceptance trajectory."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "PERF_LOG_PATH": str(log),
            "ADAPTER_BENCH_FRAMES": "6",
            "ADAPTER_BENCH_PAIRS": "4",
            "ADAPTER_BENCH_SESSIONS": "2",
            "JAX_PLATFORMS": "cpu",
        }
    )
    r = subprocess.run(
        [sys.executable, "scripts/adapter_bench.py"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert d["metric"] == "adapter_amortization_2x2"
    assert d["sessions"] == 2 and d["adapters"] == 2
    assert d["bank_rank"] == 4
    # pessimization fence with contended-box headroom: the factors path
    # collapsing (per-frame graft retraces, bank copies) reads ~0.3
    assert d["value"] >= 0.7, d
    # a hot-swap is one same-shaped bank write — never an engine build
    assert d["adapter_swap_ms"] < 500.0, d
    assert d["fingerprint"]["jax_backend"] == "cpu"
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "adapter_amortization_2x2"


@pytest.mark.slow
def test_mesh_sched_bench_contract(tmp_path):
    """Mesh-sharded scheduler amortization smoke (ISSUE 12): emits
    exactly one contract line with the dp/session labels + fingerprint
    and BANKS it.  Runs at dp=2 (two virtual devices — two bucket
    prewarms per scheduler instead of eight); `slow` tier like its
    batchsched sibling.  No ratio floor on this 2-core box: virtual
    devices oversubscribe the host so the honest CPU value is <1 (the
    committed dp8 PERF_LOG row + perf_compare fence carry the
    trajectory; the TPU watcher row is the accelerator truth) — what
    this smoke pins is the contract shape and that the sharded path
    serves at all under the bench harness."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.pop("XLA_FLAGS", None)  # the bench forces its own device flag
    env.update(
        {
            "PERF_LOG_PATH": str(log),
            "MESHSCHED_BENCH_FRAMES": "4",
            "MESHSCHED_BENCH_PAIRS": "3",
            "MESHSCHED_BENCH_SESSIONS": "2",
            "JAX_PLATFORMS": "cpu",
        }
    )
    r = subprocess.run(
        [sys.executable, "scripts/mesh_sched_bench.py"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert d["metric"] == "meshsched_amortization_dp2"
    assert d["sessions"] == 2 and d["dp"] == 2
    assert d["value"] > 0, d
    assert d["fingerprint"]["jax_backend"] == "cpu"
    assert d["fingerprint"]["device_count"] == 2
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "meshsched_amortization_dp2"


# -- perf_compare.py: the trajectory fence (ISSUE 8) -------------------------

def _perf_compare(args, timeout=60):
    return subprocess.run(
        [sys.executable, "scripts/perf_compare.py", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


def _write_jsonl(path, entries):
    path.write_text("".join(json.dumps(e) + "\n" for e in entries))


def test_perf_compare_passes_within_fence_and_fails_regression(tmp_path):
    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "e2e_fps_turbo512_singlechip", "value": 30.0,
         "unit": "fps", "backend": "tpu", "live": True,
         "recorded_at": "2026-08-01T00:00:00+00:00"},
    ])
    # within tolerance (and improvements always pass)
    _write_jsonl(fresh, [
        {"metric": "e2e_fps_turbo512_singlechip", "value": 28.0,
         "unit": "fps", "backend": "tpu"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
    # a real regression (beyond the default 35% fence) fails the run
    _write_jsonl(fresh, [
        {"metric": "e2e_fps_turbo512_singlechip", "value": 10.0,
         "unit": "fps", "backend": "tpu"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout


def test_perf_compare_direction_and_per_metric_tolerance(tmp_path):
    """Overhead ratios are lower-is-better: a RISE past the fence fails;
    per-metric tolerance overrides tighten the default."""
    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "trace_off_overhead_ratio", "value": 1.06, "unit": "x",
         "backend": "cpu", "live": True},
    ])
    _write_jsonl(fresh, [
        {"metric": "trace_off_overhead_ratio", "value": 1.30, "unit": "x",
         "backend": "cpu"},
    ])
    # 1.30 vs banked 1.06: inside the loose default fence...
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout
    # ...but outside a tightened 10% per-metric fence
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked),
                       "--tolerance-metric",
                       "trace_off_overhead_ratio=0.1"])
    assert r.returncode == 1, r.stdout
    # and a LOWER ratio (improvement) always passes
    _write_jsonl(fresh, [
        {"metric": "trace_off_overhead_ratio", "value": 0.95, "unit": "x",
         "backend": "cpu"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked),
                       "--tolerance-metric",
                       "trace_off_overhead_ratio=0.1"])
    assert r.returncode == 0, r.stdout


def test_perf_compare_share_metrics_are_lower_better(tmp_path):
    """secure_core_share_at_rate's acceptance bound is '< 0.05 core' —
    a cost metric: a 10x core-share blowup must FAIL and a halving must
    pass (the heuristic must not silently invert the fence; explicit
    --higher-better can still force the other reading)."""
    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "secure_core_share_at_rate", "value": 0.0118,
         "unit": "core_frac", "backend": "cpu", "live": True},
    ])
    _write_jsonl(fresh, [
        {"metric": "secure_core_share_at_rate", "value": 0.118,
         "unit": "core_frac", "backend": "cpu"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout
    _write_jsonl(fresh, [
        {"metric": "secure_core_share_at_rate", "value": 0.006,
         "unit": "core_frac", "backend": "cpu"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout
    # explicit overrides beat the heuristic for future metric names
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked),
                       "--higher-better", "secure_core_share_at_rate"])
    assert r.returncode == 1, r.stdout


def test_perf_compare_hardware_tier_isolation(tmp_path):
    """A CPU fresh run must NOT be fenced against a TPU banked number
    (no-trajectory; --strict makes that a failure), and fingerprinted
    entries must also match on device kind."""
    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "e2e_fps_turbo512_singlechip", "value": 30.0,
         "unit": "fps", "backend": "tpu", "live": True},
    ])
    _write_jsonl(fresh, [
        {"metric": "e2e_fps_turbo512_singlechip", "value": 0.04,
         "unit": "fps", "backend": "cpu"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0 and "NO-TRAJECTORY" in r.stdout
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked),
                       "--strict"])
    assert r.returncode == 1
    # same backend, different silicon: fingerprints keep them apart
    _write_jsonl(banked, [
        {"metric": "m", "value": 30.0, "backend": "tpu", "live": True,
         "fingerprint": {"device_kind": "TPU v5e"}},
    ])
    _write_jsonl(fresh, [
        {"metric": "m", "value": 1.0, "backend": "tpu",
         "fingerprint": {"device_kind": "TPU v2"}},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0 and "NO-TRAJECTORY" in r.stdout


def test_perf_compare_skips_replays_and_failed_runs(tmp_path):
    """live:false replay lines must never become their own baseline, and
    a failed fresh run (value 0 + error) always fails the fence."""
    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "m", "value": 50.0, "backend": "tpu", "live": False},
        {"metric": "m", "value": 30.0, "backend": "tpu", "live": True},
        {"metric": "m", "value": 0.0, "backend": "tpu",
         "error": "it died"},
    ])
    _write_jsonl(fresh, [{"metric": "m", "value": 29.0, "backend": "tpu"}])
    # fenced against the live 30.0, not the replayed 50.0 or the failure
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked),
                       "--tolerance", "0.1"])
    assert r.returncode == 0, r.stdout
    _write_jsonl(fresh, [
        {"metric": "m", "value": 0.0, "backend": "tpu",
         "error": "unreachable"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "FRESH-RUN-FAILED" in r.stdout


@pytest.mark.slow
def test_device_path_bench_contract(tmp_path):
    """Device-path microbench smoke (ISSUE 9): emits exactly one contract
    line per leg (overlap + readback isolation), BANKS both, and holds the
    loose fences — a regression that makes per-slot fetch resolve time
    scale with batch occupancy again (the whole-batch host copy) reads as
    a ~4x isolation ratio; what the fence tolerates is CI-box noise.
    `slow` tier like the batch-scheduler smoke (two tiny-model compiles +
    the bucket prewarm)."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "PERF_LOG_PATH": str(log),
            "DEVPATH_BENCH_FRAMES": "8",
            "DEVPATH_BENCH_PAIRS": "4",
            "JAX_PLATFORMS": "cpu",
        }
    )
    r = subprocess.run(
        [sys.executable, "scripts/device_path_bench.py"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 2, r.stdout
    by_metric = {}
    for ln in lines:
        d = json.loads(ln)
        for k in ("metric", "value", "unit", "vs_baseline"):
            assert k in d, d
        assert "error" not in d, d
        by_metric[d["metric"]] = d
    assert set(by_metric) == {
        "pipelined_overlap_speedup_d4", "batchsched_fetch_isolation_ratio_4s",
    }
    iso = by_metric["batchsched_fetch_isolation_ratio_4s"]
    # isolation: the mean per-slot fetch must NOT scale ~4x with occupancy
    # (whole-batch readback); headroom for a contended 1-core CI box
    assert 0 < iso["value"] <= 2.0, iso
    assert iso["sessions"] == 4
    assert iso["fetch_mean_ms_1s"] > 0 and iso["fetch_mean_ms_4s"] > 0
    ov = by_metric["pipelined_overlap_speedup_d4"]
    # overlap: pure-CPU has no RTT to hide — the fence catches the path
    # actively SERIALIZING (thread-pool fetches blocked behind a lock)
    assert ov["value"] >= 0.4, ov
    assert ov["fingerprint"]["jax_backend"] == "cpu"
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert {b["metric"] for b in banked} == set(by_metric)


def _perf_compare_main():
    """scripts/perf_compare.py as an importable module (one load): the
    new-leg tests below call its main() in-process — same code path as
    the CLI, minus ~1s of interpreter+import per invocation (tier-1
    budget; the subprocess surface itself is pinned by the older
    perf_compare tests above)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "perf_compare_inproc", os.path.join(REPO, "scripts", "perf_compare.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_perf_compare_knows_device_path_legs(tmp_path, capsys):
    """ISSUE 9 satellite: the new leg names ship with built-in
    direction-aware tolerances — the isolation ratio is lower-is-better
    with a 0.5 fence, the overlap speedup higher-is-better with 0.25 —
    without any --tolerance-metric flags."""
    main = _perf_compare_main()

    def _perf_compare(args):
        class R:
            pass

        r = R()
        r.returncode = main(args)
        r.stdout = capsys.readouterr().out
        r.stderr = ""
        return r

    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "batchsched_fetch_isolation_ratio_4s", "value": 1.0,
         "unit": "x", "backend": "cpu", "live": True, "sessions": 4},
        {"metric": "pipelined_overlap_speedup_d4", "value": 1.0,
         "unit": "x", "backend": "cpu", "live": True, "pipeline_depth": 4},
    ])
    # within the built-in fences: ratio may rise to 1.5, speedup may drop
    # to 0.75
    _write_jsonl(fresh, [
        {"metric": "batchsched_fetch_isolation_ratio_4s", "value": 1.45,
         "unit": "x", "backend": "cpu", "sessions": 4},
        {"metric": "pipelined_overlap_speedup_d4", "value": 0.8,
         "unit": "x", "backend": "cpu", "pipeline_depth": 4},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout + r.stderr
    # beyond them: the ratio RISING past 1.5 fails (direction-aware —
    # lower is better), and the speedup cratering fails
    _write_jsonl(fresh, [
        {"metric": "batchsched_fetch_isolation_ratio_4s", "value": 1.8,
         "unit": "x", "backend": "cpu", "sessions": 4},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout
    _write_jsonl(fresh, [
        {"metric": "pipelined_overlap_speedup_d4", "value": 0.6,
         "unit": "x", "backend": "cpu", "pipeline_depth": 4},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout
    # an explicit --tolerance-metric still overrides the built-in default
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked),
                       "--tolerance-metric",
                       "pipelined_overlap_speedup_d4=0.5"])
    assert r.returncode == 0, r.stdout


def test_perf_compare_knows_devtel_leg(tmp_path, capsys):
    """ISSUE 10 satellite: the devtel off-mode ratio ships with a
    built-in lower-is-better fence (0.35) — a fresh run past it fails
    with no --tolerance-metric flags."""
    main = _perf_compare_main()

    def _perf_compare(args):
        class R:
            pass

        r = R()
        r.returncode = main(args)
        r.stdout = capsys.readouterr().out
        r.stderr = ""
        return r

    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "devtel_off_overhead_ratio", "value": 1.0, "unit": "x",
         "backend": "cpu", "live": True, "label": "trace_overhead_2000f"},
    ])
    _write_jsonl(fresh, [
        {"metric": "devtel_off_overhead_ratio", "value": 1.3, "unit": "x",
         "backend": "cpu", "label": "trace_overhead_2000f"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout + r.stderr
    _write_jsonl(fresh, [
        {"metric": "devtel_off_overhead_ratio", "value": 1.4, "unit": "x",
         "backend": "cpu", "label": "trace_overhead_2000f"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout


def test_perf_compare_knows_journey_leg(tmp_path, capsys):
    """ISSUE 13 satellite: the journey-ring off-mode ratio ships with a
    built-in lower-is-better fence (0.35) — a fresh run past it fails
    with no --tolerance-metric flags."""
    main = _perf_compare_main()

    def _perf_compare(args):
        class R:
            pass

        r = R()
        r.returncode = main(args)
        r.stdout = capsys.readouterr().out
        r.stderr = ""
        return r

    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "journey_off_overhead_ratio", "value": 1.0, "unit": "x",
         "backend": "cpu", "live": True, "label": "trace_overhead_2000f"},
    ])
    _write_jsonl(fresh, [
        {"metric": "journey_off_overhead_ratio", "value": 1.3, "unit": "x",
         "backend": "cpu", "label": "trace_overhead_2000f"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout + r.stderr
    _write_jsonl(fresh, [
        {"metric": "journey_off_overhead_ratio", "value": 1.4, "unit": "x",
         "backend": "cpu", "label": "trace_overhead_2000f"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout


def test_variant_fields_fence_separately(tmp_path, capsys):
    """ISSUE 9 satellite: a quantized / cached-cadence contract line must
    never fence against (or replay as) the dense baseline — the
    quant/unet_cache fields are part of the same-config predicate."""
    main = _perf_compare_main()

    def _perf_compare(args):
        class R:
            pass

        r = R()
        r.returncode = main(args)
        r.stdout = capsys.readouterr().out
        r.stderr = ""
        return r

    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "batchsched_amortization_4s", "value": 1.7, "unit": "x",
         "backend": "cpu", "live": True, "sessions": 4},
    ])
    # a w8-quantized fresh line: NO trajectory against the dense entry
    _write_jsonl(fresh, [
        {"metric": "batchsched_amortization_4s", "value": 0.2, "unit": "x",
         "backend": "cpu", "sessions": 4, "quant": "w8"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0 and "NO-TRAJECTORY" in r.stdout, r.stdout
    # same for a DeepCache cadence line
    _write_jsonl(fresh, [
        {"metric": "batchsched_amortization_4s", "value": 0.2, "unit": "x",
         "backend": "cpu", "sessions": 4, "unet_cache": 3},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0 and "NO-TRAJECTORY" in r.stdout, r.stdout
    # dense-vs-dense still fences
    _write_jsonl(fresh, [
        {"metric": "batchsched_amortization_4s", "value": 0.2, "unit": "x",
         "backend": "cpu", "sessions": 4},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout


def test_unet_cache_env_labels_contract_line(monkeypatch):
    """ISSUE 9 satellite: the DeepCache cadence can arrive via the
    UNET_CACHE env (registry honors it) — the contract line must carry
    the unet_cache field even on the no-measurement failure path, so a
    cached-cadence record can never replay as the dense baseline.  The
    spelling parser is pinned in-process; ONE subprocess run pins the
    end-to-end labeling (tier-1 budget)."""
    import bench

    for spelling, want in (
        ("3", 3), ("deepcache:5", 5), ("0", 0), ("", 0), ("junk", 0),
    ):
        monkeypatch.setenv("UNET_CACHE", spelling)
        assert bench.env_unet_cache() == want, spelling
    monkeypatch.delenv("UNET_CACHE")
    r = _run_bench(
        {"JAX_PLATFORMS": "bogus-platform", "PERF_LOG_PATH": os.devnull,
         "UNET_CACHE": "deepcache:3"},
    )
    assert r.returncode == 0, r.stderr[-400:]
    assert _contract_line(r.stdout)["unet_cache"] == 3


# -- scripts/fleet_bench.py: the fleet router hop (ISSUE 11) -----------------

def test_fleet_bench_contract(tmp_path):
    """Fleet-router placement-overhead microbench smoke (ISSUE 11): pure
    host (never imports jax), emits exactly one contract line, BANKS it,
    and the added /offer p50 stays in single-digit-milliseconds territory
    even on a contended CI box.  The committed PERF_LOG line carries the
    real number (~1.3ms on this box); what this fence catches is the hop
    going pathological (tens of ms = per-request scans or body churn)."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update({
        "PERF_LOG_PATH": str(log),
        "FLEET_BENCH_OFFERS": "20",
    })
    r = subprocess.run(
        [sys.executable, "scripts/fleet_bench.py"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert d["metric"] == "fleet_router_offer_overhead_ms"
    assert d["offers"] == 20
    # pure-host bench: the fingerprint must say jax never entered
    assert d["fingerprint"]["jax_backend"] == "unprobed"
    assert 0 < d["value"] < 50.0, d
    assert d["routed_p50_ms"] > 0 and d["direct_p50_ms"] > 0
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "fleet_router_offer_overhead_ms"


def test_perf_compare_knows_fleet_leg(tmp_path, capsys):
    """ISSUE 11 satellite: the fleet router hop ships with a built-in
    lower-is-better fence (1.0 = up to 2x the banked ms) — a fresh run
    past it fails with no --tolerance-metric flags."""
    main = _perf_compare_main()

    def _perf_compare(args):
        class R:
            pass

        r = R()
        r.returncode = main(args)
        r.stdout = capsys.readouterr().out
        r.stderr = ""
        return r

    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "fleet_router_offer_overhead_ms", "value": 1.3,
         "unit": "ms", "backend": "host", "live": True,
         "label": "fleet_router_60o"},
    ])
    _write_jsonl(fresh, [
        {"metric": "fleet_router_offer_overhead_ms", "value": 2.5,
         "unit": "ms", "backend": "host", "label": "fleet_router_60o"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout + r.stderr
    _write_jsonl(fresh, [
        {"metric": "fleet_router_offer_overhead_ms", "value": 2.7,
         "unit": "ms", "backend": "host", "label": "fleet_router_60o"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout


# -- scripts/upgrade_bench.py: the rolling-upgrade move window (ISSUE 16) ----

def test_upgrade_bench_contract(tmp_path):
    """Upgrade session-move microbench smoke (ISSUE 16): pure host (never
    imports jax), a REAL upgrade sweep moves every session between two
    loopback agents, emits exactly one contract line, BANKS it, and the
    per-session export-to-re-point p50 stays in single-digit-to-tens-of-
    milliseconds territory even on a contended CI box.  The committed
    PERF_LOG line carries the real number (~2.6ms on this box); what this
    fence catches is the move window going pathological (snapshot
    re-copies, serialized sweeps = hundreds of ms)."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update({
        "PERF_LOG_PATH": str(log),
        "UPGRADE_BENCH_SESSIONS": "4",
    })
    r = subprocess.run(
        [sys.executable, "scripts/upgrade_bench.py"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert d["metric"] == "upgrade_session_move_ms"
    assert d["sessions"] == 4
    # pure-host bench: the fingerprint must say jax never entered
    assert d["fingerprint"]["jax_backend"] == "unprobed"
    assert 0 < d["value"] < 100.0, d
    assert d["move_p99_ms"] >= d["value"]
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "upgrade_session_move_ms"


def test_perf_compare_knows_upgrade_leg(tmp_path, capsys):
    """ISSUE 16 satellite: the upgrade move window ships with a built-in
    lower-is-better fence (1.0 = up to 2x the banked ms) — a fresh run
    past it fails with no --tolerance-metric flags."""
    main = _perf_compare_main()

    def _perf_compare(args):
        class R:
            pass

        r = R()
        r.returncode = main(args)
        r.stdout = capsys.readouterr().out
        r.stderr = ""
        return r

    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "upgrade_session_move_ms", "value": 2.6,
         "unit": "ms", "backend": "host", "live": True,
         "label": "upgrade_move_8s"},
    ])
    _write_jsonl(fresh, [
        {"metric": "upgrade_session_move_ms", "value": 5.0,
         "unit": "ms", "backend": "host", "label": "upgrade_move_8s"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout + r.stderr
    _write_jsonl(fresh, [
        {"metric": "upgrade_session_move_ms", "value": 5.5,
         "unit": "ms", "backend": "host", "label": "upgrade_move_8s"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout


# -- scripts/engine_recovery_bench.py: the fault-domain windows (ISSUE 19) ---

def test_engine_recovery_bench_evacuate_contract(tmp_path):
    """Evacuation-move microbench smoke (ISSUE 19): pure host (never
    imports jax), a REAL /fleet/evacuate sweep moves every session
    between two loopback agents, emits exactly one contract line, BANKS
    it, and the per-session export-to-re-point p50 stays in
    single-digit-to-tens-of-milliseconds territory on a contended CI
    box.  The rebuild leg (real scheduler + recompile) rides the slow
    tier below."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update({
        "PERF_LOG_PATH": str(log),
        "ENGINE_BENCH_SESSIONS": "4",
    })
    r = subprocess.run(
        [sys.executable, "scripts/engine_recovery_bench.py",
         "--leg", "evacuate"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, d
    assert "error" not in d, d
    assert d["metric"] == "evacuation_session_move_ms"
    assert d["sessions"] == 4
    # host leg: the fingerprint must say jax never entered
    assert d["fingerprint"]["jax_backend"] == "unprobed"
    assert 0 < d["value"] < 100.0, d
    assert d["move_p99_ms"] >= d["value"]
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "evacuation_session_move_ms"


@pytest.mark.slow
def test_engine_recovery_bench_rebuild_contract(tmp_path):
    """Rebuild-leg smoke (ISSUE 19): a REAL trip/quarantine/rebuild cycle
    on the tiny scheduler — the contract line carries the jax backend
    (the TPU watcher row replays this leg on hardware) and the sample
    includes the re-prewarm compile (`slow` tier: two of them)."""
    log = tmp_path / "PERF_LOG.jsonl"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.update({
        "PERF_LOG_PATH": str(log),
        "ENGINE_BENCH_REBUILDS": "1",
        "JAX_PLATFORMS": "cpu",
    })
    r = subprocess.run(
        [sys.executable, "scripts/engine_recovery_bench.py",
         "--leg", "rebuild"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    assert "error" not in d, d
    assert d["metric"] == "engine_rebuild_ms"
    assert d["trips"] == 1 and d["rebuilds"] == 1
    assert d["backend"] == "cpu"
    assert d["fingerprint"]["jax_backend"] == "cpu"
    assert d["value"] > 0, d
    assert d["rebuild_p99_ms"] >= d["value"]
    banked = [json.loads(x) for x in log.read_text().splitlines()]
    assert banked and banked[-1]["metric"] == "engine_rebuild_ms"


def test_perf_compare_knows_engine_recovery_legs(tmp_path, capsys):
    """ISSUE 19 satellite: both fault-domain windows ship with built-in
    lower-is-better fences (1.0 = up to 2x the banked ms) — a fresh run
    past either fails with no --tolerance-metric flags."""
    main = _perf_compare_main()

    def _perf_compare(args):
        class R:
            pass

        r = R()
        r.returncode = main(args)
        r.stdout = capsys.readouterr().out
        r.stderr = ""
        return r

    banked = tmp_path / "banked.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    _write_jsonl(banked, [
        {"metric": "engine_rebuild_ms", "value": 16000.0,
         "unit": "ms", "backend": "cpu", "live": True,
         "label": "engine_rebuild_3x"},
        {"metric": "evacuation_session_move_ms", "value": 7.0,
         "unit": "ms", "backend": "host", "live": True,
         "label": "evacuation_move_8s"},
    ])
    _write_jsonl(fresh, [
        {"metric": "engine_rebuild_ms", "value": 30000.0,
         "unit": "ms", "backend": "cpu", "label": "engine_rebuild_3x"},
        {"metric": "evacuation_session_move_ms", "value": 13.0,
         "unit": "ms", "backend": "host", "label": "evacuation_move_8s"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 0, r.stdout + r.stderr
    _write_jsonl(fresh, [
        {"metric": "engine_rebuild_ms", "value": 33000.0,
         "unit": "ms", "backend": "cpu", "label": "engine_rebuild_3x"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout
    _write_jsonl(fresh, [
        {"metric": "evacuation_session_move_ms", "value": 14.5,
         "unit": "ms", "backend": "host", "label": "evacuation_move_8s"},
    ])
    r = _perf_compare(["--fresh", str(fresh), "--log", str(banked)])
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout
