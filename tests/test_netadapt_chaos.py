"""Network-adaptation chaos (hermetic, tier-1): a scripted ``loss_burst``
fault plan drives a live loopback session's network rung up — encoder
bitrate steps down, resolution reduces, a frame-skip floor lands on the
compute ladder, keyframe cadence throttles — while freshness stays inside
the overload deadline (quality degrades, never freshness), and the whole
ride unwinds to normal once the loss clears.  A dual-pressure test pins
the join: the *effective* session rung is the max of compute and network
pressure.

The loss path is the real machinery end to end: RTP-shaped packets
through the seeded fault scope (resilience/faults.py ``loss_burst``) into
RFC 3550 reception accounting (media/rtcp.py ``ReceiverStats``), report
blocks over the actual RR wire format (``make_rr``/``parse_compound``),
into the session's :class:`NetworkAdaptLadder`.  Only the UDP socket is
elided — every byte format and counter in between is production code.
"""

import asyncio
import struct
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.media import rtcp
from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.resilience import faults
from ai_rtc_agent_tpu.resilience.faults import FaultPlan, FaultSpec
from ai_rtc_agent_tpu.resilience.netadapt import (
    NET_RUNG_KEYFRAME_THROTTLE,
    NET_RUNG_RAISE_FRAME_SKIP,
    KeyframeGovernor,
)
from ai_rtc_agent_tpu.resilience.overload import RUNG_PASSTHROUGH
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.signaling import (
    LoopbackProvider,
    make_loopback_offer,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.deactivate()
    yield
    faults.deactivate()


class InvertPipeline:
    def __init__(self):
        self.calls = 0

    def __call__(self, frame):
        self.calls += 1
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def restart(self):
        pass


class LossyViewer:
    """Simulated viewer downlink: our RTP through the scripted fault link
    into RFC 3550 accounting; RRs come back over the real wire format."""

    MEDIA_SSRC = 0x0ABC

    def __init__(self):
        self.scope = faults.scope("rx")
        self.stats = rtcp.ReceiverStats()
        self.seq = 0

    def clear_link(self):
        self.scope = None

    def carry(self, n: int):
        for _ in range(n):
            pkt = (
                struct.pack(
                    "!BBHII", 0x80, 96, self.seq & 0xFFFF,
                    (self.seq * 3000) & 0xFFFFFFFF, self.MEDIA_SSRC,
                )
                + b"x" * 16
            )
            self.seq += 1
            outs = (
                self.scope.apply(pkt) if self.scope is not None else [(pkt, 0.0)]
            )
            for d, _delay in outs:
                self.stats.received(d)

    def report_block(self) -> dict:
        blk = self.stats.report_block()
        rr = rtcp.make_rr(
            0x9999,
            media_ssrc=blk["ssrc"],
            fraction_lost=blk["fraction_lost"],
            cumulative_lost=blk["cumulative_lost"],
            highest_seq=blk["highest_seq"],
            jitter=blk["jitter"],
        )
        (item,) = [i for i in rtcp.parse_compound(rr) if i["type"] == "rr"]
        return item["blocks"][0]


def _netadapt_env(monkeypatch):
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("SUPERVISOR_STALL_AFTER_S", "30")
    monkeypatch.setenv("OVERLOAD_TICK_S", "0.05")
    monkeypatch.setenv("OVERLOAD_FRAME_DEADLINE_MS", "300")
    monkeypatch.setenv("NETADAPT_UP_TICKS", "2")
    monkeypatch.setenv("NETADAPT_DOWN_TICKS", "2")
    monkeypatch.setenv("NETADAPT_RR_TIMEOUT_S", "30")
    monkeypatch.setenv("ENC_DEFAULT_BITRATE", "3000000")


def _offer_body(room="netadapt"):
    return {
        "room_id": room,
        "offer": {"sdp": make_loopback_offer(), "type": "offer"},
    }


def test_loss_burst_rides_the_quality_ladder_and_unwinds(monkeypatch):
    _netadapt_env(monkeypatch)
    # 50% sustained loss, deterministic duty cycle, unbounded window —
    # the episode "clears" when the viewer's link drops the fault scope
    faults.activate(
        FaultPlan(
            specs=(
                FaultSpec(target="rx", kind="loss_burst", period=10, burst=5),
            ),
            seed=6,
        )
    )
    pipe = InvertPipeline()

    async def go():
        app = build_app(pipeline=pipe, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 200
            pc = next(iter(app["pcs"]))
            viewer_track = pc.out_tracks[0]
            (key,) = app["supervisors"].keys()
            ov = app["overload"]
            ladder = ov.ladders[key]
            na = ov.netadapt[key]
            profiles = []
            na.apply = profiles.append

            alive = True
            delivered = 0

            async def producer():
                i = 0
                while alive:
                    f = VideoFrame.from_ndarray(
                        np.full((8, 8, 3), i % 200, np.uint8)
                    )
                    f.wall_ts = time.monotonic()
                    await pc.in_track.push(f)
                    i += 1
                    await asyncio.sleep(0.01)

            async def consumer():
                nonlocal delivered
                while alive:
                    await asyncio.wait_for(viewer_track.recv(), timeout=5.0)
                    delivered += 1

            tasks = [
                asyncio.ensure_future(producer()),
                asyncio.ensure_future(consumer()),
            ]
            link = LossyViewer()

            # --- phase 1: the burst.  RRs report ~50% loss; the network
            # rung must climb to the top within the hysteresis window.
            deadline = time.monotonic() + 20.0
            while (
                time.monotonic() < deadline
                and na.rung < NET_RUNG_KEYFRAME_THROTTLE
            ):
                link.carry(40)
                na.on_receiver_report(link.report_block())
                await asyncio.sleep(0.05)
            assert na.rung == NET_RUNG_KEYFRAME_THROTTLE, (
                f"never saturated (rung={na.rung}, "
                f"loss={na.loss_ewma.value:.3f})"
            )

            # bitrate stepped DOWN monotonically through the ride
            rates = [p["bitrate"] for p in profiles]
            assert len(rates) >= 4 and rates == sorted(rates, reverse=True)
            assert rates[-1] < 3_000_000
            top = profiles[-1]
            assert top["scale"] == 2  # reduce-resolution engaged
            assert top["keyframe_interval_s"] > 0  # cadence from telemetry
            # keyframe window throttled 4x: a 30-PLI storm costs ONE IDR
            assert top["pli_coalesce_s"] == pytest.approx(4 * na.pli_coalesce_s)
            gov = KeyframeGovernor(coalesce_s=top["pli_coalesce_s"])
            grants = sum(gov.request() for _ in range(30))
            assert grants == 1 and gov.coalesced == 29

            # the join: network pressure imposes a skip FLOOR (skip4) but
            # never passthrough — quality degrades, freshness does not
            assert ladder.net_floor == 2
            assert ladder.rung == 0  # compute side is idle
            assert ladder.effective_rung == 2
            assert RUNG_PASSTHROUGH > ladder.effective_rung

            # frames kept flowing the whole time, comfortably fresh
            m = await (await client.get("/metrics")).json()
            assert delivered > 0
            assert m["overload_freshness_p99_ms"] < 300.0
            assert m["netadapt_rung_max"] == NET_RUNG_KEYFRAME_THROTTLE
            assert m["overload_rung_effective_max"] == 2
            assert m["netadapt_ladder_moves_total"] >= 4
            assert m["netadapt_loss_ewma_max"] > 0.08

            # the ride is on the session's health + black box
            h = await (await client.get("/health")).json()
            snap = h["sessions"][key]
            assert snap["netadapt"]["rung"] == NET_RUNG_KEYFRAME_THROTTLE
            assert snap["effective_rung"] == 2
            rec = app["flight"].session(key)
            kinds = [e["kind"] for e in rec.events]
            assert kinds.count("netadapt_rung") >= 4

            # --- phase 2: the burst clears.  Clean RRs wash the EWMA
            # down; every rung unwinds; full quality comes back.
            link.clear_link()
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and (
                na.rung > 0 or ladder.effective_rung > 0
            ):
                link.carry(40)
                na.on_receiver_report(link.report_block())
                await asyncio.sleep(0.05)
            assert na.rung == 0 and ladder.net_floor == 0
            assert ladder.effective_rung == 0
            assert profiles[-1]["bitrate"] == 3_000_000
            assert profiles[-1]["scale"] == 1
            assert profiles[-1]["keyframe_interval_s"] == 0.0
            m = await (await client.get("/metrics")).json()
            assert m["netadapt_rung_max"] == 0
            alive = False
            for t in tasks:
                t.cancel()
        finally:
            await client.close()

    asyncio.run(go())


def test_dual_pressure_effective_rung_is_max_of_both(monkeypatch):
    """Compute and network pressure at once: the session runs the WORSE of
    the two rungs; either side clearing alone leaves the other's rung in
    force."""
    _netadapt_env(monkeypatch)
    monkeypatch.setenv("OVERLOAD_STEP_BUDGET_MS", "100")
    monkeypatch.setenv("OVERLOAD_UP_TICKS", "2")
    monkeypatch.setenv("OVERLOAD_DOWN_TICKS", "2")
    pipe = InvertPipeline()

    async def go():
        app = build_app(pipeline=pipe, provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json=_offer_body("dual"))
            assert r.status == 200
            (key,) = app["supervisors"].keys()
            ov = app["overload"]
            ladder = ov.ladders[key]
            na = ov.netadapt[key]

            # network side: sustained heavy loss straight into the ladder
            async def pressure_until(pred, feed, deadline_s=15.0):
                deadline = time.monotonic() + deadline_s
                while time.monotonic() < deadline and not pred():
                    feed()
                    await asyncio.sleep(0.05)
                assert pred()

            await pressure_until(
                lambda: na.rung >= NET_RUNG_RAISE_FRAME_SKIP,
                lambda: na.on_receiver_report(
                    {"ssrc": 1, "fraction_lost": 128, "jitter": 50}
                ),
            )
            assert ladder.net_floor >= 1
            floor = ladder.net_floor

            # compute side: step latency over budget walks the compute
            # ladder past the network floor — the max wins
            await pressure_until(
                lambda: ladder.rung >= RUNG_PASSTHROUGH,
                lambda: ov.admission.note_step_latency(1.0),
            )
            assert ladder.effective_rung == ladder.rung >= RUNG_PASSTHROUGH
            assert ladder.effective_rung > floor

            # compute recovers (fast steps), loss persists: the effective
            # rung falls only to the NETWORK floor, not to zero
            await pressure_until(
                lambda: ladder.rung == 0,
                lambda: (
                    ov.admission.note_step_latency(0.001),
                    na.on_receiver_report(
                        {"ssrc": 1, "fraction_lost": 128, "jitter": 50}
                    ),
                ),
                deadline_s=20.0,
            )
            assert na.rung >= NET_RUNG_RAISE_FRAME_SKIP
            assert ladder.effective_rung == ladder.net_floor >= 1

            # loss clears too: everything unwinds
            await pressure_until(
                lambda: na.rung == 0 and ladder.effective_rung == 0,
                lambda: (
                    ov.admission.note_step_latency(0.001),
                    na.on_receiver_report(
                        {"ssrc": 1, "fraction_lost": 0, "jitter": 1}
                    ),
                ),
                deadline_s=20.0,
            )
        finally:
            await client.close()

    asyncio.run(go())
