"""ADVICE round-5 hardening regressions that live above the pure parsers:

* RTP version gate on the native receive socket — stray non-RTP datagrams
  must not wedge ReceiverStats / PLI targeting (rtc_native.py:307).
* Wildcard (media_ssrc=0) PLIs honored on the PLAIN tier only — legacy
  clients keep keyframe recovery; the secure tier stays exact-match
  (rtc_native.py:153, docs/connect.md).
* Duplicate INIT on an established SCTP association answers with the
  existing tag/cookie without resetting TSN state (RFC 9260 s5.2.2,
  sctp.py:406).
"""

import asyncio
import struct

from ai_rtc_agent_tpu.server.rtc_native import _RtcpState, _RtpReceiverProtocol
from ai_rtc_agent_tpu.server.secure.sctp import SctpAssociation


def _rtp(seq, ssrc=0xCAFE, pt=102):
    return struct.pack("!BBHII", 0x80, pt, seq, seq * 3000, ssrc) + b"d"


def _pli(media_ssrc):
    return struct.pack("!BBH", 0x81, 206, 2) + struct.pack("!II", 1, media_ssrc)


class FakeSource:
    def __init__(self):
        self.fed = []

    def depacketize(self, pkt):
        self.fed.append(pkt)
        return []

    def on(self, *a, **k):
        pass


def _proto():
    return _RtpReceiverProtocol(FakeSource(), _RtcpState())


# ---------------------------------------------------------------------------
# RTP version gate
# ---------------------------------------------------------------------------

def test_stray_datagram_does_not_lock_ssrc_or_reach_depacketizer():
    async def go():
        p = _proto()
        # a 16-byte junk probe (version bits 0) arrives FIRST
        junk = b"\x00" * 16
        p.datagram_received(junk, ("10.0.0.9", 5))
        assert p._last_rx_ssrc == 0
        assert p._rtcp_state.recv._base_seq is None
        assert p.source.fed == []
        # then the real publisher: stats lock onto IT, PLIs name IT
        p.datagram_received(_rtp(100), ("10.0.0.1", 4))
        assert p._last_rx_ssrc == 0xCAFE
        assert p._rtcp_state.recv.ssrc == 0xCAFE
        assert len(p.source.fed) == 1
        p.close()

    asyncio.run(go())


def test_relock_updates_pli_target():
    async def go():
        p = _proto()
        # RTP-shaped stray wins the lock first (version bits valid)
        p.datagram_received(_rtp(7, ssrc=0xDEAD), ("10.0.0.9", 5))
        assert p._last_rx_ssrc == 0xDEAD
        # the real stream keeps talking; after the re-lock threshold the
        # PLI target follows the stats onto the live stream
        from ai_rtc_agent_tpu.media.rtcp import ReceiverStats

        for i in range(ReceiverStats.RELOCK_AFTER + 1):
            p.datagram_received(_rtp(200 + i, ssrc=0xCAFE), ("10.0.0.1", 4))
        assert p._last_rx_ssrc == 0xCAFE
        p.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# wildcard PLI: plain tier honors, secure tier stays exact
# ---------------------------------------------------------------------------

def test_plain_tier_honors_wildcard_pli():
    st = _RtcpState()
    assert st.on_rtcp(_pli(0), lambda w: None, allow_wildcard_pli=True) is True


def test_secure_path_ignores_wildcard_pli():
    st = _RtcpState()
    assert st.on_rtcp(_pli(0), lambda w: None) is False
    # exact match still forces the IDR on both tiers
    st2 = _RtcpState()
    assert st2.on_rtcp(_pli(st2.ssrc), lambda w: None) is True


def test_plain_receive_socket_forwards_wildcard_pli():
    async def go():
        plis = []
        p = _RtpReceiverProtocol(
            FakeSource(), _RtcpState(), on_pli=lambda: plis.append(1)
        )
        p.datagram_received(_pli(0), ("10.0.0.2", 6))
        p.close()
        return plis

    assert asyncio.run(go()) == [1]


# ---------------------------------------------------------------------------
# SCTP: duplicate INIT on an established association
# ---------------------------------------------------------------------------

def _establish_pair():
    server = SctpAssociation("server")
    client = SctpAssociation("client")
    inflight = [(server, p) for p in client.start()]
    n = 0
    while inflight and n < 50:
        n += 1
        dst, pkt = inflight.pop(0)
        src = client if dst is server else server
        for reply in dst.handle_packet(pkt):
            inflight.append((src, reply))
    assert server.established and client.established
    return server, client


def test_retransmitted_init_does_not_reset_established_association():
    server, client = _establish_pair()
    peer_tag, cum_in, cookie = server._peer_tag, server._cum_in, server._cookie

    # a duplicate INIT (same shape the client's start() emits) slips through
    dup_init = client._packet(
        client._chunk(1, 0, client._init_params()), vtag=0
    )
    replies = server.handle_packet(dup_init)

    # RFC 9260 s5.2.2: answered with an INIT ACK carrying the EXISTING
    # cookie, association state untouched
    assert server.established
    assert server._peer_tag == peer_tag
    assert server._cum_in == cum_in
    assert server._cookie == cookie
    assert len(replies) == 1
    ctype = replies[0][12]
    assert ctype == 2  # CT_INIT_ACK
    assert cookie in replies[0]

    # and the data path still works end-to-end afterwards
    got = []
    server.on_message = lambda ch, m: got.append(m)
    ch, packets = client.open_channel("config")
    inflight = [(server, p) for p in packets]
    n = 0
    while inflight and n < 50:
        n += 1
        dst, pkt = inflight.pop(0)
        src = client if dst is server else server
        for reply in dst.handle_packet(pkt):
            inflight.append((src, reply))
    for p in client.send(ch.sid, 51, b'{"prompt": "still alive"}'):
        server.handle_packet(p)
    assert got and "still alive" in got[0]


def test_stray_datagram_does_not_redirect_pli_return_address():
    """The PLI return address must only latch onto RTP-shaped (or RTCP)
    datagrams — a junk probe must not become the keyframe-request target
    (code review this PR, extending the r5 version gate)."""

    async def go():
        p = _proto()
        p.datagram_received(_rtp(5), ("10.0.0.1", 4))  # real publisher
        assert p._last_addr == ("10.0.0.1", 4)
        p.datagram_received(b"\x00" * 40, ("6.6.6.6", 666))  # junk probe
        assert p._last_addr == ("10.0.0.1", 4)  # unchanged
        p.close()

    asyncio.run(go())
