"""Prometheus exposition conformance (obs/promexport.py, ISSUE 8).

A strict mini-parser for the text format 0.0.4 validates what a real
scraper would enforce: HELP/TYPE grammar, legal metric names, histogram
buckets cumulative with the ``+Inf`` terminal and ``_count == +Inf``,
``_sum`` present — then the round-trip: every eligible name in the
agent's live ``/metrics`` JSON snapshot appears in the exposition, with
the exact negotiated content-type.
"""

import asyncio
import re

import pytest

from ai_rtc_agent_tpu.obs.promexport import CONTENT_TYPE, labeled, render
from ai_rtc_agent_tpu.obs.slo import SloPlane
from ai_rtc_agent_tpu.obs.trace import STAGES, SessionTracer, TraceController

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$"
)
SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|[+-]Inf|NaN)$"
)
LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def validate_exposition(text: str) -> dict:
    """Parse + conformance-check; returns {family: {"type", "samples"}}
    where samples is [(name, labels-dict, float value)]."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families: dict = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            m = HELP_RE.match(line)
            assert m, f"malformed HELP line: {line!r}"
            continue
        if line.startswith("# TYPE "):
            m = TYPE_RE.match(line)
            assert m, f"malformed TYPE line: {line!r}"
            name, kind = m.groups()
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = {"type": kind, "samples": []}
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        labels = dict(LABEL_RE.findall(labels_raw)) if labels_raw else {}
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in families:
                if families[name[: -len(suffix)]]["type"] == "histogram":
                    base = name[: -len(suffix)]
        assert base in families, f"sample {name} has no TYPE declaration"
        families[base]["samples"].append((name, labels, float(value)))

    # histogram-family invariants
    for fam, info in families.items():
        if info["type"] != "histogram":
            continue
        series: dict = {}
        sums, counts = {}, {}
        for name, labels, value in info["samples"]:
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            if name == f"{fam}_bucket":
                assert "le" in labels, f"{fam} bucket without le"
                series.setdefault(key, []).append((labels["le"], value))
            elif name == f"{fam}_sum":
                sums[key] = value
            elif name == f"{fam}_count":
                counts[key] = value
            else:
                raise AssertionError(f"stray sample {name} in {fam}")
        assert series, f"histogram {fam} has no buckets"
        for key, buckets in series.items():
            les = [le for le, _ in buckets]
            assert les[-1] == "+Inf", f"{fam}{dict(key)} missing +Inf"
            bounds = [float("inf") if le == "+Inf" else float(le)
                      for le in les]
            assert bounds == sorted(bounds), f"{fam} le order"
            values = [v for _, v in buckets]
            assert values == sorted(values), (
                f"{fam}{dict(key)} buckets not cumulative: {values}"
            )
            assert key in counts, f"{fam}{dict(key)} missing _count"
            assert counts[key] == values[-1], (
                f"{fam}{dict(key)}: _count != +Inf bucket"
            )
            assert key in sums, f"{fam}{dict(key)} missing _sum"
    return families


def _slo_with_data():
    plane = SloPlane()
    ctrl = TraceController()
    ctrl.stop()
    tracer = SessionTracer("s", ctrl, slo=plane)

    class F:
        pass

    for i in range(20):
        f = F()
        tr = tracer.attach(f)
        tr.add_span("decode", 0.0, 0.002)
        tr.add_span("engine_step", 0.0, 0.02 if i % 2 else 0.2)
        tr.finish("sent")
    return plane


# -- renderer unit conformance ----------------------------------------------

def test_render_scalars_types_and_skips():
    text = render({
        "fps": 29.5,
        "frames_total": 100,
        "supervisor_degraded_total": 2,
        "trace_enabled": True,        # bool -> 0/1
        "latency_p50_ms": None,       # no data -> absent series
        "overload_queues": {"a": 1},  # nested -> JSON-only
        "host_plane_sessions": {},
        "some_list": [1, 2],
        "bad name!": 3,               # invalid name -> never emitted
    })
    fams = validate_exposition(text)
    assert fams["fps"]["type"] == "gauge"
    assert fams["frames_total"]["type"] == "counter"
    assert fams["supervisor_degraded_total"]["type"] == "counter"
    assert fams["trace_enabled"]["samples"][0][2] == 1.0
    assert "latency_p50_ms" not in fams
    assert "overload_queues" not in fams
    assert all(NAME_RE.match(f) for f in fams)


def test_render_slo_histograms_conform():
    plane = _slo_with_data()
    text = render({}, slo=plane)
    fams = validate_exposition(text)
    hist = fams["slo_stage_latency_ms"]
    assert hist["type"] == "histogram"
    stages_seen = {
        labels["stage"]
        for name, labels, _ in hist["samples"]
        if name.endswith("_bucket")
    }
    # label values come ONLY from the closed STAGES enum — every stage
    # is emitted (a fixed series set, the cardinality contract)
    assert stages_seen == set(STAGES)
    assert fams["slo_stage_budget_ms"]["type"] == "gauge"
    assert fams["slo_stage_over_budget_total"]["type"] == "counter"
    # the over-budget counter agrees with the fed data (10 of 20 over)
    over = {
        labels["stage"]: v
        for _, labels, v in fams["slo_stage_over_budget_total"]["samples"]
    }
    assert over["engine_step"] == 10.0
    assert over["decode"] == 0.0


def test_render_disabled_slo_omits_histograms():
    plane = _slo_with_data()
    plane.enabled = False
    text = render({"fps": 1.0}, slo=plane)
    assert "slo_stage_latency_ms" not in text


def test_labeled_escapes():
    line = labeled("m", {"stage": 'a"b\\c'}, 1)
    assert line == 'm{stage="a\\"b\\\\c"} 1'


# -- the agent round-trip ----------------------------------------------------

async def _with_agent_client(fn):
    from aiohttp.test_utils import TestClient, TestServer

    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    class Pipe:
        def __call__(self, frame):
            return 255 - frame

        def restart(self):
            pass

    app = build_app(pipeline=Pipe(), provider=LoopbackProvider())
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def test_metrics_prom_roundtrips_every_json_name():
    async def grab(client):
        r_json = await client.get("/metrics")
        assert r_json.status == 200
        j = await r_json.json()
        r_prom = await client.get("/metrics?format=prom")
        assert r_prom.status == 200
        return j, r_prom.headers["Content-Type"], await r_prom.text()

    j, ctype, text = asyncio.run(_with_agent_client(grab))
    assert ctype == CONTENT_TYPE == "text/plain; version=0.0.4; charset=utf-8"
    fams = validate_exposition(text)
    # every eligible JSON name (numeric scalar, valid grammar) round-trips
    for key, value in j.items():
        if value is None or isinstance(value, (dict, list, str)):
            continue
        assert key in fams, f"/metrics name {key} missing from exposition"
        kind = "counter" if key.endswith("_total") else "gauge"
        assert fams[key]["type"] == kind
        assert fams[key]["samples"][0][2] == pytest.approx(float(value))
    # and the SLO histograms ride along as genuine histogram families
    assert fams["slo_stage_latency_ms"]["type"] == "histogram"


def test_metrics_unknown_format_is_400():
    async def grab(client):
        return (await client.get("/metrics?format=xml")).status

    assert asyncio.run(_with_agent_client(grab)) == 400
