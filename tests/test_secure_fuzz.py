"""Mutation fuzz of the secure-tier parsers (STUN, DTLS, SRTP, demux).

Every datagram handler here faces the open internet; the invariant under
arbitrary byte mutation is NO uncaught exception and no association
kill (RFC 6347 s4.1.2.7 silent-discard) — malformed input may only be
ignored or answered with a well-formed reply.  Deterministic seeds: a
failure reproduces.
"""

import pytest

# the secure tier's crypto backend is optional at the package level
# (signaling degrades to loopback without it) — these tests must SKIP,
# not fail collection, on a box without it (resilience PR satellite)
pytest.importorskip("cryptography", reason="secure tier needs cryptography")

import numpy as np
import pytest

from ai_rtc_agent_tpu.server.secure import (
    DtlsEndpoint,
    SecureMediaSession,
    StunMessage,
    classify,
    generate_certificate,
)
from ai_rtc_agent_tpu.server.secure.srtp import SrtpContext
from ai_rtc_agent_tpu.server.secure.stun import IceLiteResponder

N_MUTATIONS = 400


def _mutate(rng, data: bytes) -> bytes:
    data = bytearray(data)
    op = rng.integers(0, 4)
    if op == 0 and data:  # flip bytes
        for _ in range(rng.integers(1, 8)):
            data[rng.integers(0, len(data))] ^= int(rng.integers(1, 256))
    elif op == 1:  # truncate
        data = data[: rng.integers(0, len(data) + 1)]
    elif op == 2:  # extend with noise
        data += bytes(rng.integers(0, 256, rng.integers(1, 64), dtype=np.uint8))
    else:  # splice random prefix
        k = int(rng.integers(0, min(16, len(data) + 1)))
        data[:k] = bytes(rng.integers(0, 256, k, dtype=np.uint8))
    return bytes(data)


def test_fuzz_stun_responder():
    rng = np.random.default_rng(1)
    resp = IceLiteResponder()
    # corpus signed with THE FUZZED RESPONDER'S own credentials, so an
    # unmutated message authenticates and mutations exercise the real
    # ufrag/integrity rejection paths (not an unrelated-credentials
    # short-circuit)
    msg = StunMessage(0x0001)
    msg.attributes.append((0x0006, f"{resp.ufrag}:peer".encode()))
    corpus = [msg.encode(integrity_key=resp.pwd.encode()), msg.encode()]
    assert resp.handle(corpus[0], ("198.51.100.1", 39999)) is not None
    resp.nominated_addr = None  # reset the legitimate latch; now fuzz
    resp.seen_addr = None
    for i in range(N_MUTATIONS):
        data = _mutate(rng, corpus[i % len(corpus)])
        if data == corpus[0]:
            continue  # identity mutation would legitimately authenticate
        reply = resp.handle(data, ("203.0.113.5", 40000))
        if reply is not None:  # any reply must itself parse
            StunMessage.decode(reply)
    assert resp.nominated_addr is None  # fuzz noise never steered media


def test_fuzz_dtls_server_handshake_bytes():
    """Mutated ClientHello/flight bytes against a fresh server: no raise,
    and a genuine handshake still completes afterwards on the same
    endpoint when the mutations didn't consume its message slots."""
    rng = np.random.default_rng(2)
    # corpus: a real client's first+second flights
    probe_server = DtlsEndpoint("server")
    client = DtlsEndpoint("client")
    (ch1,) = client.start()
    (hvr,) = probe_server.handle_datagram(ch1)
    (ch2,) = client.handle_datagram(hvr)
    corpus = [ch1, ch2]
    server = DtlsEndpoint("server")
    for i in range(N_MUTATIONS):
        if i % 16 == 0:  # fresh endpoint periodically: fuzz both states
            server = DtlsEndpoint("server")
        out = server.handle_datagram(_mutate(rng, corpus[i % 2]))
        assert isinstance(out, list)


def test_fuzz_established_association_survives():
    """Mutated SRTP/DTLS/STUN bytes at an ESTABLISHED session: nothing
    raises, the association stays alive, and genuine media still flows."""
    from ai_rtc_agent_tpu.server.secure.srtp import derive_srtp_contexts

    rng = np.random.default_rng(3)
    scert, ccert = generate_certificate(), generate_certificate()
    sess = SecureMediaSession(certificate=scert)
    client = DtlsEndpoint("client", ccert)
    addr = ("203.0.113.9", 41000)
    pending = client.start()
    for _ in range(40):
        nxt = []
        for d in pending:
            outs, _, _ = sess.handle(d, addr)
            for o, _a in outs:
                nxt.extend(client.handle_datagram(o))
        pending = nxt
        if client.established and sess.established:
            break
    assert sess.established
    tx, rx = derive_srtp_contexts(
        client.export_srtp_keying_material(), is_server=False,
        profile=client.srtp_profile,
    )

    import struct

    def rtp(seq):
        return struct.pack("!BBHII", 0x80, 96, seq, seq * 90, 0xABC) + b"p" * 50

    good = [tx.protect(rtp(s)) for s in range(1, 120)]
    delivered = 0
    for i, wire in enumerate(good):
        # interleave hostile mutations of real traffic
        outs, kind, payload = sess.handle(_mutate(rng, wire), addr)
        assert isinstance(outs, list)
        outs, kind, payload = sess.handle(wire, addr)
        if kind == "rtp" and payload is not None:
            delivered += 1
    assert sess.established
    assert delivered >= 110  # hostile noise cost at most a few packets
    assert sess.dtls.failed is None


def test_fuzz_srtp_unprotect_random():
    rng = np.random.default_rng(4)
    ctx = SrtpContext(b"k" * 16, b"s" * 14)
    for _ in range(N_MUTATIONS):
        blob = bytes(rng.integers(0, 256, rng.integers(0, 200), dtype=np.uint8))
        try:
            ctx.unprotect(blob)
        except ValueError:
            pass  # the only allowed outcome besides success
        try:
            ctx.unprotect_rtcp(blob)
        except ValueError:
            pass


def test_fuzz_classify_total():
    """The demux must classify every possible byte string somewhere."""
    rng = np.random.default_rng(5)
    for _ in range(N_MUTATIONS):
        blob = bytes(rng.integers(0, 256, rng.integers(0, 64), dtype=np.uint8))
        assert classify(blob) in ("stun", "dtls", "rtp", "rtcp", "drop")


def test_fuzz_sctp_association():
    """SCTP packets arrive through an AUTHENTICATED DTLS session, but a
    malicious/buggy peer still must not crash or wedge the association:
    mutations may be dropped (bad CRC/vtag) or answered, never raise.
    Valid-checksum mutations are exercised too (recomputed post-mutation)
    so chunk parsing itself gets fuzzed, not just the CRC gate."""
    import struct

    from ai_rtc_agent_tpu.server.secure.sctp import SctpAssociation, crc32c

    rng = np.random.default_rng(11)
    got = []
    server = SctpAssociation("server", on_message=lambda ch, m: got.append(m))
    client = SctpAssociation("client")
    # establish + open a channel for a live-association corpus
    (init,) = client.start()
    (init_ack,) = server.handle_packet(init)
    (cookie_echo,) = client.handle_packet(init_ack)
    (cookie_ack, ) = server.handle_packet(cookie_echo)
    client.handle_packet(cookie_ack)
    ch, open_pkts = client.open_channel("fuzz")
    corpus = [init, cookie_echo] + open_pkts + ch.send("payload " * 20)
    for i in range(N_MUTATIONS):
        data = _mutate(rng, corpus[i % len(corpus)])
        if rng.integers(0, 2) and len(data) >= 12:
            # re-checksum so the mutation reaches the chunk parsers
            fixed = bytearray(data)
            struct.pack_into("!I", fixed, 8, 0)
            struct.pack_into("<I", fixed, 8, crc32c(bytes(fixed)))
            data = bytes(fixed)
        out = server.handle_packet(data)
        assert isinstance(out, list)
    # NOTE: no survival postscript — a valid-checksum mutation can be a
    # legal ABORT (the peer IS authenticated) or occupy nearby TSNs, so
    # the unconditional invariant is exactly the loop above: no uncaught
    # exception, ever, and every reply well-formed (a list)
