"""Engine fault domain (ISSUE 19) — trip, quarantine, rebuild, evacuate.

Three layers, hermetic on CPU:

1. **Guard unit tests** against a fake scheduler: deadline trip,
   DeviceLostError trip, non-fault exceptions propagating untripped,
   the backed-off rebuild schedule (injectable sleep), exhaustion →
   evacuation hook, Retry-After and metric snapshots.
2. **Chaos integration** (real tiny-test BatchScheduler, 4 sessions
   wrapped in ResilientPipeline): injected ``device_lost`` then
   ``wedge`` mid-stream — every session serves passthrough with zero
   dropped futures, the guard trips, and ``run_rebuild`` restores every
   slot BIT-EXACT from the snapshot bank (an unmigrated control
   scheduler proves it frame-for-frame).
3. **HTTP evacuation** (real router + real agent apps, fake
   schedulers): ``POST /fleet/evacuate`` migrate-places both sessions
   on a healthy agent, journeys continue leg+1 with an ``evacuated``
   ring entry, and the sick agent parks FAILED (out of placement).
"""

import asyncio
import threading
import time

import numpy as np
import pytest

from ai_rtc_agent_tpu.resilience import faults
from ai_rtc_agent_tpu.resilience.engine_guard import (
    EngineGuard,
    EngineQuarantinedError,
)
from ai_rtc_agent_tpu.resilience.faults import (
    DeviceLostError,
    FaultPlan,
    FaultSpec,
)
from ai_rtc_agent_tpu.resilience.supervisor import ResilientPipeline
from tests.test_migration import (
    _fleet_harness,
    _MigScheduler,
    _mk_sched,
    _offer_body,
    _spawn_agent,
    _tick,
    _wait_for,
    bundle,
    cfg32,
)

__all__ = ["bundle", "cfg32"]  # re-exported module-scoped fixtures


@pytest.fixture(autouse=True)
def _no_fault_leak():
    faults.deactivate()
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# 1. guard unit tests (fake scheduler, injectable sleep/clock)
# ---------------------------------------------------------------------------

class _FakeSched:
    def __init__(self, fail_rebuilds: int = 0):
        self.guard = None
        self.captures = 0
        self.rebuild_calls = []
        self.fail_rebuilds = fail_rebuilds

    def attach_guard(self, g):
        self.guard = g

    def capture_quarantine_snapshots(self):
        self.captures += 1
        return {"sess-a": {"state_b64": "banked"}}

    def rebuild_engine(self, snaps):
        self.rebuild_calls.append(snaps)
        if len(self.rebuild_calls) <= self.fail_rebuilds:
            raise RuntimeError("device still gone")
        return len(snaps)


def _mk_guard(sched=None, **kw):
    transitions = []
    sleeps = []
    kw.setdefault("deadline_s", 0.1)
    kw.setdefault("max_attempts", 3)
    kw.setdefault("backoff_s", 1.0)
    kw.setdefault("auto_rebuild", False)
    kw.setdefault("sleep", sleeps.append)
    kw.setdefault(
        "on_transition", lambda ev, info: transitions.append((ev, info))
    )
    g = EngineGuard(sched if sched is not None else _FakeSched(), **kw)
    return g, transitions, sleeps


def test_dispatch_passes_results_through_when_armed():
    g, transitions, _ = _mk_guard()
    assert g.dispatch(lambda: 42) == 42
    assert g.state == "ARMED" and not g.quarantined
    assert g.trips == 0 and transitions == []
    assert g.retry_after_s() == 0.0
    g.close()


def test_blown_deadline_trips_and_quarantines():
    release = threading.Event()
    g, transitions, _ = _mk_guard(deadline_s=0.05)
    with pytest.raises(EngineQuarantinedError):
        g.dispatch(lambda: release.wait(5))
    release.set()  # free the abandoned worker thread
    assert g.state == "QUARANTINED" and g.quarantined
    assert g.trips == 1
    assert "deadline" in (g.last_trip_reason or "")
    assert [t[0] for t in transitions] == ["EngineDegraded"]
    assert transitions[0][1]["state"] == "QUARANTINED"
    # quarantined dispatch refuses WITHOUT running fn
    ran = []
    with pytest.raises(EngineQuarantinedError):
        g.dispatch(lambda: ran.append(1))
    assert ran == [] and g.trips == 1  # refusal, not a second trip
    assert 1.0 <= g.retry_after_s() <= 60.0
    assert g.health()["state"] == "QUARANTINED"
    assert g.snapshot()["engine_quarantined"] == 1
    assert g.snapshot()["engine_trips_total"] == 1
    g.close()


def test_device_lost_trips_and_reraises():
    g, transitions, _ = _mk_guard()

    def boom():
        raise DeviceLostError("halt 0x13")

    with pytest.raises(DeviceLostError):
        g.dispatch(boom)
    assert g.state == "QUARANTINED" and g.trips == 1
    assert "device lost" in g.last_trip_reason
    g.close()


def test_non_fault_exception_propagates_untripped():
    g, transitions, _ = _mk_guard()

    def shape_bug():
        raise ValueError("bad shapes")

    with pytest.raises(ValueError, match="bad shapes"):
        g.dispatch(shape_bug)
    assert g.state == "ARMED" and g.trips == 0 and transitions == []
    g.close()


def test_cold_dispatch_gets_the_compile_deadline():
    g, _, _ = _mk_guard(deadline_s=0.05, cold_deadline_s=5.0)
    # a 0.3s "compile" blows the warm deadline but not the cold one
    assert g.dispatch(lambda: time.sleep(0.3) or "ok", cold=True) == "ok"
    assert g.state == "ARMED"
    g.close()


def test_rebuild_success_rearms_and_banks_latency():
    sched = _FakeSched()
    g, transitions, sleeps = _mk_guard(sched)
    with pytest.raises(DeviceLostError):
        g.dispatch(lambda: (_ for _ in ()).throw(DeviceLostError("x")))
    assert g.run_rebuild() is True
    assert g.state == "ARMED" and not g.quarantined
    assert g.rebuilds == 1 and g.trips == 1
    assert sleeps == [1.0]  # one attempt, base backoff
    # snapshots were captured ONCE, before the first attempt, and the
    # SAME dict fed the rebuild (evacuation exports what the bank held)
    assert sched.captures == 1
    assert sched.rebuild_calls == [{"sess-a": {"state_b64": "banked"}}]
    names = [t[0] for t in transitions]
    assert names == ["EngineDegraded", "EngineRecovered"]
    rec = transitions[1][1]
    assert rec["state"] == "ARMED" and rec["attempt"] == 1
    assert rec["restored"] == 1 and rec["rebuild_ms"] >= 0
    snap = g.snapshot()
    assert snap["engine_rebuilds_total"] == 1
    assert snap["engine_quarantined"] == 0
    assert snap["engine_rebuild_ms_p50"] >= 0
    assert snap["engine_rebuild_ms_p99"] >= snap["engine_rebuild_ms_p50"]
    assert g.retry_after_s() == 0.0
    g.close()


def test_rebuild_exhaustion_evacuates_and_parks_failed():
    sched = _FakeSched(fail_rebuilds=3)
    evacuated = []
    g, transitions, sleeps = _mk_guard(
        sched, max_attempts=3, backoff_s=1.0,
        on_exhausted=lambda: evacuated.append(g.state),
    )
    with pytest.raises(DeviceLostError):
        g.dispatch(lambda: (_ for _ in ()).throw(DeviceLostError("x")))
    assert g.run_rebuild() is False
    assert sleeps == [1.0, 2.0, 4.0]  # exponential schedule
    assert g.state == "FAILED" and g.rebuilds == 0
    # the hook ran DURING evacuation (webhook order: degraded ->
    # evacuating; the hook sees EVACUATING, FAILED lands after)
    assert evacuated == ["EVACUATING"]
    names = [t[0] for t in transitions]
    assert names == ["EngineDegraded", "AgentEvacuating"]
    assert g.retry_after_s() == 60.0
    assert g.snapshot()["engine_quarantined"] == 1
    g.close()


# ---------------------------------------------------------------------------
# 2. chaos integration: real scheduler, device_lost then wedge mid-stream
# ---------------------------------------------------------------------------

def _wrap(sess):
    # the agent's serving shape: every scheduler session rides a
    # ResilientPipeline (errors/timeouts -> passthrough, never a hang)
    return ResilientPipeline(sess, step_timeout_s=30.0)


def _rtick(rp, frame):
    # the wrapper's pipelined surface (scheduler sessions expose
    # submit/fetch, so the wrapper binds them)
    return np.asarray(rp.fetch(rp.submit(frame)))


def _inject(sched, kind):
    """Activate a one-step engine fault and rebind the scheduler's scope
    (scopes bind at construction; the test re-binds to inject
    mid-stream the way FAULT_PLAN-at-boot would have)."""
    faults.activate(FaultPlan(
        specs=(FaultSpec(target="engine", kind=kind, start=0, stop=1),),
        seed=7,
    ))
    sched._fault_scope = faults.scope("engine")


def test_chaos_device_lost_then_wedge_bitexact_rebuild(
    bundle, cfg32, monkeypatch
):
    """4-session batch: a lost device and then a wedged step each trip
    the guard mid-stream; every session keeps serving (passthrough,
    zero dropped futures), and each rebuild restores all four slots
    bit-exact from the snapshot bank — post-recovery frames equal an
    unfaulted control scheduler's, frame for frame."""
    monkeypatch.setenv("ENGINE_SNAPSHOT_EVERY_S", "0.000001")  # bank always
    # window_ms=0: per-session ticks dispatch immediately (the test
    # drives sessions serially; a coalescing window would stall them)
    A = _mk_sched(bundle, cfg32, max_sessions=4, window_ms=0.0)
    C = _mk_sched(bundle, cfg32, max_sessions=4, window_ms=0.0)  # control
    guard = EngineGuard(
        A, deadline_s=0.5, cold_deadline_s=120.0, auto_rebuild=False,
        sleep=lambda s: None,
    )
    rng = np.random.default_rng(19)
    frames = [
        rng.integers(0, 256, (32, 32, 3), np.uint8) for _ in range(8)
    ]
    keys = ["s0", "s1", "s2", "s3"]
    try:
        live = {
            k: _wrap(A.claim(k, prompt=f"chaos {k}", seed=i))
            for i, k in enumerate(keys)
        }
        ctrl = {
            k: C.claim(k, prompt=f"chaos {k}", seed=i)
            for i, k in enumerate(keys)
        }

        def tick_all(frame):
            for k in keys:
                got = _rtick(live[k], frame)
                want = _tick(ctrl[k], frame)
                assert np.array_equal(got, want), f"{k}: frame mismatch"

        for f in frames[:4]:  # healthy streaming; bank refreshes each step
            tick_all(f)

        # -- trip 1: device lost under session s0's dispatch ------------
        _inject(A, "device_lost")
        out = _rtick(live["s0"], frames[4])
        assert np.array_equal(out, frames[4])  # passthrough
        assert guard.state == "QUARANTINED" and guard.trips == 1
        # the other three sessions keep serving passthrough — submits
        # shed immediately (zero dropped futures, nothing hangs)
        for k in keys[1:]:
            assert np.array_equal(_rtick(live[k], frames[4]), frames[4])
        # quarantine refuses claims and serves BANKED snapshots
        with pytest.raises(Exception, match="quarantined"):
            A.claim("s-new", prompt="late", seed=9)
        A.capture_quarantine_snapshots()
        banked = A.snapshot_session("s0")
        assert banked["prompt"] == "chaos s0"

        assert guard.run_rebuild() is True
        assert guard.state == "ARMED" and guard.rebuilds == 1

        # bit-exact proof #1: post-rebuild frames match the control,
        # which never saw the faulted frame (it was passthrough)
        tick_all(frames[5])

        # -- trip 2: wedge (blocks until released; only the deadline
        # layer can notice) ---------------------------------------------
        _inject(A, "wedge")
        t0 = time.monotonic()
        out = _rtick(live["s0"], frames[6])
        assert np.array_equal(out, frames[6])  # passthrough
        assert time.monotonic() - t0 < 30.0  # deadline, not the wedge
        assert guard.state == "QUARANTINED" and guard.trips == 2
        for k in keys[1:]:
            assert np.array_equal(_rtick(live[k], frames[6]), frames[6])
        faults.release_wedge()  # free the abandoned worker
        assert guard.run_rebuild() is True
        assert guard.rebuilds == 2

        # bit-exact proof #2, and the frame counters never stalled
        tick_all(frames[7])
        for k in keys:
            snap = live[k].supervisor.snapshot()
            assert snap["state"] != "FAILED"
            # every tick delivered a frame (live or passthrough):
            # zero dropped futures across both trips
            assert (
                snap["processed_frames"] + snap["passthrough_frames"]
                == len(frames)
            )
    finally:
        guard.close()
        A.close()
        C.close()


# ---------------------------------------------------------------------------
# 3. HTTP evacuation: exhaustion moves every session to a healthy agent
# ---------------------------------------------------------------------------

def test_http_evacuation_moves_sessions_and_parks_agent_failed():
    src = _MigScheduler()
    dst = _MigScheduler()

    async def go():
        # register ONLY the sick agent first so both sessions land on it
        router, router_app, agents, posted = await _fleet_harness([src])
        try:
            sids, jids = [], []
            for _ in range(2):
                r = await router.post("/offer", json=_offer_body())
                assert r.status == 200, await r.text()
                sids.append(r.headers["X-Stream-Id"])
                jids.append(r.headers["X-Journey-Id"])
            for sid in sids:
                sess = src.session(sid)
                for _ in range(3):
                    sess(np.zeros((4, 4, 3), np.uint8))

            # the healthy target joins, then the sick agent self-reports
            app2, client2 = await _spawn_agent(dst)
            agents.append((app2, client2))
            r = await router.post("/fleet/register", json={
                "worker_id": "m-agent1", "public_ip": "127.0.0.1",
                "public_port": str(client2.server.port), "status": "ready",
                "capacity": dst.max_sessions,
            })
            assert r.status == 200
            await router_app["poller"].poll_once()

            # wrong/missing token refused; unknown agent 404
            r = await router.post(
                "/fleet/evacuate", json={"agent": "m-agent0"}
            )
            assert r.status == 401
            r = await router.post(
                "/fleet/evacuate", json={"agent": "ghost"},
                headers={"Authorization": "Bearer t"},
            )
            assert r.status == 404

            r = await router.post(
                "/fleet/evacuate",
                json={"agent": "m-agent0",
                      "reason": "engine rebuild exhausted"},
                headers={"Authorization": "Bearer t"},
            )
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["agent"] == "m-agent0"
            assert body["state"] == "FAILED"
            assert body["evacuating"] == 2

            def _moved():
                got = [e for e in posted
                       if e.get("event") == "StreamMigrated"]
                return got if len(got) == 2 else None

            moved = await _wait_for(
                _moved, 10, "both StreamMigrated webhooks"
            )
            assert {e["reason"] for e in moved} == {"evacuate"}
            assert {e["target_agent"] for e in moved} == {"m-agent1"}
            assert {e["stream_id"] for e in moved} == set(sids)
            assert dst.restores == 2
            # the sick agent is FAILED and sticky (polls don't revive it)
            rec = router_app["fleet"].agents["m-agent0"]
            assert rec.state == "FAILED"
            await router_app["poller"].poll_once()
            assert rec.state == "FAILED"

            # journeys carry the WHY: an ``evacuated`` ring entry, and a
            # client re-offer continues the journey at leg 2 on the
            # healthy agent with its mid-stream state intact
            for jid in jids:
                kinds = [e["kind"] for e in
                         router_app["journeys"].get(jid)["events"]]
                assert "evacuated" in kinds and "migrated" in kinds
            r = await router.post(
                "/offer", json=_offer_body(),
                headers={"X-Journey-Id": jids[0]},
            )
            assert r.status == 200, await r.text()
            assert r.headers["X-Journey-Leg"] == "2"
            new_sid = r.headers["X-Stream-Id"]
            assert router_app["session_table"].owner(new_sid) == "m-agent1"
            assert dst.session(new_sid).counter == 3

            m = await (await router.get("/metrics")).json()
            assert m["evacuations_total"] == 1
            assert m["fleet_agents_failed"] == 1
            assert m["evacuation_session_move_ms_p50"] > 0
            r = await router.get("/metrics", params={"format": "prom"})
            text = await r.text()
            assert "# TYPE evacuations_total counter" in text
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())
