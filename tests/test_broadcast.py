"""Broadcast fan-out plane (ISSUE 17): wire-identity of the per-viewer
header rewrite, GOP-cache re-sync semantics, PLI-storm governance, the
grouped sendmmsg burst, and the /whep viewer-cap integration.

The tentpole claim these tests pin: N viewers of one publisher cost ONE
encode + packetize, a header rewrite each, and zero engine/encoder work
on re-sync.
"""

import asyncio
import json
import socket
import struct

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.media import native
from ai_rtc_agent_tpu.media.codec import NullCodec
from ai_rtc_agent_tpu.media.gop import GopCache, au_is_idr
from ai_rtc_agent_tpu.media.rtp import (
    BatchedRtpPacketizer,
    RtpHeaderRewriter,
    RtpReorderBuffer,
    split_nals,
)
from ai_rtc_agent_tpu.media.sockio import BatchSender
from ai_rtc_agent_tpu.resilience import faults
from ai_rtc_agent_tpu.server.broadcast import BroadcastGroup

rng = np.random.default_rng(17)


def _mkau(sizes, sc=4):
    au = b""
    for i, s in enumerate(sizes):
        code = b"\x00\x00\x00\x01" if (i % 2 == 0 or sc == 4) else b"\x00\x00\x01"
        au += (
            code
            + bytes([0x65 if s > 200 else 0x67])
            + rng.integers(0, 256, s - 1, dtype=np.uint8).tobytes()
        )
    return au


def _traw_idr(n=64):
    """A NullCodec-tier access unit (all-intra — an IDR boundary)."""
    return b"\x00\x00\x00\x01" + NullCodec.MAGIC + bytes(range(256))[:n]


def _delta_au(n=64):
    """A non-IDR H264 AU (NAL type 1, coded slice of a non-IDR picture)."""
    return b"\x00\x00\x00\x01" + bytes([0x61]) + b"\x42" * n


# ---------------------------------------------------------------------------
# header-rewrite wire identity (satellite 2)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "au,stap",
    [
        (_mkau([31]), False),            # single NALU
        (_mkau([31, 5001]), False),      # small + FU-A fragmentation
        (_mkau([9, 12, 3000, 7, 8]), True),  # STAP-A aggregation
    ],
    ids=["single-nal", "fu-a", "stap-a"],
)
def test_rewrite_wire_identity(au, stap):
    """A rewritten frame is byte-identical to a dedicated per-viewer
    packetize of the same AU — SSRC, seq space, ts offset and PT are the
    ONLY fields the viewer leg owns; marker bits, FU-A framing and
    STAP-A layout ride through the copy untouched."""
    src = BatchedRtpPacketizer(ssrc=0x5EED, payload_type=96, stap_a=stap)
    ded = BatchedRtpPacketizer(ssrc=0xBEEF, payload_type=102, stap_a=stap)
    ded.seq = 777
    rw = RtpHeaderRewriter(
        ssrc=0xBEEF, payload_type=102, seq0=777, ts_offset=1234
    )
    for i in range(3):  # seq continuity across frames too
        ts = 9000 + i * 3000
        pkts = src.packetize(au, ts)
        want = ded.packetize(au, (ts + 1234) & 0xFFFFFFFF)
        got = rw.rewrite(pkts)
        assert [bytes(p) for p in got] == [bytes(p) for p in want], i
    assert rw.seq == ded.seq


def test_rewrite_touches_only_header_fields():
    """Field isolation: masking seq/ts/ssrc/PT out of both sides leaves
    source and rewritten packets equal byte-for-byte."""
    src = BatchedRtpPacketizer(ssrc=0x5EED, payload_type=96)
    rw = RtpHeaderRewriter(ssrc=0xBEEF, payload_type=102, seq0=9, ts_offset=7)
    pkts = src.packetize(_mkau([31, 5001]), 12345)
    out = rw.rewrite(pkts)
    for a, b in zip(pkts, out):
        a, b = bytearray(bytes(a)), bytearray(bytes(b))
        for buf in (a, b):
            buf[1] = buf[1] & 0x80  # PT (marker bit kept)
            buf[2:12] = bytes(10)   # seq + ts + ssrc
        assert a == b


def test_rewrite_identity_fast_path_and_desync():
    """An aligned viewer (same SSRC/PT, seq cursor matching the source)
    gets the SOURCE views back — zero copies; a desynced cursor drops it
    to the copying path for good."""
    src = BatchedRtpPacketizer(ssrc=0x5EED, payload_type=96)
    rw = RtpHeaderRewriter(ssrc=0x5EED, seq0=src.seq)
    for i in range(2):
        pkts = src.packetize(_mkau([31, 3000]), i * 3000)
        assert rw.aligned(pkts)
        out = rw.rewrite(pkts)
        assert all(o is p for o, p in zip(out, pkts))  # the very objects
        assert rw.seq == src.seq  # cursor advanced in lockstep
    rw.seq = (rw.seq + 5) & 0xFFFF  # a GOP replay desyncs the cursor
    pkts = src.packetize(_mkau([31]), 9000)
    assert not rw.aligned(pkts)
    out = rw.rewrite(pkts)
    assert out[0] is not pkts[0]
    assert bytes(out[0])[4:] == bytes(pkts[0])[4:]  # ts+ssrc+payload equal
    assert bytes(out[0])[2:4] != bytes(pkts[0])[2:4]  # own seq space


def test_rewrite_plan_shared_across_viewers():
    """fan_out computes plan() once per frame; passing it to every
    copying viewer must give the same bytes as a solo rewrite."""
    src = BatchedRtpPacketizer(ssrc=0x5EED, payload_type=96)
    pkts = src.packetize(_mkau([31, 5001, 12]), 3000)
    a = RtpHeaderRewriter(ssrc=0xA, seq0=1)
    b = RtpHeaderRewriter(ssrc=0xB, seq0=2, ts_offset=99)
    a2 = RtpHeaderRewriter(ssrc=0xA, seq0=1)
    b2 = RtpHeaderRewriter(ssrc=0xB, seq0=2, ts_offset=99)
    plan = a.plan(pkts)
    assert [bytes(p) for p in a.rewrite(pkts, plan)] == [
        bytes(p) for p in a2.rewrite(pkts)
    ]
    assert [bytes(p) for p in b.rewrite(pkts, plan)] == [
        bytes(p) for p in b2.rewrite(pkts)
    ]


def test_rewrite_pooled_views_survive_fault_injector():
    """Pooled-view stabilization pinned through the fault injector
    (satellite 2): rewritten views pushed through a deterministic
    reorder plan — which makes the downstream reorder buffer HOLD
    packets while the rewriter's 2-slot pool keeps wrapping — must
    still reassemble every AU byte-correct (copy-on-hold discipline)."""
    if native.load() is None:
        pytest.skip("native lib unavailable (depacketizer half)")
    from ai_rtc_agent_tpu.media.rtp import RtpDepacketizer

    plan = faults.FaultPlan(
        # start=1: the first packet passes clean so the reorder buffer
        # syncs its cursor to the true seq0 (as a live session does on
        # its first in-order packet); everything after is pairwise-swapped
        specs=(faults.FaultSpec(target="rx", kind="reorder", p=1.0,
                                start=1),),
        seed=3,
    )
    faults.activate(plan)
    try:
        scope = faults.scope("rx")
        src = BatchedRtpPacketizer(ssrc=0x5EED, mtu=600, pool_slots=2)
        rw = RtpHeaderRewriter(ssrc=0x7777, seq0=0, pool_slots=2)
        rb = RtpReorderBuffer()
        d = RtpDepacketizer()
        aus = [_mkau([31, 5001]), _mkau([1400, 40]), _mkau([2000]),
               _mkau([12, 13, 1200, 9], sc=3)]
        # trailing flush AU: the scope may end a burst still HOLDING the
        # last packet; only the first len(aus) outputs are asserted
        feed = aus + [_mkau([25])]
        got = []
        try:
            for ci, au in enumerate(feed):
                for p in rw.rewrite(src.packetize(au, 1000 + ci)):
                    for data, _delay in scope.apply(p):
                        for pkt in rb.push(data):
                            r = d.push(pkt)
                            if r is not None:
                                got.append(r)
        finally:
            d.close()
        assert scope.stats["reorder"] > 0  # the plan actually fired
        want = [
            (
                b"".join(
                    b"\x00\x00\x00\x01" + au[s:e] for s, e in split_nals(au)
                ),
                1000 + ci,
            )
            for ci, au in enumerate(aus)
        ]
        assert [(bytes(a), ts) for a, ts in got[:len(aus)]] == want
    finally:
        faults.deactivate()


# ---------------------------------------------------------------------------
# GOP cache (satellite 3)
# ---------------------------------------------------------------------------

def test_gop_cache_idr_boundary_detection():
    assert au_is_idr(_traw_idr())          # NullCodec all-intra tier
    assert au_is_idr(_mkau([500]))         # NAL type 5 (0x65)
    assert not au_is_idr(_delta_au())      # NAL type 1


def test_gop_cache_gop_membership_and_stabilization():
    c = GopCache(max_aus=16)
    assert not c.add(_delta_au(), 0)  # mid-GOP, no IDR yet: stays empty
    assert c.aus == 0
    assert c.add(_traw_idr(), 100)
    c.add(_delta_au(), 200)
    c.add(_delta_au(), 300)
    assert c.aus == 3 and c.idrs == 1
    # a new IDR starts a NEW GOP: the old one is gone
    assert c.add(_traw_idr(), 400)
    assert c.aus == 1 and c.idrs == 2
    assert c.snapshot() == [(_traw_idr(), 400)]
    # pooled-view discipline: add() must stabilize to bytes (this IDR
    # view becomes the new GOP head, then its backing is scribbled)
    backing = bytearray(_traw_idr())
    c.add(memoryview(backing), 500)
    backing[:] = b"\x00" * len(backing)
    assert c.snapshot() == [(_traw_idr(), 500)]


def test_gop_cache_overflow_clears_whole_and_rearms():
    c = GopCache(max_aus=3)
    c.add(_traw_idr(), 0)
    c.add(_delta_au(), 1)
    c.add(_delta_au(), 2)
    assert c.aus == 3 and c.overflows == 0
    c.add(_delta_au(), 3)  # 4th AU: the GOP outgrew the cache
    # whole-cache clear — an IDR-less tail can't re-sync anyone
    assert c.aus == 0 and c.overflows == 1
    c.add(_delta_au(), 4)  # still mid-GOP: stays empty
    assert c.aus == 0
    assert c.add(_traw_idr(), 5)  # next boundary re-arms
    assert c.aus == 1

    b = GopCache(max_bytes=len(_traw_idr()) + 10)
    b.add(_traw_idr(), 0)
    b.add(_delta_au(), 1)  # byte bound exceeded
    assert b.aus == 0 and b.overflows == 1


# ---------------------------------------------------------------------------
# BroadcastGroup: fan-out + PLI-storm governance (acceptance criterion)
# ---------------------------------------------------------------------------

def _counts(group):
    snap = group.stats.stage_snapshot_us()
    return {k: v for k, v in snap.items() if k.endswith("_total")}


def test_pli_storm_one_idr_zero_engine_steps():
    """The acceptance pin: a PLI storm from 16 viewers produces exactly
    ONE granted re-sync (a GOP-cache replay) and ZERO engine/encoder
    work — no sink exists, and the upstream-IDR escalation hook is never
    called."""

    async def go():
        group = BroadcastGroup("pub", width=8, height=8, coalesce_s=60.0)
        await group.start()  # AU mode: no track, no sink, no engine
        engine_calls = []
        group.idr_fallback = lambda: engine_calls.append(1)
        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.setblocking(False)
        try:
            group.feed_au(_traw_idr(), 0)
            group.feed_au(_delta_au(), 3000)
            for i in range(16):
                group.add_viewer(f"v{i}", addr=rx.getsockname())
            assert group.viewer_count == 16
            replays0 = _counts(group).get("broadcast_gop_replays_total", 0)
            granted0 = group.governor.granted
            for i in range(16):
                group.on_viewer_pli(viewer_id=f"v{i}")
            c = _counts(group)
            assert group.governor.granted - granted0 == 1
            assert c.get("broadcast_gop_replays_total", 0) - replays0 == 1
            assert c.get("broadcast_pli_coalesced_total", 0) == 15
            assert c.get("broadcast_pli_total", 0) == 16
            # zero engine/encoder touches: no sink, no upstream escalation
            assert group._sink is None
            assert c.get("broadcast_encoder_idr_total", 0) == 0
            assert engine_calls == []
        finally:
            rx.close()
            await group.close()

    asyncio.run(go())


def test_group_fan_out_delivers_and_patches_pt():
    """AU-mode fan-out: each viewer's wire bytes equal a DEDICATED
    packetizer run in that viewer's own seq/PT space — join replay and
    live traffic form one continuous stream per viewer, and the shared
    replay packetizer's cursor accounts for AUs the viewer never saw."""

    async def go():
        group = BroadcastGroup("pub", width=8, height=8, coalesce_s=60.0)
        await group.start()
        rxs = []
        for _ in range(2):
            rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            rx.bind(("127.0.0.1", 0))
            rx.setblocking(False)
            rxs.append(rx)
        try:
            group.feed_au(_traw_idr(), 0)  # arms the cache (group seq 0)
            group.add_viewer("plain", addr=rxs[0].getsockname())
            group.add_viewer(
                "pt102", addr=rxs[1].getsockname(), payload_type=102
            )
            # each join replayed the cached GOP (group seq 1 then 2);
            # now one live AU (group seq 3)
            group.feed_au(_delta_au(), 3000)
            # viewer "plain" joined at group seq 1 and stays identity-
            # aligned through its replay, then continues in its own seq
            # space: exactly a dedicated packetizer starting at seq 1
            ref = BatchedRtpPacketizer(ssrc=0x5EED, payload_type=96)
            ref.seq = 1
            want_plain = [bytes(p) for p in ref.packetize(_traw_idr(), 0)]
            want_plain += [bytes(p) for p in ref.packetize(_delta_au(), 3000)]
            # viewer "pt102" joined one replay later (seq 2) with its own
            # negotiated PT — always the copying path
            ded = BatchedRtpPacketizer(ssrc=0x5EED, payload_type=102)
            ded.seq = 2
            want_pt = [bytes(p) for p in ded.packetize(_traw_idr(), 0)]
            want_pt += [bytes(p) for p in ded.packetize(_delta_au(), 3000)]

            async def drain(rx, n):
                got = []
                for _ in range(100):
                    try:
                        while True:
                            got.append(rx.recv(4096))
                    except BlockingIOError:
                        if len(got) >= n:
                            break
                        await asyncio.sleep(0.01)
                return got

            got_plain = await drain(rxs[0], len(want_plain))
            got_pt = await drain(rxs[1], len(want_pt))
            assert got_plain == want_plain
            assert got_pt == want_pt
            assert all(g[1] & 0x7F == 102 for g in got_pt)
            snap = group.snapshot()
            assert snap["viewers"] == 2 and snap["gop_idrs"] == 1
        finally:
            for rx in rxs:
                rx.close()
            await group.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# grouped sendmmsg burst (media/sockio.py)
# ---------------------------------------------------------------------------

def _rx_sock():
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.setblocking(False)
    return rx


def _drain_sync(rx, n):
    got = []
    for _ in range(200):
        try:
            while True:
                got.append(rx.recv(4096))
        except BlockingIOError:
            if len(got) >= n:
                break
            asyncio.run(asyncio.sleep(0.005))
    return got


def test_send_grouped_duplicate_batch_iovec_reuse():
    """Aligned viewers hand send_grouped the SAME pkts list object; the
    duplicate batches must still deliver full, correct bytes to every
    destination (their iovecs are word-copied from the first staging,
    never re-staged) — and fresh content on the next burst must not leak
    the previous staging."""
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    tx.setblocking(False)
    rx1, rx2, rx3 = _rx_sock(), _rx_sock(), _rx_sock()
    try:
        sender = BatchSender(use_sendmmsg=True)
        shared = [b"A" * 100, b"B" * 700, b"C" * 33]
        other = [b"D" * 50]
        batches = [
            (shared, rx1.getsockname()),
            (shared, rx2.getsockname()),  # duplicate list object
            (other, rx3.getsockname()),
        ]
        sent = sender.send_grouped(tx, batches)
        assert sent == 7
        assert _drain_sync(rx1, 3) == shared
        assert _drain_sync(rx2, 3) == shared
        assert _drain_sync(rx3, 1) == other
        # same layout, new bytes: the span-signature skip must only skip
        # the msg_name writes, never the byte staging
        shared2 = [b"x" * 100, b"y" * 700, b"z" * 33]
        batches2 = [
            (shared2, rx1.getsockname()),
            (shared2, rx2.getsockname()),
            (other, rx3.getsockname()),
        ]
        assert sender.send_grouped(tx, batches2) == 7
        assert _drain_sync(rx1, 3) == shared2
        assert _drain_sync(rx2, 3) == shared2
    finally:
        for s in (tx, rx1, rx2, rx3):
            s.close()


def test_send_grouped_then_uniform_send_rewrites_names():
    """A grouped burst leaves per-entry msg_names behind; the next
    uniform-destination send() must not spray packets at stale viewer
    addresses."""
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    tx.setblocking(False)
    rx1, rx2 = _rx_sock(), _rx_sock()
    try:
        sender = BatchSender(use_sendmmsg=True)
        sender.send_grouped(tx, [([b"g" * 20], rx1.getsockname()),
                                 ([b"h" * 20], rx2.getsockname())])
        _drain_sync(rx1, 1), _drain_sync(rx2, 1)
        pkts = [b"u%d" % i * 10 for i in range(4)]
        assert sender.send(tx, pkts, rx1.getsockname()) == 4
        assert _drain_sync(rx1, 4) == pkts
        assert _drain_sync(rx2, 0) == []
    finally:
        for s in (tx, rx1, rx2):
            s.close()


# ---------------------------------------------------------------------------
# /whep integration: viewers stop charging engine slots (tentpole wiring)
# ---------------------------------------------------------------------------

def test_whep_broadcast_viewer_cap_and_gauges(monkeypatch):
    """Viewer admission is the BROADCAST_MAX_VIEWERS cap (503 +
    Retry-After past it), never an engine slot; the audience reads as
    aggregate gauges on /capacity, /health and /metrics; and a closed
    viewer releases its slot."""
    if native.load() is None:
        pytest.skip("native lib unavailable")
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider

    monkeypatch.setenv("WARMUP_FRAMES", "0")
    monkeypatch.setenv("BROADCAST_MAX_VIEWERS", "1")

    class InvertPipeline:
        def __call__(self, frame):
            arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
            return 255 - arr

    async def go():
        provider = NativeRtpProvider(use_h264=native.h264_available())
        app = build_app(pipeline=InvertPipeline(), provider=provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/whip",
                data=json.dumps(
                    {"native_rtp": True, "video": True, "width": 64,
                     "height": 64}
                ),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201

            def whep_offer(port):
                return json.dumps(
                    {"native_rtp": True, "video": False,
                     "client_addr": ["127.0.0.1", port]}
                )

            r = await client.post(
                "/whep", data=whep_offer(39001),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            loc = r.headers["Location"]
            groups = app["state"]["broadcast_groups"]
            assert sum(g.viewer_count for g in groups.values()) == 1

            r = await client.post(
                "/whep", data=whep_offer(39002),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 503
            assert r.headers["Retry-After"] == "2"

            for path in ("/capacity", "/health"):
                body = await (await client.get(path)).json()
                b = body["broadcast"]
                assert b["broadcast_viewers"] == 1
                assert b["broadcast_max_viewers"] == 1
                assert b["broadcast_viewer_slots_free"] == 0
            m = await (await client.get("/metrics")).json()
            assert m["broadcast"]["broadcast_viewers"] == 1

            # closing the viewer releases its slot for the next join
            r = await client.delete(loc)
            assert r.status == 200
            r = await client.post(
                "/whep", data=whep_offer(39003),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
        finally:
            await client.close()

    asyncio.run(go())
