"""Device telemetry plane (obs/devtel.py) — ISSUE 10 tentpole.

The heart is the hermetic retrace-breach test: prewarm a tiny batch
scheduler (warmup-phase compiles, zero breaches), flip to serving, force
a bucket recompile at serve time, and assert the breach fires on every
surface (plane counters, FrameStats ``retrace_breaches_total``, the
attributed compile record).  Everything else is clockless units plus
the agent wiring (webhook + black box + /metrics/prom/health) driven by
a synthetic compile record — no model builds.

The one module-scoped tiny scheduler is shared by every test that needs
real compiles (tier-1 budget discipline).
"""

import asyncio

import numpy as np
import pytest

from ai_rtc_agent_tpu.obs import devtel
from ai_rtc_agent_tpu.obs.devtel import (
    PHASE_SERVING,
    PHASE_WARMUP,
    DevTelPlane,
)
from ai_rtc_agent_tpu.utils.profiling import FrameStats


@pytest.fixture(autouse=True)
def _detach():
    """Every test leaves the module-level plane slot empty — the global
    jax.monitoring listener (unregisterable by design) then no-ops."""
    yield
    devtel.deactivate()


def _plane(monkeypatch=None, **env):
    if monkeypatch is not None:
        for k, v in env.items():
            monkeypatch.setenv(k, str(v))
    return DevTelPlane()


# -- phase machine + breach rules (no jax) -----------------------------------

def test_warmup_compiles_never_breach():
    p = _plane()
    assert p.phase == PHASE_WARMUP
    p.record_compile(3.0, context="sbucket-4:full")
    assert p.compiles_total == 1 and p.warmup_compiles == 1
    assert p.retrace_breaches == 0 and p.last_breach is None


def test_serving_compile_is_a_breach_with_attribution():
    p = _plane()
    fired = []
    p.on_breach = fired.append
    p.serving()
    assert p.phase == PHASE_SERVING
    p.record_compile(3.0, context="sbucket-2:cached")
    assert p.retrace_breaches == 1 and p.serving_compiles == 1
    assert p.last_breach["context"] == "sbucket-2:cached"
    assert p.last_breach["phase"] == PHASE_SERVING
    assert fired and fired[0]["duration_ms"] == 3000.0


def test_sub_threshold_serving_compile_recorded_but_quiet(monkeypatch):
    monkeypatch.setenv("DEVTEL_RETRACE_MIN_MS", "100")
    p = DevTelPlane()
    p.serving()
    p.record_compile(0.05, context="eager-op")  # 50ms < 100ms floor
    assert p.serving_compiles == 1
    assert p.retrace_breaches == 0


def test_expected_scope_blesses_serving_compiles():
    p = devtel.activate(_plane())
    p.serving()
    with devtel.expected_scope("sched-state-build"):
        devtel._dispatch(devtel._COMPILE_EVENT, 2.0)
    assert p.compiles_total == 1 and p.retrace_breaches == 0
    assert p.compiles[-1]["expected"] is True
    assert p.compiles[-1]["context"] == "sched-state-build"


def test_compile_scope_attributes_and_nests():
    p = devtel.activate(_plane())
    with devtel.compile_scope("outer-key"):
        devtel._dispatch(devtel._COMPILE_EVENT, 0.01)
        with devtel.expected_scope("inner-build"):
            devtel._dispatch(devtel._COMPILE_EVENT, 0.01)
        # restored after the nested scope exits
        devtel._dispatch(devtel._COMPILE_EVENT, 0.01)
    devtel._dispatch(devtel._COMPILE_EVENT, 0.01)
    ctxs = [(c["context"], c["expected"]) for c in p.compiles]
    assert ctxs == [
        ("outer-key", False), ("inner-build", True),
        ("outer-key", False), ("unattributed", False),
    ]


def test_breach_fanout_coalesces_but_counters_stay_exact(monkeypatch):
    monkeypatch.setenv("DEVTEL_BREACH_COALESCE_S", "60")
    p = DevTelPlane(stats=FrameStats())
    fired = []
    p.on_breach = fired.append
    p.serving()
    for _ in range(3):  # one logical retrace = several XLA compile events
        p.record_compile(1.0, context="sbucket-2:full")
    assert p.retrace_breaches == 3
    assert p.stats.snapshot()["retrace_breaches_total"] == 3
    assert len(fired) == 1  # one alert volley per coalesce window


def test_breach_callback_failure_never_breaks_recording():
    p = _plane()
    p.serving()
    p.on_breach = lambda info: (_ for _ in ()).throw(RuntimeError("bug"))
    p.record_compile(1.0)  # must not raise
    assert p.retrace_breaches == 1


# -- transfer + AOT accounting + memory (no jax compiles) --------------------

def test_transfer_and_aot_counters_and_snapshot_names():
    p = devtel.activate(_plane())
    devtel.note_h2d(1000)
    devtel.note_h2d(24)
    devtel.note_d2h(512)
    p.note_aot("hit")
    p.note_aot("miss")
    p.note_aot("build", seconds=2.5)
    p.set_aot_inventory(3, 4096)
    snap = p.snapshot()
    assert snap["devtel_h2d_transfers_total"] == 2
    assert snap["devtel_h2d_bytes_total"] == 1024
    assert snap["devtel_d2h_transfers_total"] == 1
    assert snap["devtel_d2h_bytes_total"] == 512
    assert snap["aot_cache_hits_total"] == 1
    assert snap["aot_cache_misses_total"] == 1
    assert snap["aot_cache_builds_total"] == 1
    assert snap["aot_cache_entries"] == 3
    assert snap["aot_cache_bytes"] == 4096
    assert snap["devtel_enabled"] == 1
    # every key is a legal snake_case /metrics name (the prom exporter
    # round-trips them; the registry grammar is the stricter one)
    import re

    for k in snap:
        assert re.match(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$", k), k


def test_disabled_plane_is_inert(monkeypatch):
    monkeypatch.setenv("DEVTEL_ENABLE", "0")
    p = devtel.activate(DevTelPlane())
    assert p.enabled is False and p.watchdog == "disabled"
    devtel.note_h2d(100)
    devtel.note_d2h(100)
    devtel._dispatch(devtel._COMPILE_EVENT, 1.0)
    assert p.h2d_transfers == 0 and p.d2h_transfers == 0
    assert p.compiles_total == 0
    # the scope helpers collapse to the shared null context
    assert devtel.compile_scope("x") is devtel._NULL
    assert devtel.expected_scope() is devtel._NULL


def test_inactive_module_hooks_are_noops():
    devtel.deactivate()
    devtel.note_h2d(1)  # must not raise with no plane at all
    devtel.note_d2h(1)
    devtel.note_aot("hit")
    assert devtel.active() is None


def test_memory_sample_safe_on_cpu_and_rides_snapshot():
    p = devtel.activate(_plane())
    p.sample_memory(force=True)
    snap = p.snapshot()
    # CPU exposes no memory_stats -> no device_mem_* keys; the
    # live-buffer count works everywhere jax does
    assert "device_live_buffers" in snap
    assert isinstance(snap["device_live_buffers"], int)


def test_session_and_health_views():
    p = _plane()
    p.serving()
    p.record_compile(1.0, context="sbucket-1:full")
    sv = p.session_view()
    assert sv["phase"] == PHASE_SERVING and sv["retrace_breaches"] == 1
    assert sv["last_breach"]["context"] == "sbucket-1:full"
    h = p.health()
    assert h["compiles_total"] == 1
    assert h["recent_compiles"][-1]["context"] == "sbucket-1:full"


# -- the real listener (one tiny jit) ----------------------------------------

def test_jax_monitoring_listener_records_real_compiles():
    import jax
    import jax.numpy as jnp

    p = devtel.activate(_plane())
    assert p.watchdog == "jax-monitoring"
    with devtel.compile_scope("unit-key"):
        jax.jit(lambda x: x * 7 + 311)(jnp.ones((11,)))
    assert p.compiles_total >= 1
    assert any(c["context"] == "unit-key" for c in p.compiles)
    assert p.retrace_breaches == 0  # warmup phase


# -- AOT cache emission (aot/cache.py through the plane) ---------------------

def test_aot_cache_emits_hits_misses_builds_and_inventory(tmp_path):
    import jax.numpy as jnp

    from ai_rtc_agent_tpu.aot.cache import EngineCache

    p = devtel.activate(_plane())
    cache = EngineCache(str(tmp_path))
    args = (jnp.ones((3,)),)
    assert cache.load_or_build("unit-dev", lambda x: x + 1, args) is not None
    assert p.aot_misses == 1 and p.aot_builds == 1
    assert p.aot_entries == 1 and p.aot_bytes > 0
    assert p.aot_build_seconds > 0.0
    assert cache.load_or_build("unit-dev", lambda x: x + 1, args) is not None
    assert p.aot_hits == 1
    # miss with build=False still counts (and still returns None)
    assert cache.load_or_build(
        "unit-dev-2", lambda x: x + 1, args, build=False
    ) is None
    assert p.aot_misses == 2


# -- the hermetic retrace-breach story (module-scoped tiny scheduler) --------

@pytest.fixture(scope="module")
def bundle():
    from ai_rtc_agent_tpu.models import registry

    return registry.load_model_bundle("tiny-test")


@pytest.fixture(scope="module")
def cfg():
    from ai_rtc_agent_tpu.models import registry

    return registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
    )


def test_scheduler_prewarm_clean_then_forced_retrace_breaches(
    bundle, cfg, monkeypatch
):
    """The ISSUE 10 acceptance pin: prewarm compiles land in the warmup
    phase with ZERO breaches; after serving() a forced bucket recompile
    at serve time IS a breach — attributed to its (k, variant), counted
    at /metrics via FrameStats, alert callback fired — and the staged
    H2D / per-row D2H meters saw the frame that forced it."""
    from ai_rtc_agent_tpu.stream.scheduler import BatchScheduler

    # the production default: a tiny-model bucket compile runs seconds
    # even on this box, first-use eager-op noise tens of ms — the floor
    # separates them cleanly (measured 3.5-6s vs <=53ms)
    monkeypatch.setenv("DEVTEL_RETRACE_MIN_MS", "250")
    stats = FrameStats()
    fired = []
    p = devtel.activate(DevTelPlane(stats=stats, on_breach=fired.append))

    # max_sessions=1: the story only needs the solo bucket — prewarm
    # compiles ONE geometry instead of two (tier-1 budget)
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=1, window_ms=10_000.0, queue_bound=2, prewarm=True,
    )
    try:
        # prewarm compiled both bucket geometries — all warmup, no alarm
        assert p.compiles_total > 0
        assert p.warmup_compiles == p.compiles_total
        assert p.retrace_breaches == 0
        prewarm_ctxs = {c["context"] for c in p.compiles}
        assert "sbucket-1:full" in prewarm_ctxs, prewarm_ctxs

        sess = s.claim("dev-sess")
        frame = np.random.default_rng(0).integers(
            0, 255, (cfg.height, cfg.width, 3), np.uint8
        )
        p.serving()
        # a warmed dispatch first: serving-phase traffic on prewarmed
        # buckets (plus its first-use eager ops) must not breach — the
        # claim's state build is an expected scope, the bucket is warm
        out = sess(frame)
        assert isinstance(out, np.ndarray) and out.shape == frame.shape
        assert p.retrace_breaches == 0, [
            c for c in p.compiles if c["phase"] == "serving"
        ]
        assert p.h2d_transfers >= 1  # stage_frame metered the submit
        assert p.d2h_transfers >= 1  # _resolve_row metered the readback

        # force the serve-time retrace: evict the solo bucket executable
        # so the next dispatch lazily recompiles it mid-serve
        s._bucket_steps.pop((1, "full"))
        out2 = sess(frame)
        assert isinstance(out2, np.ndarray)
        assert p.retrace_breaches >= 1
        assert p.last_breach["context"] == "sbucket-1:full"
        assert p.last_breach["phase"] == "serving"
        assert stats.snapshot()["retrace_breaches_total"] >= 1
        assert fired, "breach alert callback did not fire"
        sess.release()
    finally:
        s.close()


# -- agent wiring: the three alert surfaces ----------------------------------

def test_agent_retrace_breach_rides_all_three_surfaces(monkeypatch):
    """server/agent.py wiring: a serving-phase breach lands in the
    flight-recorder event log of every live session, fires the
    StreamDegraded webhook with state=RETRACE_BREACH, and shows up at
    /metrics (JSON + Prometheus exposition), /health (process +
    per-session dicts)."""
    from aiohttp.test_utils import TestClient, TestServer

    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    class Pipe:
        def __call__(self, frame):
            return frame

        def restart(self):
            pass

    class FakeSup:
        def snapshot(self):
            return {"state": "HEALTHY"}

        def stop(self):
            pass

    async def go():
        app = build_app(pipeline=Pipe(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            plane = app["devtel"]
            assert plane is not None
            assert plane.phase == PHASE_SERVING  # startup flips it
            flight = app["flight"]
            rec = flight.register("sess-1")
            app["supervisors"]["sess-1"] = FakeSup()
            posted = []

            class _Resp:
                status = 200

            class _Sess:
                async def post(self, url, headers=None, json=None):
                    posted.append(json)
                    return _Resp()

            handler = app["stream_event_handler"]
            handler.webhook_url = "http://orchestrator/hook"
            handler.token = "tok"
            handler._session_factory = lambda: _Sess()

            plane.record_compile(2.0, context="sbucket-4:full")
            for _ in range(10):  # call_soon_threadsafe + webhook task
                await asyncio.sleep(0.01)
                if posted:
                    break
            # 1) black box: every live session carries the retrace event
            events = [e for e in rec.events if e["kind"] == "retrace"]
            assert events and events[0]["context"] == "sbucket-4:full"
            # 2) webhook: StreamDegraded-style alert
            assert posted, "breach did not reach the webhook"
            body = posted[0]
            assert body["event"] == "StreamDegraded"
            assert body["state"] == "RETRACE_BREACH"
            assert "sbucket-4:full" in body["reason"]
            # 3) /metrics: JSON + the Prometheus exposition
            r = await client.get("/metrics")
            j = await r.json()
            assert j["retrace_breaches_total"] == 1
            assert j["devtel_serving_compiles_total"] == 1
            assert j["devtel_enabled"] == 1
            assert "aot_cache_hits_total" in j
            r = await client.get("/metrics?format=prom")
            text = await r.text()
            assert "retrace_breaches_total 1" in text
            assert "# TYPE devtel_compiles_total counter" in text
            # /health: process dict + the per-session devtel view
            r = await client.get("/health")
            h = await r.json()
            assert h["devtel"]["retrace_breaches"] == 1
            assert h["devtel"]["phase"] == PHASE_SERVING
            assert (
                h["sessions"]["sess-1"]["devtel"]["last_breach"]["context"]
                == "sbucket-4:full"
            )
        finally:
            await client.close()

    asyncio.run(go())


def test_agent_devtel_kill_switch(monkeypatch):
    """DEVTEL_ENABLE=0: no plane, no /metrics keys, /health silent."""
    from aiohttp.test_utils import TestClient, TestServer

    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    monkeypatch.setenv("DEVTEL_ENABLE", "0")

    class Pipe:
        def __call__(self, frame):
            return frame

    async def go():
        app = build_app(pipeline=Pipe(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            assert app["devtel"] is None
            r = await client.get("/metrics")
            j = await r.json()
            assert "devtel_enabled" not in j
            assert "aot_cache_hits_total" not in j
            r = await client.get("/health")
            h = await r.json()
            assert "devtel" not in h
        finally:
            await client.close()

    asyncio.run(go())
