"""Webhook eventing + TURN credential tests (reference lib/events.py,
agent.py:80-120 parity)."""

import asyncio
import json

import pytest

from ai_rtc_agent_tpu.server import turn
from ai_rtc_agent_tpu.server.events import (
    StreamEndedEvent,
    StreamEventHandler,
    StreamStartedEvent,
)


def test_event_models_schema():
    e = StreamStartedEvent(stream_id="s1", room_id="r1", timestamp=123)
    d = e.model_dump()
    assert d == {
        "stream_id": "s1",
        "room_id": "r1",
        "timestamp": 123,
        "event": "StreamStarted",
        # fleet journey correlation (ISSUE 13): None outside a fleet —
        # single-process payloads carry the fields, unset
        "journey_id": None,
        "journey_leg": None,
    }
    assert StreamEndedEvent(stream_id="s", room_id="r", timestamp=1).event == "StreamEnded"


def test_handler_disabled_without_env(monkeypatch):
    monkeypatch.delenv("WEBHOOK_URL", raising=False)
    monkeypatch.delenv("AUTH_TOKEN", raising=False)
    h = StreamEventHandler()
    assert h.handle_stream_started("s", "r") is None


def test_handler_posts_with_bearer(monkeypatch):
    monkeypatch.setenv("WEBHOOK_URL", "http://wh.example/hook")
    monkeypatch.setenv("AUTH_TOKEN", "tok123")
    posted = {}

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.update(url=url, headers=headers, body=json)
            return FakeResp()

    async def go():
        h = StreamEventHandler(session_factory=FakeSession)
        t = h.handle_stream_started("sid", "rid")
        assert t is not None
        await t

    asyncio.run(go())
    assert posted["url"] == "http://wh.example/hook"
    assert posted["headers"]["Authorization"] == "Bearer tok123"
    assert posted["body"]["event"] == "StreamStarted"
    assert posted["body"]["stream_id"] == "sid"


def test_unknown_event_raises():
    h = StreamEventHandler()
    with pytest.raises(ValueError):
        h._event("Bogus", "s", "r")


def test_twilio_disabled_without_env(monkeypatch):
    monkeypatch.delenv("TWILIO_ACCOUNT_SID", raising=False)
    monkeypatch.delenv("TWILIO_AUTH_TOKEN", raising=False)
    assert turn.get_twilio_token() is None
    assert turn.get_ice_servers() == []


def test_twilio_token_and_turn_filter(monkeypatch):
    monkeypatch.setenv("TWILIO_ACCOUNT_SID", "AC123")
    monkeypatch.setenv("TWILIO_AUTH_TOKEN", "secret")
    seen = {}

    def fake_post(url, headers):
        seen["url"] = url
        seen["auth"] = headers["Authorization"]
        return 201, {
            "ice_servers": [
                {"url": "stun:stun.twilio.com", "urls": "stun:stun.twilio.com"},
                {
                    "url": "turn:turn.twilio.com?transport=udp",
                    "urls": "turn:turn.twilio.com?transport=udp",
                    "username": "u",
                    "credential": "c",
                },
            ]
        }

    servers = turn.get_ice_servers(http_post=fake_post)
    assert "AC123" in seen["url"]
    assert seen["auth"].startswith("Basic ")
    assert len(servers) == 1  # stun filtered out, turn kept
    assert servers[0]["username"] == "u"

    links = turn.get_link_headers(servers)
    assert 'rel="ice-server"' in links[0]


def test_udp_port_pinning(monkeypatch):
    """patch_loop_datagram pins sockets to the operator's port list
    (reference agent.py:32-69)."""
    import socket

    from ai_rtc_agent_tpu.server.agent import patch_loop_datagram

    async def go():
        patch_loop_datagram([19999])
        loop = asyncio.get_event_loop()

        class Proto(asyncio.DatagramProtocol):
            pass

        transport, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=("127.0.0.1", 0)
        )
        port = transport.get_extra_info("sockname")[1]
        transport.close()
        return port

    assert asyncio.run(go()) == 19999


def test_ice_servers_env_override(monkeypatch):
    """ICE_SERVERS env supplies arbitrary TURN/STUN servers (the reference
    supports only Twilio and documents the gap, docs/run.md)."""
    from ai_rtc_agent_tpu.server import turn

    servers = [
        {"urls": ["turn:turn.example.com:3478"], "username": "u", "credential": "c"}
    ]
    import json

    monkeypatch.setenv("ICE_SERVERS", json.dumps(servers))
    assert turn.get_ice_servers() == servers

    monkeypatch.setenv("ICE_SERVERS", "not json")
    assert turn.get_ice_servers() == []

    monkeypatch.setenv("ICE_SERVERS", '{"urls": "x"}')  # not a list
    assert turn.get_ice_servers() == []
