"""Live session migration (ISSUE 15) — snapshot/restore stream state.

Three layers, hermetic:

1. **Bit-identity between two loopback agents** (real tiny schedulers):
   a session migrated MID-STREAM resumes with frame continuity (no gap,
   no keyframe re-prime — its first post-migration frame equals an
   unmigrated control's) and every post-migration step is bit-identical
   to the control; the abort-safety regressions ride the same builds —
   a schema/fingerprint/corrupt-blob restore REFUSES and the source
   session keeps serving bit-identically.
2. **Checkpoint blob round-trip property** (parallel/checkpoint.py):
   dtype/shape/bit-exactness across every leaf kind the session pytree
   actually carries (f32, bf16, uint8 frames, uint32 PRNG key arrays),
   plus corrupt/truncated-blob refusal.
3. **HTTP orchestration** (real agent apps + real router, fake
   schedulers): POST /fleet/drain?mode=migrate runs export -> counted-
   reservation import -> StreamMigrated webhook -> pinned re-offer
   adoption (leg+1, ``migrated`` journey ring kind); a 4xx import is
   terminal after exactly ONE attempt (the retry-4xx rule) and leaves
   the source serving; MIGRATE_TIMEOUT_S falls back to kill-drain.
"""

import asyncio
import base64
import time

import numpy as np
import pytest

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.parallel.checkpoint import (
    deserialize_pytree,
    serialize_pytree,
)
from ai_rtc_agent_tpu.stream.scheduler import (
    SESSION_SNAPSHOT_SCHEMA,
    BatchScheduler,
    CapacityError,
    SnapshotMismatch,
)


@pytest.fixture(scope="module")
def bundle():
    return registry.load_model_bundle("tiny-test")


@pytest.fixture(scope="module")
def cfg32():
    # TWO denoising stages: the latent ring then carries real cross-frame
    # state, so "the migrated state mattered" is assertable (a 1-stage
    # turbo config is a pure function of the input frame)
    return registry.default_stream_config(
        "tiny-test", t_index_list=(0, 1), num_inference_steps=2,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        height=32, width=32,
    )


def _mk_sched(bundle, cfg, **kw):
    kw.setdefault("max_sessions", 2)
    kw.setdefault("window_ms", 10_000.0)
    kw.setdefault("prewarm", False)
    return BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt, **kw
    )


def _tick(sess, frame):
    return np.asarray(sess.fetch(sess.submit(frame)))


# ---------------------------------------------------------------------------
# 1. bit-identity between two loopback agents (the acceptance pin)
# ---------------------------------------------------------------------------

def test_migrate_mid_stream_bit_identical_and_abort_safe(bundle, cfg32):
    """Agent A serves a session for 6 frames; its snapshot restores on
    agent B; frames 6..11 on B are BIT-IDENTICAL to an unmigrated
    control — and the first post-migration frame proves continuity (no
    re-prime: a fresh session's output differs).  The source session on
    A keeps serving bit-identically after the export AND after refused
    restores (schema / fingerprint / corrupt blob / full pool)."""
    A = _mk_sched(bundle, cfg32)
    B = _mk_sched(bundle, cfg32)
    C = _mk_sched(bundle, cfg32)  # the unmigrated control plane
    rng = np.random.default_rng(11)
    frames = [
        rng.integers(0, 256, (32, 32, 3), np.uint8) for _ in range(12)
    ]
    try:
        sa = A.claim("sa", prompt="migration prompt", seed=5)
        sc = C.claim("sc", prompt="migration prompt", seed=5)
        # live control-plane updates must ride the snapshot too
        sa.update_guidance(guidance_scale=1.4, delta=0.8)
        sc.update_guidance(guidance_scale=1.4, delta=0.8)
        for f in frames[:6]:
            assert np.array_equal(_tick(sa, f), _tick(sc, f))

        snap = A.snapshot_session("sa")
        assert snap["schema"] == SESSION_SNAPSHOT_SCHEMA
        assert snap["prompt"] == "migration prompt"
        assert snap["guidance_scale"] == pytest.approx(1.4)

        # -- abort-safety: every refused restore leaves B untouched ----
        bad = dict(snap)
        bad["schema"] = SESSION_SNAPSHOT_SCHEMA + 1
        with pytest.raises(SnapshotMismatch, match="schema"):
            B.restore_session(bad, "x")
        bad = dict(snap)
        bad["fingerprint"] = dict(snap["fingerprint"], height=64)
        with pytest.raises(SnapshotMismatch, match="fingerprint"):
            B.restore_session(bad, "x")
        bad = dict(snap)
        blob = bytearray(base64.b64decode(snap["state_b64"]))
        blob[len(blob) // 2] ^= 0xFF  # flip one payload bit
        bad["state_b64"] = base64.b64encode(bytes(blob)).decode()
        with pytest.raises(SnapshotMismatch, match="unusable|checksum"):
            B.restore_session(bad, "x")
        bad = dict(snap)
        bad["state_b64"] = snap["state_b64"][: len(snap["state_b64"]) // 2]
        with pytest.raises(SnapshotMismatch):
            B.restore_session(bad, "x")
        bad = dict(snap)
        bad["t_index_list"] = [0]  # wrong length for the compiled steps
        with pytest.raises(SnapshotMismatch, match="t_index_list"):
            B.restore_session(bad, "x")
        assert B.live_sessions == 0  # nothing landed

        # -- the move -------------------------------------------------
        sb = B.restore_session(snap, "sb")
        assert sb.prompt == "migration prompt"
        assert sb.guidance_scale == pytest.approx(1.4)
        out_first = _tick(sb, frames[6])
        ctrl_first = _tick(sc, frames[6])
        # frame continuity: the migrated session continues the control's
        # stream exactly...
        assert np.array_equal(out_first, ctrl_first)
        for f in frames[7:]:
            assert np.array_equal(_tick(sb, f), _tick(sc, f))

        # ...while the SOURCE was never touched by the export or the
        # refused restores: its state is still parked after frame 5, so
        # stepping frame 6 NOW reproduces the control's frame-6 output
        assert np.array_equal(_tick(sa, frames[6]), ctrl_first)

        # ...and a FRESH session does NOT reproduce the control's frame
        # (the migrated state genuinely mattered — no keyframe re-prime)
        sb.release()
        fresh = B.claim("fresh", prompt="migration prompt", seed=5)
        fresh.update_guidance(guidance_scale=1.4, delta=0.8)
        assert not np.array_equal(_tick(fresh, frames[6]), ctrl_first)

        # full pool refuses with CapacityError (the 503 path), state
        # intact
        B.claim("filler")
        with pytest.raises(CapacityError):
            B.restore_session(snap, "overflow")
    finally:
        for s in (A, B, C):
            s.close()


def _mk_adapter_reg(bundle):
    """Synthetic two-style registry (rank 2 -> bucket 4) — deterministic,
    so two independently-built schedulers carry identical banks (the
    restarted-agent / destination-agent boot path)."""
    from ai_rtc_agent_tpu.adapters import AdapterRegistry
    from ai_rtc_agent_tpu.models import loader as LD

    mq = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q"
    mv = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_v"
    rng = np.random.default_rng(7)

    def groups(mods):
        return {
            m: {
                "down": (rng.normal(size=(2, 8)) * 0.2).astype(np.float32),
                "up": (rng.normal(size=(8, 2)) * 0.2).astype(np.float32),
                "alpha": 2.0,
            }
            for m in mods
        }

    reg = AdapterRegistry(
        bundle.params["unet"], LD.unet_key_map(bundle.unet_cfg)
    )
    reg.add("styleA", groups([mq]))
    reg.add("styleB", groups([mq, mv]))
    return reg


def test_migrate_adapter_style_rides_snapshot_and_crash_resume(bundle, cfg32):
    """ISSUE 20 satellite: migration carries style.  The schema-2 payload
    names the adapter and the state row carries its factor bank; restore
    lands the rows BIT-EXACT and the destination session keeps serving
    the styled stream identically.  A schema-1 (pre-adapter) snapshot is
    REFUSED by the version gate; an adapterless scheduler refuses the
    bank-carrying fingerprint (and vice versa) BEFORE touching state.
    Crash-resume (the AGENT_DEAD flow: the dead agent's banked snapshot
    restored on a fresh boot) restores the adapter too."""
    A = _mk_sched(bundle, cfg32, adapters=_mk_adapter_reg(bundle))
    B = _mk_sched(bundle, cfg32, adapters=_mk_adapter_reg(bundle))
    D = _mk_sched(bundle, cfg32)  # adapterless
    A2 = None
    rng = np.random.default_rng(13)
    frames = [
        rng.integers(0, 256, (32, 32, 3), np.uint8) for _ in range(10)
    ]
    try:
        sa = A.claim("sa", prompt="styled stream", seed=5, adapter="styleA")
        for f in frames[:4]:
            _tick(sa, f)
        snap = A.snapshot_session("sa")
        assert snap["schema"] == SESSION_SNAPSHOT_SCHEMA == 2
        assert snap["adapter"] == "styleA"
        assert snap["fingerprint"]["adapter_rank"] == 4
        assert snap["fingerprint"]["adapter_targets"]

        # schema 1 (pre-adapter) -> the version gate refuses it outright
        old = dict(snap)
        old["schema"] = 1
        with pytest.raises(SnapshotMismatch, match="schema"):
            B.restore_session(old, "x")
        # bank-carrying rows can't land on an adapterless bank shape...
        with pytest.raises(SnapshotMismatch, match="fingerprint"):
            D.restore_session(snap, "x")
        # ...and a bankless row can't land on a bank-carrying scheduler
        D.claim("sd", prompt="plain", seed=6)
        snap_plain = D.snapshot_session("sd")
        assert snap_plain["adapter"] is None
        with pytest.raises(SnapshotMismatch, match="fingerprint"):
            B.restore_session(snap_plain, "x")
        assert B.live_sessions == 0 and D.live_sessions == 1

        # the move: style name + factor rows land bit-exact
        sb = B.restore_session(snap, "sb")
        assert sb.adapter == "styleA"
        for path in A.states["adapters"]:
            for part in ("down", "up"):
                np.testing.assert_array_equal(
                    np.asarray(B.states["adapters"][path][part][sb.slot]),
                    np.asarray(A.states["adapters"][path][part][sa.slot]),
                )
        # continuity: the export never touched the source, so both sides
        # keep serving the styled stream identically
        for f in frames[4:6]:
            assert np.array_equal(_tick(sb, f), _tick(sa, f))

        # crash-resume: B's periodic bank survives B; a fresh boot (same
        # ADAPTER_DIR catalog) restores the styled session mid-stream
        bank = B.snapshot_session("sb")
        B.close()
        A2 = _mk_sched(bundle, cfg32, adapters=_mk_adapter_reg(bundle))
        s2 = A2.restore_session(bank, "sb")
        assert s2.adapter == "styleA"
        for f in frames[6:8]:
            assert np.array_equal(_tick(s2, f), _tick(sa, f))
        # restart() on the resumed session keeps the style bound
        s2.restart()
        assert s2.adapter == "styleA"
        assert A2.snapshot()["adapter_sessions"] == 1
    finally:
        for s in (A, B, D, A2):
            if s is not None:
                s.close()


def test_snapshot_unknown_session_and_fingerprint_shape(bundle, cfg32):
    sched = _mk_sched(bundle, cfg32)
    try:
        with pytest.raises(KeyError):
            sched.snapshot_session("nobody")
        fp = sched.snapshot_fingerprint()
        assert fp["model_id"] == ""  # built without a model id
        assert fp["height"] == 32 and fp["width"] == 32
        assert fp["fbs"] == 1
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# 2. checkpoint blob round-trip property (satellite)
# ---------------------------------------------------------------------------

def test_checkpoint_blob_roundtrip_every_leaf_kind():
    """Every leaf kind the session pytree actually carries survives the
    blob bit-exactly: f32/bf16 state rows, uint8 frames, uint32 PRNG key
    arrays, 0-d scalars, nested dict/list/tuple structure and python
    scalars."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    tree = {
        "x_buf": rng.standard_normal((2, 4, 4, 4)).astype(np.float32),
        "noise_bf16": jnp.asarray(
            rng.standard_normal((3, 8)), jnp.bfloat16
        ),
        "frame_u8": rng.integers(0, 256, (32, 32, 3)).astype(np.uint8),
        "prng_key": jax.random.PRNGKey(123),
        "coeffs": {
            "timesteps": np.asarray([999], np.int32),
            "scalar0d": np.float32(0.125),
        },
        "meta": ["prompt", 1.5, None, True, (np.int64(7),)],
    }
    back = deserialize_pytree(serialize_pytree(tree))
    flat_a, td_a = jax.tree.flatten(tree)
    flat_b, td_b = jax.tree.flatten(back)
    assert td_a == td_b
    for a, b in zip(flat_a, flat_b):
        aa, bb = np.asarray(a), np.asarray(b)
        assert aa.dtype == bb.dtype
        assert aa.shape == bb.shape
        assert aa.tobytes() == bb.tobytes()  # BIT exact, not just close


def test_checkpoint_blob_refuses_corrupt_and_truncated():
    blob = serialize_pytree({"a": np.arange(16, dtype=np.float32)})
    with pytest.raises(ValueError, match="magic|version"):
        deserialize_pytree(b"NOTMAGIC" + blob[8:])
    with pytest.raises(ValueError, match="truncated"):
        deserialize_pytree(blob[:6])
    with pytest.raises(ValueError, match="truncated"):
        deserialize_pytree(blob[:-4])  # payload cut short
    flipped = bytearray(blob)
    flipped[-1] ^= 0x01  # corrupt the last payload byte
    with pytest.raises(ValueError, match="checksum"):
        deserialize_pytree(bytes(flipped))
    # a header-length field pointing past the end is truncation, not a
    # crash
    import struct

    bad = blob[:8] + struct.pack("<I", 10_000_000) + blob[12:]
    with pytest.raises(ValueError, match="truncated"):
        deserialize_pytree(bad)


def test_similarity_filter_state_roundtrip():
    """The filter's stochastic decisions replay exactly after
    export/restore (RNG position + previous-frame digest + streak)."""
    from ai_rtc_agent_tpu.stream.engine import SimilarityFilter

    rng = np.random.default_rng(2)
    a = SimilarityFilter(0.5, 3, seed=9)
    frames = [
        rng.integers(0, 256, (32, 32, 3), np.uint8) for _ in range(4)
    ] + [np.full((32, 32, 3), 7, np.uint8)] * 6
    for f in frames[:5]:
        a.should_skip(f, have_output=True)
    b = SimilarityFilter(0.5, 3, seed=0)  # wrong seed on purpose
    b.restore_state(a.export_state())
    for f in frames[5:]:
        assert a.should_skip(f, have_output=True) == b.should_skip(
            f, have_output=True
        )
    with pytest.raises(ValueError):
        b.restore_state({"skip_count": "x"})


# ---------------------------------------------------------------------------
# 3. HTTP orchestration: two real agent apps + the real router
# ---------------------------------------------------------------------------

class _MigSession:
    """Duck-typed scheduler session whose identity is a state counter —
    adoption continuity is assertable without a model."""

    owns_step_signal = True

    def __init__(self, owner, slot, key, counter=0):
        from ai_rtc_agent_tpu.resilience.overload import DeadlineQueue

        self._owner = owner
        self.slot = slot
        self.session_key = key
        self.counter = counter
        self.prompt = "p"
        self.window_queue = DeadlineQueue(2)

    def __call__(self, frame):
        self.counter += 1
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def update_prompt(self, p):
        self.prompt = p

    def update_t_index_list(self, t):
        pass

    def release(self):
        self._owner.released.append(self.session_key)


class _MigScheduler:
    """Fake batch scheduler speaking the migration surface: snapshot
    carries the session counter, restore recreates it (or refuses —
    ``refuse_restores`` models a mismatched target; ``explode_restores``
    models an unexpected runtime failure inside the install)."""

    def __init__(self, max_sessions=2, refuse_restores=False,
                 restore_delay_s=0.0, explode_restores=False):
        self.max_sessions = max_sessions
        self.sessions = {}
        self.released = []
        self.restores = 0
        self.refuse_restores = refuse_restores
        self.restore_delay_s = restore_delay_s
        self.explode_restores = explode_restores
        self.on_step = None

    @property
    def free_slots(self):
        return self.max_sessions - len(
            [s for s in self.sessions.values()
             if s.session_key not in self.released]
        )

    def claim(self, session_key=None, prompt=None, seed=None):
        if self.free_slots <= 0:
            raise CapacityError("full")
        sess = _MigSession(self, len(self.sessions), session_key)
        self.sessions[session_key] = sess
        return sess

    def session(self, key):
        # scan by the session_key ATTRIBUTE (the real scheduler's
        # semantics): adoption renames a restored session to the freshly
        # minted stream id
        for s in self.sessions.values():
            if s.session_key == key and key not in self.released:
                return s
        return None

    def snapshot_session(self, key):
        sess = self.sessions.get(key)
        if sess is None:
            raise KeyError(key)
        return {
            "schema": SESSION_SNAPSHOT_SCHEMA,
            "kind": "scheduler",
            "counter": sess.counter,
            "prompt": sess.prompt,
        }

    def restore_session(self, snap, key=None):
        self.restores += 1
        if self.restore_delay_s:
            time.sleep(self.restore_delay_s)
        if self.explode_restores:
            raise RuntimeError("injected install failure")
        if self.refuse_restores or snap.get("schema") != (
            SESSION_SNAPSHOT_SCHEMA
        ):
            raise SnapshotMismatch("refused by test target")
        if self.free_slots <= 0:
            raise CapacityError("full")
        sess = _MigSession(
            self, len(self.sessions), key, counter=int(snap["counter"])
        )
        sess.prompt = snap.get("prompt", "p")
        self.sessions[key] = sess
        return sess

    def update_prompt(self, p):
        pass

    def update_t_index_list(self, t):
        pass

    def snapshot(self):
        return {"batchsched_sessions": self.max_sessions - self.free_slots,
                "batchsched_max_sessions": self.max_sessions}

    def session_snapshots(self):
        return {
            s.session_key: {"slot": s.slot}
            for s in self.sessions.values()
            if s.session_key not in self.released
        }

    def close(self):
        pass


async def _spawn_agent(sched):
    from aiohttp.test_utils import TestClient, TestServer

    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    class _Stub:
        def __call__(self, frame):
            return frame

    app = build_app(
        pipeline=_Stub(), provider=LoopbackProvider(), batch_scheduler=sched
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    return app, client


def _offer_body():
    from ai_rtc_agent_tpu.server.signaling import make_loopback_offer

    return {
        "room_id": "mig-room",
        "offer": {"sdp": make_loopback_offer(), "type": "offer"},
    }


async def _fleet_harness(scheds):
    """Real router + one real agent app per fake scheduler, registered
    and polled once.  -> (router_client, router_app, agents, posted)."""
    from aiohttp.test_utils import TestClient, TestServer

    from ai_rtc_agent_tpu.fleet.registry import FleetRegistry
    from ai_rtc_agent_tpu.fleet.router import build_router_app
    from ai_rtc_agent_tpu.server.events import StreamEventHandler

    posted = []

    class _Resp:
        status = 200

    class _CaptureSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return _Resp()

    events = StreamEventHandler(
        session_factory=_CaptureSession,
        webhook_url="http://client-notify.example/hook", token="t",
    )
    reg = FleetRegistry(dead_after=2)
    router_app = build_router_app(
        registry=reg, events_handler=events, poll=True
    )
    router = TestClient(TestServer(router_app))
    await router.start_server()
    agents = []
    for i, sched in enumerate(scheds):
        app, client = await _spawn_agent(sched)
        agents.append((app, client))
        r = await router.post("/fleet/register", json={
            "worker_id": f"m-agent{i}", "public_ip": "127.0.0.1",
            "public_port": str(client.server.port), "status": "ready",
            "capacity": sched.max_sessions,
        })
        assert r.status == 200
    await router_app["poller"].poll_once()
    return router, router_app, agents, posted


async def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while True:
        r = predicate()
        if r:
            return r
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        await asyncio.sleep(0.05)


def test_http_migrate_drain_moves_session_and_repins_reoffer():
    """The full wire story: drain?mode=migrate exports off the source,
    imports on the target under a counted reservation, fires
    StreamMigrated, and the client's echoed re-offer is PINNED to the
    target where the imported session is ADOPTED as journey leg 2 with
    its state counter intact (mid-stream resume, not a fresh claim)."""
    src_sched = _MigScheduler()
    dst_sched = _MigScheduler()

    async def go():
        router, router_app, agents, posted = await _fleet_harness(
            [src_sched, dst_sched]
        )
        try:
            r = await router.post("/offer", json=_offer_body())
            assert r.status == 200, await r.text()
            sid = r.headers["X-Stream-Id"]
            jid = r.headers["X-Journey-Id"]
            assert router_app["session_table"].owner(sid) == "m-agent0"
            # stream a little: the counter IS the mid-stream state
            sess = src_sched.session(sid)
            for _ in range(5):
                sess(np.zeros((4, 4, 3), np.uint8))
            assert sess.counter == 5

            r = await router.post(
                "/fleet/drain?agent=m-agent0&mode=migrate"
            )
            body = await r.json()
            assert body["draining"] and body["mode"] == "migrate"
            assert body["migrating"] == 1

            migrated = await _wait_for(
                lambda: [e for e in posted
                         if e.get("event") == "StreamMigrated"],
                10, "StreamMigrated webhook",
            )
            ev = migrated[0]
            assert ev["stream_id"] == sid
            assert ev["journey_id"] == jid
            assert ev["source_agent"] == "m-agent0"
            assert ev["target_agent"] == "m-agent1"
            assert ev["reason"] == "drain"
            assert dst_sched.restores == 1
            # the source kept serving the whole time
            assert src_sched.released == []

            # the client re-offers echoing its journey id -> pinned to
            # the target, adopted, leg 2
            r = await router.post(
                "/offer", json=_offer_body(),
                headers={"X-Journey-Id": jid},
            )
            assert r.status == 200, await r.text()
            assert r.headers["X-Journey-Id"] == jid
            assert r.headers["X-Journey-Leg"] == "2"
            new_sid = r.headers["X-Stream-Id"]
            assert router_app["session_table"].owner(new_sid) == "m-agent1"
            adopted = dst_sched.session(new_sid)
            assert adopted is not None
            assert adopted.counter == 5  # mid-stream state, not a fresh claim
            # adoption consumed the parked import (no double-adopt)
            dst_app = agents[1][0]
            assert dst_app["imported_sessions"] == {}
            # one journey, both legs; the ring tells the move story
            record = router_app["journeys"].get(jid)
            kinds = [e["kind"] for e in record["events"]]
            assert "migrated" in kinds
            assert [leg["agent"] for leg in record["legs"]] == [
                "m-agent0", "m-agent1",
            ]
            m = await (await router.get("/metrics")).json()
            assert m["migrations_total"] == 1
            assert m.get("migrations_failed_total", 0) == 0
            assert m["migration_ms_p50"] > 0
            # a moved session's banked export is dropped — the source
            # dying later must not crash-restore a SECOND copy
            assert m["migration_snapshots_banked"] == 0
            # prom rendering stays label-free and conformant
            r = await router.get("/metrics", params={"format": "prom"})
            text = await r.text()
            assert "# TYPE migrations_total counter" in text
            assert "migration_ms_p50" in text
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())


def test_http_migrate_abort_safety_and_retry_4xx_terminal():
    """A target that REFUSES the restore (schema-mismatch 409) gets
    exactly ONE import attempt (the retry-4xx rule) and the source keeps
    serving — the drain degrades to kill semantics with a
    ``migrate_failed`` ring entry and captured evidence."""
    src_sched = _MigScheduler()
    dst_sched = _MigScheduler(refuse_restores=True)

    async def go():
        router, router_app, agents, posted = await _fleet_harness(
            [src_sched, dst_sched]
        )
        try:
            r = await router.post("/offer", json=_offer_body())
            assert r.status == 200
            sid = r.headers["X-Stream-Id"]
            jid = r.headers["X-Journey-Id"]
            r = await router.post(
                "/fleet/drain?agent=m-agent0&mode=migrate"
            )
            assert (await r.json())["migrating"] == 1

            def failed():
                rec = router_app["journeys"].get(jid)
                return [e for e in rec["events"]
                        if e["kind"] == "migrate_failed"]

            await _wait_for(failed, 10, "migrate_failed ring entry")
            assert dst_sched.restores == 1  # 409 was TERMINAL: one attempt
            assert src_sched.released == []  # source serving untouched
            assert not [e for e in posted
                        if e.get("event") == "StreamMigrated"]
            m = await (await router.get("/metrics")).json()
            assert m["migrations_failed_total"] == 1
            assert m.get("migrations_total", 0) == 0
            # the banked export still serves the crash path
            assert m["migration_snapshots_banked"] == 1
            # kill-drain semantics continue: agent frozen, recyclable
            # once the client eventually leaves
            rec = router_app["fleet"].agents["m-agent0"]
            assert rec.draining
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())


def test_http_crash_restore_reuses_banked_snapshot():
    """AGENT_DEAD with a recent snapshot banked (an interrupted
    drain-as-move exported it before the source died): the crash path
    reuses the restore surface — import on a survivor + StreamMigrated
    (reason=agent_dead) instead of the plain AGENT_DEAD re-point — and
    the client resumes mid-stream."""
    src_sched = _MigScheduler()
    dst_sched = _MigScheduler(refuse_restores=True)

    async def go():
        router, router_app, agents, posted = await _fleet_harness(
            [src_sched, dst_sched]
        )
        try:
            r = await router.post("/offer", json=_offer_body())
            assert r.status == 200
            sid = r.headers["X-Stream-Id"]
            jid = r.headers["X-Journey-Id"]
            sess = src_sched.session(sid)
            for _ in range(7):
                sess(np.zeros((4, 4, 3), np.uint8))
            # a migrate-drain whose import FAILS still banks the export
            await router.post("/fleet/drain?agent=m-agent0&mode=migrate")
            await _wait_for(
                lambda: router_app["snapshot_bank"].get(sid), 10,
                "banked snapshot",
            )
            assert not [e for e in posted
                        if e.get("event") == "StreamMigrated"]

            # the target recovers; then the SOURCE dies (SIGKILL shape:
            # consecutive poll failures) -> crash restore from the bank
            dst_sched.refuse_restores = False
            reg = router_app["fleet"]
            rec = reg.agents["m-agent0"]
            reg.note_poll_fail(rec)
            reg.note_poll_fail(rec)
            assert rec.state == "DEAD"

            migrated = await _wait_for(
                lambda: [e for e in posted
                         if e.get("event") == "StreamMigrated"],
                10, "crash-restore StreamMigrated",
            )
            ev = migrated[0]
            assert ev["reason"] == "agent_dead"
            assert ev["target_agent"] == "m-agent1"
            assert ev["journey_id"] == jid
            # no plain AGENT_DEAD re-point for this stream — the restore
            # superseded it
            assert not [e for e in posted
                        if e.get("state") == "AGENT_DEAD"]
            # the echoed re-offer adopts the restored mid-stream state
            r = await router.post(
                "/offer", json=_offer_body(),
                headers={"X-Journey-Id": jid},
            )
            assert r.status == 200
            new_sid = r.headers["X-Stream-Id"]
            assert router_app["session_table"].owner(new_sid) == "m-agent1"
            adopted = dst_sched.session(new_sid)
            assert adopted is not None and adopted.counter == 7
            m = await (await router.get("/metrics")).json()
            assert m["migrations_total"] == 1
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())


def test_http_migrate_timeout_falls_back_to_kill_drain():
    """A hung target trips MIGRATE_TIMEOUT_S: the sweep is abandoned
    (migration_fallbacks_total), the source keeps serving, and the drain
    keeps its ordinary kill semantics."""
    src_sched = _MigScheduler()
    dst_sched = _MigScheduler(restore_delay_s=1.5)

    async def go():
        router, router_app, agents, posted = await _fleet_harness(
            [src_sched, dst_sched]
        )
        router_app["migrate_timeout_s"] = 0.2
        try:
            r = await router.post("/offer", json=_offer_body())
            assert r.status == 200
            r = await router.post(
                "/fleet/drain?agent=m-agent0&mode=migrate"
            )
            assert (await r.json())["migrating"] == 1

            await _wait_for(
                lambda: not router_app["migrate_tasks"], 10,
                "migrate sweep to finish",
            )
            m = await (await router.get("/metrics")).json()
            assert m["migration_fallbacks_total"] == 1
            assert m.get("migrations_total", 0) == 0
            assert src_sched.released == []
            assert router_app["fleet"].agents["m-agent0"].draining
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())


def test_http_migrate_drain_idempotent_and_cancel_stops_new_moves():
    """Code-review regressions: (a) re-asserting an already-draining
    migrate drain must NOT spawn a second sweep over the same sessions;
    (b) action=cancel stops NEW moves mid-sweep (in-flight ones finish)."""
    # the SOURCE advertises the most capacity so both offers land on it
    src_sched = _MigScheduler(max_sessions=4)
    dst_sched = _MigScheduler(max_sessions=2, restore_delay_s=0.3)

    async def go():
        router, router_app, agents, posted = await _fleet_harness(
            [src_sched, dst_sched]
        )
        router_app["migrate_max_parallel"] = 1
        try:
            for _ in range(2):
                r = await router.post("/offer", json=_offer_body())
                assert r.status == 200
            r = await router.post("/fleet/drain?agent=m-agent0&mode=migrate")
            assert (await r.json())["migrating"] == 2
            # an operator retry of the same drain: no second sweep
            r = await router.post("/fleet/drain?agent=m-agent0&mode=migrate")
            assert (await r.json())["migrating"] == 0
            # cancel while the FIRST move's import is still sleeping:
            # the superseded sweep's QUEUED session must never leave
            await router.post(
                "/fleet/drain?agent=m-agent0&action=cancel"
            )
            stale_restores = dst_sched.restores
            # ...and an IMMEDIATE restart must start a FRESH sweep (the
            # superseded sweep finishing its in-flight move does not
            # block it — cancel-then-restart migrates, it does not
            # silently degrade to kill semantics)
            r = await router.post(
                "/fleet/drain?agent=m-agent0&mode=migrate"
            )
            assert (await r.json())["migrating"] >= 1
            await _wait_for(
                lambda: not router_app["migrate_tasks"], 10,
                "sweeps to finish",
            )
            assert dst_sched.restores > stale_restores  # fresh sweep ran
            # a retry of the RUNNING fresh sweep still no-ops
            r = await router.post(
                "/fleet/drain?agent=m-agent0&mode=migrate"
            )
            # (sweep just finished, so this may re-sweep leftovers —
            # both outcomes are valid; the invariant is no CONCURRENT
            # duplicate, pinned by the stale_restores check above)
            await _wait_for(
                lambda: not router_app["migrate_tasks"], 10,
                "trailing sweep to finish",
            )
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())


def test_http_import_releases_reservation_on_unexpected_failure():
    """An install blowing up with an unexpected error (not a refusal,
    not capacity) answers 500 — and must NOT strand the counted
    admission reservation for its TTL (the router retries 5xx; a
    phantom reservation per episode would 503 real offers)."""
    sched = _MigScheduler(explode_restores=True)

    async def go():
        app, client = await _spawn_agent(sched)
        try:
            cap0 = (await (await client.get("/capacity")).json())["capacity"]
            r = await client.post("/migrate/import", json={
                "token": "boom",
                "snapshot": {
                    "kind": "scheduler",
                    "schema": SESSION_SNAPSHOT_SCHEMA,
                    "counter": 1,
                },
            })
            assert r.status == 500
            cap1 = (await (await client.get("/capacity")).json())["capacity"]
            assert cap1 == cap0  # reservation released, not stranded
        finally:
            await client.close()

    asyncio.run(go())


def test_http_ended_session_mid_sweep_is_not_a_failed_migration():
    """A client hanging up while its session waits in the sweep queue is
    a SUCCESSFUL drain outcome: no migrations_failed count, no
    migrate_failed ring entry, no evidence pull."""
    # the SOURCE advertises the most capacity so both offers land on it
    src_sched = _MigScheduler(max_sessions=4)
    dst_sched = _MigScheduler(max_sessions=2, restore_delay_s=0.3)

    async def go():
        router, router_app, agents, posted = await _fleet_harness(
            [src_sched, dst_sched]
        )
        router_app["migrate_max_parallel"] = 1
        src_app = agents[0][0]
        try:
            sids = []
            for _ in range(2):
                r = await router.post("/offer", json=_offer_body())
                assert r.status == 200
                sids.append(r.headers["X-Stream-Id"])
            r = await router.post("/fleet/drain?agent=m-agent0&mode=migrate")
            assert (await r.json())["migrating"] == 2
            # while the first move's import sleeps, the SECOND session
            # ends naturally: StreamEnded prunes the table and the agent
            # stops exporting it
            router_app["session_table"].forget(sids[1])
            src_app["supervisors"].pop(sids[1], None)
            src_sched.released.append(sids[1])
            await _wait_for(
                lambda: not router_app["migrate_tasks"], 10,
                "sweep to finish",
            )
            m = await (await router.get("/metrics")).json()
            assert m.get("migrations_failed_total", 0) == 0
            assert m["migrations_total"] == 1  # the live one moved
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())


def test_http_concurrent_import_same_token_restores_once():
    """A retry racing a FIRST import still inside its restore must not
    land a second slot: one request restores, the other answers 503 (or
    the idempotent parked result) — never two restores."""
    sched = _MigScheduler(restore_delay_s=0.3)

    async def go():
        app, client = await _spawn_agent(sched)
        try:
            body = {
                "token": "race",
                "snapshot": {
                    "kind": "scheduler",
                    "schema": SESSION_SNAPSHOT_SCHEMA,
                    "counter": 1,
                },
            }
            r1, r2 = await asyncio.gather(
                client.post("/migrate/import", json=body),
                client.post("/migrate/import", json=body),
            )
            statuses = sorted([r1.status, r2.status])
            assert statuses in ([200, 200], [200, 503]), statuses
            assert sched.restores == 1
            assert len(app["imported_sessions"]) == 1
        finally:
            await client.close()

    asyncio.run(go())


def test_http_stale_pin_is_ignored():
    """A migration pin older than the target's import TTL is dead (the
    parked session expired): the re-offer must fall back to ordinary
    placement instead of chasing the old target with a dead token."""
    src_sched = _MigScheduler()
    dst_sched = _MigScheduler()

    async def go():
        router, router_app, agents, posted = await _fleet_harness(
            [src_sched, dst_sched]
        )
        try:
            r = await router.post("/offer", json=_offer_body())
            assert r.status == 200
            jid = r.headers["X-Journey-Id"]
            await router.post("/fleet/drain?agent=m-agent0&mode=migrate")
            await _wait_for(
                lambda: jid in router_app["migrations"], 10, "pin"
            )
            router_app["migrations"][jid]["ts"] -= 60.0  # age past TTL
            r = await router.post(
                "/offer", json=_offer_body(),
                headers={"X-Journey-Id": jid},
            )
            assert r.status == 200
            new_sid = r.headers["X-Stream-Id"]
            # not adopted: wherever it landed, it is a FRESH claim (the
            # restored counter never surfaces) and the stale pin is gone
            adopted = dst_sched.session(new_sid)
            assert adopted is None or adopted.counter == 0
            assert jid not in router_app["migrations"]
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    asyncio.run(go())


def test_http_migrate_requires_journey_plane():
    """mode=migrate without the journey plane would silently degrade
    every move to a fresh re-prime (the re-offer pin is keyed by journey
    id) — the router refuses with 409 instead."""
    import os

    sched = _MigScheduler()

    async def go():
        router, router_app, agents, posted = await _fleet_harness([sched])
        try:
            assert router_app["journeys"] is None
            r = await router.post("/fleet/drain?agent=m-agent0&mode=migrate")
            assert r.status == 409
            assert "journey" in (await r.text())
            # plain kill-drain still works
            r = await router.post("/fleet/drain?agent=m-agent0")
            assert r.status == 200
        finally:
            for _app, client in agents:
                await client.close()
            await router.close()

    os.environ["JOURNEY_ENABLE"] = "0"
    try:
        asyncio.run(go())
    finally:
        os.environ.pop("JOURNEY_ENABLE", None)


def test_http_migrate_kill_switch_and_agent_surface():
    """MIGRATE_ENABLE=0 removes the surface end to end: the agent's
    export/import endpoints 404 and the router refuses mode=migrate with
    409 (drain itself still works).  With it on, the agent endpoints
    enforce the reservation-first + schema-refusal contract directly."""
    sched = _MigScheduler()

    async def go_disabled():
        app, client = await _spawn_agent(sched)
        try:
            r = await client.get("/migrate/export?session=x")
            assert r.status == 404
            r = await client.post("/migrate/import", json={})
            assert r.status == 404
        finally:
            await client.close()

    async def go_enabled():
        app, client = await _spawn_agent(sched)
        try:
            # unknown session -> 404; missing selector -> 400
            r = await client.get("/migrate/export")
            assert r.status == 400
            r = await client.get("/migrate/export?session=nobody")
            assert r.status == 404
            # import: schema mismatch -> 409 AND the reservation it took
            # is released (capacity unchanged)
            cap0 = (await (await client.get("/capacity")).json())["capacity"]
            r = await client.post("/migrate/import", json={
                "token": "t1",
                "snapshot": {"kind": "scheduler", "schema": 999},
            })
            assert r.status == 409
            cap1 = (await (await client.get("/capacity")).json())["capacity"]
            assert cap0 == cap1
            # a good import parks the session AND holds a reservation
            r = await client.post("/migrate/import", json={
                "token": "t2",
                "snapshot": {
                    "kind": "scheduler",
                    "schema": SESSION_SNAPSHOT_SCHEMA,
                    "counter": 3,
                },
            })
            assert r.status == 200
            body = await r.json()
            assert body["restored"] is True
            assert "t2" in app["imported_sessions"]
            cap2 = (await (await client.get("/capacity")).json())["capacity"]
            assert cap2 == cap1 - 1  # reservation counted, not double-sold
            # a RETRIED import under the same token (lost response) is
            # idempotent: no second restore, no second slot, the parked
            # session stays reachable
            restores_before = sched.restores
            r = await client.post("/migrate/import", json={
                "token": "t2",
                "snapshot": {
                    "kind": "scheduler",
                    "schema": SESSION_SNAPSHOT_SCHEMA,
                    "counter": 3,
                },
            })
            assert r.status == 200
            assert (await r.json())["restored"] is True
            assert sched.restores == restores_before
            cap3 = (await (await client.get("/capacity")).json())["capacity"]
            assert cap3 == cap2
            # unknown kind -> 400
            r = await client.post("/migrate/import", json={
                "token": "t3", "snapshot": {"kind": "??", "schema": 1},
            })
            assert r.status == 400
        finally:
            await client.close()

    import os

    os.environ["MIGRATE_ENABLE"] = "0"
    try:
        asyncio.run(go_disabled())
    finally:
        os.environ.pop("MIGRATE_ENABLE", None)
    asyncio.run(go_enabled())
