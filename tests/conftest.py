"""Test harness config: force CPU JAX with a virtual 8-device mesh.

Per SURVEY.md section 4 the multi-chip story is tested on a simulated mesh
(`--xla_force_host_platform_device_count=8`) — the standard JAX stand-in for
multi-chip without real hardware.  Must run before jax initializes a backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# NOTE (PR 6): the persistent XLA compilation cache
# (JAX_COMPILATION_CACHE_DIR) was tried here as a wall-time shave and
# REVERTED: on this jax 0.4.37 CPU backend with the virtual 8-device
# mesh it served stale/colliding executables across engine instances —
# tp-parity and quant-parity tests got all-zero frames from one engine,
# on fresh AND warm caches.  Do not re-enable without a jax upgrade and
# a green parity run.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# A sitecustomize hook may have imported jax already and pinned
# jax_platforms to an accelerator plugin (e.g. the axon TPU tunnel) —
# in that case the env var above is read too late, so force the config
# directly.  Backend init of the plugin would otherwise hang the suite.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-geometry tests (minutes on the 1-core CPU box)"
    )
