"""Continuous batch scheduler (stream/scheduler.py) — ISSUE 7.

The load-bearing guarantee is BIT-IDENTITY: a session served through the
cross-session batch scheduler must produce exactly the frames a dedicated
StreamEngine would, across dynamic join/leave, bucket transitions
(k=1/2/4 with padding), per-session prompt/guidance/t-index updates and
similarity skips.  That assertion runs in a SUBPROCESS without the
harness's 8-virtual-device flag (tests/batchsched_equiv_driver.py): the
virtual-device simulation changes XLA's CPU thread partitioning per batch
shape, which can flip a float rounding tie by one uint8 step — real
single-device serving (what the scheduler targets) is exact, and the
driver pins it.  Everything else here is hermetic in-process.
"""

import asyncio
import os
import subprocess
import sys

import numpy as np
import pytest

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.stream.scheduler import BatchScheduler, CapacityError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bundle():
    return registry.load_model_bundle("tiny-test")


@pytest.fixture(scope="module")
def cfg():
    return registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
    )


def test_equivalence_dense_subprocess():
    """The tier-1 acceptance pin: the full join/leave/prompt/guidance/
    t-index/similarity/restart drive, every frame compared BIT-EXACT
    against dedicated engines, on a clean single-device CPU runtime.
    The ISSUE 9/13 variant legs (w8, DeepCache, fbs — each re-tracing
    the whole k=4/2/1 geometry set) run in the slow composition test
    below (ISSUE 17 budget shave: this lighter sibling keeps the
    bit-identity guarantee in tier-1 at a third of the compile bill)."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "tests/batchsched_equiv_driver.py",
         "--leg", "dense"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("EQUIV_OK")]
    assert lines, r.stdout
    assert int(lines[0].split()[1]) >= 40  # the dense drive alone


# slow tier (ISSUE 17 budget shave): the variant COMPOSITION legs each
# re-trace k=4/2/1 — most of the driver's wall clock; tier-1 keeps the
# dense bit-identity drive above as the lighter sibling
@pytest.mark.slow
def test_equivalence_bit_identical_subprocess():
    """The full composition: the dense drive PLUS the ISSUE 9 variant
    legs (w8 quant and the DeepCache cadence THROUGH the scheduler's
    bucket steps, k=4/2/1, same documented exact tolerance), the fbs=2
    leg and the ISSUE 20 adapter leg (per-session LoRA factor banks vs
    offline-fused dedicated engines across join/leave/hot-swap/restart;
    tolerance = the documented rounding-tie class, zero-factor slots
    bit-exact)."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "tests/batchsched_equiv_driver.py"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("EQUIV_OK")]
    assert lines, r.stdout
    assert int(lines[0].split()[1]) >= 70  # dense + both variant legs
    for leg, floor in (("EQUIV_W8_OK", 15), ("EQUIV_DC_OK", 15),
                       ("EQUIV_ADAPTER_OK", 25)):
        leg_lines = [
            ln for ln in r.stdout.splitlines() if ln.startswith(leg)
        ]
        assert leg_lines, f"{leg} leg missing: {r.stdout}"
        assert int(leg_lines[0].split()[1]) >= floor


@pytest.mark.slow
def test_sharded_equivalence_subprocess():
    """ISSUE 12 acceptance pin: the dp=2 mesh-sharded scheduler vs
    dedicated engines across join/leave spanning the shard boundary,
    control-plane updates, restart and rejoin — run under the
    8-virtual-device flag (the sharded serving simulation).  Tolerance:
    a single uint8 rounding tie (the virtual-device flag changes XLA's
    CPU thread partitioning between the sharded batch-k and batch-1
    graphs — PR 7's documented tie class; the driver reports the count,
    observed 0 on this box).

    Slow tier (ISSUE 14 budget shave): the dp COMPOSITION leg — tier-1
    keeps the single-device equivalence driver, the dp churn/retrace pin
    and the shard-aware key coverage as the lighter siblings."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.pop("XLA_FLAGS", None)  # the driver forces its own 8-device flag
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "tests/batchsched_equiv_driver.py",
         "--leg", "sharded"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [
        ln for ln in r.stdout.splitlines() if ln.startswith("EQUIV_SHARD_OK")
    ]
    assert lines, r.stdout
    assert int(lines[0].split()[1]) >= 15


# slow tier (ISSUE 18 budget shave): prewarm=True compiles every
# (k, variant, dp) geometry before the churn even starts — most of this
# test's wall clock; tier-1 keeps
# test_shard_aware_bucket_keys_and_prewarm_coverage below, which pins
# the same prewarm-coverage + shard-keyed-executable mechanism without
# the compile bill
@pytest.mark.slow
def test_sharded_churn_never_retraces(bundle):
    """ISSUE 12 acceptance pin: a prewarmed dp-sharded scheduler serves a
    join -> leave -> rejoin churn (control-plane writes and a restart
    included) with ZERO devtel retrace breaches — prewarm covers every
    (k, variant, dp) geometry, attributed under the mesh-carrying scope
    name, and every serving-phase dispatch hits a warm executable."""
    from ai_rtc_agent_tpu.obs import devtel
    from ai_rtc_agent_tpu.obs.devtel import DevTelPlane

    cfg32 = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        height=32, width=32,
    )
    plane = devtel.activate(DevTelPlane())
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg32, bundle.encode_prompt,
        max_sessions=2, window_ms=10_000.0, prewarm=True, dp=2,
    )
    rng = np.random.default_rng(5)

    def tick(sessions):
        fs = [
            rng.integers(0, 256, (32, 32, 3), np.uint8) for _ in sessions
        ]
        hs = [x.submit(f) for x, f in zip(sessions, fs)]
        return [x.fetch(h) for x, h in zip(sessions, hs)]

    try:
        # prewarm attributed with the mesh shape in the scope name,
        # expected (a serve-time re-prewarm must never false-alarm),
        # all in the warmup phase
        ctxs = {c["context"] for c in plane.compiles}
        assert "sbucket-2:full:dp2" in ctxs, ctxs
        assert all(
            c["expected"] for c in plane.compiles
            if c["context"] == "sbucket-2:full:dp2"
        )
        assert plane.retrace_breaches == 0
        a = s.claim("a", prompt="pa", seed=1)
        b = s.claim("b", prompt="pb", seed=2)
        tick([a, b])  # warm the host-side eager ops too (agent warmup)
        b.release()
        tick([a])
        plane.serving()
        # churn across the shard boundary on warm executables only
        tick([a])
        b2 = s.claim("b2", prompt="pb2", seed=9)  # rejoin -> shard 1
        tick([a, b2])
        a.update_prompt("new prompt")
        b2.update_guidance(guidance_scale=1.5)
        a.restart()
        tick([a, b2])
        a.release()
        tick([b2])
        assert plane.retrace_breaches == 0, [
            c for c in plane.compiles if c["phase"] == "serving"
        ]
    finally:
        devtel.deactivate(plane)
        s.close()


def test_shard_aware_bucket_keys_and_prewarm_coverage(bundle, cfg, tmp_path):
    """Unit pins for the dp key plane: bucket sizes are dp multiples
    (padding rows land on idle shards), every key carries the mesh shape
    (``dp-N`` via aot/cache.mesh_key_extra) so sharded executables never
    collide with single-device slots, prewarm covers every (k, variant,
    dp) geometry, AOT export refuses (a serialized program is
    per-topology), and slot->shard residence is slot-major."""
    import jax

    from ai_rtc_agent_tpu.aot.cache import mesh_key_extra

    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=4, window_ms=10_000.0, prewarm=False, dp=2,
    )
    try:
        assert s.dp == 2
        assert s._bucket_sizes == [2, 4]  # dp multiples, never k=1
        keys = s.bucket_keys("tiny-test")
        assert set(keys) == {(2, "full"), (4, "full")}
        assert all("dp-2" in k for k in keys.values())
        assert mesh_key_extra(s.mesh) == {"dp": 2}
        assert mesh_key_extra(None) == {}
        # devtel attribution scope carries the mesh; dp=1 spelling intact
        assert s._bucket_label(2, "full") == "sbucket-2:full:dp2"
        # per-topology: the sharded scheduler never adopts/exports AOT
        assert s.use_aot_cache(
            "tiny-test", cache_dir=str(tmp_path), build_on_miss=True
        ) is False
        # slot-major shard residence: contiguous S/dp blocks per device
        devs = [s._slot_device(i) for i in range(4)]
        assert devs[0] == devs[1] and devs[2] == devs[3]
        assert devs[0] != devs[2]
        assert {devs[0], devs[2]} <= set(jax.devices())
        # the stacked states are born sharded over the session axis
        leaf = s.states["noise"]
        assert len(leaf.sharding.device_set) == 2
        snap = s.snapshot()
        assert snap["batchsched_dp"] == 2
        assert snap["batchsched_shard_sessions"] == {"0": 0, "1": 0}
    finally:
        s.close()


def test_capacity_and_window_shed(bundle, cfg):
    """Slot exhaustion raises CapacityError (503 at the agent); the
    bounded coalescing window sheds its OLDEST frame as an immediate
    passthrough (ShedFrame) — the waiter never hangs.  No device step is
    ever dispatched (huge window, partial batch), so this is compile-free."""
    from ai_rtc_agent_tpu.resilience.overload import ShedFrame

    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=2, window_ms=10_000.0, queue_bound=2, prewarm=False,
    )
    try:
        a = s.claim("a")
        s.claim("b")
        with pytest.raises(CapacityError):
            s.claim("c")
        # only session a submits: the dispatcher holds the (huge) window
        # waiting for b, so a's queue fills — the 3rd submit evicts the
        # 1st, whose waiter resolves as ShedFrame RIGHT AWAY
        f = np.zeros((64, 64, 3), np.uint8)
        h1 = a.submit(f)
        a.submit(f + 1)
        a.submit(f + 2)
        out = h1.future.result(timeout=2.0)
        assert isinstance(out, ShedFrame)
        assert a.fetch(h1) is out  # fetch passes the marker through raw
        assert a.window_queue.shed_overflow == 1
        snap = s.snapshot()
        assert snap["batchsched_sessions"] == 2
        assert snap["batchsched_max_sessions"] == 2
        assert s.session_snapshots()["a"]["window_shed"] == 1
    finally:
        s.close()


def test_global_t_index_default_outlives_sessions(bundle):
    """POST /config semantics (review round 1): a global t_index update
    with ZERO live sessions must become the default future claims prepare
    with — exactly like the prompt/guidance defaults — and invalid
    updates must fail the call, not the next claim.  Compile-free (no
    frame is ever dispatched)."""
    from ai_rtc_agent_tpu.stream.engine import _coeff_state

    cfg8 = registry.default_stream_config(
        "tiny-test", t_index_list=(2,), num_inference_steps=8,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
    )
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg8, bundle.encode_prompt,
        max_sessions=2, window_ms=10_000.0, prewarm=False,
    )
    try:
        with pytest.raises(ValueError):
            s.update_t_index_list([1, 2])  # wrong length, zero sessions
        s.update_t_index_list([5])
        sess = s.claim("late-joiner")
        assert sess.t_index_list == [5]
        want = _coeff_state(cfg8, s._template.schedule, (5,))
        got = np.asarray(s.states["coeffs"]["timesteps"][sess.slot])
        np.testing.assert_array_equal(got, np.asarray(want["timesteps"]))
    finally:
        s.close()


def test_refuses_incompatible_configs(bundle):
    # DeepCache COMPOSES with the scheduler since ISSUE 9: a cadence
    # config registers the capture+cached bucket pair instead of refusing
    # (parity with dedicated engines is pinned by the equivalence driver)
    deep = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        unet_cache_interval=2,
    )
    s = BatchScheduler(
        bundle.stream_models, bundle.params, deep, bundle.encode_prompt,
        max_sessions=2, prewarm=False,
    )
    try:
        assert s._cache_interval == 2
        assert s._variants == ("capture", "cached")
        # every bucket geometry keys a PAIR, each with the variant field
        keys = s.bucket_keys("tiny-test")
        assert set(keys) == {(1, "capture"), (1, "cached"),
                             (2, "capture"), (2, "cached")}
        assert "variant-capture" in keys[(1, "capture")]
        assert "variant-cached" in keys[(2, "cached")]
    finally:
        s.close()
    # fbs composes with the session axis since ISSUE 12 (a second
    # batching dimension: [k, fbs, ...] bucket steps) — but not with the
    # similarity filter, whose skips would desync the fbs groups
    fbs = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        frame_buffer_size=2,
    )
    s2 = BatchScheduler(
        bundle.stream_models, bundle.params, fbs, bundle.encode_prompt,
        max_sessions=2, prewarm=False,
    )
    try:
        assert s2.fbs == 2
        assert s2.queue_bound >= 2  # holds at least one group
        specs = s2._bucket_specs(2)
        assert specs[2].shape == (2, 2, 64, 64, 3)  # [k, fbs, H, W, 3]
    finally:
        s2.close()
    fbs_sim = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        frame_buffer_size=2, similar_image_filter=True,
    )
    with pytest.raises(ValueError, match="similarity filter"):
        BatchScheduler(
            bundle.stream_models, bundle.params, fbs_sim,
            bundle.encode_prompt, max_sessions=2, prewarm=False,
        )
    # the dp axis must divide the slot capacity evenly
    with pytest.raises(ValueError, match="multiple of the dp axis"):
        BatchScheduler(
            bundle.stream_models, bundle.params, deep, bundle.encode_prompt,
            max_sessions=3, prewarm=False, dp=2,
        )


def test_amortized_admission_feed_and_step_recovery(
    bundle, cfg, tmp_path, rng
):
    """One compile-bearing in-process test (ISSUE 20 budget shave: the
    AOT export->adopt roundtrip that used to ride here — three more
    compiles — moved to the slow sibling below): (a) on_step receives
    PER-BATCH-AMORTIZED latency (dt / occupancy — what the overload
    plane's step-EWMA is wired to); (b) the bucket step donates the
    stacked state; (c) a failed step rebuilds the donated state from the
    tracked control planes and serving resumes."""
    feeds = []
    # every phase below relies on a+b coalescing into ONE k=2 batch; a
    # wide window makes that deterministic on a throttled box (a 2 ms
    # window let the dispatcher fire session a's frame solo before b's
    # submit ever ran — observed once at 865 s of suite load).  The
    # happy path never waits the window out: b's submit completes the
    # batch and dispatches inline.
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        model_id="tiny-test", max_sessions=2, window_ms=500.0,
        prewarm=False, aot_build_on_miss=False, cache_dir=str(tmp_path),
    )
    s.on_step = lambda dt, occ: feeds.append((dt, occ))
    try:
        status = s.aot_status("tiny-test", cache_dir=str(tmp_path))
        assert status == {(1, "full"): False, (2, "full"): False}
        a = s.claim("a", prompt="pa", seed=1)
        b = s.claim("b", prompt="pb", seed=2)
        f = np.zeros((64, 64, 3), np.uint8)
        pre_step_leaf = s.states["noise"]  # donation audit (ISSUE 9)
        ha, hb = a.submit(f), b.submit(f)
        oa, ob = a.fetch(ha), b.fetch(hb)
        assert oa.shape == (64, 64, 3) and ob.shape == (64, 64, 3)
        # the bucket step donates the stacked state pytree: the pre-step
        # buffers must be GONE (a defensive copy here doubles the HBM
        # footprint of every session's ring at real geometry)
        assert pre_step_leaf.is_deleted()
        # the FIRST dispatch at a bucket size carries its (lazy) compile —
        # the warm-step rule keeps it out of the admission feed
        assert feeds == []
        ha, hb = a.submit(f), b.submit(f)
        a.fetch(ha), b.fetch(hb)
        assert feeds and feeds[-1][1] == 2 and feeds[-1][0] > 0

        # (c) review round 3: a FAILED step must not brick the scheduler —
        # the donated stacked state is rebuilt from each session's tracked
        # control plane and serving resumes (the engine-restart recovery
        # semantics).  Sabotage the k=2 bucket for one dispatch.
        real_step = s._bucket_steps[(2, "full")]

        def _boom(*args, **kw):
            raise RuntimeError("injected step failure")

        s._bucket_steps[(2, "full")] = _boom
        ha = a.submit(f)
        with pytest.raises(RuntimeError, match="injected step failure"):
            b.submit(f)  # completes the batch -> inline dispatch raises
        with pytest.raises(RuntimeError, match="injected step failure"):
            a.fetch(ha)  # the rider's future carries the same failure
        s._bucket_steps[(2, "full")] = real_step
        ha, hb = a.submit(f), b.submit(f)
        oa, ob = a.fetch(ha), b.fetch(hb)  # fresh states serve again
        assert oa.shape == (64, 64, 3) and ob.shape == (64, 64, 3)
    finally:
        s.close()


# slow tier (ISSUE 20 budget shave): exporting every bucket geometry +
# the cold-scheduler adoption re-pays every tiny-model compile through
# jax.export; tier-1 keeps the admission-feed/donation/recovery sibling
# above (one lazy compile) and test_shard_aware_bucket_keys_and_prewarm_
# coverage's key-plane pins
@pytest.mark.slow
def test_aot_export_adopt_roundtrip(bundle, cfg, tmp_path, rng):
    """Every bucket geometry exports through the engine cache
    (sbucket/sessions keys), a fresh scheduler adopts WITHOUT building,
    and aot_status/EngineCache.has report the prebuilt set (the build
    CLI's pre-warm surface)."""
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        model_id="tiny-test", max_sessions=2, window_ms=500.0,
        prewarm=False, aot_build_on_miss=False, cache_dir=str(tmp_path),
    )
    try:
        status = s.aot_status("tiny-test", cache_dir=str(tmp_path))
        assert status == {(1, "full"): False, (2, "full"): False}
        # export every bucket, then adopt from a cold scheduler
        assert s.use_aot_cache(
            "tiny-test", cache_dir=str(tmp_path), build_on_miss=True
        )
        assert all(
            s.aot_status("tiny-test", cache_dir=str(tmp_path)).values()
        )
    finally:
        s.close()

    s2 = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        model_id="tiny-test", max_sessions=2, window_ms=500.0,
        prewarm=False, aot_build_on_miss=False, cache_dir=str(tmp_path),
    )
    try:
        assert s2._aot_adopted  # ctor adoption found every bucket
        sess = s2.claim("aot", prompt="aot check", seed=5)
        out = sess(rng.integers(0, 256, (64, 64, 3), np.uint8))
        assert out.shape == (64, 64, 3) and out.dtype == np.uint8
    finally:
        s2.close()


# ---------------------------------------------------------------------------
# agent wiring — a duck-typed scheduler stands in so the HTTP surface is
# covered without model compiles
# ---------------------------------------------------------------------------


class _StubPipeline:
    """Injected so on_startup never builds a real model pipeline; with a
    scheduler present the claim path ignores it entirely."""

    def __call__(self, frame):
        return frame


class _FakeSession:
    owns_step_signal = True

    def __init__(self, owner, slot, key):
        self._owner = owner
        self.slot = slot
        self.session_key = key
        self.prompt = None
        from ai_rtc_agent_tpu.resilience.overload import DeadlineQueue

        self.window_queue = DeadlineQueue(2)

    def __call__(self, frame):
        arr = frame if isinstance(frame, np.ndarray) else frame.to_ndarray()
        return 255 - arr

    def update_prompt(self, p):
        self.prompt = p

    def update_t_index_list(self, t):
        pass

    def release(self):
        self._owner.released.append(self.slot)

    def snapshot(self):
        return {"slot": self.slot, "frames_submitted": 0}


class _FakeScheduler:
    def __init__(self, max_sessions=2):
        self.max_sessions = max_sessions
        self.claimed = []
        self.released = []
        self.prompt = None
        self.on_step = None

    @property
    def free_slots(self):
        return self.max_sessions - (len(self.claimed) - len(self.released))

    def claim(self, session_key=None, prompt=None, seed=None):
        if self.free_slots <= 0:
            raise CapacityError("full")
        sess = _FakeSession(self, len(self.claimed), session_key)
        self.claimed.append(sess)
        return sess

    def update_prompt(self, p):
        self.prompt = p

    def update_t_index_list(self, t):
        pass

    def snapshot(self):
        return {
            "batchsched_sessions": len(self.claimed) - len(self.released),
            "batchsched_max_sessions": self.max_sessions,
            "batchsched_steps_total": 7,
        }

    def session_snapshots(self):
        return {
            s.session_key: s.snapshot()
            for s in self.claimed
            if s.slot not in self.released
        }

    def close(self):
        pass


def test_agent_serves_sessions_through_scheduler():
    """/offer claims a scheduler session (per-connection control plane),
    /metrics + /capacity + /health carry the scheduler view, the window
    queue joins the overload queue registry, and teardown releases the
    slot."""
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import (
        LoopbackProvider,
        make_loopback_offer,
    )
    from aiohttp.test_utils import TestClient, TestServer

    fake = _FakeScheduler()

    async def go():
        app = build_app(
            pipeline=_StubPipeline(),
            provider=LoopbackProvider(),
            batch_scheduler=fake,
        )
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "r",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
            )
            assert r.status == 200
            assert len(fake.claimed) == 1
            key = fake.claimed[0].session_key
            ov = app["overload"]
            assert f"batchwin:{key}" in ov.queues

            body = await (await client.get("/metrics")).json()
            assert body["batchsched_sessions"] == 1
            assert body["batchsched_steps_total"] == 7
            body = await (await client.get("/capacity")).json()
            assert body["capacity"] == 1  # 2 slots, 1 claimed

            body = await (await client.get("/health")).json()
            assert body["sessions"][key]["batchsched"]["slot"] == 0

            # global /config routes to the scheduler (all live sessions)
            r = await client.post("/config", json={"prompt": "global p"})
            assert r.status == 200
            assert fake.prompt == "global p"

            pc = next(iter(app["pcs"]))
            await pc.close()
            await asyncio.sleep(0.05)
            assert fake.released == [0]
            assert f"batchwin:{key}" not in ov.queues
        finally:
            await client.close()

    asyncio.run(go())


def test_agent_scheduler_full_returns_503():
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import (
        LoopbackProvider,
        make_loopback_offer,
    )
    from aiohttp.test_utils import TestClient, TestServer

    fake = _FakeScheduler(max_sessions=0)

    async def go():
        app = build_app(
            pipeline=_StubPipeline(),
            provider=LoopbackProvider(),
            batch_scheduler=fake,
        )
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/offer",
                json={
                    "room_id": "r",
                    "offer": {"sdp": make_loopback_offer(), "type": "offer"},
                },
            )
            assert r.status == 503
            assert "Retry-After" in r.headers
        finally:
            await client.close()

    asyncio.run(go())


def test_deepcache_uncaptured_rider_forces_capture(bundle):
    """code-review r1: the global tick reset at install only guarantees
    the NEXT batch captures — a slot that sits that batch out (no frame
    yet) must still never ride a cached step over its zeroed deep-feature
    row.  Any batch carrying an uncaptured rider is FORCED to capture,
    then the cadence resumes."""
    cfg = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        unet_cache_interval=3,
    )
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=2, window_ms=10_000.0, prewarm=False,
    )
    try:
        variants = []
        orig = s._bucket_step

        def spy(k, variant="full"):
            variants.append((k, variant))
            return orig(k, variant)

        s._bucket_step = spy
        a = s.claim("a", prompt="pa", seed=1)
        c = s.claim("c", prompt="pc", seed=9)
        assert a.slot in s._uncaptured and c.slot in s._uncaptured
        # pretend the cadence advanced while the slots sat out the
        # post-install capture batch (mid-cadence: 4 % 3 != 0 -> the
        # unforced choice would be the CACHED graph over zeroed rows)
        s._tick = 4
        f = np.zeros((64, 64, 3), np.uint8)
        ha, hc = a.submit(f), c.submit(f)  # huge window -> inline k=2
        a.fetch(ha), c.fetch(hc)
        assert variants[-1] == (2, "capture"), variants
        assert a.slot not in s._uncaptured and c.slot not in s._uncaptured
        # with the riders captured and the tick mid-cadence, the NEXT
        # batch's unforced choice is the cached graph (asserted on the
        # selection state, not by paying the cached compile — the
        # capture->cached alternation itself is pinned by the equivalence
        # driver's DC leg; tier-1 budget)
        assert s._tick % s._cache_interval != 0
    finally:
        s.close()


def _mk_adapter_registry(bundle, r=2):
    """Synthetic two-style registry over the tiny UNet: styleA touches one
    attn linear, styleB two (the bank target set is the union, so styleA
    rows carry explicit zeros at the second target); rank 2 pads to the
    smallest blessed bucket, 4."""
    from ai_rtc_agent_tpu.adapters import AdapterRegistry
    from ai_rtc_agent_tpu.models import loader as LD

    mq = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q"
    mv = "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_v"
    rng = np.random.default_rng(7)

    def groups(mods):
        return {
            m: {
                "down": (rng.normal(size=(r, 8)) * 0.2).astype(np.float32),
                "up": (rng.normal(size=(8, r)) * 0.2).astype(np.float32),
                "alpha": float(r),
            }
            for m in mods
        }

    reg = AdapterRegistry(
        bundle.params["unet"], LD.unet_key_map(bundle.unet_cfg)
    )
    reg.add("styleA", groups([mq]))
    reg.add("styleB", groups([mq, mv]))
    return reg


def test_adapter_bucket_keys_bank_shape_and_metrics(bundle, cfg):
    """Unit pins for the adapter key plane (ISSUE 20): a bound factor bank
    joins the AOT key space as its padded rank (``lrank-R`` via
    aot/cache.adapter_key_extra — empty-when-disabled like the dp extra),
    the devtel bucket label carries ``:rR``, the stacked bank is
    [S, ...]-shaped over the union target set, snapshot/fingerprint expose
    the bank, and style validation refuses BEFORE touching a slot.
    Compile-free (prewarm off, no frame dispatched)."""
    from ai_rtc_agent_tpu.aot.cache import adapter_key_extra

    assert adapter_key_extra(0) == {}
    assert adapter_key_extra(4) == {"lrank": 4}

    reg = _mk_adapter_registry(bundle)
    assert reg.bank_rank == 4 and reg.rank_of("styleA") == 4
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=4, window_ms=10_000.0, prewarm=False, adapters=reg,
    )
    try:
        # rank joins the key space: (k, variant, rank, dp)
        assert s._bucket_label(2, "full") == "sbucket-2:full:r4"
        keys = s.bucket_keys("tiny-test")
        assert keys and all("lrank-4" in k for k in keys.values())
        # the stacked bank rides the session pytree: [S, R, in]/[S, out, R]
        bank = s.states["adapters"]
        assert set(bank) == set(reg.targets)
        for f in bank.values():
            assert f["down"].shape == (4, 4, 8)
            assert f["up"].shape == (4, 8, 4)
        # validation refuses BEFORE slot allocation / bank writes
        with pytest.raises(KeyError):
            s.claim("x", adapter="nope")
        assert s.snapshot()["batchsched_sessions"] == 0
        a = s.claim("a", adapter="styleA")
        assert a.adapter == "styleA"
        with pytest.raises(KeyError):
            a.update_adapter("nope")
        assert a.adapter == "styleA"  # refused swap never lands
        a.update_adapter("styleB")
        assert a.adapter == "styleB"
        # global update: live slots swap AND future claims inherit
        s.update_adapter("styleA")
        assert a.adapter == "styleA"
        b = s.claim("b")
        assert b.adapter == "styleA"
        snap = s.snapshot()
        assert snap["adapter_rank"] == 4
        assert snap["adapter_sessions"] == 2
        assert snap["adapter_swaps_total"] >= 2
        fp = s.snapshot_fingerprint()
        assert fp["adapter_rank"] == 4 and fp["adapter_targets"]
        assert a.snapshot()["adapter"] == "styleA"
    finally:
        s.close()
    # an adapterless scheduler keeps every pre-existing surface unchanged
    s2 = BatchScheduler(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        max_sessions=2, window_ms=10_000.0, prewarm=False,
    )
    try:
        assert s2._bucket_label(2, "full") == "sbucket-2:full"
        assert "adapters" not in s2.states
        assert "adapter_rank" not in s2.snapshot_fingerprint()
        with pytest.raises(ValueError, match="ADAPTER_DIR"):
            s2.claim("x", adapter="styleA")
        with pytest.raises(ValueError, match="ADAPTER_DIR"):
            s2.update_adapter("styleA")
    finally:
        s2.close()


# slow tier: prewarm=True pays every (k, variant, rank) compile up front —
# tier-1 keeps test_adapter_bucket_keys_bank_shape_and_metrics above,
# which pins the same key/bank mechanism compile-free
@pytest.mark.slow
def test_adapter_hot_swap_never_retraces(bundle):
    """ISSUE 20 acceptance pin: join/leave/hot-swap/clear/restart on a
    prewarmed adapter-carrying scheduler with ZERO devtel retrace
    breaches — the closed rank-bucket contract makes every swap a
    same-shaped ``.at[slot].set`` bank write, never a new graph."""
    from ai_rtc_agent_tpu.obs import devtel
    from ai_rtc_agent_tpu.obs.devtel import DevTelPlane

    cfg32 = registry.default_stream_config(
        "tiny-test", t_index_list=(0,), num_inference_steps=1,
        timestep_spacing="trailing", scheduler="turbo", cfg_type="none",
        height=32, width=32,
    )
    reg = _mk_adapter_registry(bundle)
    plane = devtel.activate(DevTelPlane())
    s = BatchScheduler(
        bundle.stream_models, bundle.params, cfg32, bundle.encode_prompt,
        max_sessions=2, window_ms=10_000.0, prewarm=True, dp=1,
        adapters=reg,
    )
    rng = np.random.default_rng(5)

    def tick(sessions):
        fs = [
            rng.integers(0, 256, (32, 32, 3), np.uint8) for _ in sessions
        ]
        hs = [x.submit(f) for x, f in zip(sessions, fs)]
        return [x.fetch(h) for x, h in zip(sessions, hs)]

    try:
        # prewarm attributed under the rank-carrying scope, all expected
        ctxs = {c["context"] for c in plane.compiles}
        assert "sbucket-2:full:r4" in ctxs, ctxs
        assert plane.retrace_breaches == 0
        a = s.claim("a", prompt="pa", seed=1, adapter="styleA")
        b = s.claim("b", prompt="pb", seed=2)
        tick([a, b])  # warm the host-side eager ops too
        a.update_adapter("styleB")  # ...including the bank-write path
        b.release()
        tick([a])
        plane.serving()
        # churn on warm executables ONLY: swap, clear, rejoin with a
        # style, swap the rejoiner, restart a styled session, global clear
        a.update_adapter(None)
        tick([a])
        b2 = s.claim("b2", prompt="pb2", seed=9, adapter="styleB")
        tick([a, b2])
        b2.update_adapter("styleA")
        tick([a, b2])
        a.update_adapter("styleA")
        a.restart()
        tick([a, b2])
        s.update_adapter(None)
        tick([a, b2])
        assert plane.retrace_breaches == 0, [
            c for c in plane.compiles if c["phase"] == "serving"
        ]
        assert s.snapshot()["adapter_swaps_total"] >= 5
    finally:
        devtel.deactivate(plane)
        s.close()
