"""scripts/profile_step.py plumbing (SURVEY §5 tracing/profiling).

The profiler CLI is a queue-adjacent operator tool; this pins that it runs
end-to-end on the hermetic tiny config, emits its one-line JSON summary,
and actually writes a TensorBoard-loadable trace directory.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_profile_step_tiny_writes_trace(tmp_path):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = tmp_path / "trace"
    r = subprocess.run(
        [sys.executable, os.path.join("scripts", "profile_step.py"),
         "--config", "tiny64", "--warm", "1", "--steps", "2",
         "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[-1])
    assert d["config"] == "tiny64" and d["traced_steps"] == 2
    assert d["fps_in_trace"] > 0
    # a real trace landed (plugins/profile/<run>/*.xplane.pb)
    found = [
        f for _, _, files in os.walk(out) for f in files
        if f.endswith(".xplane.pb")
    ]
    assert found, f"no xplane.pb under {out}"
