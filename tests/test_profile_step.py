"""scripts/profile_step.py plumbing (SURVEY §5 tracing/profiling).

The profiler CLI is a queue-adjacent operator tool; this pins that it runs
end-to-end on the hermetic tiny config, emits its one-line JSON summary,
and actually writes a TensorBoard-loadable trace directory.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_profile_step_tiny_writes_trace(tmp_path):
    """`slow` tier since PR 9: a 31s subprocess smoke of an OPERATOR tool
    (fresh jax import + tiny-model compile + jax.profiler trace) — tier-1
    wall-time goes to serving invariants first (ROADMAP standing
    constraint; the tier-1 budget finished 22s under the timeout)."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = tmp_path / "trace"
    r = subprocess.run(
        [sys.executable, os.path.join("scripts", "profile_step.py"),
         "--config", "tiny64", "--warm", "1", "--steps", "2",
         "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[-1])
    assert d["config"] == "tiny64" and d["traced_steps"] == 2
    assert d["fps_in_trace"] > 0
    # a real trace landed (plugins/profile/<run>/*.xplane.pb)
    found = [
        f for _, _, files in os.walk(out) for f in files
        if f.endswith(".xplane.pb")
    ]
    assert found, f"no xplane.pb under {out}"
