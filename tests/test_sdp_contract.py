"""SDP contract tests against browser/OBS-shaped WHIP/WHEP offers.

VERDICT r2 next-round #3: aiortc cannot be installed (zero egress), so the
agent's SDP surface is pinned with recorded-shape fixtures instead — real
Chrome-style and OBS-style offer bodies POSTed at the live aiohttp app with
the native-rtp provider, asserting the answers' codec selection, direction
mirroring, Location headers and inline (non-trickle) candidates
(reference surface: agent.py:123-208, 285-395; OBS gather workaround
agent.py:369-376).
"""

import asyncio
import os

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.server import sdp
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "sdp")


def fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name)) as f:
        return f.read()


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# parser-level contract
# ---------------------------------------------------------------------------

def test_parse_browser_offer_prefers_packetization_mode_1():
    offer = sdp.parse(fixture("browser_whip_offer.sdp"))
    video = offer.video()
    assert video is not None
    assert video.direction == "sendonly"
    assert video.mid == "0"
    # 102 is packetization-mode=1, 104 is mode 0 -> 102 must win
    assert video.h264_payloads() == [102, 104]
    assert video.rtpmap[96] == "VP8/90000"


def test_parse_obs_offer_candidates_and_addr():
    offer = sdp.parse(fixture("obs_whip_offer.sdp"))
    video = offer.video()
    assert video.h264_payloads() == [102]
    assert video.connection == "198.51.100.23"
    # sendonly publisher receives nothing: no client media address
    assert sdp.client_media_addr(offer) is None


def test_client_media_addr_for_viewer():
    offer = sdp.parse(fixture("plainrtp_whep_offer.sdp"))
    assert sdp.client_media_addr(offer) == ("127.0.0.1", 46002)


def test_build_answer_rejects_non_video_sections():
    text = (
        "v=0\r\no=- 1 1 IN IP4 0.0.0.0\r\ns=-\r\nt=0 0\r\n"
        "m=audio 5004 RTP/AVP 111\r\na=mid:a0\r\na=rtpmap:111 opus/48000/2\r\n"
        "m=video 5006 RTP/AVP 102\r\na=mid:v0\r\n"
        "a=rtpmap:102 H264/90000\r\na=sendonly\r\n"
    )
    answer = sdp.build_answer(sdp.parse(text), host="127.0.0.1", video_port=40000)
    lines = answer.splitlines()
    assert "m=audio 0 RTP/AVP 111" in lines  # rejected: port 0
    assert "m=video 40000 RTP/AVP 102" in lines
    assert "a=mid:v0" in lines and "a=mid:a0" in lines


# ---------------------------------------------------------------------------
# agent-level contract (live aiohttp app, native-rtp provider)
# ---------------------------------------------------------------------------

class FakePipeline:
    def __call__(self, frame):
        return frame

    def update_prompt(self, p):
        pass

    def update_t_index_list(self, t):
        pass


async def _client():
    app = build_app(pipeline=FakePipeline(), provider=NativeRtpProvider())
    client = TestClient(TestServer(app))
    await client.start_server()
    return app, client


def _has_crypto() -> bool:
    import importlib.util

    return importlib.util.find_spec("cryptography") is not None


@pytest.mark.skipif(
    not _has_crypto(),
    reason="fingerprinted offers route to the secure tier (needs cryptography)",
)
@pytest.mark.parametrize(
    "name", ["browser_whip_offer.sdp", "obs_whip_offer.sdp"]
)
def test_whip_answer_contract(name, monkeypatch):
    """201 + Location + an answer that picks the offered H264 payload,
    mirrors mid, inverts direction and carries inline candidates."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")

    async def go():
        app, client = await _client()
        try:
            r = await client.post(
                "/whip",
                data=fixture(name),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            assert r.headers["Location"].startswith("/whip/")
            assert r.content_type == "application/sdp"
            answer = await r.text()
            assert answer.startswith("v=0")
            parsed = sdp.parse(answer)
            video = parsed.video()
            # the offered packetization-mode=1 H264 payload type (102 in
            # both fixtures) is echoed, with our rtpmap for it
            assert video.payloads == [102]
            assert video.rtpmap[102].upper() == "H264/90000"
            assert video.mid == sdp.parse(fixture(name)).video().mid
            # publisher offered sendonly -> we answer recvonly
            assert video.direction == "recvonly"
            # full gather, never trickle (OBS parity): candidate is INLINE
            # and points at the UDP port we actually bound
            cands = [a for a in video.attrs if a.startswith("candidate:")]
            assert cands and "end-of-candidates" in video.attrs
            assert f" {video.port} typ host" in cands[0]
            assert video.port > 0
        finally:
            await client.close()

    run(go())


def test_whep_answer_contract(monkeypatch):
    """A plain-RTP viewer offer (recvonly) gets a sendonly answer; the
    agent learns the viewer's receive address from c=/m= lines."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")

    async def go():
        app, client = await _client()
        try:
            # publisher first (JSON envelope tier works alongside real SDP)
            r = await client.post(
                "/whip",
                data='{"native_rtp": true, "video": true}',
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            r = await client.post(
                "/whep",
                data=fixture("plainrtp_whep_offer.sdp"),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            answer = await r.text()
            parsed = sdp.parse(answer)
            assert parsed.video().direction == "sendonly"
            # the pc now targets the viewer's advertised address
            whep_pcs = app["state"]["whep_pcs"]
            (pc,) = whep_pcs.values()
            assert pc._client_addr == ("127.0.0.1", 46002)
            assert pc._h264_pt == 102
        finally:
            await client.close()

    run(go())


def test_videoless_whip_is_400_and_leaks_nothing(monkeypatch):
    """Valid-but-videoless SDP must 400 (not 500) and leave no half-built
    pc behind in app['pcs']/whip_pcs (code-review r3: repeated bad posts
    previously grew both containers without bound)."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    bad = (
        "v=0\r\no=- 1 1 IN IP4 0.0.0.0\r\ns=-\r\nt=0 0\r\n"
        "m=audio 5004 RTP/AVP 111\r\na=rtpmap:111 opus/48000/2\r\n"
    )

    async def go():
        app, client = await _client()
        try:
            for _ in range(3):
                r = await client.post(
                    "/whip", data=bad,
                    headers={"Content-Type": "application/sdp"},
                )
                assert r.status == 400
            assert app["pcs"] == set()
            assert app["state"]["whip_pcs"] == {}
            # same guarantee on the bidirectional endpoint
            r = await client.post(
                "/offer",
                json={"room_id": "x", "offer": {"sdp": bad, "type": "offer"}},
            )
            assert r.status == 400
            assert app["pcs"] == set()
        finally:
            await client.close()

    run(go())


def test_whip_whep_fuzz_never_500(monkeypatch):
    """Hostile signaling bodies (garbage SDP, binary, truncated m= lines,
    empty) must map to 4xx — never a 500 and never a leaked pc."""
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    bodies = [
        b"v=0\r\nm=video garbage line\r\n",
        b"v=0",
        b"",
        b"\xff\xfe\x00binary\x9c",
        b"m=video 1 RTP/AVP",  # m= before v=, too few fields
        ("v=0\r\n" + "a=x:" + "A" * 5000 + "\r\n").encode(),
        b"not sdp and not json",
    ]

    async def go():
        app, client = await _client()
        try:
            # publisher so whep reaches its parse path (else 401 short-circuit)
            r = await client.post(
                "/whip",
                data='{"native_rtp": true, "video": true}',
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            baseline_pcs = len(app["pcs"])
            for ep in ("/whip", "/whep"):
                for body in bodies:
                    r = await client.post(
                        ep, data=body,
                        headers={"Content-Type": "application/sdp"},
                    )
                    assert 400 <= r.status < 500, (ep, body[:30], r.status)
                # unknown charset= parameter passes the content-type gate
                # but must still be a client error (was a 500)
                r = await client.post(
                    ep, data=b"v=0",
                    headers={"Content-Type": "application/sdp; charset=bogus"},
                )
                assert 400 <= r.status < 500, (ep, "charset", r.status)
                # bare c= was an IndexError 500; the lenient parse now
                # ACCEPTS the (otherwise valid) video offer
                r = await client.post(
                    ep, data=b"v=0\r\nm=video 1 RTP/AVP 96\r\nc=\r\n",
                    headers={"Content-Type": "application/sdp"},
                )
                assert r.status in (201, 400), (ep, "bare c=", r.status)
                if r.status == 201:  # clean the accepted session back up
                    await client.delete(r.headers["Location"])
            assert len(app["pcs"]) == baseline_pcs  # nothing leaked
        finally:
            await client.close()

    run(go())


def test_bundle_group_echoed_for_accepted_mid():
    """Browsers offer a=group:BUNDLE; max-bundle policies refuse an answer
    that drops the group (RFC 9143 s7.3) — the accepted video mid must be
    echoed, rejected sections leave the group."""
    offer = sdp.parse(fixture("browser_whip_offer.sdp"))
    assert offer.bundle == ["0"]
    answer = sdp.build_answer(offer, host="127.0.0.1", video_port=4000)
    assert "a=group:BUNDLE 0" in answer

    # an offer without BUNDLE gets no group line
    text = fixture("browser_whip_offer.sdp").replace(
        "a=group:BUNDLE 0\n", ""
    )
    answer2 = sdp.build_answer(
        sdp.parse(text), host="127.0.0.1", video_port=4000
    )
    assert "BUNDLE" not in answer2


@pytest.mark.skipif(
    _has_crypto(),
    reason="exercises the no-crypto degrade path (cryptography installed here)",
)
def test_secure_offer_without_crypto_backend_is_clean_400():
    """A fingerprinted (secure) offer on a box without the crypto backend
    must be refused with a 400 naming the reason — not a 500 (resilience
    PR; was the seed's only way to answer browser-shaped WHIP here)."""

    async def go():
        app = build_app(pipeline=lambda f: f, provider=NativeRtpProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/whip",
                data=fixture("browser_whip_offer.sdp"),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 400
            assert "encrypted tier" in await r.text()
            assert len(app["pcs"]) == 0  # the half-built pc did not leak
        finally:
            await client.close()

    run(go())
