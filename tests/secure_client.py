"""Shared browser-shaped secure test client (STUN -> DTLS -> SRTP).

One implementation of the handshake/drain state machine for every secure
test (test_secure_e2e.py, test_secure_soak.py) — hand-rolled copies of
this scaffold drifted, so protocol changes now land in exactly one place.
Not a fixture module: plain helpers, imported explicitly.
"""

from __future__ import annotations

import asyncio
import re

from ai_rtc_agent_tpu.server.secure import (
    DtlsEndpoint,
    StunMessage,
    derive_srtp_contexts,
    generate_certificate,
)
from ai_rtc_agent_tpu.server.secure import stun as stun_mod


def sdp_attr(text: str, name: str) -> str | None:
    m = re.search(rf"^a={name}:(.*)$", text, re.MULTILINE)
    return m.group(1).strip() if m else None


def secure_offer(
    fingerprint: str,
    ufrag: str = "cliu",
    pwd: str = "clientpwd0123456789abc",
    direction: str = "sendrecv",
    pt: int = 102,
    datachannel: bool = False,
) -> str:
    """A Chrome-shaped offer (modeled on tests/fixtures/sdp/
    browser_whip_offer.sdp) carrying a real client DTLS identity.
    ``datachannel`` adds the m=application section Chrome emits for
    createDataChannel (RFC 8841)."""
    bundle = "0 1" if datachannel else "0"
    sdp = (
        "v=0\r\n"
        "o=- 4611731400430051336 2 IN IP4 127.0.0.1\r\n"
        "s=-\r\nt=0 0\r\n"
        f"a=group:BUNDLE {bundle}\r\n"
        f"m=video 9 UDP/TLS/RTP/SAVPF {pt}\r\n"
        "c=IN IP4 0.0.0.0\r\n"
        f"a=ice-ufrag:{ufrag}\r\n"
        f"a=ice-pwd:{pwd}\r\n"
        f"a=fingerprint:sha-256 {fingerprint}\r\n"
        "a=setup:actpass\r\n"
        "a=mid:0\r\n"
        f"a={direction}\r\n"
        "a=rtcp-mux\r\n"
        f"a=rtpmap:{pt} H264/90000\r\n"
        f"a=fmtp:{pt} level-asymmetry-allowed=1;packetization-mode=1;"
        "profile-level-id=42001f\r\n"
    )
    if datachannel:
        sdp += (
            "m=application 9 UDP/DTLS/SCTP webrtc-datachannel\r\n"
            "c=IN IP4 0.0.0.0\r\n"
            f"a=ice-ufrag:{ufrag}\r\n"
            f"a=ice-pwd:{pwd}\r\n"
            f"a=fingerprint:sha-256 {fingerprint}\r\n"
            "a=setup:actpass\r\n"
            "a=mid:1\r\n"
            "a=sctp-port:5000\r\n"
            "a=max-message-size:262144\r\n"
        )
    return sdp


class SecureTestPeer:
    """Owns the client socket + DTLS association for one secure session."""

    def __init__(self, name: str = "test-peer", ufrag: str = "cliu"):
        self.cert = generate_certificate(name)
        self.ufrag = ufrag
        self.q: asyncio.Queue = asyncio.Queue()
        self.transport = None
        self.dtls: DtlsEndpoint | None = None
        self.tx = None
        self.rx = None
        self.server_addr = None

    async def open_socket(self):
        loop = asyncio.get_running_loop()
        peer = self

        class _Recv(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                peer.q.put_nowait(data)

        self.transport, _ = await loop.create_datagram_endpoint(
            _Recv, local_addr=("127.0.0.1", 0)
        )
        return self

    async def establish(self, answer_sdp: str, timeout: float = 20.0):
        """Authenticated STUN binding + DTLS handshake against the answer's
        media port; derives the SRTP contexts for the negotiated profile."""
        m = re.search(r"^m=video (\d+) UDP/TLS/RTP/SAVPF", answer_sdp, re.M)
        assert m, f"not a secure answer:\n{answer_sdp}"
        self.server_addr = ("127.0.0.1", int(m.group(1)))
        server_ufrag = sdp_attr(answer_sdp, "ice-ufrag")
        server_pwd = sdp_attr(answer_sdp, "ice-pwd")
        server_fp = sdp_attr(answer_sdp, "fingerprint").split(" ", 1)[1]

        req = StunMessage(stun_mod.BINDING_REQUEST)
        req.attributes.append(
            (stun_mod.ATTR_USERNAME, f"{server_ufrag}:{self.ufrag}".encode())
        )
        req.attributes.append((stun_mod.ATTR_USE_CANDIDATE, b""))
        self.transport.sendto(
            req.encode(integrity_key=server_pwd.encode()), self.server_addr
        )
        data = await asyncio.wait_for(self.q.get(), 5)
        resp = StunMessage.decode(data)
        assert resp.message_type == stun_mod.BINDING_SUCCESS
        assert resp.verify_integrity(server_pwd.encode(), data)

        self.dtls = DtlsEndpoint(
            "client", self.cert, verify_fingerprint=server_fp
        )
        for d in self.dtls.start():
            self.transport.sendto(d, self.server_addr)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while not self.dtls.established and loop.time() < deadline:
            try:
                data = await asyncio.wait_for(self.q.get(), 3)
            except asyncio.TimeoutError:
                for d in self.dtls.retransmit():
                    self.transport.sendto(d, self.server_addr)
                continue
            assert self.dtls.failed is None, self.dtls.failed
            for d in self.dtls.handle_datagram(data):
                self.transport.sendto(d, self.server_addr)
        assert self.dtls.established, self.dtls.failed
        self.tx, self.rx = derive_srtp_contexts(
            self.dtls.export_srtp_keying_material(),
            is_server=False,
            profile=self.dtls.srtp_profile,
        )
        return self

    def _sctp_tx(self, packets) -> None:
        for p in packets:
            for d in self.dtls.send_application_data(p):
                self.transport.sendto(d, self.server_addr)

    async def open_datachannel(self, label: str = "config", timeout: float = 10.0):
        """Browser-shaped datachannel open: SCTP association over the
        established DTLS session, then DCEP OPEN.  Returns the open
        channel (send via `dc_send`, drain replies via `drain_dc`)."""
        from ai_rtc_agent_tpu.server.secure.sctp import SctpAssociation

        assert self.dtls is not None and self.dtls.established
        self.sctp = SctpAssociation("client")
        self._sctp_tx(self.sctp.start())
        ch = None
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            if self.sctp.established and ch is None:
                ch, pkts = self.sctp.open_channel(label)
                self._sctp_tx(pkts)
            if ch is not None and ch.readyState == "open":
                return ch
            try:
                data = await asyncio.wait_for(self.q.get(), 1)
            except asyncio.TimeoutError:
                self._sctp_tx(self.sctp.retransmit_due())
                continue
            for d in self.dtls.handle_datagram(data):
                self.transport.sendto(d, self.server_addr)
            for m in self.dtls.recv_application_data():
                self._sctp_tx(self.sctp.handle_packet(m))
        raise AssertionError("datachannel open timed out")

    def dc_send(self, channel, message) -> None:
        self._sctp_tx(channel.send(message))

    async def drain_dc(self, duration: float = 1.0) -> None:
        """Pump inbound datagrams through DTLS+SCTP for `duration` seconds
        (channel message handlers fire from inside handle_packet)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + duration
        while loop.time() < deadline:
            try:
                data = await asyncio.wait_for(self.q.get(), 0.2)
            except asyncio.TimeoutError:
                continue
            for d in self.dtls.handle_datagram(data):
                self.transport.sendto(d, self.server_addr)
            for m in self.dtls.recv_application_data():
                self._sctp_tx(self.sctp.handle_packet(m))

    def send_rtp(self, packets):
        for pkt in packets:
            self.transport.sendto(self.tx.protect(pkt), self.server_addr)

    def drain_classified(self) -> tuple:
        """-> (rtp_wires, rtcp_items): everything queued, split by RFC 5761
        payload-type demux.  RTP stays as WIRE bytes (replay-window-safe
        duplicate detection); RTCP is SRTCP-unprotected and parsed."""
        from ai_rtc_agent_tpu.media import rtcp as rtcp_mod

        rtp_wires, rtcp_items = [], []
        try:
            while True:
                wire = self.q.get_nowait()
                if rtcp_mod.is_rtcp(wire):
                    try:
                        rtcp_items.extend(
                            rtcp_mod.parse_compound(
                                self.rx.unprotect_rtcp(wire)
                            )
                        )
                    except ValueError:
                        pass
                else:
                    rtp_wires.append(wire)
        except asyncio.QueueEmpty:
            pass
        return rtp_wires, rtcp_items

    def send_rtcp(self, packet: bytes) -> None:
        self.transport.sendto(self.tx.protect_rtcp(packet), self.server_addr)

    def drain_into(self, ring_source) -> None:
        """Unprotect everything queued and feed it to the decode ring
        (non-RTP / replayed datagrams are skipped)."""
        try:
            while True:
                wire = self.q.get_nowait()
                try:
                    ring_source.feed_packet(self.rx.unprotect(wire))
                except ValueError:
                    pass
        except asyncio.QueueEmpty:
            pass

    def close(self):
        if self.transport is not None:
            self.transport.close()
