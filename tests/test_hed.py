"""HED annotator (VERDICT r2 missing #4).

The reference's ControlNet path supports exactly one conditioning
processor — HED (reference lib/wrapper.py:39-40, 617-643).  These pin the
in-graph equivalent: apply shape/range, the torch-checkpoint key map
(ControlNetHED layout), and a full conditioned stream step with
annotator="hed".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ai_rtc_agent_tpu.models import hed as H
from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.stream.engine import StreamEngine


def test_apply_hed_shape_and_range():
    params = H.init_hed(jax.random.PRNGKey(0), stages=H.TINY_STAGES)
    img = jnp.asarray(
        np.random.default_rng(0).random((2, 16, 16, 3), dtype=np.float32)
    )
    edge = H.apply_hed(params, img)
    assert edge.shape == (2, 16, 16, 3)
    assert float(edge.min()) >= 0.0 and float(edge.max()) <= 1.0
    np.testing.assert_array_equal(np.asarray(edge[..., 0]), np.asarray(edge[..., 2]))


def test_torch_key_map_roundtrip(tmp_path):
    """A ControlNetHED-layout torch state dict streams into the tree: every
    conv/projection/norm tensor lands (transposed OIHW->HWIO)."""
    torch = pytest.importorskip("torch")

    params = H.init_hed(jax.random.PRNGKey(1), stages=H.TINY_STAGES)
    sd = {"netNetwork.norm": torch.zeros(1, 3, 1, 1) + 0.5}
    expect = 1
    rng = np.random.default_rng(2)
    for i, (cin, cout, n) in enumerate(H.TINY_STAGES, start=1):
        c = cin
        for j in range(n):
            sd[f"netNetwork.block{i}.convs.{j}.weight"] = torch.from_numpy(
                rng.standard_normal((cout, c, 3, 3)).astype(np.float32)
            )
            sd[f"netNetwork.block{i}.convs.{j}.bias"] = torch.from_numpy(
                rng.standard_normal((cout,)).astype(np.float32)
            )
            expect += 2
            c = cout
        sd[f"netNetwork.block{i}.projection.weight"] = torch.from_numpy(
            rng.standard_normal((1, cout, 1, 1)).astype(np.float32)
        )
        sd[f"netNetwork.block{i}.projection.bias"] = torch.zeros(1)
        expect += 2
    path = tmp_path / "ControlNetHED.pth"
    torch.save(sd, str(path))

    params, n = H.load_hed_from_torch(params, str(path))
    assert n == expect
    # spot-check the OIHW->HWIO transpose on the first conv
    w_torch = sd["netNetwork.block1.convs.0.weight"].numpy()
    np.testing.assert_array_equal(
        np.asarray(params["block1"]["convs"][0]["kernel"]),
        np.transpose(w_torch, (2, 3, 1, 0)),
    )
    assert float(np.asarray(params["norm"]).ravel()[0]) == 0.5


def test_hed_conditioned_stream_step():
    """Full conditioned stream step with annotator='hed' (tiny geometry)."""
    bundle = registry.load_model_bundle(
        "tiny-test", controlnet="tiny-cnet", annotator="hed"
    )
    assert "hed" in bundle.params
    cfg = registry.default_stream_config(
        "tiny-test", use_controlnet=True, annotator="hed"
    )
    eng = StreamEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        jit_compile=False, donate=False,
    )
    eng.prepare("hed stream", guidance_scale=1.0, seed=3)
    frame = np.random.default_rng(4).integers(0, 256, (64, 64, 3), np.uint8)
    out = eng(frame)
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8


def test_hed_requires_bundle_params():
    """annotator='hed' without HED params must fail loudly at trace time."""
    bundle = registry.load_model_bundle("tiny-test", controlnet="tiny-cnet")
    cfg = registry.default_stream_config(
        "tiny-test", use_controlnet=True, annotator="hed"
    )
    eng = StreamEngine(
        bundle.stream_models, bundle.params, cfg, bundle.encode_prompt,
        jit_compile=False, donate=False,
    )
    eng.prepare("boom", guidance_scale=1.0, seed=3)
    with pytest.raises(ValueError, match="hed"):
        eng(np.zeros((64, 64, 3), np.uint8))
