"""Pallas kill-switch + build-time fallback (VERDICT r2 item 2 / weak #3).

The fused epilogue and flash attention default ON for TPU serving; if either
miscompiles at the served geometry the agent must degrade to composed XLA
ops instead of dying on the first connection:

  * FUSED_EPILOGUE=0 env kill-switch (models/registry.default_stream_config)
  * StreamDiffusionPipeline probes one step at build time and rebuilds with
    the Pallas paths disabled on failure (stream/pipeline.py).
"""

import numpy as np
import pytest

import jax

from ai_rtc_agent_tpu.models import registry
from ai_rtc_agent_tpu.stream.engine import StreamEngine
from ai_rtc_agent_tpu.stream.pipeline import StreamDiffusionPipeline


def test_fused_epilogue_env_killswitch(monkeypatch):
    # simulate a TPU backend: fused epilogue defaults ON ...
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert registry.default_stream_config("tiny-test").use_fused_epilogue
    # ... and FUSED_EPILOGUE=0 turns it off without a code change
    monkeypatch.setenv("FUSED_EPILOGUE", "0")
    assert not registry.default_stream_config("tiny-test").use_fused_epilogue


def test_fused_epilogue_env_force_on(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert not registry.default_stream_config("tiny-test").use_fused_epilogue
    monkeypatch.setenv("FUSED_EPILOGUE", "1")
    assert registry.default_stream_config("tiny-test").use_fused_epilogue


def test_explicit_override_beats_env(monkeypatch):
    monkeypatch.setenv("FUSED_EPILOGUE", "0")
    cfg = registry.default_stream_config("tiny-test", use_fused_epilogue=True)
    assert cfg.use_fused_epilogue


def test_build_time_fallback_disables_fused_epilogue(monkeypatch):
    """A synthetic Pallas failure during the build probe must yield a
    serving pipeline on the composed path, not an exception."""
    orig_call = StreamEngine.__call__

    def failing_when_fused(self, frame):
        if self.cfg.use_fused_epilogue:
            raise RuntimeError("synthetic pallas miscompile")
        return orig_call(self, frame)

    monkeypatch.setattr(StreamEngine, "__call__", failing_when_fused)
    cfg = registry.default_stream_config("tiny-test", use_fused_epilogue=True)
    pipe = StreamDiffusionPipeline("tiny-test", config=cfg)
    assert pipe.config.use_fused_epilogue is False
    out = pipe(np.zeros((64, 64, 3), np.uint8))
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8


def test_stage2_fallback_disables_attention_without_env_mutation(monkeypatch):
    """When the composed epilogue still fails, the rebuild must carry
    attn_impl='xla' in ITS OWN config — process-global ATTN_IMPL stays
    untouched so other pipelines keep their attention choice."""
    import os

    monkeypatch.setenv("ATTN_IMPL", "pallas")
    orig_call = StreamEngine.__call__

    def failing_unless_xla(self, frame):
        if self.cfg.attn_impl != "xla":
            raise RuntimeError("synthetic pallas miscompile")
        return orig_call(self, frame)

    monkeypatch.setattr(StreamEngine, "__call__", failing_unless_xla)
    cfg = registry.default_stream_config("tiny-test", use_fused_epilogue=True)
    pipe = StreamDiffusionPipeline("tiny-test", config=cfg)
    assert pipe.config.attn_impl == "xla"
    assert pipe.config.use_fused_epilogue is False
    assert os.environ["ATTN_IMPL"] == "pallas"  # global env untouched
    out = pipe(np.zeros((64, 64, 3), np.uint8))
    assert out.shape == (64, 64, 3)


def test_probe_skipped_when_no_pallas_path(monkeypatch):
    """CPU default config (fused off, xla attention) must not pay a probe
    step at pipeline build (the suite builds many pipelines)."""
    calls = []
    orig_call = StreamEngine.__call__

    def counting(self, frame):
        calls.append(1)
        return orig_call(self, frame)

    monkeypatch.setattr(StreamEngine, "__call__", counting)
    StreamDiffusionPipeline("tiny-test")
    assert calls == []
