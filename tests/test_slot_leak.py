"""Slot-leak regression suite (ISSUE 4 satellite): the invariant at
server/agent.py — "a leaked slot is permanent 503s" — held only by
convention.  These tests pin it: EVERY failure path of /offer, /whip and
/whep releases the engine slot (and /whep, which never claims one, must
not touch the count), proven by a follow-up /offer succeeding after each
failure."""

import asyncio
import json
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.parallel.multipeer import CapacityError
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.signaling import (
    LoopbackPeerConnection,
    LoopbackProvider,
    SessionDescription,
    make_loopback_offer,
)


class FakePeer:
    def __init__(self, owner):
        self._owner = owner
        self._released = False

    def release(self):
        # double-release must be harmless (failed -> closed fires both)
        if not self._released:
            self._released = True
            with self._owner._lock:
                self._owner.free += 1

    def __call__(self, frame):
        return frame


class FakeSlotPipeline:
    """Claim/release ledger standing in for MultiPeerPipeline."""

    def __init__(self, slots=1):
        self.slots = slots
        self.free = slots
        self.claims = 0
        self._lock = threading.Lock()

    def claim(self):
        with self._lock:
            if self.free == 0:
                raise CapacityError("full")
            self.free -= 1
            self.claims += 1
        return FakePeer(self)

    @property
    def free_slots(self):
        return self.free

    def close(self):
        pass


def _app(provider=None, slots=1):
    fake = FakeSlotPipeline(slots)
    app = build_app(
        provider=provider or LoopbackProvider(), multipeer_pipeline=fake
    )
    return app, fake


def _offer_body():
    return {"room_id": "r", "offer": {"sdp": make_loopback_offer(), "type": "offer"}}


async def _assert_slot_free_and_claimable(client, fake):
    """The invariant: after any failure the slot count is fully restored
    and the slot is claimable again (no permanent 503) — checked on the
    ledger directly, since several scenarios leave the provider itself
    deliberately broken."""
    assert fake.free == fake.slots, "slot leaked"
    peer = fake.claim()  # would raise CapacityError on a leak
    peer.release()


def _run(provider, drive):
    async def go():
        app, fake = _app(provider)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await drive(client, fake, app)
            # releases are scheduled via ensure_future(to_thread(...)) —
            # let them land before auditing the ledger
            for _ in range(20):
                if fake.free == fake.slots:
                    break
                await asyncio.sleep(0.05)
            await _assert_slot_free_and_claimable(client, fake)
        finally:
            await client.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# /offer failure paths
# ---------------------------------------------------------------------------

class SdpParseErrorProvider(LoopbackProvider):
    """session_description raises AFTER the slot claim (the parse happens
    inside the guarded region of offer())."""

    def session_description(self, sdp, type):
        raise ValueError("unparseable SDP")


def test_offer_sdp_parse_error_releases_slot():
    async def drive(client, fake, app):
        r = await client.post("/offer", json=_offer_body())
        assert r.status == 400
        assert fake.claims == 1  # the claim actually happened

    _run(SdpParseErrorProvider(), drive)


class RemoteDescriptionFailsProvider(LoopbackProvider):
    """setRemoteDescription raises — the negotiation-failure shape (bad
    m= sections, ICE setup failure in the native tier)."""

    class _PC(LoopbackPeerConnection):
        async def setRemoteDescription(self, desc):
            raise ValueError("no video m-section")

    def peer_connection(self, ice_servers=None):
        return self._PC(configuration=ice_servers)


def test_offer_set_remote_description_failure_releases_slot():
    async def drive(client, fake, app):
        r = await client.post("/offer", json=_offer_body())
        assert r.status == 400
        assert fake.claims == 1
        assert not app["pcs"], "half-built pc leaked"

    _run(RemoteDescriptionFailsProvider(), drive)


class OnTrackExplodesProvider(LoopbackProvider):
    """The on_track handler itself raises (supervisor/track wiring bug) —
    a non-client error: 500 to the caller, slot still released."""

    class _PC(LoopbackPeerConnection):
        async def setRemoteDescription(self, desc):
            self.remoteDescription = desc
            raise RuntimeError("on_track wiring exploded")

    def peer_connection(self, ice_servers=None):
        return self._PC(configuration=ice_servers)


def test_offer_unexpected_exception_releases_slot():
    async def drive(client, fake, app):
        r = await client.post("/offer", json=_offer_body())
        assert r.status == 500
        assert fake.claims == 1
        assert not app["pcs"]

    _run(OnTrackExplodesProvider(), drive)


class AnswerFailsProvider(LoopbackProvider):
    class _PC(LoopbackPeerConnection):
        async def createAnswer(self):
            raise ValueError("answer construction failed")

    def peer_connection(self, ice_servers=None):
        return self._PC(configuration=ice_servers)


def test_offer_create_answer_failure_releases_slot():
    async def drive(client, fake, app):
        r = await client.post("/offer", json=_offer_body())
        assert r.status == 400
        assert fake.claims == 1

    _run(AnswerFailsProvider(), drive)


def test_offer_failure_after_on_track_ends_supervision():
    """on_track fires during setRemoteDescription and registers a
    supervisor + overload ladder; a later failure (createAnswer) must end
    them — a leaked watchdog task polls forever and a leaked ladder can
    hold an admission freeze."""

    async def drive(client, fake, app):
        r = await client.post("/offer", json=_offer_body())
        assert r.status == 400
        assert fake.claims == 1
        assert app["supervisors"] == {}, "supervisor leaked on failed offer"
        assert app["overload"].ladders == {}, "overload ladder leaked"

    _run(AnswerFailsProvider(), drive)


def test_whip_failure_after_on_track_ends_supervision():
    async def drive(client, fake, app):
        r = await client.post(
            "/whip", data=make_loopback_offer(),
            headers={"Content-Type": "application/sdp"},
        )
        assert r.status == 400
        assert app["supervisors"] == {}, "supervisor leaked on failed whip"
        assert app["overload"].ladders == {}
        assert not app["state"]["whip_tracks"], "publisher track leaked"

    _run(AnswerFailsProvider(), drive)


def test_offer_teardown_race_failed_then_closed_releases_once():
    """connectionstatechange fires release on BOTH 'failed' and 'closed';
    the release must be idempotent — the slot comes back exactly once."""

    async def drive(client, fake, app):
        r = await client.post("/offer", json=_offer_body())
        assert r.status == 200
        assert fake.free == 0
        pc = next(iter(app["pcs"]))
        pc.connectionState = "failed"
        await pc._emit("connectionstatechange")
        pc.connectionState = "closed"
        await pc._emit("connectionstatechange")
        for _ in range(20):
            if fake.free == fake.slots:
                break
            await asyncio.sleep(0.05)
        assert fake.free == fake.slots, "double release corrupted the ledger"

    _run(LoopbackProvider(), drive)


def test_offer_capacity_exhausted_is_503_not_claim():
    """At zero free slots /offer answers 503 + Retry-After and the ledger
    is untouched (no claim to leak)."""

    async def go():
        app, fake = _app(slots=1)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 200
            assert fake.free == 0
            r = await client.post("/offer", json=_offer_body())
            assert r.status == 503
            assert "Retry-After" in r.headers
            assert fake.claims == 1
        finally:
            await client.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# /whip failure paths
# ---------------------------------------------------------------------------

def _whip(client, body="x", ct="application/sdp"):
    return client.post(body and "/whip" or "/whip", data=body,
                       headers={"Content-Type": ct})


def test_whip_bad_content_type_never_claims():
    async def drive(client, fake, app):
        r = await client.post("/whip", data="x",
                              headers={"Content-Type": "text/plain"})
        assert r.status == 400
        assert fake.claims == 0  # refused BEFORE the claim

    _run(LoopbackProvider(), drive)


def test_whip_sdp_parse_error_releases_slot():
    async def drive(client, fake, app):
        r = await client.post("/whip", data="junk",
                              headers={"Content-Type": "application/sdp"})
        assert r.status == 400
        assert fake.claims == 1
        assert not app["state"]["whip_pcs"], "session entry leaked"

    _run(SdpParseErrorProvider(), drive)


def test_whip_negotiation_failure_releases_slot_and_session_entries():
    async def drive(client, fake, app):
        r = await client.post(
            "/whip", data=make_loopback_offer(),
            headers={"Content-Type": "application/sdp"},
        )
        assert r.status == 400
        assert fake.claims == 1
        assert not app["state"]["whip_pcs"]
        assert app["state"]["source_track"] is None

    _run(RemoteDescriptionFailsProvider(), drive)


def test_whip_unexpected_exception_releases_slot():
    async def drive(client, fake, app):
        r = await client.post(
            "/whip", data=make_loopback_offer(),
            headers={"Content-Type": "application/sdp"},
        )
        assert r.status == 500
        assert fake.claims == 1
        assert not app["state"]["whip_pcs"]

    _run(OnTrackExplodesProvider(), drive)


def test_whip_teardown_failed_state_releases_slot():
    async def drive(client, fake, app):
        r = await client.post(
            "/whip", data=make_loopback_offer(),
            headers={"Content-Type": "application/sdp"},
        )
        assert r.status == 201
        assert fake.free == 0
        pc = next(iter(app["pcs"]))
        pc.connectionState = "failed"
        await pc._emit("connectionstatechange")

    _run(LoopbackProvider(), drive)


# ---------------------------------------------------------------------------
# /whep failure paths (claims NO slot — and must not corrupt the ledger)
# ---------------------------------------------------------------------------

def test_whep_paths_do_not_touch_the_slot_ledger():
    async def go():
        app, fake = _app()
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # no publisher yet -> 401; bad content type -> 400
            r = await client.post("/whep", data="x",
                                  headers={"Content-Type": "application/sdp"})
            assert r.status == 401
            r = await client.post("/whep", data="x",
                                  headers={"Content-Type": "text/plain"})
            assert r.status == 400
            assert fake.claims == 0 and fake.free == fake.slots

            # publish, then make the viewer's answer fail: the whep pc and
            # session entry must clean up, the publisher's slot untouched
            r = await client.post(
                "/whip", data=make_loopback_offer(),
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            assert fake.free == fake.slots - 1
            n_pcs = len(app["pcs"])

            real_pc = LoopbackProvider.peer_connection

            class _FailingWhepPC(LoopbackPeerConnection):
                async def createAnswer(self):
                    raise ValueError("viewer answer failed")

            app["provider"].peer_connection = (
                lambda ice_servers=None: _FailingWhepPC()
            )
            r = await client.post("/whep", data=make_loopback_offer(),
                                  headers={"Content-Type": "application/sdp"})
            assert r.status == 400
            assert len(app["pcs"]) == n_pcs, "whep pc leaked"
            assert not app["state"]["whep_pcs"], "whep session entry leaked"
            assert fake.free == fake.slots - 1  # publisher keeps its slot
            app["provider"].peer_connection = real_pc.__get__(app["provider"])
        finally:
            await client.close()

    asyncio.run(go())
