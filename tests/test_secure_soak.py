"""Secure-tier soak: several concurrent encrypted peers, sustained frames,
no leaks/errors, sane metrics.

The stability evidence a single-roundtrip e2e cannot give: three
browser-shaped peers handshake and stream concurrently against one agent
process; every peer gets ITS OWN processed stream back (distinct DTLS
associations, distinct SRTP keys), teardown releases cleanly.
"""

import pytest

# the secure tier's crypto backend is optional at the package level
# (signaling degrades to loopback without it) — these tests must SKIP,
# not fail collection, on a box without it (resilience PR satellite)
pytest.importorskip("cryptography", reason="secure tier needs cryptography")

import asyncio
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.media import native
from ai_rtc_agent_tpu.media.frames import VideoFrame
from ai_rtc_agent_tpu.media.plane import H264RingSource, H264Sink
from ai_rtc_agent_tpu.server.agent import build_app
from ai_rtc_agent_tpu.server.rtc_native import NativeRtpProvider
from tests.secure_client import SecureTestPeer, secure_offer

N_PEERS = 3
N_FRAMES = 40
W = H = 64


@pytest.fixture(scope="module")
def native_lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native lib unavailable")
    return lib


class TintPipeline:
    """Deterministic transform so each peer's return stream is
    attributable: output = 255 - input (shared pipeline, distinct inputs)."""

    def __init__(self):
        self.prompts = []

    def update_prompt(self, p):
        self.prompts.append(p)

    def update_t_index_list(self, t):
        pass

    def __call__(self, frame):
        arr = frame.to_ndarray(format="rgb24")
        out = VideoFrame.from_ndarray(255 - arr)
        out.pts = frame.pts
        out.time_base = frame.time_base
        out.wall_ts = frame.wall_ts
        return out


async def _secure_peer(http, idx: int, use_h264: bool):
    """One full peer lifecycle; returns (decoded_frames, expected_mean)."""
    peer = await SecureTestPeer(f"soak-peer-{idx}", ufrag=f"pr{idx}a").open_socket()
    r = await http.post(
        "/offer",
        json={
            "room_id": f"soak{idx}",
            "offer": {
                "sdp": secure_offer(
                    peer.cert.fingerprint,
                    ufrag=peer.ufrag,
                    pwd=f"soakpeerpwd0123456789{idx}",
                    datachannel=True,
                ),
                "type": "offer",
            },
        },
    )
    assert r.status == 200
    await peer.establish((await r.json())["sdp"])
    # every soak peer also runs the datachannel control plane (r5): DCEP
    # open + one config message per session, concurrently with media
    ch = await peer.open_datachannel("config")
    peer.dc_send(ch, json.dumps({"prompt": f"soak prompt {idx}"}))
    await peer.drain_dc(0.3)

    val = 40 + idx * 60  # distinct constant input per peer
    sink = H264Sink(W, H, use_h264=use_h264, payload_type=102)
    back = H264RingSource(W, H, use_h264=use_h264)
    decoded = []

    def pop_all():
        while (item := back.poll()) is not None:
            decoded.append(item[0])

    try:
        for i in range(N_FRAMES):
            f = VideoFrame.from_ndarray(np.full((H, W, 3), val, np.uint8))
            f.pts = i * 3000
            peer.send_rtp(sink.consume(f))
            await asyncio.sleep(0.03)
            peer.drain_into(back)
            pop_all()
        for _ in range(80):
            if len(decoded) >= 5:
                break
            await asyncio.sleep(0.05)
            peer.drain_into(back)
            pop_all()
    finally:
        sink.close()
        back.close()
        peer.close()
    return decoded, 255 - val


def test_three_concurrent_secure_peers(native_lib, monkeypatch):
    monkeypatch.setenv("WARMUP_FRAMES", "0")
    use_h264 = native.h264_available()

    async def go():
        provider = NativeRtpProvider(
            default_width=W, default_height=H, use_h264=use_h264
        )
        app = build_app(pipeline=TintPipeline(), provider=provider)
        http = TestClient(TestServer(app))
        await http.start_server()
        try:
            results = await asyncio.gather(
                *(_secure_peer(http, i, use_h264) for i in range(N_PEERS))
            )
            for idx, (decoded, expect) in enumerate(results):
                assert decoded, f"peer {idx} got no frames back"
                mean = float(decoded[-1].astype(np.float32).mean())
                assert abs(mean - expect) < 25, (
                    f"peer {idx} stream not its own: mean {mean} vs {expect}"
                )
            m = await http.get("/metrics")
            snap = await m.json()
            assert snap.get("secure_sessions_total", 0) >= N_PEERS
            assert snap.get("srtp_drops_total", 0) == 0
            # every session's datachannel config arrived (shared pipeline)
            assert sorted(app["pipeline"].prompts) == sorted(
                f"soak prompt {i}" for i in range(N_PEERS)
            )
        finally:
            await http.close()

    asyncio.run(go())
