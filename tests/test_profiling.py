"""Direct unit coverage for utils/profiling.py ``FrameStats`` (ISSUE 5).

The class is load-bearing for ``GET /metrics`` (server/agent.py), the
PR 2 host-plane stage gauges (``stage_snapshot_us``) and the overload
counters, but until now was only exercised incidentally through the
server tests.  These tests pin the observable contract directly:
empty-window snapshots, deque wraparound at ``window``, ``record_stage``
percentile math, counter/gauge semantics, and a thread-safety smoke.
"""

import threading

from ai_rtc_agent_tpu.utils.profiling import FrameStats


# -- empty-window behavior ----------------------------------------------------

def test_empty_snapshot_has_null_latencies_and_zero_fps():
    s = FrameStats().snapshot()
    assert s["frames_total"] == 0
    assert s["fps"] == 0.0
    assert s["latency_p50_ms"] is None
    assert s["latency_p90_ms"] is None
    assert s["latency_max_ms"] is None


def test_single_sample_no_fps_but_latency_present():
    st = FrameStats()
    st.record(0.050, t=100.0)
    s = st.snapshot()
    # fps needs >=2 timestamps spanning nonzero time
    assert s["fps"] == 0.0
    assert s["latency_p50_ms"] == 50.0
    assert s["latency_max_ms"] == 50.0
    assert s["frames_total"] == 1


def test_identical_timestamps_do_not_divide_by_zero():
    st = FrameStats()
    st.record(0.010, t=5.0)
    st.record(0.010, t=5.0)
    assert st.snapshot()["fps"] == 0.0


def test_empty_stage_snapshot_us_is_empty():
    assert FrameStats().stage_snapshot_us() == {}


# -- fps + wraparound ---------------------------------------------------------

def test_fps_over_explicit_timestamps():
    st = FrameStats()
    for i in range(31):  # 31 samples, 1 s apart -> 30 intervals / 30 s
        st.record(0.001, t=float(i))
    assert st.snapshot()["fps"] == 30 / 30.0


def test_window_wraparound_drops_oldest_but_total_is_monotonic():
    st = FrameStats(window=4)
    for i in range(10):
        # latencies 0..9 ms; timestamps 1 s apart
        st.record(i / 1e3, t=float(i))
    s = st.snapshot()
    # frames_total counts every record, the window only bounds percentiles
    assert s["frames_total"] == 10
    # only the last 4 samples (6..9 ms) remain: max is 9, p50 sits mid-window
    assert s["latency_max_ms"] == 9.0
    assert s["latency_p50_ms"] == 8.0  # sorted [6,7,8,9][4//2]
    # fps window follows the same 4 samples: 3 intervals over 3 s
    assert s["fps"] == 1.0


def test_record_stage_wraps_at_window_too():
    st = FrameStats(window=3)
    for v in (1.0, 2.0, 3.0, 4.0):
        st.record_stage("encode", v)
    s = st.snapshot()
    # deque holds [2,3,4]: p50 = sorted[1] = 3
    assert s["encode_p50_ms"] == 3000.0
    assert s["encode_p90_ms"] == 4000.0


# -- record_stage percentile math --------------------------------------------

def test_stage_percentiles_ms_and_us_agree():
    st = FrameStats()
    for i in range(1, 101):  # 1..100 µs
        st.record_stage("packetize", i / 1e6)
    s = st.snapshot()
    u = st.stage_snapshot_us()
    # p50 = sorted[100//2] = 51st value = 51 µs
    assert u["packetize_p50_us"] == 51.0
    assert u["packetize_p90_us"] == 91.0
    assert u["packetize_p99_us"] == 100.0
    assert u["packetize_count"] == 100
    assert abs(s["packetize_p50_ms"] - 0.051) < 1e-9
    assert abs(s["packetize_p90_ms"] - 0.091) < 1e-9


def test_stage_snapshot_us_filters_and_carries_counters():
    st = FrameStats()
    st.record_stage("send", 10 / 1e6)
    st.record_stage("infer", 5 / 1e3)
    st.count("tx_packets", 7)
    u = st.stage_snapshot_us(stages=("send",))
    assert "send_p50_us" in u
    assert "infer_p50_us" not in u  # filtered out
    assert u["tx_packets_total"] == 7  # counters always ride along


def test_stages_are_independent_deques():
    st = FrameStats(window=2)
    st.record_stage("decode", 0.001)
    st.record_stage("encode", 0.002)
    st.record_stage("encode", 0.003)
    st.record_stage("encode", 0.004)  # encode wraps; decode must not
    s = st.snapshot()
    assert s["decode_p50_ms"] == 1.0
    assert s["encode_p50_ms"] == 4.0  # sorted [3,4][2//2]


# -- counters + gauges --------------------------------------------------------

def test_counts_accumulate_and_land_as_total():
    st = FrameStats()
    st.count("srtp_drops")
    st.count("srtp_drops", 2)
    assert st.snapshot()["srtp_drops_total"] == 3


def test_gauge_is_last_value_wins():
    st = FrameStats()
    st.gauge("rr_jitter_ms", 4.0)
    st.gauge("rr_jitter_ms", 2.5)
    assert st.snapshot()["rr_jitter_ms"] == 2.5


def test_timed_context_manager_records_one_sample():
    st = FrameStats()
    with st.timed():
        pass
    s = st.snapshot()
    assert s["frames_total"] == 1
    assert s["latency_max_ms"] is not None and s["latency_max_ms"] >= 0.0


# -- thread-safety smoke ------------------------------------------------------

def test_concurrent_mixed_recording_is_consistent():
    """4 writer threads hammer every mutating entry point while a reader
    snapshots concurrently; no exception, and the monotonic totals come
    out exact (the deques themselves are bounded, so only the counters
    can prove nothing was lost)."""
    st = FrameStats(window=64)
    n_threads, per_thread = 4, 500
    errors = []

    def writer(tid):
        try:
            for i in range(per_thread):
                st.record(0.001 * (i % 7), t=float(i))
                st.record_stage("encode", 0.001)
                st.count("events")
                st.gauge("g", i)
        except Exception as e:  # pragma: no cover - failure diagnostics
            errors.append(e)

    def reader():
        try:
            for _ in range(200):
                st.snapshot()
                st.stage_snapshot_us()
        except Exception as e:  # pragma: no cover - failure diagnostics
            errors.append(e)

    threads = [
        threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
    ] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    s = st.snapshot()
    assert s["frames_total"] == n_threads * per_thread
    assert s["events_total"] == n_threads * per_thread
