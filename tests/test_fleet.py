"""Fleet control plane units (ISSUE 11): registry state machine,
capacity-aware placement, Retry-After honoring, drain, crash
replacement, and the aggregate /metrics rollup.

Everything here is in-process and clockless where possible; the
hermetic 3-real-process acceptance lives in tests/test_fleet_procs.py.
"""

import asyncio
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai_rtc_agent_tpu.fleet.registry import FleetPoller, FleetRegistry
from ai_rtc_agent_tpu.fleet.router import build_router_app
from ai_rtc_agent_tpu.server.events import StreamEventHandler
from ai_rtc_agent_tpu.utils.profiling import FrameStats


def run(coro):
    return asyncio.run(coro)


class Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _reg(**kw):
    kw.setdefault("clock", Clock())
    kw.setdefault("stats", FrameStats())
    return FleetRegistry(**kw)


def _info(wid, port=9000, **extra):
    return {"worker_id": wid, "public_ip": "127.0.0.1",
            "public_port": str(port), "status": "ready", **extra}


# ---------------------------------------------------------------------------
# registry: membership + health state machine
# ---------------------------------------------------------------------------

def test_register_bounded_and_revive():
    reg = _reg(max_agents=2)
    a = reg.register(_info("a", 9001, capacity=4))
    b = reg.register(_info("b", 9002))
    assert a.capacity == 4 and b.capacity == -1
    assert reg.register(_info("c", 9003)) is None  # bounded membership
    # refresh updates in place
    a2 = reg.register(_info("a", 9001, capacity=1, saturated=True))
    assert a2 is a and a.capacity == 1 and a.saturated
    # a recycled replacement publishing over a DEAD record revives fresh
    reg.mark_dead(a)
    a3 = reg.register(_info("a", 9001, capacity=4))
    assert a3 is not a and a3.state == "HEALTHY" and a3.fail_count == 0
    with pytest.raises(ValueError):
        reg.register({"status": "ready"})  # no identity


def test_poll_failures_mark_dead_once():
    died = []
    reg = _reg(dead_after=2, on_dead=died.append)
    a = reg.register(_info("a"))
    reg.note_poll_fail(a)
    assert a.state == "HEALTHY" and not died
    reg.note_poll_fail(a)
    assert a.state == "DEAD" and died == [a]
    reg.note_poll_fail(a)  # dead stays dead, on_dead fires ONCE
    assert died == [a]
    # a successful poll cannot resurrect a corpse — only re-registration
    reg.note_poll(a, {"capacity": 3}, {"status": "HEALTHY", "sessions": {}})
    assert a.state == "DEAD"


def test_poll_drives_states_and_drain_to_recyclable():
    reg = _reg()
    a = reg.register(_info("a"))
    reg.note_poll(a, {"capacity": 3, "saturated": False},
                  {"status": "DEGRADED", "sessions": {"s1": {}}})
    assert a.state == "DEGRADED" and a.live_sessions == 1 and a.capacity == 3
    reg.note_poll(a, None, {"status": "HEALTHY", "sessions": {}})
    assert a.state == "HEALTHY"
    # drain: state pins DRAINING; zero live sessions flips recyclable
    a.draining = True
    reg.note_poll(a, None, {"status": "HEALTHY", "sessions": {"s": {}}})
    assert a.state == "DRAINING" and not a.recyclable
    reg.note_poll(a, None, {"status": "HEALTHY", "sessions": {}})
    assert a.recyclable


def test_pick_least_loaded_with_tiers_and_backoff():
    clock = Clock()
    reg = _reg(clock=clock)
    a = reg.register(_info("a", capacity=1))
    b = reg.register(_info("b", capacity=3))
    assert reg.pick() is b  # most free capacity wins
    reg.note_placed(b)
    reg.note_placed(b)  # b effective 1, tie with a -> fewest live+placed
    assert reg.pick() is a
    reg.note_placed(a)
    assert reg.pick() is b  # a exhausted (effective 0)
    reg.note_placed(b)
    assert reg.pick() is None  # whole fleet structurally full
    # capacity poll resets the optimistic decrement
    reg.note_poll(b, {"capacity": 2, "saturated": False}, None)
    assert reg.pick() is b
    # Retry-After honor window: a backoff blocks pick until it expires
    b.backoff(30.0, clock())
    assert reg.pick() is None
    clock.now = 31.0
    assert reg.pick() is b
    # DEGRADED serves only when no HEALTHY agent can
    b.state = "DEGRADED"
    reg.note_poll(a, {"capacity": 1, "saturated": False}, None)
    a.state = "HEALTHY"
    assert reg.pick() is a
    a.state = "DEGRADED"
    assert reg.pick() in (a, b)
    a.state = "DEAD"
    b.state = "DEAD"
    assert reg.pick() is None


def test_unbounded_capacity_sorts_first_and_saturated_blocks():
    reg = _reg()
    a = reg.register(_info("a", capacity=5))
    b = reg.register(_info("b"))  # no capacity field -> unbounded (-1)
    assert reg.pick() is b
    b.saturated = True
    assert reg.pick() is a


def test_ingest_event_marks_owner_degraded():
    reg = _reg()
    a = reg.register(_info("a"))
    reg.ingest_event(
        {"event": "StreamDegraded", "state": "RETRACE_BREACH",
         "stream_id": "s1"},
        "a",
    )
    assert a.state == "DEGRADED"
    snap = reg.stats.snapshot()
    assert snap["fleet_breaches_total"] == 1
    assert snap["fleet_events_ingested_total"] == 1
    # unattributable events still count, mark nothing
    reg.ingest_event({"event": "StreamDegraded", "state": "DEGRADED",
                      "stream_id": "???"}, None)
    assert reg.stats.snapshot()["fleet_breaches_total"] == 2
    # recovery events are not breaches
    reg.ingest_event({"event": "StreamRecovered", "state": "HEALTHY",
                      "stream_id": "s1"}, "a")
    assert reg.stats.snapshot()["fleet_breaches_total"] == 2


def test_registry_snapshot_rollup_is_aggregate_only():
    reg = _reg()
    a = reg.register(_info("a", capacity=2))
    b = reg.register(_info("b", capacity=4))
    reg.note_poll(a, None, {"status": "HEALTHY",
                            "sessions": {"x": {}, "y": {}}})
    b.state = "DEAD"
    snap = reg.snapshot()
    assert snap["fleet_agents"] == 2
    assert snap["fleet_agents_healthy"] == 1
    assert snap["fleet_agents_dead"] == 1
    assert snap["fleet_sessions"] == 2
    assert snap["fleet_capacity_free"] == 2  # dead agent's 4 excluded
    # aggregate values only — nothing keyed by agent identity
    assert all(not isinstance(v, dict) for v in snap.values())


def test_retry_after_hint_is_soonest_agent():
    clock = Clock()
    reg = _reg(clock=clock)
    a = reg.register(_info("a"))
    b = reg.register(_info("b"))
    assert reg.retry_after_hint(2.0) == 2.0  # nothing hinted: default
    a.backoff(30.0, clock())
    b.backoff(5.0, clock())
    assert reg.retry_after_hint(2.0) == 5.0  # soonest admitting agent
    clock.now = 4.5
    # b's remaining window is 0.5s — floored at 1s so clients never hammer
    assert reg.retry_after_hint(2.0) == 1.0


# ---------------------------------------------------------------------------
# fake agent for router tests
# ---------------------------------------------------------------------------

class FakeAgent:
    """Minimal agent surface the router drives: /offer (+X-Stream-Id),
    /whip, /whep, /broadcast/pull, /capacity, /health, /drain — with a
    switchable 503 mode."""

    def __init__(self, name, capacity=2, retry_after=7):
        self.name = name
        self.capacity = capacity
        self.retry_after = retry_after
        self.mode = "ok"
        self.fail_delete = False  # transient 5xx mode for DELETE
        self.refuse_pull = False  # 409 mode for /broadcast/pull
        self.sessions: dict = {}
        self.hits = {"offer": 0, "whip": 0, "whep": 0, "pull": [],
                     "drain": [], "delete": [], "flight": []}
        # journey fragments served at GET /debug/flight?journey= —
        # {journey_id: fragment-dict}, set by tests simulating an agent
        # that holds records for the journey
        self.flight: dict = {}
        self.server = None

    def _app(self):
        app = web.Application()

        async def offer(req):
            self.hits["offer"] += 1
            if self.mode == "503":
                return web.Response(
                    status=503, text="overloaded",
                    headers={"Retry-After": str(self.retry_after)},
                )
            sid = f"{self.name}-s{len(self.sessions) + 1}"
            self.sessions[sid] = {
                "journey": req.headers.get("X-Journey-Id"),
                "leg": req.headers.get("X-Journey-Leg"),
            }
            headers = {"X-Stream-Id": sid}
            # a journey-aware agent echoes the binding (server/agent.py)
            if req.headers.get("X-Journey-Id"):
                headers["X-Journey-Id"] = req.headers["X-Journey-Id"]
                headers["X-Journey-Leg"] = req.headers.get(
                    "X-Journey-Leg", "1"
                )
            return web.json_response(
                {"sdp": "answer-sdp", "type": "answer"}, headers=headers
            )

        async def whip(req):
            self.hits["whip"] += 1
            sid = f"{self.name}-w{len(self.sessions) + 1}"
            self.sessions[sid] = {}
            return web.Response(
                status=201, text="answer-sdp",
                headers={"Location": f"/whip/{sid}"},
            )

        async def whep(req):
            self.hits["whep"] += 1
            sid = f"{self.name}-v{len(self.sessions) + 1}"
            self.sessions[sid] = {}
            return web.Response(
                status=201, text="answer-sdp",
                headers={"Location": f"/whep/{sid}"},
            )

        async def broadcast_pull(req):
            body = await req.json()
            self.hits["pull"].append(body["owner_url"])
            if self.refuse_pull:
                return web.Response(status=409, text="fan-out disabled")
            return web.json_response({"publisher": "default"})

        async def whip_delete(req):
            sid = req.match_info["session"]
            self.hits["delete"].append(sid)
            if self.fail_delete:
                return web.Response(status=503, text="transient")
            return web.Response(
                status=200 if self.sessions.pop(sid, None) is not None
                else 404
            )

        async def capacity(req):
            return web.json_response({
                "capacity": max(0, self.capacity - len(self.sessions)),
                "saturated": self.mode == "503",
                "retry_after_s": 0.0,
            })

        async def health(req):
            return web.json_response({
                "status": "HEALTHY",
                "sessions": {k: {} for k in self.sessions},
            })

        async def drain(req):
            body = await req.json()
            self.hits["drain"].append(body["action"])
            return web.json_response({"draining": body["action"] == "freeze"})

        async def debug_flight(req):
            jid = req.query.get("journey", "")
            self.hits["flight"].append(jid)
            frag = self.flight.get(jid)
            if frag is None:
                return web.json_response(
                    {"error": f"no records for journey {jid!r}"},
                    status=404,
                )
            return web.json_response(frag)

        app.router.add_post("/offer", offer)
        app.router.add_get("/debug/flight", debug_flight)
        app.router.add_post("/whip", whip)
        app.router.add_delete("/whip/{session}", whip_delete)
        app.router.add_post("/whep", whep)
        app.router.add_post("/broadcast/pull", broadcast_pull)
        app.router.add_get("/capacity", capacity)
        app.router.add_get("/health", health)
        app.router.add_post("/drain", drain)
        return app

    async def start(self):
        self.server = TestServer(self._app())
        await self.server.start_server()
        return self

    @property
    def port(self):
        return self.server.port

    async def close(self):
        await self.server.close()


async def _router(agents, *, clock=None, dead_after=3, events=None,
                  poll=False):
    reg = FleetRegistry(clock=clock or Clock(), dead_after=dead_after)
    app = build_router_app(registry=reg, poll=poll, events_handler=events)
    client = TestClient(TestServer(app))
    await client.start_server()
    for agent in agents:
        r = await client.post("/fleet/register", json=_info(
            agent.name, agent.port, capacity=agent.capacity
        ))
        assert r.status == 200
    return app, client, reg


_OFFER = {"room_id": "r1", "offer": {"sdp": "v=0 m=video", "type": "offer"}}


def test_router_places_and_proxies_offer():
    async def go():
        a = await FakeAgent("a").start()
        app, client, reg = await _router([a])
        try:
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            body = await r.json()
            assert body["type"] == "answer"
            assert r.headers["X-Stream-Id"] == "a-s1"
            assert app["session_table"].owner("a-s1") == "a"
            m = await (await client.get("/metrics")).json()
            assert m["fleet_placements_total"] == 1
            assert m["fleet_agents"] == 1
        finally:
            await client.close()
            await a.close()

    run(go())


def test_router_spreads_by_capacity():
    async def go():
        agents = [await FakeAgent(n).start() for n in ("a", "b", "c")]
        app, client, reg = await _router(agents)
        try:
            for _ in range(3):
                r = await client.post("/offer", json=_OFFER)
                assert r.status == 200
            # least-loaded greedy with optimistic decrement: one each
            assert [ag.hits["offer"] for ag in agents] == [1, 1, 1]
        finally:
            await client.close()
            for ag in agents:
                await ag.close()

    run(go())


def test_router_honors_retry_after_and_replaces():
    """ISSUE 11 satellite: a saturated agent's 503 carries Retry-After —
    the request re-places elsewhere, and that agent is NOT re-offered
    within its hint window (no hot loop)."""
    async def go():
        clock = Clock()
        sat = await FakeAgent("sat", capacity=8, retry_after=30).start()
        sat.mode = "503"
        ok = await FakeAgent("ok", capacity=2).start()
        app, client, reg = await _router([sat, ok], clock=clock)
        try:
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200  # re-placed onto the healthy agent
            assert sat.hits["offer"] == 1 and ok.hits["offer"] == 1
            # within the hint window the saturated agent is never retried
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            assert sat.hits["offer"] == 1 and ok.hits["offer"] == 2
            # after the window it becomes eligible again
            sat.mode = "ok"
            clock.now = 31.0
            reg.agents["sat"].saturated = False  # poll would clear this
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            assert sat.hits["offer"] == 2
            m = await (await client.get("/metrics")).json()
            assert m["fleet_placement_retries_total"] == 1
        finally:
            await client.close()
            await sat.close()
            await ok.close()

    run(go())


def test_fleet_saturated_returns_one_coherent_503():
    async def go():
        clock = Clock()
        a = await FakeAgent("a", retry_after=9).start()
        b = await FakeAgent("b", retry_after=4).start()
        a.mode = b.mode = "503"
        app, client, reg = await _router([a, b], clock=clock)
        try:
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 503
            # ONE coherent refusal: the soonest agent's hint, not a fan
            # of client-visible retries
            assert int(r.headers["Retry-After"]) == 4
            assert a.hits["offer"] + b.hits["offer"] == 2  # once each
            # second request inside both windows: no agent contacted
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 503
            assert int(r.headers["Retry-After"]) >= 1
            assert a.hits["offer"] + b.hits["offer"] == 2
            m = await (await client.get("/metrics")).json()
            assert m["fleet_rejects_total"] == 2
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_whip_location_and_routed_delete():
    async def go():
        a = await FakeAgent("a").start()
        app, client, reg = await _router([a])
        try:
            r = await client.post(
                "/whip", data="v=0 m=video",
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            sid = r.headers["Location"].rsplit("/", 1)[-1]
            assert app["session_table"].owner(sid) == "a"
            r = await client.delete(f"/whip/{sid}")
            assert r.status == 200
            assert a.hits["delete"] == [sid]
            assert app["session_table"].owner(sid) is None
            # unknown session: the router answers, no agent guessing
            r = await client.delete("/whip/nope")
            assert r.status == 404
        finally:
            await client.close()
            await a.close()

    run(go())


def test_whep_edge_pull_places_viewer_off_owner():
    """ISSUE 17 two-level fan-out: a /whep viewer lands on a NON-owner
    edge agent after the router arranges its single pulled copy of the
    publisher's stream (idempotent POST /broadcast/pull), so per-box
    viewer caps multiply across the fleet."""
    async def go():
        a = await FakeAgent("a").start()
        b = await FakeAgent("b").start()
        app, client, reg = await _router([a, b])
        try:
            r = await client.post(
                "/whip", data="v=0 m=video",
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            owner = a if a.hits["whip"] else b
            edge = b if owner is a else a
            r = await client.post(
                "/whep", data="viewer-offer",
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            # the edge was told where to pull from, then got the viewer
            assert edge.hits["pull"] == [f"http://127.0.0.1:{owner.port}"]
            assert edge.hits["whep"] == 1 and owner.hits["whep"] == 0
            sid = r.headers["Location"].rsplit("/", 1)[-1]
            assert app["session_table"].owner(sid) == edge.name
            m = await (await client.get("/metrics")).json()
            assert m["fleet_edge_pulls_total"] == 1
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_whep_edge_pull_refusal_falls_back_to_owner():
    """An edge that refuses the pull (fan-out disabled there — 409) must
    not strand the viewer: the placement falls back to the owning agent,
    which is always correct, just not scaled out."""
    async def go():
        a = await FakeAgent("a").start()
        b = await FakeAgent("b").start()
        app, client, reg = await _router([a, b])
        try:
            r = await client.post(
                "/whip", data="v=0 m=video",
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            owner = a if a.hits["whip"] else b
            edge = b if owner is a else a
            edge.refuse_pull = True
            r = await client.post(
                "/whep", data="viewer-offer",
                headers={"Content-Type": "application/sdp"},
            )
            assert r.status == 201
            assert len(edge.hits["pull"]) == 1
            assert owner.hits["whep"] == 1 and edge.hits["whep"] == 0
            m = await (await client.get("/metrics")).json()
            assert m["fleet_edge_pull_refused_total"] == 1
            assert "fleet_edge_pulls_total" not in m
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_drain_flow_to_recyclable_and_cancel():
    async def go():
        a = await FakeAgent("a").start()
        b = await FakeAgent("b").start()
        app, client, reg = await _router([a, b])
        try:
            # one live session on a
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200 and a.hits["offer"] == 1
            reg.agents["a"].live_sessions = 1
            r = await client.post("/fleet/drain?agent=a")
            body = await r.json()
            assert r.status == 200 and body["draining"]
            assert body["agent_ack"] and a.hits["drain"] == ["freeze"]
            assert not body["recyclable"]  # session still live
            # placement never lands on a draining agent
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200 and b.hits["offer"] == 1
            # sessions finish -> the poll feed flips recyclable
            a.sessions.clear()
            reg.note_poll(reg.agents["a"], None,
                          {"status": "HEALTHY", "sessions": {}})
            h = await (await client.get("/fleet/health")).json()
            assert h["agents"]["a"]["state"] == "DRAINING"
            assert h["agents"]["a"]["recyclable"]
            m = await (await client.get("/metrics")).json()
            assert m["fleet_drains_total"] == 1
            assert m["fleet_agents_recyclable"] == 1
            # cancel reverts both sides
            r = await client.post("/fleet/drain?agent=a&action=cancel")
            assert (await r.json())["draining"] is False
            assert a.hits["drain"] == ["freeze", "unfreeze"]
            assert reg.agents["a"].state == "HEALTHY"
            # unknown agent / bad action are client errors
            assert (await client.post("/fleet/drain?agent=zz")).status == 404
            assert (await client.post("/fleet/drain")).status == 400
            assert (
                await client.post("/fleet/drain?agent=a&action=zap")
            ).status == 400
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_dead_agent_repoints_sessions_through_webhooks():
    """Crash replacement: DEAD agent -> every session the router placed
    there gets a StreamDegraded(state=AGENT_DEAD) webhook so the client
    re-offers; the table forgets the dead placements."""
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        a = await FakeAgent("a").start()
        b = await FakeAgent("b").start()
        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        app, client, reg = await _router(
            [a, b], dead_after=2, events=events
        )
        try:
            for _ in range(2):
                assert (await client.post("/offer", json=_OFFER)).status == 200
            placed_a = [
                sid for sid in list(app["session_table"]._m)
                if app["session_table"].owner(sid) == "a"
            ]
            assert placed_a  # least-loaded spread put >=1 session on a
            rec = reg.agents["a"]
            reg.note_poll_fail(rec)
            reg.note_poll_fail(rec)
            assert rec.state == "DEAD"
            # webhook fan-out is fire-and-forget tasks — let them run
            await asyncio.sleep(0)
            await asyncio.gather(*list(events._tasks))
            assert len(posted) == len(placed_a)
            ev = posted[0]
            assert ev["event"] == "StreamDegraded"
            assert ev["state"] == "AGENT_DEAD"
            assert ev["stream_id"] in placed_a
            assert ev["room_id"] == "r1"
            for sid in placed_a:
                assert app["session_table"].owner(sid) is None
            # the client's re-offer lands on the replacement
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            assert r.headers["X-Stream-Id"].startswith("b-")
            m = await (await client.get("/metrics")).json()
            assert m["fleet_sessions_repointed_total"] == len(placed_a)
            assert m["fleet_agents_died_total"] == 1
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_router_events_ingest_marks_owner_and_checks_token():
    async def go():
        a = await FakeAgent("a").start()
        events = StreamEventHandler(webhook_url=None, token="sekret")
        app, client, reg = await _router([a], events=events)
        try:
            assert (await client.post("/offer", json=_OFFER)).status == 200
            ev = {"event": "StreamDegraded", "state": "RETRACE_BREACH",
                  "stream_id": "a-s1", "room_id": "", "timestamp": 1}
            r = await client.post("/fleet/events", json=ev)
            assert r.status == 401  # token configured, none sent
            r = await client.post(
                "/fleet/events", json=ev,
                headers={"Authorization": "Bearer sekret"},
            )
            assert r.status == 200
            assert reg.agents["a"].state == "DEGRADED"
            m = await (await client.get("/metrics")).json()
            assert m["fleet_breaches_total"] == 1
        finally:
            await client.close()
            await a.close()

    run(go())


def test_register_endpoint_validates_and_bounds():
    async def go():
        reg = FleetRegistry(clock=Clock(), max_agents=1)
        app = build_router_app(registry=reg, poll=False)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/fleet/register", json=_info("a", 9001))
            assert r.status == 200
            r = await client.post("/fleet/register", json=_info("b", 9002))
            assert r.status == 503 and "Retry-After" in r.headers
            r = await client.post("/fleet/register", data="not json")
            assert r.status == 400
            r = await client.post("/fleet/register", json={"status": "x"})
            assert r.status == 400
        finally:
            await client.close()

    run(go())


def test_poller_updates_and_detects_death():
    async def go():
        a = await FakeAgent("a", capacity=5).start()
        reg = FleetRegistry(clock=Clock(), dead_after=2)
        rec = reg.register(_info("a", a.port))
        poller = FleetPoller(reg, interval_s=999.0, timeout_s=1.0)
        await poller.start()
        try:
            a.sessions["s1"] = {}
            await poller.poll_once()
            assert rec.capacity == 4  # the agent's own counted view
            assert rec.live_sessions == 1
            assert rec.state == "HEALTHY"
            await a.close()  # the process "dies"
            await poller.poll_once()
            assert rec.state == "HEALTHY" and rec.fail_count == 1
            await poller.poll_once()
            assert rec.state == "DEAD"
        finally:
            await poller.stop()
            if a.server.started:
                await a.close()

    run(go())


# ---------------------------------------------------------------------------
# prometheus conformance of the fleet rollup
# ---------------------------------------------------------------------------

def test_fleet_metrics_prom_conformance():
    from test_promexport import validate_exposition

    async def go():
        a = await FakeAgent("a").start()
        app, client, reg = await _router([a])
        try:
            assert (await client.post("/offer", json=_OFFER)).status == 200
            r = await client.get("/metrics", params={"format": "prom"})
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = await r.text()
            families = validate_exposition(text)
            assert families["fleet_placements_total"]["type"] == "counter"
            assert families["fleet_agents"]["type"] == "gauge"
            assert families["fleet_sessions"]["type"] == "gauge"
            # journey families (ISSUE 13) ride the same rollup with
            # dedicated HELP rows
            assert families["journeys_total"]["type"] == "counter"
            assert families["journey_legs_total"]["type"] == "counter"
            assert families["journey_replacements_total"]["type"] == "counter"
            assert families["journeys_tracked"]["type"] == "gauge"
            assert families["journey_bundles_stored"]["type"] == "gauge"
            assert "# HELP journeys_total session journeys placed" in text
            assert ("# HELP journey_replacements_total crash re-placements"
                    in text)
            # NEVER labeled by unbounded agent/session/journey identity:
            # the fleet rollup is aggregate-only, so no sample —
            # including every journey family — carries any label at all
            for fam in families.values():
                for _name, labels, _v in fam["samples"]:
                    assert labels == {}, (fam, labels)
            r = await client.get("/metrics", params={"format": "nope"})
            assert r.status == 400
        finally:
            await client.close()
            await a.close()

    run(go())


# ---------------------------------------------------------------------------
# agent-side drain endpoint (the admission-freeze rung over HTTP)
# ---------------------------------------------------------------------------

def test_agent_drain_endpoint_freezes_admission():
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import (
        LoopbackProvider,
        make_loopback_offer,
    )

    class FakePipeline:
        def __call__(self, frame):
            return frame

        def update_prompt(self, p):
            pass

        def update_t_index_list(self, t):
            pass

    async def go():
        app = build_app(pipeline=FakePipeline(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/drain", json={"action": "freeze"})
            body = await r.json()
            assert r.status == 200 and body["draining"] and body["changed"]
            cap = await (await client.get("/capacity")).json()
            assert cap["saturated"] and cap["draining"]
            assert cap["capacity"] == 0
            h = await (await client.get("/health")).json()
            assert h["overload"]["draining"] and h["overload"]["frozen"]
            # a draining agent admits nothing, with a Retry-After
            r = await client.post("/offer", json={
                "room_id": "r",
                "offer": {"sdp": make_loopback_offer(), "type": "offer"},
            })
            assert r.status == 503 and "Retry-After" in r.headers
            m = await (await client.get("/metrics")).json()
            assert m["overload_draining"] == 1
            # freeze is idempotent; unfreeze restores admission
            r = await client.post("/drain", json={"action": "freeze"})
            assert (await r.json())["changed"] is False
            r = await client.post("/drain", json={"action": "unfreeze"})
            assert (await r.json())["draining"] is False
            r = await client.post("/offer", json={
                "room_id": "r",
                "offer": {"sdp": make_loopback_offer(), "type": "offer"},
            })
            assert r.status == 200
            assert r.headers["X-Stream-Id"]  # the router's session key
            # bad bodies are client errors
            assert (await client.post("/drain", data="x")).status == 400
            assert (
                await client.post("/drain", json={"action": "zap"})
            ).status == 400
        finally:
            await client.close()

    run(go())


def test_agent_drain_without_overload_plane_is_409(monkeypatch):
    from ai_rtc_agent_tpu.server.agent import build_app
    from ai_rtc_agent_tpu.server.signaling import LoopbackProvider

    monkeypatch.setenv("OVERLOAD_CONTROL", "0")

    async def go():
        app = build_app(pipeline=object(), provider=LoopbackProvider())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/drain", json={"action": "freeze"})
            assert r.status == 409
        finally:
            await client.close()

    run(go())


# ---------------------------------------------------------------------------
# code-review round regressions (ISSUE 11)
# ---------------------------------------------------------------------------

def test_register_evicts_dead_corpse_when_full():
    """Orchestrators recycle crashed agents under NEW ids — DEAD records
    must not lock replacements out of a bounded registry."""
    reg = _reg(max_agents=2)
    a = reg.register(_info("a", 9001))
    reg.register(_info("b", 9002))
    assert reg.register(_info("c", 9003)) is None  # full of LIVE agents
    reg.mark_dead(a)
    c = reg.register(_info("c", 9003))  # corpse evicted, newcomer admitted
    assert c is not None and "a" not in reg.agents
    assert len(reg.agents) == 2


def test_poller_survives_garbage_200s_and_counts_them_dead():
    """A reverse proxy answering 200 with a non-agent body (JSON array,
    error page) must neither kill the poll task (AttributeError on
    .get) nor read as health — the agent behind it still reaches DEAD."""
    async def go():
        app = web.Application()

        async def garbage(req):
            return web.json_response(["not", "an", "agent"])

        app.router.add_get("/capacity", garbage)
        app.router.add_get("/health", garbage)
        server = TestServer(app)
        await server.start_server()
        reg = FleetRegistry(clock=Clock(), dead_after=2)
        rec = reg.register(_info("gw", server.port))
        poller = FleetPoller(reg, interval_s=999.0, timeout_s=1.0)
        await poller.start()
        try:
            await poller.poll_once()
            assert rec.fail_count == 1 and rec.state == "HEALTHY"
            await poller.poll_once()  # the loop is still alive to get here
            assert rec.state == "DEAD"
        finally:
            await poller.stop()
            await server.close()

    run(go())


def test_stream_ended_forgets_session_table_entry():
    """StreamEnded ingest prunes the placement map: a long-ended session
    must not draw an AGENT_DEAD re-point later, nor crowd live sessions
    out of the bounded table."""
    async def go():
        a = await FakeAgent("a").start()
        events = StreamEventHandler(webhook_url=None, token=None)
        app, client, reg = await _router([a], events=events)
        try:
            assert (await client.post("/offer", json=_OFFER)).status == 200
            assert app["session_table"].owner("a-s1") == "a"
            r = await client.post("/fleet/events", json={
                "event": "StreamEnded", "stream_id": "a-s1",
                "room_id": "r1", "timestamp": 1,
            })
            assert r.status == 200
            assert app["session_table"].owner("a-s1") is None
        finally:
            await client.close()
            await a.close()

    run(go())


def test_routed_delete_keeps_mapping_on_agent_5xx():
    """A transient agent error on DELETE must not burn the placement
    mapping — the client's retry has to still route."""
    async def go():
        a = await FakeAgent("a").start()
        app, client, reg = await _router([a])
        try:
            r = await client.post(
                "/whip", data="v=0 m=video",
                headers={"Content-Type": "application/sdp"},
            )
            sid = r.headers["Location"].rsplit("/", 1)[-1]
            a.fail_delete = True
            r = await client.delete(f"/whip/{sid}")
            assert r.status == 503
            assert app["session_table"].owner(sid) == "a"  # retained
            a.fail_delete = False
            r = await client.delete(f"/whip/{sid}")  # retry routes + lands
            assert r.status == 200
            assert app["session_table"].owner(sid) is None
        finally:
            await client.close()
            await a.close()

    run(go())


def test_drain_before_first_poll_is_not_recyclable():
    """live_sessions defaults to 0 before any /health poll — draining a
    never-polled agent must not advertise recyclable (an orchestrator
    would hard-drop whatever it is actually serving)."""
    async def go():
        a = await FakeAgent("a").start()
        app, client, reg = await _router([a])
        try:
            assert reg.agents["a"].last_ok is None  # no poll ran
            r = await client.post("/fleet/drain?agent=a")
            body = await r.json()
            assert body["draining"] and not body["recyclable"]
            # polled evidence of zero sessions DOES flip it
            reg.note_poll(reg.agents["a"], None,
                          {"status": "HEALTHY", "sessions": {}})
            assert reg.agents["a"].recyclable
        finally:
            await client.close()
            await a.close()

    run(go())


# ---------------------------------------------------------------------------
# session journeys (ISSUE 13): correlation ids, the router ring, evidence
# auto-capture, incident bundles
# ---------------------------------------------------------------------------

from ai_rtc_agent_tpu.fleet.journey import JourneyLog


def _jlog(monkeypatch=None, clock=None, **env):
    if monkeypatch is not None:
        for k, v in env.items():
            monkeypatch.setenv(k, str(v))
    return JourneyLog(clock=clock or Clock())


def test_journey_log_legs_ring_eviction_and_latency(monkeypatch):
    clock = Clock()
    jl = _jlog(monkeypatch, clock=clock, JOURNEY_MAX=2, JOURNEY_RING=4)
    j1 = jl.mint()
    assert jl.next_leg(j1) == 1 and not jl.known(j1)  # lazily materialized
    assert jl.place(j1, "a0", "s1", "offer", "room", retried=1) == 1
    assert jl.known(j1) and jl.journey_for_stream("s1") == j1
    # placement->first-frame latency off the StreamStarted ingest
    clock.now = 0.25
    jl.note_started("s1")
    snap = jl.snapshot()
    assert snap["journey_started_total"] == 1
    assert snap["journey_place_to_start_ms_p50"] == 250.0
    # re-placement increments the leg and the replacement counter
    assert jl.next_leg(j1) == 2
    assert jl.place(j1, "a1", "s2", "offer", "room") == 2
    rec = jl.get(j1)
    assert [leg["agent"] for leg in rec["legs"]] == ["a0", "a1"]
    kinds = [e["kind"] for e in rec["events"]]
    assert kinds == ["placed", "started", "re_placed"]
    assert rec["events"][0]["retried"] == 1
    assert jl.snapshot()["journey_replacements_total"] == 1
    # the ring is bounded (JOURNEY_RING=4): oldest entries evicted
    for i in range(6):
        jl.note(j1, "degraded", state="DEGRADED", i=i)
    assert len(jl.get(j1)["events"]) == 4
    # ended forgets the stream mapping, keeps the record
    jl.end_stream("s2")
    assert jl.journey_for_stream("s2") is None and jl.known(j1)
    # the journey TABLE is bounded (JOURNEY_MAX=2): oldest evicted with
    # its stream mappings
    j2, j3 = jl.mint(), jl.mint()
    jl.place(j2, "a0", "s3", "whip", "")
    jl.place(j3, "a0", "s4", "whip", "")
    assert not jl.known(j1) and jl.journey_for_stream("s1") is None
    assert jl.snapshot()["journeys_evicted_total"] == 1
    assert jl.snapshot()["journeys_tracked"] == 2
    # aggregate-only: nothing keyed by journey identity
    assert all(not isinstance(v, (dict, list))
               for v in jl.snapshot().values())


def test_journey_evidence_and_bundles_survive_eviction(monkeypatch):
    jl = _jlog(monkeypatch, JOURNEY_MAX=1, JOURNEY_EVIDENCE=2,
               JOURNEY_BUNDLES=2)
    j1 = jl.mint()
    jl.place(j1, "a0", "s1", "offer", "")
    for i in range(3):  # bounded evidence: oldest dropped
        jl.add_evidence(j1, "a0", {"snapshots": [], "i": i})
    assert [e["fragment"]["i"] for e in jl.evidence_for(j1)] == [1, 2]
    # re-seals COALESCE per journey: a flapping session's breach
    # volleys must not evict other journeys' only incident record
    jl.seal_bundle(j1, "breach DEGRADED")
    bundle = jl.seal_bundle(j1, "AGENT_DEAD a0")
    assert len(jl.bundles) == 1  # replaced, not appended
    assert jl.bundles_for(j1)[0]["reason"] == "AGENT_DEAD a0"
    assert bundle["journey_id"] == j1
    assert [e["kind"] for e in bundle["journey"]["events"]][-1] == "bundle"
    assert len(bundle["evidence"]) == 2
    # sealed bundles outlive the journey table's eviction churn
    j2 = jl.mint()
    jl.place(j2, "a1", "s2", "offer", "")
    assert not jl.known(j1)
    assert jl.bundles_for(j1) and jl.bundles_for(j1)[0]["reason"].startswith(
        "AGENT_DEAD"
    )
    assert jl.seal_bundle("j-unknown", "x") is None
    snap = jl.snapshot()
    assert snap["journey_bundles_sealed_total"] == 2
    assert snap["journey_evidence_captured_total"] == 3
    # an explicit leg (what the router already forwarded to the agent)
    # wins over the recomputed one — concurrent re-offers or a table
    # eviction racing the proxy await must not desync record vs agent
    jl.place(j2, "a2", "s9", "offer", "", leg=7)
    assert jl.get(j2)["legs"][-1]["leg"] == 7
    # a typo'd ring kind is a programming error, not telemetry
    with pytest.raises(ValueError):
        jl.note(j2, "agent-dead")


def test_journey_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv("JOURNEY_ENABLE", "0")
    jl = JourneyLog(clock=Clock())
    assert jl.enabled is False
    jid = jl.mint()
    assert jl.place(jid, "a0", "s1", "offer", "") == 1
    assert not jl.known(jid)
    jl.note(jid, "degraded")
    assert jl.snapshot()["journey_events_total"] == 0


def _fragment(agent, jid, session="s", taken_at=10.0, snap_id="flt-1"):
    """A minimal agent-side journey fragment: one stored snapshot with a
    frame span + the journey binding (the shape server/agent.py serves
    at GET /debug/flight?journey=)."""
    return {
        "agent": agent,
        "journey_id": jid,
        "sessions": {},
        "snapshots": [{
            "id": snap_id,
            "session": session,
            "reason": "DEGRADED: test",
            "taken_at": taken_at,
            "journey": {"journey_id": jid, "leg": 1, "agent": agent},
            "events": [{"t": taken_at, "kind": "supervisor",
                        "old": "HEALTHY", "new": "DEGRADED"}],
            "frames": [{
                "frame_id": 1, "session": session, "born": taken_at,
                "terminal": "sent",
                "spans": [["engine_step", taken_at, taken_at + 0.01]],
                "marks": [["terminal:sent", taken_at + 0.01]],
            }],
        }],
        "devtel": {"phase": "serving", "recent_compiles": []},
    }


def test_router_mints_forwards_and_continues_journeys():
    """The correlation tentpole at the router: a placed session gets a
    journey id (forwarded to the agent, echoed to the client); an
    AGENT_DEAD webhook carries it; the client's re-offer echoing it
    continues the SAME journey with leg 2 on the replacement agent."""
    posted = []

    class FakeResp:
        status = 200

    class FakeSession:
        async def post(self, url, headers=None, json=None):
            posted.append(json)
            return FakeResp()

    async def go():
        a = await FakeAgent("a").start()
        b = await FakeAgent("b").start()
        events = StreamEventHandler(
            session_factory=FakeSession,
            webhook_url="http://client-notify.example/hook", token="t",
        )
        app, client, reg = await _router([a, b], dead_after=2,
                                         events=events)
        try:
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            jid = r.headers["X-Journey-Id"]
            assert jid.startswith("j-")
            assert r.headers["X-Journey-Leg"] == "1"
            sid = r.headers["X-Stream-Id"]
            # the agent saw the forwarded headers
            owner = app["session_table"].owner(sid)
            agent = a if owner == "a" else b
            assert agent.sessions[sid]["journey"] == jid
            jl = app["journeys"]
            rec = jl.get(jid)
            assert [e["kind"] for e in rec["events"]] == ["placed"]
            assert rec["legs"][0] == {
                "leg": 1, "agent": owner, "stream_id": sid, "kind": "offer",
                "room_id": "r1", "placed_at": rec["legs"][0]["placed_at"],
            }

            # the agent dies: the AGENT_DEAD webhook carries the journey
            # id and a bundle seals (with whatever evidence exists)
            dead_rec = reg.agents[owner]
            reg.note_poll_fail(dead_rec)
            reg.note_poll_fail(dead_rec)
            assert dead_rec.state == "DEAD"
            await asyncio.sleep(0)
            await asyncio.gather(*list(events._tasks))
            ev = next(e for e in posted if e.get("state") == "AGENT_DEAD")
            assert ev["journey_id"] == jid and ev["journey_leg"] == 1
            kinds = [e["kind"] for e in jl.get(jid)["events"]]
            assert "agent_dead" in kinds and "bundle" in kinds
            assert jl.bundles_for(jid)

            # the client re-offers echoing the id: SAME journey, leg 2,
            # on the surviving agent
            r = await client.post("/offer", json=_OFFER,
                                  headers={"X-Journey-Id": jid})
            assert r.status == 200
            assert r.headers["X-Journey-Id"] == jid
            assert r.headers["X-Journey-Leg"] == "2"
            survivor = "b" if owner == "a" else "a"
            assert app["session_table"].owner(
                r.headers["X-Stream-Id"]
            ) == survivor
            rec = jl.get(jid)
            assert rec["legs"][1]["leg"] == 2
            assert rec["legs"][1]["agent"] == survivor
            assert [e["kind"] for e in rec["events"]][-1] == "re_placed"

            # an UNKNOWN echoed id cannot graft onto ring state: a fresh
            # journey is minted instead
            r = await client.post("/offer", json=_OFFER,
                                  headers={"X-Journey-Id": "j-forged"})
            assert r.status == 200
            assert r.headers["X-Journey-Id"] != "j-forged"

            m = await (await client.get("/metrics")).json()
            assert m["journeys_total"] == 2
            assert m["journey_legs_total"] == 3
            assert m["journey_replacements_total"] == 1
            assert m["journey_bundles_sealed_total"] == 1
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_breach_webhook_autocaptures_evidence_and_bundle():
    """The alert-path auto-capture: a StreamDegraded breach volley makes
    the router pull the owning agent's ?journey= fragment and seal a
    bundle — BEFORE any crash can erase the records."""
    async def go():
        a = await FakeAgent("a").start()
        events = StreamEventHandler(webhook_url=None, token=None)
        app, client, reg = await _router([a], events=events)
        try:
            r = await client.post("/offer", json=_OFFER)
            jid = r.headers["X-Journey-Id"]
            sid = r.headers["X-Stream-Id"]
            a.flight[jid] = _fragment("a", jid, session=sid)
            breach = {
                "event": "StreamDegraded", "state": "DEGRADED",
                "stream_id": sid, "room_id": "r1", "timestamp": 1,
                "journey_id": jid, "journey_leg": 1,
                "reason": "step timeout",
            }
            # a volley of near-simultaneous breach webhooks (DEGRADED →
            # FAILED within ms) dedups to ONE in-flight pull — duplicate
            # fragments must not churn the bounded evidence ring
            r = await client.post("/fleet/events", json=breach)
            assert r.status == 200
            r = await client.post(
                "/fleet/events", json={**breach, "state": "FAILED"}
            )
            assert r.status == 200
            await asyncio.gather(*list(app["journey_tasks"]))
            jl = app["journeys"]
            ev = jl.evidence_for(jid)
            assert len(ev) == 1 and ev[0]["agent"] == "a"
            assert ev[0]["fragment"]["snapshots"][0]["id"] == "flt-1"
            assert a.hits["flight"] == [jid]
            assert not app["journey_inflight"]  # key released with the task
            bundles = jl.bundles_for(jid)
            assert bundles and bundles[0]["reason"] == "breach DEGRADED"
            assert bundles[0]["evidence"]  # the capture rode the seal
            kinds = [e["kind"] for e in jl.get(jid)["events"]]
            assert kinds[:2] == ["placed", "degraded"]
            assert "evidence" in kinds and "bundle" in kinds
            # a session-table eviction must not blind the capture:
            # attribution falls back to the journey's own last leg
            app["session_table"].forget(sid)
            r = await client.post(
                "/fleet/events", json={**breach, "state": "SLO_BREACH"}
            )
            assert r.status == 200
            await asyncio.gather(*list(app["journey_tasks"]))
            assert len(jl.evidence_for(jid)) == 2
            assert a.hits["flight"] == [jid, jid]
            m = await (await client.get("/metrics")).json()
            assert m["journey_evidence_captured_total"] == 2
        finally:
            await client.close()
            await a.close()

    run(go())


def test_journey_bundle_endpoint_one_get_and_chrome_merge():
    """The one-GET incident bundle: router ring + stored evidence (the
    dead agent's) + live fragments (the survivor's) in one body, and
    ?format=chrome merging every captured leg into a single validated
    Perfetto doc with per-agent disjoint pids."""
    from test_obs import _validate_chrome

    async def go():
        a = await FakeAgent("a").start()
        b = await FakeAgent("b").start()
        events = StreamEventHandler(webhook_url=None, token=None)
        app, client, reg = await _router([a, b], dead_after=2,
                                         events=events)
        try:
            r = await client.post("/offer", json=_OFFER)
            jid = r.headers["X-Journey-Id"]
            sid = r.headers["X-Stream-Id"]
            owner = app["session_table"].owner(sid)
            dead_agent, live_agent = (a, b) if owner == "a" else (b, a)
            # breach -> evidence banked from the soon-to-die agent
            dead_agent.flight[jid] = _fragment(owner, jid, session=sid)
            await client.post("/fleet/events", json={
                "event": "StreamDegraded", "state": "RETRACE_BREACH",
                "stream_id": sid, "room_id": "r1", "timestamp": 1,
                "journey_id": jid,
            })
            await asyncio.gather(*list(app["journey_tasks"]))
            # the agent dies; the client re-offers onto the survivor
            rec = reg.agents[owner]
            reg.note_poll_fail(rec)
            reg.note_poll_fail(rec)
            r = await client.post("/offer", json=_OFFER,
                                  headers={"X-Journey-Id": jid})
            assert r.status == 200
            sid2 = r.headers["X-Stream-Id"]
            live_agent.flight[jid] = _fragment(
                live_agent.name, jid, session=sid2, taken_at=20.0,
                snap_id="flt-2",
            )
            live_agent.flight[jid]["snapshots"][0]["journey"]["leg"] = 2

            # ONE GET: the whole story
            r = await client.get(f"/fleet/debug/journey/{jid}")
            assert r.status == 200
            bundle = await r.json()
            kinds = [e["kind"] for e in bundle["journey"]["events"]]
            for expected in ("placed", "degraded", "agent_dead",
                             "re_placed"):
                assert expected in kinds, kinds
            # the dead agent's records came from the evidence store...
            assert [e["agent"] for e in bundle["evidence"]] == [owner]
            srcs = {f["source"] for f in bundle["fragments"]}
            assert "unreachable" in srcs  # the corpse answers nothing
            # ...the survivor's from the live fan-out
            live = [f for f in bundle["fragments"]
                    if f.get("source") == "live"]
            assert [f["agent"] for f in live] == [live_agent.name]
            assert bundle["bundles"]  # sealed on the alert paths
            # every piece shares the one journey id
            assert bundle["journey_id"] == jid
            assert all(
                s["journey"]["journey_id"] == jid
                for f in live for s in f["snapshots"]
            )

            # the merged Perfetto doc validates with per-agent pids
            r = await client.get(f"/fleet/debug/journey/{jid}",
                                 params={"format": "chrome"})
            assert r.status == 200
            doc = await r.json()
            evs = _validate_chrome(doc)
            agent_by_pid = {
                e["pid"]: e["args"]["agent"] for e in evs
                if e["ph"] == "M" and e["name"] == "process_name"
            }
            assert len(agent_by_pid) >= 2
            assert set(agent_by_pid.values()) == {owner, live_agent.name}
            assert all(
                e["args"]["journey_id"] == jid for e in evs
                if e["ph"] == "X"
            )

            # crisp error surfaces: unknown id 404, unknown param 400,
            # bad format 400 — all JSON bodies
            r = await client.get("/fleet/debug/journey/j-nope")
            assert r.status == 404 and "error" in await r.json()
            r = await client.get(f"/fleet/debug/journey/{jid}",
                                 params={"fromat": "chrome"})
            assert r.status == 400
            assert "fromat" in (await r.json())["error"]
            r = await client.get(f"/fleet/debug/journey/{jid}",
                                 params={"format": "jsonl"})
            assert r.status == 400
            # the directory endpoint lists it
            idx = await (await client.get("/fleet/debug/journeys")).json()
            assert [j["journey_id"] for j in idx["journeys"]] == [jid]
            assert idx["journeys"][0]["legs"] == 2
            assert idx["bundles"]
        finally:
            await client.close()
            await a.close()
            await b.close()

    run(go())


def test_journey_kill_switch_removes_plane(monkeypatch):
    monkeypatch.setenv("JOURNEY_ENABLE", "0")

    async def go():
        a = await FakeAgent("a").start()
        app, client, reg = await _router([a])
        try:
            assert app["journeys"] is None
            r = await client.post("/offer", json=_OFFER)
            assert r.status == 200
            assert "X-Journey-Id" not in r.headers
            assert a.sessions["a-s1"]["journey"] is None
            r = await client.get("/fleet/debug/journeys")
            assert r.status == 404 and "error" in await r.json()
            r = await client.get("/fleet/debug/journey/j-x")
            assert r.status == 404
            m = await (await client.get("/metrics")).json()
            assert "journeys_total" not in m
        finally:
            await client.close()
            await a.close()

    run(go())
